"""Device-side input pipeline: async prefetch + double-buffered
host->device transfers + shape bucketing.

Reference mapping (SURVEY.md §2.27-2.28): the reference keeps the
device fed with ``AsyncDataSetIterator`` (background ETL thread +
bounded queue) and ``ParallelWrapper.prefetchBuffer(n)``. On TPU the
missing half of that story is the *transfer*: a fit loop that calls
``jnp.asarray`` per minibatch serializes host->device copies with the
dispatch thread, and any ragged batch (partial final minibatch,
variable sequence length) recompiles the whole training executable.

This module closes both gaps:

- ``DevicePrefetchIterator`` wraps any ``DataSetIterator`` /
  ``MultiDataSetIterator``. Host ETL runs on the
  ``AsyncDataSetIterator`` queue machinery; a second transfer thread
  issues ``jax.device_put`` for the next ``depth`` batches (default 2)
  with the correct committed placement — replicated single-device by
  default, ``NamedSharding(P('data', ...))`` when a mesh is given — so
  the transfer of batch N+1 overlaps device step N. ``depth=0`` is the
  fully synchronous fallback (no threads); on CPU everything still
  works, the device_put is just a cheap host copy.
- ``BatchShapePolicy`` stabilizes shapes so every batch hits ONE
  compiled executable per bucket: ``pad_last`` pads the final partial
  minibatch up to the full batch size, ``bucket`` additionally pads
  sequence lengths up to power-of-two buckets. Padding is masked: the
  labels mask is scaled by padded_N/real_N on real rows and zeroed on
  padding, which keeps the loss EXACTLY what the unpadded batch
  produces (see ``loss.compute_loss``'s normalization invariant —
  divisor is the padded minibatch size, so the scale cancels it).

Loss-equivalence fine print:
- batch padding is exact for every loss (both sum- and mean-reduced
  normalizations scale linearly with N);
- sequence (time) padding is exact for per-timestep sum-reduced losses
  (MCXENT & friends — the RNN masking convention), and off by a
  constant factor real_T/bucket_T for mean-reduced losses (MSE/MAE...)
  — gradient direction is unchanged, it acts as a per-bucket LR scale;
- layers that consume cross-batch statistics (BatchNormalization in
  train mode) see the padded rows; pad_last/bucket change their batch
  statistics slightly. Use ``exact`` if bit-exact BN stats matter.

Telemetry (profiler/telemetry.py registry):
``dl4j_tpu_prefetch_queue_depth`` gauge,
``dl4j_tpu_prefetch_transfer_overlap_ms`` histogram (time between a
batch's device_put issue and its consumption — >0 means the transfer
was in flight while the previous step ran),
``dl4j_tpu_prefetch_padded_examples_total`` counter, and
``dl4j_tpu_shape_bucket_{hits,misses}_total`` counters that the
recompile-storm detector reads to recommend enabling bucketing.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from typing import Optional, Sequence

import jax
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator
from deeplearning4j_tpu.datasets.multi_dataset import (
    MultiDataSet, MultiDataSetIterator,
)
from deeplearning4j_tpu.datasets.record_reader_iterator import (
    AsyncDataSetIterator,
)
from deeplearning4j_tpu.profiler import telemetry as _telemetry

log = logging.getLogger("deeplearning4j_tpu")


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _norm_mask(m: Optional[np.ndarray]) -> Optional[np.ndarray]:
    """Drop a trailing singleton channel: [N,T,1] -> [N,T]."""
    if m is not None and m.ndim >= 2 and m.shape[-1] == 1:
        return m[..., 0]
    return m


def _pad_axis(a: np.ndarray, axis: int, count: int,
              value: float = 0.0) -> np.ndarray:
    if count <= 0:
        return a
    shape = list(a.shape)
    shape[axis] = count
    pad = np.full(shape, value, a.dtype)
    return np.concatenate([a, pad], axis)


class BatchShapePolicy:
    """Shape-stabilization policy applied by the prefetcher.

    Modes:
    - ``exact``: pass batches through untouched.
    - ``pad_last``: pad the final partial minibatch up to
      ``batch_size`` with zero rows, masked out of the loss.
    - ``bucket``: ``pad_last`` + pad [N,T,F] sequence lengths up to
      power-of-two buckets (>= ``min_seq_bucket``, or the explicit
      ``seq_buckets`` list), generating/extending the features mask so
      padded timesteps are zeroed at the input and the labels mask so
      the loss is unchanged. Guarantees ONE executable per bucket.

    ``pad_last``/``bucket`` ALWAYS attach a labels mask (all-ones-
    scaled for full batches) so mask presence — part of the jit
    signature — is uniform across the stream.
    """

    MODES = ("exact", "pad_last", "bucket")

    def __init__(self, mode: str = "pad_last",
                 batch_size: Optional[int] = None,
                 min_seq_bucket: int = 8,
                 seq_buckets: Optional[Sequence[int]] = None):
        if mode not in self.MODES:
            raise ValueError(f"unknown BatchShapePolicy mode {mode!r} — "
                             f"expected one of {self.MODES}")
        self.mode = mode
        self.batch_size = int(batch_size) if batch_size else None
        self.min_seq_bucket = int(min_seq_bucket)
        self.seq_buckets = sorted(int(b) for b in seq_buckets) \
            if seq_buckets else None
        self._seen: set = set()

    def bucket_t(self, t: int) -> int:
        """Bucketed sequence length for a raw length ``t``: the
        smallest explicit bucket >= t, or the next power of two
        (floored at min_seq_bucket). A length beyond every explicit
        bucket stays as-is — data is never truncated."""
        if self.seq_buckets:
            for b in self.seq_buckets:
                if t <= b:
                    return b
            return t
        return max(self.min_seq_bucket, _next_pow2(t))

    # ------------------------------------------------------------------
    def apply(self, ds):
        """DataSet/MultiDataSet -> shape-stabilized copy (host side)."""
        if self.mode == "exact":
            return ds
        if isinstance(ds, MultiDataSet):
            out, padded = self._apply_multi(ds)
        else:
            out, padded = self._apply_single(ds)
        self._record(out, padded)
        return out

    def _apply_single(self, ds: DataSet):
        x = np.asarray(ds.features)
        y = np.asarray(ds.labels)
        fm = _norm_mask(None if ds.features_mask is None
                        else np.asarray(ds.features_mask))
        n = x.shape[0]
        target_n = max(self.batch_size or n, n)
        x, fm = self._pad_feature(x, fm, n)
        y, lm = self._pad_label(
            y, None if ds.labels_mask is None
            else np.asarray(ds.labels_mask), fm_orig=fm, n=n,
            target_n=target_n)
        pad_n = target_n - n
        if pad_n:
            x = _pad_axis(x, 0, pad_n)
            y = _pad_axis(y, 0, pad_n)
            lm = _pad_axis(lm, 0, pad_n)
            if fm is not None:
                # padded EXAMPLE rows keep an all-ones features mask:
                # their loss is killed by the zero labels mask, and a
                # fully-masked row would turn masked max-pooling into
                # -inf -> NaN even under a zero loss weight
                fm = _pad_axis(fm, 0, pad_n, value=1.0)
        return DataSet(x, y, fm, lm), pad_n

    def _pad_feature(self, x, fm, n):
        """Time-bucket one [N,T,F] feature array (+ its [N,T] mask)."""
        if self.mode != "bucket" or x.ndim != 3:
            return x, fm
        t = x.shape[1]
        if fm is None:
            fm = np.ones((n, t), np.float32)
        bt = self.bucket_t(t)
        if bt > t:
            x = _pad_axis(x, 1, bt - t)
            fm = _pad_axis(fm, 1, bt - t)
        return x, fm

    def _pad_label(self, y, lm, fm_orig, n, target_n):
        """Labels mask carrying the loss-preserving scale: real rows
        weighted target_n/n, padding weighted 0. The divisor inside
        ``compute_loss`` is the PADDED minibatch size, so the scale
        cancels it and the loss equals the unpadded batch's exactly."""
        scale = target_n / float(n)
        lm = _norm_mask(lm)
        if y.ndim == 3:
            t_real = y.shape[1]
            if lm is None:
                # RNN convention (mirrors _fit_batch): with per-timestep
                # labels the features mask doubles as the label mask
                if fm_orig is not None and fm_orig.shape[1] >= t_real:
                    lm = np.array(fm_orig[:, :t_real], np.float32)
                else:
                    lm = np.ones((n, t_real), np.float32)
            lm = np.asarray(lm, np.float32)
            if lm.ndim == 1 or lm.shape[1] != t_real:
                # per-example weights ([N] / [N,1]) on sequence labels:
                # broadcast to per-timestep so time padding composes
                # (each real step carries the example's weight); the
                # features mask still gates which steps are real
                lm = np.broadcast_to(lm.reshape(n, -1)[:, :1],
                                     (n, t_real)).copy()
                if fm_orig is not None and fm_orig.shape[1] >= t_real:
                    lm = lm * fm_orig[:, :t_real]
            lm = lm * scale
            if self.mode == "bucket":
                bt = self.bucket_t(t_real)
                if bt > t_real:
                    y = _pad_axis(y, 1, bt - t_real)
                    lm = _pad_axis(lm, 1, bt - t_real)
        else:
            if lm is None:
                lm = np.ones((n, 1), np.float32)
            lm = np.asarray(lm, np.float32) * scale
        return y, lm

    def _apply_multi(self, mds: MultiDataSet):
        feats = [np.asarray(a) for a in mds.features]
        labs = [np.asarray(a) for a in mds.labels]
        fms = [None if m is None else _norm_mask(np.asarray(m))
               for m in (mds.features_mask_arrays
                         or [None] * len(feats))]
        lms = list(mds.labels_mask_arrays or [None] * len(labs))
        n = feats[0].shape[0]
        target_n = max(self.batch_size or n, n)
        pad_n = target_n - n
        new_f, new_fm = [], []
        for a, m in zip(feats, fms):
            a, m = self._pad_feature(a, m, n)
            a = _pad_axis(a, 0, pad_n)
            if m is not None:
                m = _pad_axis(m, 0, pad_n, value=1.0)
            new_f.append(a)
            new_fm.append(m)
        new_l, new_lm = [], []
        for a, m in zip(labs, lms):
            # no per-output fm->lm convention in the graph fit loop, so
            # the base mask is all-ones when absent
            a, m = self._pad_label(
                a, None if m is None else np.asarray(m),
                fm_orig=None, n=n, target_n=target_n)
            new_l.append(_pad_axis(a, 0, pad_n))
            new_lm.append(_pad_axis(m, 0, pad_n))
        out = MultiDataSet(
            new_f, new_l,
            new_fm if any(m is not None for m in new_fm) else None,
            new_lm)
        return out, pad_n * len(feats)

    def _record(self, out, padded: int) -> None:
        if not _telemetry.enabled():
            return
        reg = _telemetry.MetricsRegistry.get_default()
        if padded:
            reg.counter(
                _telemetry.PREFETCH_PADDED_EXAMPLES,
                "zero-padded examples added by the batch shape policy"
            ).inc(padded)
        if self.mode != "bucket":
            return
        if isinstance(out, MultiDataSet):
            key = tuple(tuple(np.asarray(a).shape)
                        for a in (*out.features, *out.labels))
        else:
            key = (tuple(np.asarray(out.features).shape),
                   tuple(np.asarray(out.labels).shape))
        if key in self._seen:
            reg.counter(_telemetry.BUCKET_HITS,
                        "batches landing in an already-seen shape "
                        "bucket (no new executable)").inc()
        else:
            self._seen.add(key)
            reg.counter(_telemetry.BUCKET_MISSES,
                        "first batch per shape bucket (one compile "
                        "each — total bounded by #buckets)").inc()


class _StateTaggingIterator(DataSetIterator):
    """Attaches the underlying iterator's post-batch ``get_state()`` to
    each batch (as a ``_iter_state`` attribute) so the prefetcher can
    answer ``get_state()`` for the batch the CONSUMER last saw, not for
    wherever the lookahead workers have raced to — the difference is
    the whole point of checkpointable prefetch (SURVEY.md §5: resume
    mid-epoch on the NEXT batch)."""

    def __init__(self, underlying: DataSetIterator):
        self.underlying = underlying
        # set by DevicePrefetchIterator.set_state: the worker's
        # epoch-opening reset() must not discard a just-restored
        # mid-epoch position
        self._skip_next_reset = False

    def reset(self):
        if self._skip_next_reset:
            self._skip_next_reset = False
            return
        self.underlying.reset()

    def hasNext(self) -> bool:
        return self.underlying.hasNext()

    def next(self) -> DataSet:
        ds = self.underlying.next()
        try:
            ds._iter_state = self.underlying.get_state()
        except Exception:
            ds._iter_state = None
        return ds

    def batch(self) -> int:
        return self.underlying.batch()

    def resetSupported(self) -> bool:
        return self.underlying.resetSupported()


class DevicePrefetchIterator(DataSetIterator):
    """Async host-ETL + device-transfer prefetcher.

    Wraps a ``DataSetIterator`` (or ``MultiDataSetIterator`` — the
    constructor transparently returns the Multi variant) and yields
    batches whose arrays are already device-resident with committed
    placement, so the fit loops skip the per-step host->device copy.

    - ``depth``: device-side queue size (transfers in flight). 2 gives
      double buffering; 0 is the synchronous no-thread fallback.
    - ``policy``: a ``BatchShapePolicy`` (its ``batch_size`` is filled
      from ``underlying.batch()`` when unset).
    - ``mesh``: shard batches ``P('data', ...)`` over this mesh
      (``ShardedTrainer``/``ParallelWrapper``); default places on the
      first local device, replicated-single-chip semantics.
    - ``dtype``: cast features on the host before transfer (pass the
      network's ``_dtype`` to avoid an on-device cast).

    Thread lifecycle: ``shutdown()`` stops and joins both the host-ETL
    and transfer threads (also usable as a context manager). ``reset``
    restarts cleanly; exceptions in either worker re-raise on the
    consumer thread.
    """

    _SENTINEL = object()

    def __new__(cls, underlying=None, *args, **kwargs):
        if cls is DevicePrefetchIterator \
                and isinstance(underlying, MultiDataSetIterator):
            cls = DevicePrefetchMultiIterator
        return object.__new__(cls)

    def __init__(self, underlying, depth: int = 2,
                 policy: Optional[BatchShapePolicy] = None,
                 mesh=None, device=None, dtype=None,
                 host_queue_size: int = 4,
                 transfer_retries: int = 0,
                 transfer_backoff: float = 0.05,
                 quarantine: bool = False):
        if depth < 0:
            raise ValueError(f"depth must be >= 0, got {depth}")
        self.underlying = underlying
        self.depth = int(depth)
        # transient-transfer policy (util/resilience.py configures this
        # when a FaultTolerance drives the fit): retry a failed
        # device transfer with exponential backoff + jitter; after the
        # budget, either quarantine the poison batch (skip + count) or
        # re-raise (the legacy kill-the-run default)
        self._transfer_retries = max(int(transfer_retries), 0)
        self._transfer_backoff = float(transfer_backoff)
        self._quarantine = bool(quarantine)
        # checkpointable-position support: tag every batch with the
        # underlying's post-batch state when it can report one
        self._last_state = None
        self._tagged: Optional[_StateTaggingIterator] = None
        try:
            underlying.get_state()
        except Exception:
            pass
        else:
            self._tagged = _StateTaggingIterator(underlying)
        self.policy = policy if policy is not None \
            else BatchShapePolicy("exact")
        if self.policy.batch_size is None and self.policy.mode != "exact":
            filled = None
            b = getattr(underlying, "batch", None)
            if callable(b):
                try:
                    filled = int(b()) or None
                except (TypeError, NotImplementedError):
                    filled = None
            if filled is None:
                # a padding policy with no resolvable batch size would
                # silently never pad — the one-executable guarantee the
                # caller asked for would quietly not hold
                raise ValueError(
                    f"BatchShapePolicy({self.policy.mode!r}) needs a "
                    "batch_size, and the underlying iterator does not "
                    "report one via batch() — pass "
                    "BatchShapePolicy(..., batch_size=N) explicitly")
            # never mutate the caller's policy: a shared policy reused
            # across fits would carry the FIRST iterator's batch size
            # to later ones
            self.policy = BatchShapePolicy(
                self.policy.mode, batch_size=filled,
                min_seq_bucket=self.policy.min_seq_bucket,
                seq_buckets=self.policy.seq_buckets)
        self._mesh = mesh
        self._device = device
        self._dtype = dtype
        self._host_queue_size = max(int(host_queue_size), 1)
        self._host = None
        #: depth==0 quarantine-mode lookahead: (batch, post-batch
        #: iterator state) — hasNext() must absorb quarantined batches
        #: so the hasNext()==True -> next() contract survives a
        #: quarantined FINAL batch
        self._peek0 = None
        self._thread: Optional[threading.Thread] = None
        self._q: Optional[queue.Queue] = None
        self._stop: Optional[threading.Event] = None
        self._error: Optional[BaseException] = None
        self._peek = None
        self._exhausted = False
        self._consumed = False   # any next() since the last (re)start
        self._closed = False
        # workers start LAZILY on first consumption: every fit loop
        # consumes via __iter__ -> reset(), and an eager start here
        # would have that reset discard the just-prefetched batches
        # and in-flight transfers, paying the pipeline spin-up twice

    # ------------------------------------------------------- placement
    def _place(self, a, dtype=None):
        """Device placement: committed data-parallel NamedSharding when
        a mesh is set, committed to ``device`` when one was given,
        otherwise an UNcommitted put to the default device — the
        transfer still runs ahead of the step, but the jit signature
        stays identical to the jnp.asarray path (a committed batch
        would flip the step's outputs to committed and cost one extra
        executable per shape on the uncommitted->committed params
        transition)."""
        if a is None:
            return None
        arr = np.asarray(a)
        if dtype is not None and arr.dtype != dtype:
            arr = arr.astype(dtype)
        if self._mesh is not None:
            from deeplearning4j_tpu.parallel.mesh import data_parallel_spec

            return jax.device_put(arr, data_parallel_spec(self._mesh, arr))
        if self._device is not None:
            return jax.device_put(arr, self._device)
        return jax.device_put(arr)

    def _prepare(self, ds):
        """Policy + device transfer. Returns (placed batch, issue time)
        — the issue time feeds the transfer-overlap histogram."""
        from deeplearning4j_tpu.profiler import chaos as _chaos

        monkey = _chaos.active()
        if monkey is not None:
            # inside _prepare so every RETRY attempt re-rolls — a
            # transient injected error clears on the next attempt
            monkey.maybe_fail_transfer()
        tag = getattr(ds, "_iter_state", None)
        ds = self.policy.apply(ds)
        t_issue = time.perf_counter()
        if isinstance(ds, MultiDataSet):
            placed = MultiDataSet(
                [self._place(a, self._dtype) for a in ds.features],
                [self._place(a) for a in ds.labels],
                [self._place(a) for a in ds.features_mask_arrays] or None,
                [self._place(a) for a in ds.labels_mask_arrays] or None)
        else:
            placed = DataSet(self._place(ds.features, self._dtype),
                             self._place(ds.labels),
                             self._place(ds.features_mask),
                             self._place(ds.labels_mask))
        placed._iter_state = tag
        return placed, t_issue

    def configure_retries(self, retries: int, backoff: float = 0.05,
                          quarantine: bool = True) -> None:
        """Set the transient-transfer policy (FaultTolerance seam)."""
        self._transfer_retries = max(int(retries), 0)
        self._transfer_backoff = float(backoff)
        self._quarantine = bool(quarantine)

    def _prepare_with_retry(self, ds, stop=None):
        """``_prepare`` under the transient-failure policy: up to
        ``transfer_retries`` retries with exponential backoff + jitter
        (decorrelates retry storms when many workers share a flaky
        link), then quarantine (return None — the batch is dropped,
        counted and logged) or re-raise. retries=0 is the legacy
        fail-fast path, byte-for-byte."""
        if self._transfer_retries == 0 and not self._quarantine:
            return self._prepare(ds)
        attempts = 0
        while True:
            try:
                return self._prepare(ds)
            except Exception as e:
                attempts += 1
                if attempts > self._transfer_retries:
                    if not self._quarantine:
                        raise
                    if _telemetry.enabled():
                        _telemetry.MetricsRegistry.get_default().counter(
                            _telemetry.TRANSFER_QUARANTINES,
                            "batches dropped after exhausting transfer "
                            "retries (poison-batch quarantine)").inc()
                    log.warning(
                        "DevicePrefetch: quarantining a batch after %d "
                        "failed transfer attempts (%s: %s) — training "
                        "continues on the next batch",
                        attempts, type(e).__name__, e)
                    return None
                if stop is not None and stop.is_set():
                    raise   # tearing down — don't sleep through it
                if _telemetry.enabled():
                    _telemetry.MetricsRegistry.get_default().counter(
                        _telemetry.TRANSFER_RETRIES,
                        "transient host->device transfer retries").inc()
                delay = self._transfer_backoff * (2 ** (attempts - 1))
                delay += random.uniform(0, self._transfer_backoff)
                time.sleep(min(delay, 2.0))

    # ------------------------------------------------------- threading
    def _gauge_depth(self) -> None:
        if _telemetry.enabled() and self._q is not None:
            _telemetry.MetricsRegistry.get_default().gauge(
                _telemetry.PREFETCH_QUEUE_DEPTH,
                "device-resident batches queued ahead of the fit loop"
            ).set(self._q.qsize())

    def _ensure_started(self) -> None:
        if self.depth == 0 or self._thread is not None or self._closed:
            return
        if self._host is None:
            self._host = AsyncDataSetIterator(
                self._tagged if self._tagged is not None
                else self.underlying,
                queue_size=self._host_queue_size)
        else:
            self._host.reset()   # reopen after shutdown
        self._start()

    def _start(self) -> None:
        # _last_state deliberately survives a (re)start: the lazy start
        # right after set_state() must not wipe the restored position
        # out of get_state(); an explicit reset() clears it instead
        self._error = None
        self._exhausted = False
        self._consumed = False
        self._peek = None
        self._stop = threading.Event()
        self._q = queue.Queue(maxsize=self.depth)
        stop, q = self._stop, self._q

        def worker():
            try:
                while not stop.is_set() and self._host.hasNext():
                    item = self._prepare_with_retry(self._host.next(),
                                                    stop=stop)
                    if item is None:
                        continue   # quarantined poison batch — skip
                    # put with a poll so stop can't wedge a producer
                    # blocked on a full queue (same discipline as
                    # AsyncDataSetIterator's worker)
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            self._gauge_depth()
                            break
                        except queue.Full:
                            continue
            except BaseException as e:
                self._error = e
            finally:
                while True:
                    try:
                        q.put(self._SENTINEL, timeout=0.5)
                        break
                    except queue.Full:
                        if not stop.is_set():
                            # consumer alive but slow (mid-compile):
                            # wait for space — dropping here would
                            # silently lose a live batch. An iterator
                            # abandoned without shutdown() leaves this
                            # daemon thread polling at 2Hz holding
                            # `depth` device batches — API misuse the
                            # suite's thread-leak gate catches.
                            continue
                        # reset/shutdown drain: consumer is gone, drop
                        # one stale item to make room for the sentinel
                        try:
                            q.get_nowait()
                        except queue.Empty:
                            pass

        self._thread = threading.Thread(target=worker, daemon=True,
                                        name="DevicePrefetch-transfer")
        self._thread.start()

    def _stop_transfer(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        if not self._exhausted:
            while self._q.get() is not self._SENTINEL:
                pass
        self._thread.join()
        self._thread = None
        self._peek = None
        self._exhausted = True

    def shutdown(self) -> None:
        """Stop and join both worker threads. Idempotent; ``reset()``
        reopens the pipeline afterwards."""
        self._closed = True
        self._exhausted = True
        if self._thread is not None:
            self._stop_transfer()
        if self._host is not None:
            self._host.shutdown()

    def __enter__(self) -> "DevicePrefetchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ------------------------------------------------------- iteration
    def reset(self):
        self._peek0 = None
        if self._tagged is not None and self._tagged._skip_next_reset:
            # an explicit reset() after set_state() means the caller
            # wants the NEXT epoch from the restored epoch counter
            # (the boundary-resume idiom) — the suppress flag only
            # protects the restored position from the host worker's
            # AUTOMATIC start-of-pipeline reset, so consume it here and
            # let this reset reach the underlying iterator
            self._tagged._skip_next_reset = False
        if self.depth == 0:
            self._last_state = None
            self.underlying.reset()
            return
        self._closed = False
        if self._thread is None:
            self._exhausted = False   # reopen; workers start lazily
            # a reset after set_state discards the restored position
            # (the tagger's skip flag was consumed above, so the lazily
            # started worker will really reset the underlying)
            self._last_state = None
            return
        if not self._consumed and not self._exhausted \
                and self._error is None:
            # untouched running pipeline: it is already primed at epoch
            # start — keep the prefetched batches instead of discarding
            # and re-transferring them
            return
        self._stop_transfer()
        self._last_state = None   # reset discards any restored position
        self._host.reset()
        self._start()

    def hasNext(self) -> bool:
        if self.depth == 0:
            if self._peek0 is not None:
                return True
            if not self._quarantine:
                return self.underlying.hasNext()
            # quarantine mode must answer hasNext through the transfer:
            # a quarantined FINAL batch would otherwise turn a True
            # hasNext into a StopIteration out of next()
            while self.underlying.hasNext():
                item = self._prepare_with_retry(self.underlying.next())
                if item is not None:
                    try:
                        st = self.underlying.get_state()
                    except Exception:
                        st = None
                    self._peek0 = (item[0], st)
                    return True
            return False
        self._ensure_started()
        if self._exhausted:
            return False
        if self._peek is None:
            item = self._q.get()
            self._gauge_depth()
            if item is self._SENTINEL:
                self._exhausted = True
                if self._error is not None:
                    err, self._error = self._error, None
                    raise err
                return False
            ds, t_issue = item
            if _telemetry.enabled():
                _telemetry.MetricsRegistry.get_default().histogram(
                    _telemetry.TRANSFER_OVERLAP_MS,
                    "ms between a batch's device_put issue and its "
                    "consumption by the fit loop (>0: the transfer "
                    "overlapped the previous device step)").observe(
                    (time.perf_counter() - t_issue) * 1e3)
            self._peek = ds
        return True

    def next(self):
        if self.depth == 0:
            if self._peek0 is None and not self._quarantine:
                ds, _ = self._prepare_with_retry(self.underlying.next())
                return ds
            if not self.hasNext():   # fills _peek0, absorbing
                raise StopIteration  # quarantined batches
            ds, st = self._peek0
            self._peek0 = None
            if st is not None:
                self._last_state = st
            return ds
        if not self.hasNext():
            raise StopIteration
        ds, self._peek = self._peek, None
        self._consumed = True
        tag = getattr(ds, "_iter_state", None)
        if tag is not None:
            self._last_state = tag
        return ds

    # ----------------------------------------------- checkpointable state
    def get_state(self) -> dict:
        """Position of the batch the CONSUMER last received — not the
        lookahead workers' position, which is up to
        ``host_queue_size + depth`` batches ahead. Restoring this state
        re-produces every prefetched-but-unconsumed batch, which is the
        correct resume semantics: nothing consumed twice, nothing
        skipped."""
        if self.depth == 0:
            if self._peek0 is not None or (self._quarantine
                                           and self._last_state is not None):
                # a lookahead batch is (or was) in flight: the
                # underlying iterator is one batch ahead of the
                # consumer — report the consumed position
                if self._last_state is None:
                    raise RuntimeError(
                        "DevicePrefetchIterator.get_state: no batch "
                        "consumed since the last reset — the position "
                        "is epoch start; checkpoint with "
                        "iterator_state=None instead")
                return {"underlying": self._last_state}
            return {"underlying": self.underlying.get_state()}
        if self._tagged is None:
            raise NotImplementedError(
                f"{type(self.underlying).__name__} does not support "
                "state capture")
        if self._last_state is None:
            raise RuntimeError(
                "DevicePrefetchIterator.get_state: no batch consumed "
                "since the last reset — the position is epoch start; "
                "checkpoint with iterator_state=None instead")
        return {"underlying": self._last_state}

    def set_state(self, state: dict) -> None:
        under_state = state.get("underlying", state) \
            if isinstance(state, dict) else state
        if self.depth == 0:
            self._peek0 = None
            self.underlying.set_state(under_state)
            self._last_state = under_state
            return
        if self._tagged is None:
            raise NotImplementedError(
                f"{type(self.underlying).__name__} does not support "
                "state restore")
        # tear down the running pipeline: its queues hold batches from
        # the OLD position
        if self._thread is not None:
            self._stop_transfer()
        if self._host is not None:
            self._host.shutdown()
        self.underlying.set_state(under_state)
        # the lazily-restarted host worker opens with a reset() — it
        # must not discard the position just restored
        self._tagged._skip_next_reset = True
        self._closed = False
        self._exhausted = False
        self._consumed = False
        self._last_state = under_state

    def batch(self) -> int:
        if self.policy.mode != "exact" and self.policy.batch_size:
            return self.policy.batch_size
        b = getattr(self.underlying, "batch", None)
        if callable(b):
            return b()
        raise NotImplementedError(
            f"{type(self.underlying).__name__} does not expose batch()")

    def resetSupported(self) -> bool:
        sup = getattr(self.underlying, "resetSupported", None)
        return sup() if callable(sup) else True

    def asyncSupported(self) -> bool:
        return False  # already async — do not double-wrap


class DevicePrefetchMultiIterator(DevicePrefetchIterator,
                                  MultiDataSetIterator):
    """MultiDataSetIterator-typed variant (so ``ComputationGraph.fit``
    and ``ShardedTrainer.fit`` route it down the MultiDataSet path).
    Constructed automatically by ``DevicePrefetchIterator(multi_iter)``.
    """


__all__ = ["BatchShapePolicy", "DevicePrefetchIterator",
           "DevicePrefetchMultiIterator"]
