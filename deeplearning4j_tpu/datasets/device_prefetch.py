"""Device-side input pipeline: async prefetch + double-buffered
host->device transfers + shape bucketing.

Reference mapping (SURVEY.md §2.27-2.28): the reference keeps the
device fed with ``AsyncDataSetIterator`` (background ETL thread +
bounded queue) and ``ParallelWrapper.prefetchBuffer(n)``. On TPU the
missing half of that story is the *transfer*: a fit loop that calls
``jnp.asarray`` per minibatch serializes host->device copies with the
dispatch thread, and any ragged batch (partial final minibatch,
variable sequence length) recompiles the whole training executable.

This module closes both gaps:

- ``DevicePrefetchIterator`` wraps any ``DataSetIterator`` /
  ``MultiDataSetIterator``. Host ETL runs on the
  ``AsyncDataSetIterator`` queue machinery; a second transfer thread
  issues ``jax.device_put`` for the next ``depth`` batches (default 2)
  with the correct committed placement — replicated single-device by
  default, ``NamedSharding(P('data', ...))`` when a mesh is given — so
  the transfer of batch N+1 overlaps device step N. ``depth=0`` is the
  fully synchronous fallback (no threads); on CPU everything still
  works, the device_put is just a cheap host copy.
- ``BatchShapePolicy`` stabilizes shapes so every batch hits ONE
  compiled executable per bucket: ``pad_last`` pads the final partial
  minibatch up to the full batch size, ``bucket`` additionally pads
  sequence lengths up to power-of-two buckets. Padding is masked: the
  labels mask is scaled by padded_N/real_N on real rows and zeroed on
  padding, which keeps the loss EXACTLY what the unpadded batch
  produces (see ``loss.compute_loss``'s normalization invariant —
  divisor is the padded minibatch size, so the scale cancels it).

Loss-equivalence fine print:
- batch padding is exact for every loss (both sum- and mean-reduced
  normalizations scale linearly with N);
- sequence (time) padding is exact for per-timestep sum-reduced losses
  (MCXENT & friends — the RNN masking convention), and off by a
  constant factor real_T/bucket_T for mean-reduced losses (MSE/MAE...)
  — gradient direction is unchanged, it acts as a per-bucket LR scale;
- layers that consume cross-batch statistics (BatchNormalization in
  train mode) see the padded rows; pad_last/bucket change their batch
  statistics slightly. Use ``exact`` if bit-exact BN stats matter.

Telemetry (profiler/telemetry.py registry):
``dl4j_tpu_prefetch_queue_depth`` gauge,
``dl4j_tpu_prefetch_transfer_overlap_ms`` histogram (time between a
batch's device_put issue and its consumption — >0 means the transfer
was in flight while the previous step ran),
``dl4j_tpu_prefetch_padded_examples_total`` counter, and
``dl4j_tpu_shape_bucket_{hits,misses}_total`` counters that the
recompile-storm detector reads to recommend enabling bucketing.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional, Sequence

import jax
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator
from deeplearning4j_tpu.datasets.multi_dataset import (
    MultiDataSet, MultiDataSetIterator,
)
from deeplearning4j_tpu.datasets.record_reader_iterator import (
    AsyncDataSetIterator,
)
from deeplearning4j_tpu.profiler import telemetry as _telemetry


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _norm_mask(m: Optional[np.ndarray]) -> Optional[np.ndarray]:
    """Drop a trailing singleton channel: [N,T,1] -> [N,T]."""
    if m is not None and m.ndim >= 2 and m.shape[-1] == 1:
        return m[..., 0]
    return m


def _pad_axis(a: np.ndarray, axis: int, count: int,
              value: float = 0.0) -> np.ndarray:
    if count <= 0:
        return a
    shape = list(a.shape)
    shape[axis] = count
    pad = np.full(shape, value, a.dtype)
    return np.concatenate([a, pad], axis)


class BatchShapePolicy:
    """Shape-stabilization policy applied by the prefetcher.

    Modes:
    - ``exact``: pass batches through untouched.
    - ``pad_last``: pad the final partial minibatch up to
      ``batch_size`` with zero rows, masked out of the loss.
    - ``bucket``: ``pad_last`` + pad [N,T,F] sequence lengths up to
      power-of-two buckets (>= ``min_seq_bucket``, or the explicit
      ``seq_buckets`` list), generating/extending the features mask so
      padded timesteps are zeroed at the input and the labels mask so
      the loss is unchanged. Guarantees ONE executable per bucket.

    ``pad_last``/``bucket`` ALWAYS attach a labels mask (all-ones-
    scaled for full batches) so mask presence — part of the jit
    signature — is uniform across the stream.
    """

    MODES = ("exact", "pad_last", "bucket")

    def __init__(self, mode: str = "pad_last",
                 batch_size: Optional[int] = None,
                 min_seq_bucket: int = 8,
                 seq_buckets: Optional[Sequence[int]] = None):
        if mode not in self.MODES:
            raise ValueError(f"unknown BatchShapePolicy mode {mode!r} — "
                             f"expected one of {self.MODES}")
        self.mode = mode
        self.batch_size = int(batch_size) if batch_size else None
        self.min_seq_bucket = int(min_seq_bucket)
        self.seq_buckets = sorted(int(b) for b in seq_buckets) \
            if seq_buckets else None
        self._seen: set = set()

    def bucket_t(self, t: int) -> int:
        """Bucketed sequence length for a raw length ``t``: the
        smallest explicit bucket >= t, or the next power of two
        (floored at min_seq_bucket). A length beyond every explicit
        bucket stays as-is — data is never truncated."""
        if self.seq_buckets:
            for b in self.seq_buckets:
                if t <= b:
                    return b
            return t
        return max(self.min_seq_bucket, _next_pow2(t))

    # ------------------------------------------------------------------
    def apply(self, ds):
        """DataSet/MultiDataSet -> shape-stabilized copy (host side)."""
        if self.mode == "exact":
            return ds
        if isinstance(ds, MultiDataSet):
            out, padded = self._apply_multi(ds)
        else:
            out, padded = self._apply_single(ds)
        self._record(out, padded)
        return out

    def _apply_single(self, ds: DataSet):
        x = np.asarray(ds.features)
        y = np.asarray(ds.labels)
        fm = _norm_mask(None if ds.features_mask is None
                        else np.asarray(ds.features_mask))
        n = x.shape[0]
        target_n = max(self.batch_size or n, n)
        x, fm = self._pad_feature(x, fm, n)
        y, lm = self._pad_label(
            y, None if ds.labels_mask is None
            else np.asarray(ds.labels_mask), fm_orig=fm, n=n,
            target_n=target_n)
        pad_n = target_n - n
        if pad_n:
            x = _pad_axis(x, 0, pad_n)
            y = _pad_axis(y, 0, pad_n)
            lm = _pad_axis(lm, 0, pad_n)
            if fm is not None:
                # padded EXAMPLE rows keep an all-ones features mask:
                # their loss is killed by the zero labels mask, and a
                # fully-masked row would turn masked max-pooling into
                # -inf -> NaN even under a zero loss weight
                fm = _pad_axis(fm, 0, pad_n, value=1.0)
        return DataSet(x, y, fm, lm), pad_n

    def _pad_feature(self, x, fm, n):
        """Time-bucket one [N,T,F] feature array (+ its [N,T] mask)."""
        if self.mode != "bucket" or x.ndim != 3:
            return x, fm
        t = x.shape[1]
        if fm is None:
            fm = np.ones((n, t), np.float32)
        bt = self.bucket_t(t)
        if bt > t:
            x = _pad_axis(x, 1, bt - t)
            fm = _pad_axis(fm, 1, bt - t)
        return x, fm

    def _pad_label(self, y, lm, fm_orig, n, target_n):
        """Labels mask carrying the loss-preserving scale: real rows
        weighted target_n/n, padding weighted 0. The divisor inside
        ``compute_loss`` is the PADDED minibatch size, so the scale
        cancels it and the loss equals the unpadded batch's exactly."""
        scale = target_n / float(n)
        lm = _norm_mask(lm)
        if y.ndim == 3:
            t_real = y.shape[1]
            if lm is None:
                # RNN convention (mirrors _fit_batch): with per-timestep
                # labels the features mask doubles as the label mask
                if fm_orig is not None and fm_orig.shape[1] >= t_real:
                    lm = np.array(fm_orig[:, :t_real], np.float32)
                else:
                    lm = np.ones((n, t_real), np.float32)
            lm = np.asarray(lm, np.float32)
            if lm.ndim == 1 or lm.shape[1] != t_real:
                # per-example weights ([N] / [N,1]) on sequence labels:
                # broadcast to per-timestep so time padding composes
                # (each real step carries the example's weight); the
                # features mask still gates which steps are real
                lm = np.broadcast_to(lm.reshape(n, -1)[:, :1],
                                     (n, t_real)).copy()
                if fm_orig is not None and fm_orig.shape[1] >= t_real:
                    lm = lm * fm_orig[:, :t_real]
            lm = lm * scale
            if self.mode == "bucket":
                bt = self.bucket_t(t_real)
                if bt > t_real:
                    y = _pad_axis(y, 1, bt - t_real)
                    lm = _pad_axis(lm, 1, bt - t_real)
        else:
            if lm is None:
                lm = np.ones((n, 1), np.float32)
            lm = np.asarray(lm, np.float32) * scale
        return y, lm

    def _apply_multi(self, mds: MultiDataSet):
        feats = [np.asarray(a) for a in mds.features]
        labs = [np.asarray(a) for a in mds.labels]
        fms = [None if m is None else _norm_mask(np.asarray(m))
               for m in (mds.features_mask_arrays
                         or [None] * len(feats))]
        lms = list(mds.labels_mask_arrays or [None] * len(labs))
        n = feats[0].shape[0]
        target_n = max(self.batch_size or n, n)
        pad_n = target_n - n
        new_f, new_fm = [], []
        for a, m in zip(feats, fms):
            a, m = self._pad_feature(a, m, n)
            a = _pad_axis(a, 0, pad_n)
            if m is not None:
                m = _pad_axis(m, 0, pad_n, value=1.0)
            new_f.append(a)
            new_fm.append(m)
        new_l, new_lm = [], []
        for a, m in zip(labs, lms):
            # no per-output fm->lm convention in the graph fit loop, so
            # the base mask is all-ones when absent
            a, m = self._pad_label(
                a, None if m is None else np.asarray(m),
                fm_orig=None, n=n, target_n=target_n)
            new_l.append(_pad_axis(a, 0, pad_n))
            new_lm.append(_pad_axis(m, 0, pad_n))
        out = MultiDataSet(
            new_f, new_l,
            new_fm if any(m is not None for m in new_fm) else None,
            new_lm)
        return out, pad_n * len(feats)

    def _record(self, out, padded: int) -> None:
        if not _telemetry.enabled():
            return
        reg = _telemetry.MetricsRegistry.get_default()
        if padded:
            reg.counter(
                _telemetry.PREFETCH_PADDED_EXAMPLES,
                "zero-padded examples added by the batch shape policy"
            ).inc(padded)
        if self.mode != "bucket":
            return
        if isinstance(out, MultiDataSet):
            key = tuple(tuple(np.asarray(a).shape)
                        for a in (*out.features, *out.labels))
        else:
            key = (tuple(np.asarray(out.features).shape),
                   tuple(np.asarray(out.labels).shape))
        if key in self._seen:
            reg.counter(_telemetry.BUCKET_HITS,
                        "batches landing in an already-seen shape "
                        "bucket (no new executable)").inc()
        else:
            self._seen.add(key)
            reg.counter(_telemetry.BUCKET_MISSES,
                        "first batch per shape bucket (one compile "
                        "each — total bounded by #buckets)").inc()


class DevicePrefetchIterator(DataSetIterator):
    """Async host-ETL + device-transfer prefetcher.

    Wraps a ``DataSetIterator`` (or ``MultiDataSetIterator`` — the
    constructor transparently returns the Multi variant) and yields
    batches whose arrays are already device-resident with committed
    placement, so the fit loops skip the per-step host->device copy.

    - ``depth``: device-side queue size (transfers in flight). 2 gives
      double buffering; 0 is the synchronous no-thread fallback.
    - ``policy``: a ``BatchShapePolicy`` (its ``batch_size`` is filled
      from ``underlying.batch()`` when unset).
    - ``mesh``: shard batches ``P('data', ...)`` over this mesh
      (``ShardedTrainer``/``ParallelWrapper``); default places on the
      first local device, replicated-single-chip semantics.
    - ``dtype``: cast features on the host before transfer (pass the
      network's ``_dtype`` to avoid an on-device cast).

    Thread lifecycle: ``shutdown()`` stops and joins both the host-ETL
    and transfer threads (also usable as a context manager). ``reset``
    restarts cleanly; exceptions in either worker re-raise on the
    consumer thread.
    """

    _SENTINEL = object()

    def __new__(cls, underlying=None, *args, **kwargs):
        if cls is DevicePrefetchIterator \
                and isinstance(underlying, MultiDataSetIterator):
            cls = DevicePrefetchMultiIterator
        return object.__new__(cls)

    def __init__(self, underlying, depth: int = 2,
                 policy: Optional[BatchShapePolicy] = None,
                 mesh=None, device=None, dtype=None,
                 host_queue_size: int = 4):
        if depth < 0:
            raise ValueError(f"depth must be >= 0, got {depth}")
        self.underlying = underlying
        self.depth = int(depth)
        self.policy = policy if policy is not None \
            else BatchShapePolicy("exact")
        if self.policy.batch_size is None and self.policy.mode != "exact":
            filled = None
            b = getattr(underlying, "batch", None)
            if callable(b):
                try:
                    filled = int(b()) or None
                except (TypeError, NotImplementedError):
                    filled = None
            if filled is None:
                # a padding policy with no resolvable batch size would
                # silently never pad — the one-executable guarantee the
                # caller asked for would quietly not hold
                raise ValueError(
                    f"BatchShapePolicy({self.policy.mode!r}) needs a "
                    "batch_size, and the underlying iterator does not "
                    "report one via batch() — pass "
                    "BatchShapePolicy(..., batch_size=N) explicitly")
            # never mutate the caller's policy: a shared policy reused
            # across fits would carry the FIRST iterator's batch size
            # to later ones
            self.policy = BatchShapePolicy(
                self.policy.mode, batch_size=filled,
                min_seq_bucket=self.policy.min_seq_bucket,
                seq_buckets=self.policy.seq_buckets)
        self._mesh = mesh
        self._device = device
        self._dtype = dtype
        self._host_queue_size = max(int(host_queue_size), 1)
        self._host = None
        self._thread: Optional[threading.Thread] = None
        self._q: Optional[queue.Queue] = None
        self._stop: Optional[threading.Event] = None
        self._error: Optional[BaseException] = None
        self._peek = None
        self._exhausted = False
        self._consumed = False   # any next() since the last (re)start
        self._closed = False
        # workers start LAZILY on first consumption: every fit loop
        # consumes via __iter__ -> reset(), and an eager start here
        # would have that reset discard the just-prefetched batches
        # and in-flight transfers, paying the pipeline spin-up twice

    # ------------------------------------------------------- placement
    def _place(self, a, dtype=None):
        """Device placement: committed data-parallel NamedSharding when
        a mesh is set, committed to ``device`` when one was given,
        otherwise an UNcommitted put to the default device — the
        transfer still runs ahead of the step, but the jit signature
        stays identical to the jnp.asarray path (a committed batch
        would flip the step's outputs to committed and cost one extra
        executable per shape on the uncommitted->committed params
        transition)."""
        if a is None:
            return None
        arr = np.asarray(a)
        if dtype is not None and arr.dtype != dtype:
            arr = arr.astype(dtype)
        if self._mesh is not None:
            from deeplearning4j_tpu.parallel.mesh import data_parallel_spec

            return jax.device_put(arr, data_parallel_spec(self._mesh, arr))
        if self._device is not None:
            return jax.device_put(arr, self._device)
        return jax.device_put(arr)

    def _prepare(self, ds):
        """Policy + device transfer. Returns (placed batch, issue time)
        — the issue time feeds the transfer-overlap histogram."""
        ds = self.policy.apply(ds)
        t_issue = time.perf_counter()
        if isinstance(ds, MultiDataSet):
            placed = MultiDataSet(
                [self._place(a, self._dtype) for a in ds.features],
                [self._place(a) for a in ds.labels],
                [self._place(a) for a in ds.features_mask_arrays] or None,
                [self._place(a) for a in ds.labels_mask_arrays] or None)
        else:
            placed = DataSet(self._place(ds.features, self._dtype),
                             self._place(ds.labels),
                             self._place(ds.features_mask),
                             self._place(ds.labels_mask))
        return placed, t_issue

    # ------------------------------------------------------- threading
    def _gauge_depth(self) -> None:
        if _telemetry.enabled() and self._q is not None:
            _telemetry.MetricsRegistry.get_default().gauge(
                _telemetry.PREFETCH_QUEUE_DEPTH,
                "device-resident batches queued ahead of the fit loop"
            ).set(self._q.qsize())

    def _ensure_started(self) -> None:
        if self.depth == 0 or self._thread is not None or self._closed:
            return
        if self._host is None:
            self._host = AsyncDataSetIterator(
                self.underlying, queue_size=self._host_queue_size)
        else:
            self._host.reset()   # reopen after shutdown
        self._start()

    def _start(self) -> None:
        self._error = None
        self._exhausted = False
        self._consumed = False
        self._peek = None
        self._stop = threading.Event()
        self._q = queue.Queue(maxsize=self.depth)
        stop, q = self._stop, self._q

        def worker():
            try:
                while not stop.is_set() and self._host.hasNext():
                    item = self._prepare(self._host.next())
                    # put with a poll so stop can't wedge a producer
                    # blocked on a full queue (same discipline as
                    # AsyncDataSetIterator's worker)
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            self._gauge_depth()
                            break
                        except queue.Full:
                            continue
            except BaseException as e:
                self._error = e
            finally:
                while True:
                    try:
                        q.put(self._SENTINEL, timeout=0.5)
                        break
                    except queue.Full:
                        if not stop.is_set():
                            # consumer alive but slow (mid-compile):
                            # wait for space — dropping here would
                            # silently lose a live batch. An iterator
                            # abandoned without shutdown() leaves this
                            # daemon thread polling at 2Hz holding
                            # `depth` device batches — API misuse the
                            # suite's thread-leak gate catches.
                            continue
                        # reset/shutdown drain: consumer is gone, drop
                        # one stale item to make room for the sentinel
                        try:
                            q.get_nowait()
                        except queue.Empty:
                            pass

        self._thread = threading.Thread(target=worker, daemon=True,
                                        name="DevicePrefetch-transfer")
        self._thread.start()

    def _stop_transfer(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        if not self._exhausted:
            while self._q.get() is not self._SENTINEL:
                pass
        self._thread.join()
        self._thread = None
        self._peek = None
        self._exhausted = True

    def shutdown(self) -> None:
        """Stop and join both worker threads. Idempotent; ``reset()``
        reopens the pipeline afterwards."""
        self._closed = True
        self._exhausted = True
        if self._thread is not None:
            self._stop_transfer()
        if self._host is not None:
            self._host.shutdown()

    def __enter__(self) -> "DevicePrefetchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ------------------------------------------------------- iteration
    def reset(self):
        if self.depth == 0:
            self.underlying.reset()
            return
        self._closed = False
        if self._thread is None:
            self._exhausted = False   # reopen; workers start lazily
            return
        if not self._consumed and not self._exhausted \
                and self._error is None:
            # untouched running pipeline: it is already primed at epoch
            # start — keep the prefetched batches instead of discarding
            # and re-transferring them
            return
        self._stop_transfer()
        self._host.reset()
        self._start()

    def hasNext(self) -> bool:
        if self.depth == 0:
            return self.underlying.hasNext()
        self._ensure_started()
        if self._exhausted:
            return False
        if self._peek is None:
            item = self._q.get()
            self._gauge_depth()
            if item is self._SENTINEL:
                self._exhausted = True
                if self._error is not None:
                    err, self._error = self._error, None
                    raise err
                return False
            ds, t_issue = item
            if _telemetry.enabled():
                _telemetry.MetricsRegistry.get_default().histogram(
                    _telemetry.TRANSFER_OVERLAP_MS,
                    "ms between a batch's device_put issue and its "
                    "consumption by the fit loop (>0: the transfer "
                    "overlapped the previous device step)").observe(
                    (time.perf_counter() - t_issue) * 1e3)
            self._peek = ds
        return True

    def next(self):
        if self.depth == 0:
            ds, _ = self._prepare(self.underlying.next())
            return ds
        if not self.hasNext():
            raise StopIteration
        ds, self._peek = self._peek, None
        self._consumed = True
        return ds

    def batch(self) -> int:
        if self.policy.mode != "exact" and self.policy.batch_size:
            return self.policy.batch_size
        b = getattr(self.underlying, "batch", None)
        if callable(b):
            return b()
        raise NotImplementedError(
            f"{type(self.underlying).__name__} does not expose batch()")

    def resetSupported(self) -> bool:
        sup = getattr(self.underlying, "resetSupported", None)
        return sup() if callable(sup) else True

    def asyncSupported(self) -> bool:
        return False  # already async — do not double-wrap


class DevicePrefetchMultiIterator(DevicePrefetchIterator,
                                  MultiDataSetIterator):
    """MultiDataSetIterator-typed variant (so ``ComputationGraph.fit``
    and ``ShardedTrainer.fit`` route it down the MultiDataSet path).
    Constructed automatically by ``DevicePrefetchIterator(multi_iter)``.
    """


__all__ = ["BatchShapePolicy", "DevicePrefetchIterator",
           "DevicePrefetchMultiIterator"]
