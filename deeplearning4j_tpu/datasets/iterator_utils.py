"""Utility DataSet iterators — the reference's iterator tool family.

Reference (eclipse/deeplearning4j monorepo):
- ``nd4j/.../org/nd4j/linalg/dataset/api/iterator/KFoldIterator.java``
  — k-fold cross-validation splits of one DataSet.
- ``.../iterator/ViewIterator.java`` — minibatch view over one DataSet.
- ``.../iterator/SamplingDataSetIterator.java`` — with-replacement
  random minibatches.
- ``.../iterator/CachingDataSetIterator.java`` +
  ``cache/{InMemoryDataSetCache,InFileDataSetCache}.java`` — pull the
  underlying iterator once, serve later epochs from the cache.
- ``deeplearning4j/.../datasets/iterator/MultipleEpochsIterator.java``,
  ``EarlyTerminationDataSetIterator.java``,
  ``ExistingMiniBatchDataSetIterator.java`` (pre-saved minibatch files
  written by ``DataSet.save``).

These are HOST-side plumbing by design: batches stay numpy until the
compiled training step consumes them, so no TPU redesign applies — the
value is API parity for migrating pipelines. File formats use
``np.savez`` (features/labels/masks), the natural substrate here, not
the reference's Java binary layout.
"""
from __future__ import annotations

import os
import re
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator


class KFoldIterator(DataSetIterator):
    """k-fold splits (reference: KFoldIterator — ``next()`` returns the
    TRAIN set of fold i, ``testFold()`` the held-out fold). Folds are
    contiguous index ranges like the reference; shuffle the DataSet
    first for random folds."""

    def __init__(self, k: int, dataset: DataSet):
        n = dataset.numExamples()
        if k < 2 or k > n:
            raise ValueError(f"need 2 <= k <= numExamples, got k={k}, "
                             f"n={n}")
        self._ds = dataset
        self.k = k
        self._bounds = np.linspace(0, n, k + 1).astype(int)
        self._fold = 0

    def reset(self):
        self._fold = 0

    def hasNext(self) -> bool:
        return self._fold < self.k

    def _split(self, fold: int):
        lo, hi = self._bounds[fold], self._bounds[fold + 1]
        n = self._ds.numExamples()
        test = np.arange(lo, hi)
        train = np.concatenate([np.arange(0, lo), np.arange(hi, n)])
        return train, test

    def next(self) -> DataSet:
        train, _ = self._split(self._fold)
        self._fold += 1
        return self._take(train)

    def testFold(self) -> DataSet:
        """Held-out fold of the most recent ``next()``."""
        if self._fold == 0:
            raise ValueError("call next() before testFold()")
        _, test = self._split(self._fold - 1)
        return self._take(test)

    def _take(self, idx: np.ndarray) -> DataSet:
        return _slice_ds(self._ds, idx)

    def batch(self) -> int:
        return int(self._bounds[1] - self._bounds[0])


class ViewIterator(DataSetIterator):
    """Sequential minibatch view over one DataSet (reference:
    ViewIterator)."""

    def __init__(self, dataset: DataSet, batch_size: int):
        self._ds = dataset
        self._bs = int(batch_size)
        self._i = 0

    def reset(self):
        self._i = 0

    def hasNext(self) -> bool:
        return self._i < self._ds.numExamples()

    def next(self) -> DataSet:
        j = min(self._i + self._bs, self._ds.numExamples())
        out = _slice_ds(self._ds, np.arange(self._i, j))
        self._i = j
        return out

    def batch(self) -> int:
        return self._bs


def _slice_ds(ds: DataSet, idx: np.ndarray) -> DataSet:
    """Row-select features/labels AND masks (dropping masks silently
    turns padded timesteps into real data downstream)."""
    fm, lm = ds.features_mask, ds.labels_mask
    return DataSet(np.asarray(ds.features)[idx],
                   np.asarray(ds.labels)[idx],
                   np.asarray(fm)[idx] if fm is not None else None,
                   np.asarray(lm)[idx] if lm is not None else None)


class SamplingDataSetIterator(DataSetIterator):
    """With-replacement random minibatches (reference:
    SamplingDataSetIterator — ``totalNumSamples`` per epoch)."""

    def __init__(self, dataset: DataSet, batch_size: int,
                 total_num_samples: int, seed: int = 123):
        self._ds = dataset
        self._bs = int(batch_size)
        self._total = int(total_num_samples)
        self._seed = seed
        self._epoch = 0
        self._drawn = 0
        self._rng = np.random.default_rng(seed)

    def reset(self):
        self._drawn = 0
        self._epoch += 1
        self._rng = np.random.default_rng(self._seed + self._epoch)

    def hasNext(self) -> bool:
        return self._drawn < self._total

    def next(self) -> DataSet:
        n = min(self._bs, self._total - self._drawn)
        idx = self._rng.integers(0, self._ds.numExamples(), size=n)
        self._drawn += n
        return _slice_ds(self._ds, idx)

    def batch(self) -> int:
        return self._bs


class MultipleEpochsIterator(DataSetIterator):
    """Replays the underlying iterator N times as one pass (reference:
    deeplearning4j MultipleEpochsIterator)."""

    def __init__(self, num_epochs: int, underlying: DataSetIterator):
        if num_epochs < 1:
            raise ValueError("num_epochs must be >= 1")
        self._n = num_epochs
        self._it = underlying
        self._epoch = 0

    def reset(self):
        self._epoch = 0
        self._it.reset()

    def hasNext(self) -> bool:
        if self._it.hasNext():
            return True
        if self._epoch + 1 < self._n:
            self._epoch += 1
            self._it.reset()
            return self._it.hasNext()
        return False

    def next(self) -> DataSet:
        if not self.hasNext():
            raise StopIteration
        return self._it.next()

    def batch(self) -> int:
        return self._it.batch()


class EarlyTerminationDataSetIterator(DataSetIterator):
    """Caps the number of minibatches per epoch (reference:
    EarlyTerminationDataSetIterator)."""

    def __init__(self, underlying: DataSetIterator,
                 max_minibatches: int):
        if max_minibatches < 1:
            raise ValueError("max_minibatches must be >= 1")
        self._it = underlying
        self._max = max_minibatches
        self._count = 0

    def reset(self):
        self._count = 0
        self._it.reset()

    def hasNext(self) -> bool:
        return self._count < self._max and self._it.hasNext()

    def next(self) -> DataSet:
        if not self.hasNext():
            raise StopIteration
        self._count += 1
        return self._it.next()

    def batch(self) -> int:
        return self._it.batch()


class CachingDataSetIterator(DataSetIterator):
    """First epoch pulls from the underlying iterator and fills the
    cache; later epochs serve from the cache without touching the
    source (reference: CachingDataSetIterator). ``cache_dir=None`` is
    the InMemoryDataSetCache role; a path is the InFileDataSetCache
    role (one npz per minibatch)."""

    def __init__(self, underlying: DataSetIterator,
                 cache_dir: Optional[str] = None,
                 namespace: str = "default"):
        self._it = underlying
        self._dir = cache_dir
        self._ns = namespace
        self._mem: List[DataSet] = []
        self._complete = False
        self._pos = 0
        if cache_dir is not None:
            os.makedirs(os.path.join(cache_dir, namespace),
                        exist_ok=True)

    def _cache_path(self, i: int) -> str:
        return os.path.join(self._dir, self._ns, f"batch-{i}.npz")

    def reset(self):
        self._pos = 0
        if not self._complete:
            self._mem = []
            self._it.reset()

    def hasNext(self) -> bool:
        if self._complete:
            return self._pos < (len(self._mem) if self._dir is None
                                else self._n_files)
        return self._it.hasNext()

    def next(self) -> DataSet:
        if self._complete:
            if self._dir is None:
                ds = self._mem[self._pos]
            else:
                ds = DataSet.load(self._cache_path(self._pos))
            self._pos += 1
            return ds
        ds = self._it.next()
        if self._dir is None:
            self._mem.append(ds)
        else:
            ds.save(self._cache_path(self._pos))
        self._pos += 1
        if not self._it.hasNext():
            self._complete = True
            self._n_files = self._pos
        return ds

    def batch(self) -> int:
        return self._it.batch()


class ExistingMiniBatchDataSetIterator(DataSetIterator):
    """Serves pre-saved minibatch files from a directory (reference:
    ExistingMiniBatchDataSetIterator over ``DataSet.save`` output;
    default pattern ``dataset-%d.npz``)."""

    def __init__(self, directory: str, pattern: str = "dataset-%d.npz"):
        self._dir = directory
        self._pattern = pattern
        rx = re.compile(
            "^" + re.escape(pattern).replace("%d", r"(\d+)") + "$")
        found = []
        for name in os.listdir(directory):
            m = rx.match(name)
            if m:
                found.append((int(m.group(1)), name))
        if not found:
            raise ValueError(
                f"no files matching {pattern!r} in {directory}")
        self._files = [n for _, n in sorted(found)]
        self._i = 0
        self._batch = DataSet.load(
            os.path.join(directory, self._files[0])).numExamples()

    def reset(self):
        self._i = 0

    def hasNext(self) -> bool:
        return self._i < len(self._files)

    def next(self) -> DataSet:
        ds = DataSet.load(os.path.join(self._dir, self._files[self._i]))
        self._i += 1
        return ds

    def batch(self) -> int:
        return self._batch


__all__ = ["KFoldIterator", "ViewIterator", "SamplingDataSetIterator",
           "MultipleEpochsIterator", "EarlyTerminationDataSetIterator",
           "CachingDataSetIterator", "ExistingMiniBatchDataSetIterator"]
