"""Data normalizers (reference: org/nd4j/linalg/dataset/api/preprocessor/**
— NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler.
SURVEY.md §2.27). fit(iterator) accumulates stats; transform mutates
DataSets in place (reference contract); serializable with the model."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataNormalization:
    def fit(self, data):
        raise NotImplementedError

    def transform(self, ds: DataSet) -> DataSet:
        raise NotImplementedError

    def preProcess(self, ds: DataSet) -> DataSet:
        return self.transform(ds)

    def state_dict(self) -> dict:
        raise NotImplementedError

    def load_state_dict(self, d: dict):
        raise NotImplementedError


class NormalizerStandardize(DataNormalization):
    """Zero-mean/unit-variance per feature."""

    def __init__(self):
        self.mean = None
        self.std = None

    def fit(self, data):
        """data: DataSetIterator or DataSet."""
        if isinstance(data, DataSet):
            x = np.asarray(data.features)
            feats = x.reshape(-1, x.shape[-1]) if x.ndim > 1 else x[:, None]
            self.mean = feats.mean(0)
            self.std = feats.std(0) + 1e-8
            return
        # streaming accumulation over an iterator
        n, s, s2 = 0, None, None
        for ds in data:
            x = np.asarray(ds.features)
            feats = x.reshape(-1, x.shape[-1])
            if s is None:
                s = feats.sum(0)
                s2 = (feats ** 2).sum(0)
            else:
                s += feats.sum(0)
                s2 += (feats ** 2).sum(0)
            n += feats.shape[0]
        self.mean = s / n
        self.std = np.sqrt(np.maximum(s2 / n - self.mean ** 2, 0)) + 1e-8

    def transform(self, ds: DataSet) -> DataSet:
        ds.features = (jnp.asarray(ds.features) - self.mean) / self.std
        return ds

    def revert(self, ds: DataSet) -> DataSet:
        ds.features = jnp.asarray(ds.features) * self.std + self.mean
        return ds

    def state_dict(self):
        return {"mean": self.mean, "std": self.std}

    def load_state_dict(self, d):
        self.mean = np.asarray(d["mean"])
        self.std = np.asarray(d["std"])


class NormalizerMinMaxScaler(DataNormalization):
    """Scale features to [min_range, max_range] (default [0,1])."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range
        self.data_min = None
        self.data_max = None

    def fit(self, data):
        if isinstance(data, DataSet):
            x = np.asarray(data.features).reshape(-1, np.asarray(data.features).shape[-1])
            self.data_min = x.min(0)
            self.data_max = x.max(0)
            return
        mn, mx = None, None
        for ds in data:
            x = np.asarray(ds.features).reshape(-1, np.asarray(ds.features).shape[-1])
            bmn, bmx = x.min(0), x.max(0)
            mn = bmn if mn is None else np.minimum(mn, bmn)
            mx = bmx if mx is None else np.maximum(mx, bmx)
        self.data_min, self.data_max = mn, mx

    def transform(self, ds: DataSet) -> DataSet:
        rng = np.maximum(self.data_max - self.data_min, 1e-8)
        scaled = (jnp.asarray(ds.features) - self.data_min) / rng
        ds.features = scaled * (self.max_range - self.min_range) + self.min_range
        return ds

    def state_dict(self):
        return {"data_min": self.data_min, "data_max": self.data_max,
                "range": np.asarray([self.min_range, self.max_range])}

    def load_state_dict(self, d):
        self.data_min = np.asarray(d["data_min"])
        self.data_max = np.asarray(d["data_max"])
        self.min_range, self.max_range = (float(v) for v in d["range"])


class ImagePreProcessingScaler(DataNormalization):
    """uint8 pixels -> [min,max] (reference: ImagePreProcessingScaler)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0,
                 max_pixel: float = 255.0):
        self.min_range = min_range
        self.max_range = max_range
        self.max_pixel = max_pixel

    def fit(self, data):
        pass  # stateless

    def transform(self, ds: DataSet) -> DataSet:
        x = jnp.asarray(ds.features, jnp.float32) / self.max_pixel
        ds.features = x * (self.max_range - self.min_range) + self.min_range
        return ds

    def state_dict(self):
        return {"range": np.asarray([self.min_range, self.max_range, self.max_pixel])}

    def load_state_dict(self, d):
        self.min_range, self.max_range, self.max_pixel = (float(v) for v in d["range"])
