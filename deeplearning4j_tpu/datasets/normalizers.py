"""Data normalizers (reference: org/nd4j/linalg/dataset/api/preprocessor/**
— NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler.
SURVEY.md §2.27). fit(iterator) accumulates stats; transform mutates
DataSets in place (reference contract); serializable with the model."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataNormalization:
    def fit(self, data):
        raise NotImplementedError

    def transform(self, ds: DataSet) -> DataSet:
        raise NotImplementedError

    def preProcess(self, ds: DataSet) -> DataSet:
        return self.transform(ds)

    def state_dict(self) -> dict:
        raise NotImplementedError

    def load_state_dict(self, d: dict):
        raise NotImplementedError


class _RunningMoments:
    """Streaming mean/std accumulator over [..., F] batches."""

    def __init__(self):
        self.n, self.s, self.s2 = 0, None, None

    def add(self, x: np.ndarray) -> None:
        feats = x.reshape(-1, x.shape[-1]) if x.ndim > 1 else x[:, None]
        if self.s is None:
            self.s = feats.sum(0)
            self.s2 = (feats ** 2).sum(0)
        else:
            self.s += feats.sum(0)
            self.s2 += (feats ** 2).sum(0)
        self.n += feats.shape[0]

    def finalize(self):
        if self.n == 0:
            raise ValueError("fit() saw no data")
        mean = self.s / self.n
        std = np.sqrt(np.maximum(self.s2 / self.n - mean ** 2, 0)) + 1e-8
        return mean, std


class NormalizerStandardize(DataNormalization):
    """Zero-mean/unit-variance per feature. ``fitLabel(True)`` extends
    the contract to labels (reference: AbstractDataSetNormalizer#
    fitLabel — the regression workflow where targets need
    normalization and ``revertLabels`` recovers predictions)."""

    def __init__(self):
        self.mean = None
        self.std = None
        self.label_mean = None
        self.label_std = None
        self._fit_label = False

    def fitLabel(self, fit: bool = True) -> "NormalizerStandardize":
        self._fit_label = fit
        return self

    def fit(self, data):
        """data: DataSetIterator or DataSet. ONE streaming pass feeds
        both the feature and (optional) label accumulators, so
        out-of-core iterators keep constant memory."""
        fm, lm = _RunningMoments(), _RunningMoments()
        for ds in ([data] if isinstance(data, DataSet) else data):
            fm.add(np.asarray(ds.features))
            if self._fit_label:
                lm.add(np.asarray(ds.labels))
        self.mean, self.std = fm.finalize()
        if self._fit_label:
            self.label_mean, self.label_std = lm.finalize()

    def transform(self, ds: DataSet) -> DataSet:
        ds.features = (jnp.asarray(ds.features) - self.mean) / self.std
        if self.label_mean is not None:
            ds.labels = (jnp.asarray(ds.labels)
                         - self.label_mean) / self.label_std
        return ds

    def revert(self, ds: DataSet) -> DataSet:
        ds.features = jnp.asarray(ds.features) * self.std + self.mean
        if self.label_mean is not None:
            ds.labels = (jnp.asarray(ds.labels) * self.label_std
                         + self.label_mean)
        return ds

    def revertLabels(self, labels):
        """Un-normalize predictions (reference: revertLabels)."""
        if self.label_mean is None:
            return labels
        return jnp.asarray(labels) * self.label_std + self.label_mean

    def state_dict(self):
        d = {"mean": self.mean, "std": self.std}
        if self.label_mean is not None:
            d["label_mean"] = self.label_mean
            d["label_std"] = self.label_std
        return d

    def load_state_dict(self, d):
        self.mean = np.asarray(d["mean"])
        self.std = np.asarray(d["std"])
        if "label_mean" in d:
            self.label_mean = np.asarray(d["label_mean"])
            self.label_std = np.asarray(d["label_std"])
            self._fit_label = True
        else:   # clear any stale label stats from a previous fit
            self.label_mean = self.label_std = None
            self._fit_label = False


class NormalizerMinMaxScaler(DataNormalization):
    """Scale features to [min_range, max_range] (default [0,1])."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range
        self.data_min = None
        self.data_max = None

    def fit(self, data):
        if isinstance(data, DataSet):
            x = np.asarray(data.features).reshape(-1, np.asarray(data.features).shape[-1])
            self.data_min = x.min(0)
            self.data_max = x.max(0)
            return
        mn, mx = None, None
        for ds in data:
            x = np.asarray(ds.features).reshape(-1, np.asarray(ds.features).shape[-1])
            bmn, bmx = x.min(0), x.max(0)
            mn = bmn if mn is None else np.minimum(mn, bmn)
            mx = bmx if mx is None else np.maximum(mx, bmx)
        self.data_min, self.data_max = mn, mx

    def transform(self, ds: DataSet) -> DataSet:
        rng = np.maximum(self.data_max - self.data_min, 1e-8)
        scaled = (jnp.asarray(ds.features) - self.data_min) / rng
        ds.features = scaled * (self.max_range - self.min_range) + self.min_range
        return ds

    def state_dict(self):
        return {"data_min": self.data_min, "data_max": self.data_max,
                "range": np.asarray([self.min_range, self.max_range])}

    def load_state_dict(self, d):
        self.data_min = np.asarray(d["data_min"])
        self.data_max = np.asarray(d["data_max"])
        self.min_range, self.max_range = (float(v) for v in d["range"])


class VGG16ImagePreProcessor(DataNormalization):
    """Subtract the ImageNet per-channel pixel means — the zoo VGG16/19
    input contract (reference: VGG16ImagePreProcessor). Channel order
    here is RGB in NHWC (the TPU-native layout; the reference subtracts
    the same means in NCHW BGR-trained order — values are per-channel,
    so only the layout differs)."""

    MEANS = np.array([123.68, 116.779, 103.939], np.float32)

    def fit(self, data):
        pass  # stateless

    def transform(self, ds: DataSet) -> DataSet:
        ds.features = jnp.asarray(ds.features, jnp.float32) - self.MEANS
        return ds

    def revert(self, ds: DataSet) -> DataSet:
        ds.features = jnp.asarray(ds.features) + self.MEANS
        return ds

    def state_dict(self):
        return {}

    def load_state_dict(self, d):
        pass


class CompositeDataSetPreProcessor(DataNormalization):
    """Apply preprocessors in sequence (reference:
    CompositeDataSetPreProcessor). ``fit`` fits each child on the data
    AS TRANSFORMED by the children before it — the statistics a child
    computes must describe the distribution it will actually see at
    transform time. The source iterator is materialized once (a
    one-shot iterator must not be consumed per child)."""

    def __init__(self, *preprocessors: DataNormalization):
        self.preprocessors = list(preprocessors)

    def fit(self, data):
        batches = [data] if isinstance(data, DataSet) else list(data)
        for p in self.preprocessors:
            p.fit(batches[0] if len(batches) == 1 else batches)
            batches = [p.transform(DataSet(
                np.array(np.asarray(b.features)),
                np.array(np.asarray(b.labels)),
                b.features_mask, b.labels_mask)) for b in batches]

    def transform(self, ds: DataSet) -> DataSet:
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def state_dict(self):
        return {f"p{i}": p.state_dict()
                for i, p in enumerate(self.preprocessors)}

    def load_state_dict(self, d):
        for i, p in enumerate(self.preprocessors):
            p.load_state_dict(d[f"p{i}"])


class ImagePreProcessingScaler(DataNormalization):
    """uint8 pixels -> [min,max] (reference: ImagePreProcessingScaler)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0,
                 max_pixel: float = 255.0):
        self.min_range = min_range
        self.max_range = max_range
        self.max_pixel = max_pixel

    def fit(self, data):
        pass  # stateless

    def transform(self, ds: DataSet) -> DataSet:
        x = jnp.asarray(ds.features, jnp.float32) / self.max_pixel
        ds.features = x * (self.max_range - self.min_range) + self.min_range
        return ds

    def state_dict(self):
        return {"range": np.asarray([self.min_range, self.max_range, self.max_pixel])}

    def load_state_dict(self, d):
        self.min_range, self.max_range, self.max_pixel = (float(v) for v in d["range"])
