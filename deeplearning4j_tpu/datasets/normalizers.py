"""Data normalizers (reference: org/nd4j/linalg/dataset/api/preprocessor/**
— NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler.
SURVEY.md §2.27). fit(iterator) accumulates stats; transform mutates
DataSets in place (reference contract); serializable with the model."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataNormalization:
    def fit(self, data):
        raise NotImplementedError

    def transform(self, ds: DataSet) -> DataSet:
        raise NotImplementedError

    def preProcess(self, ds: DataSet) -> DataSet:
        return self.transform(ds)

    def state_dict(self) -> dict:
        raise NotImplementedError

    def load_state_dict(self, d: dict):
        raise NotImplementedError


class _RunningMoments:
    """Streaming mean/std accumulator over [..., F] batches."""

    def __init__(self):
        self.n, self.s, self.s2 = 0, None, None

    def add(self, x: np.ndarray) -> None:
        feats = x.reshape(-1, x.shape[-1]) if x.ndim > 1 else x[:, None]
        if self.s is None:
            self.s = feats.sum(0)
            self.s2 = (feats ** 2).sum(0)
        else:
            self.s += feats.sum(0)
            self.s2 += (feats ** 2).sum(0)
        self.n += feats.shape[0]

    def finalize(self):
        if self.n == 0:
            raise ValueError("fit() saw no data")
        mean = self.s / self.n
        std = np.sqrt(np.maximum(self.s2 / self.n - mean ** 2, 0)) + 1e-8
        return mean, std


def _check_arity(got: int, expected: int, kind: str) -> None:
    if got != expected:
        raise ValueError(
            f"MultiDataSet has {got} {kind} arrays but the normalizer "
            f"was fitted on {expected} — refusing to silently "
            f"truncate")


class _RunningMinMax:
    """Streaming per-column min/max accumulator over [..., F] batches."""

    def __init__(self):
        self.mn = None
        self.mx = None

    def add(self, x: np.ndarray) -> None:
        flat = x.reshape(-1, x.shape[-1]) if x.ndim > 1 else x[:, None]
        bmn, bmx = flat.min(0), flat.max(0)
        if self.mn is None:
            self.mn, self.mx = bmn, bmx
        else:
            self.mn = np.minimum(self.mn, bmn)
            self.mx = np.maximum(self.mx, bmx)

    def finalize(self):
        if self.mn is None:
            raise ValueError("fit() saw no data")
        return self.mn, self.mx


class NormalizerStandardize(DataNormalization):
    """Zero-mean/unit-variance per feature. ``fitLabel(True)`` extends
    the contract to labels (reference: AbstractDataSetNormalizer#
    fitLabel — the regression workflow where targets need
    normalization and ``revertLabels`` recovers predictions)."""

    def __init__(self):
        self.mean = None
        self.std = None
        self.label_mean = None
        self.label_std = None
        self._fit_label = False

    def fitLabel(self, fit: bool = True) -> "NormalizerStandardize":
        self._fit_label = fit
        return self

    def fit(self, data):
        """data: DataSetIterator or DataSet. ONE streaming pass feeds
        both the feature and (optional) label accumulators, so
        out-of-core iterators keep constant memory."""
        fm, lm = _RunningMoments(), _RunningMoments()
        for ds in ([data] if isinstance(data, DataSet) else data):
            fm.add(np.asarray(ds.features))
            if self._fit_label:
                lm.add(np.asarray(ds.labels))
        self.mean, self.std = fm.finalize()
        if self._fit_label:
            self.label_mean, self.label_std = lm.finalize()
        else:
            # a previous fit with fitLabel(True) must not leave stale
            # label stats normalizing labels with outdated statistics
            self.label_mean = self.label_std = None

    def transform(self, ds: DataSet) -> DataSet:
        ds.features = (jnp.asarray(ds.features) - self.mean) / self.std
        if self.label_mean is not None:
            ds.labels = (jnp.asarray(ds.labels)
                         - self.label_mean) / self.label_std
        return ds

    def revert(self, ds: DataSet) -> DataSet:
        ds.features = jnp.asarray(ds.features) * self.std + self.mean
        if self.label_mean is not None:
            ds.labels = (jnp.asarray(ds.labels) * self.label_std
                         + self.label_mean)
        return ds

    def revertLabels(self, labels):
        """Un-normalize predictions (reference: revertLabels)."""
        if self.label_mean is None:
            return labels
        return jnp.asarray(labels) * self.label_std + self.label_mean

    def state_dict(self):
        d = {"mean": self.mean, "std": self.std}
        if self.label_mean is not None:
            d["label_mean"] = self.label_mean
            d["label_std"] = self.label_std
        return d

    def load_state_dict(self, d):
        self.mean = np.asarray(d["mean"])
        self.std = np.asarray(d["std"])
        if "label_mean" in d:
            self.label_mean = np.asarray(d["label_mean"])
            self.label_std = np.asarray(d["label_std"])
            self._fit_label = True
        else:   # clear any stale label stats from a previous fit
            self.label_mean = self.label_std = None
            self._fit_label = False


class NormalizerMinMaxScaler(DataNormalization):
    """Scale features to [min_range, max_range] (default [0,1])."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range
        self.data_min = None
        self.data_max = None

    def fit(self, data):
        acc = _RunningMinMax()
        for ds in ([data] if isinstance(data, DataSet) else data):
            acc.add(np.asarray(ds.features))
        self.data_min, self.data_max = acc.finalize()

    def transform(self, ds: DataSet) -> DataSet:
        rng = np.maximum(self.data_max - self.data_min, 1e-8)
        scaled = (jnp.asarray(ds.features) - self.data_min) / rng
        ds.features = scaled * (self.max_range - self.min_range) + self.min_range
        return ds

    def state_dict(self):
        return {"data_min": self.data_min, "data_max": self.data_max,
                "range": np.asarray([self.min_range, self.max_range])}

    def load_state_dict(self, d):
        self.data_min = np.asarray(d["data_min"])
        self.data_max = np.asarray(d["data_max"])
        self.min_range, self.max_range = (float(v) for v in d["range"])


class MultiNormalizerStandardize:
    """Per-input (and optional per-output) standardization of
    MultiDataSets (reference: org/nd4j/linalg/dataset/api/preprocessor/
    MultiNormalizerStandardize — the normalizer for multi-input
    ComputationGraph pipelines). One streaming pass accumulates
    independent moments for every feature (and label) array."""

    def __init__(self):
        self.means: list = []
        self.stds: list = []
        self.label_means: list = []
        self.label_stds: list = []
        self._fit_label = False

    def fitLabel(self, fit: bool = True) -> "MultiNormalizerStandardize":
        self._fit_label = fit
        return self

    def fit(self, data):
        """data: MultiDataSet or an iterator of them."""
        first = True
        fms: list = []
        lms: list = []
        for mds in self._as_batches(data):
            if first:
                fms = [_RunningMoments() for _ in mds.features]
                lms = [_RunningMoments() for _ in mds.labels] \
                    if self._fit_label else []
                first = False
            _check_arity(len(mds.features), len(fms), "feature")
            if lms:
                _check_arity(len(mds.labels), len(lms), "label")
            for m, x in zip(fms, mds.features):
                m.add(np.asarray(x))
            for m, y in zip(lms, mds.labels):
                m.add(np.asarray(y))
        if first:
            raise ValueError("fit() saw no data")
        self.means, self.stds = map(list, zip(*[m.finalize()
                                                for m in fms]))
        if lms:
            self.label_means, self.label_stds = map(
                list, zip(*[m.finalize() for m in lms]))

    @staticmethod
    def _as_batches(data):
        from deeplearning4j_tpu.datasets.multi_dataset import MultiDataSet
        return [data] if isinstance(data, MultiDataSet) else data

    def transform(self, mds):
        if not self.means:
            raise ValueError("MultiNormalizerStandardize not fitted — "
                             "call fit() first")
        _check_arity(len(mds.features), len(self.means), "feature")
        mds.features = [
            (jnp.asarray(x) - m) / s
            for x, m, s in zip(mds.features, self.means, self.stds)]
        if self.label_means:
            _check_arity(len(mds.labels), len(self.label_means),
                         "label")
            mds.labels = [
                (jnp.asarray(y) - m) / s
                for y, m, s in zip(mds.labels, self.label_means,
                                   self.label_stds)]
        return mds

    def preProcess(self, mds):
        return self.transform(mds)

    def revertLabels(self, labels: list) -> list:
        if not self.label_means:
            return labels
        _check_arity(len(labels), len(self.label_means), "label")
        return [jnp.asarray(y) * s + m
                for y, m, s in zip(labels, self.label_means,
                                   self.label_stds)]

    def state_dict(self) -> dict:
        d = {}
        for i, (m, s) in enumerate(zip(self.means, self.stds)):
            d[f"mean{i}"] = m
            d[f"std{i}"] = s
        for i, (m, s) in enumerate(zip(self.label_means,
                                       self.label_stds)):
            d[f"label_mean{i}"] = m
            d[f"label_std{i}"] = s
        return d

    def load_state_dict(self, d: dict) -> None:
        self.means, self.stds = [], []
        self.label_means, self.label_stds = [], []
        i = 0
        while f"mean{i}" in d:
            self.means.append(np.asarray(d[f"mean{i}"]))
            self.stds.append(np.asarray(d[f"std{i}"]))
            i += 1
        i = 0
        while f"label_mean{i}" in d:
            self.label_means.append(np.asarray(d[f"label_mean{i}"]))
            self.label_stds.append(np.asarray(d[f"label_std{i}"]))
            i += 1
        self._fit_label = bool(self.label_means)


class MultiNormalizerMinMaxScaler:
    """Per-input min/max scaling of MultiDataSets (reference:
    MultiNormalizerMinMaxScaler)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range
        self.mins: list = []
        self.maxs: list = []

    def fit(self, data):
        accs: list = []
        first = True
        for mds in MultiNormalizerStandardize._as_batches(data):
            if first:
                accs = [_RunningMinMax() for _ in mds.features]
                first = False
            _check_arity(len(mds.features), len(accs), "feature")
            for a, x in zip(accs, mds.features):
                a.add(np.asarray(x))
        if first:
            raise ValueError("fit() saw no data")
        self.mins, self.maxs = map(list, zip(*[a.finalize()
                                               for a in accs]))

    def transform(self, mds):
        if not self.mins:
            raise ValueError("MultiNormalizerMinMaxScaler not fitted — "
                             "call fit() first")
        _check_arity(len(mds.features), len(self.mins), "feature")
        out = []
        for x, mn, mx in zip(mds.features, self.mins, self.maxs):
            rng = np.maximum(mx - mn, 1e-8)
            scaled = (jnp.asarray(x) - mn) / rng
            out.append(scaled * (self.max_range - self.min_range)
                       + self.min_range)
        mds.features = out
        return mds

    def preProcess(self, mds):
        return self.transform(mds)

    def state_dict(self) -> dict:
        d = {"range": np.asarray([self.min_range, self.max_range])}
        for i, (mn, mx) in enumerate(zip(self.mins, self.maxs)):
            d[f"min{i}"] = mn
            d[f"max{i}"] = mx
        return d

    def load_state_dict(self, d: dict) -> None:
        self.min_range, self.max_range = (float(v) for v in d["range"])
        self.mins, self.maxs = [], []
        i = 0
        while f"min{i}" in d:
            self.mins.append(np.asarray(d[f"min{i}"]))
            self.maxs.append(np.asarray(d[f"max{i}"]))
            i += 1


class VGG16ImagePreProcessor(DataNormalization):
    """Subtract the ImageNet per-channel pixel means — the zoo VGG16/19
    input contract (reference: VGG16ImagePreProcessor). Channel order
    here is RGB in NHWC (the TPU-native layout; the reference subtracts
    the same means in NCHW BGR-trained order — values are per-channel,
    so only the layout differs)."""

    MEANS = np.array([123.68, 116.779, 103.939], np.float32)

    def fit(self, data):
        pass  # stateless

    def transform(self, ds: DataSet) -> DataSet:
        ds.features = jnp.asarray(ds.features, jnp.float32) - self.MEANS
        return ds

    def revert(self, ds: DataSet) -> DataSet:
        ds.features = jnp.asarray(ds.features) + self.MEANS
        return ds

    def state_dict(self):
        return {}

    def load_state_dict(self, d):
        pass


class CompositeDataSetPreProcessor(DataNormalization):
    """Apply preprocessors in sequence (reference:
    CompositeDataSetPreProcessor). ``fit`` fits each child on the data
    AS TRANSFORMED by the children before it — the statistics a child
    computes must describe the distribution it will actually see at
    transform time. The source iterator is materialized once (a
    one-shot iterator must not be consumed per child)."""

    def __init__(self, *preprocessors: DataNormalization):
        self.preprocessors = list(preprocessors)

    def fit(self, data):
        batches = [data] if isinstance(data, DataSet) else list(data)
        for p in self.preprocessors:
            p.fit(batches[0] if len(batches) == 1 else batches)
            batches = [p.transform(DataSet(
                np.array(np.asarray(b.features)),
                np.array(np.asarray(b.labels)),
                b.features_mask, b.labels_mask)) for b in batches]

    def transform(self, ds: DataSet) -> DataSet:
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def state_dict(self):
        return {f"p{i}": p.state_dict()
                for i, p in enumerate(self.preprocessors)}

    def load_state_dict(self, d):
        for i, p in enumerate(self.preprocessors):
            p.load_state_dict(d[f"p{i}"])


class ImagePreProcessingScaler(DataNormalization):
    """uint8 pixels -> [min,max] (reference: ImagePreProcessingScaler)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0,
                 max_pixel: float = 255.0):
        self.min_range = min_range
        self.max_range = max_range
        self.max_pixel = max_pixel

    def fit(self, data):
        pass  # stateless

    def transform(self, ds: DataSet) -> DataSet:
        x = jnp.asarray(ds.features, jnp.float32) / self.max_pixel
        ds.features = x * (self.max_range - self.min_range) + self.min_range
        return ds

    def state_dict(self):
        return {"range": np.asarray([self.min_range, self.max_range, self.max_pixel])}

    def load_state_dict(self, d):
        self.min_range, self.max_range, self.max_pixel = (float(v) for v in d["range"])
