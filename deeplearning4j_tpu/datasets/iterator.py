"""DataSet iterators (reference: org/nd4j/linalg/dataset/api/iterator/
DataSetIterator + impls; SURVEY.md §2.27). Python-iterable plus the
reference's reset/hasNext surface so both idioms work."""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Base iterator protocol (reference interface)."""

    def reset(self):
        raise NotImplementedError

    def hasNext(self) -> bool:
        raise NotImplementedError

    def next(self) -> DataSet:
        raise NotImplementedError

    def batch(self) -> int:
        raise NotImplementedError

    def resetSupported(self) -> bool:
        return True

    def get_state(self) -> dict:
        """Checkpointable iterator position (SURVEY.md §5: the Orbax-
        style checkpoint carries data-iterator state so a resumed run
        continues mid-epoch on the NEXT batch, not a repeated one)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support state capture")

    def set_state(self, state: dict) -> None:
        raise NotImplementedError(
            f"{type(self).__name__} does not support state restore")

    def asyncSupported(self) -> bool:
        return True

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        while self.hasNext():
            yield self.next()


class ListDataSetIterator(DataSetIterator):
    """Iterate a list of pre-built DataSets (reference:
    ListDataSetIterator)."""

    def __init__(self, datasets: Sequence[DataSet], batch_size: Optional[int] = None):
        self._ds = list(datasets)
        self._i = 0
        self._batch = batch_size or (self._ds[0].numExamples() if self._ds else 0)

    def reset(self):
        self._i = 0

    def hasNext(self) -> bool:
        return self._i < len(self._ds)

    def next(self) -> DataSet:
        ds = self._ds[self._i]
        self._i += 1
        return ds

    def get_state(self) -> dict:
        return {"i": int(self._i)}

    def set_state(self, state: dict) -> None:
        i = int(state["i"])
        if not 0 <= i <= len(self._ds):
            raise ValueError(
                f"iterator state position {i} out of range for "
                f"{len(self._ds)} datasets (checkpoint from a "
                "different dataset?)")
        self._i = i

    def batch(self) -> int:
        return self._batch


class ArrayDataSetIterator(DataSetIterator):
    """Minibatch over in-memory arrays with optional shuffling.

    The workhorse for tests/benchmarks (reference analog:
    IteratorDataSetIterator over an INDArray-backed DataSet).
    """

    def __init__(self, features, labels, batch_size: int,
                 shuffle: bool = False, seed: int = 123, drop_last: bool = False):
        self._x = np.asarray(features)
        self._y = np.asarray(labels)
        assert self._x.shape[0] == self._y.shape[0]
        self._bs = batch_size
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0
        self._drop_last = drop_last
        self._order = np.arange(self._x.shape[0])
        self._i = 0
        self._maybe_shuffle()

    def _maybe_shuffle(self):
        if self._shuffle:
            rng = np.random.default_rng(self._seed + self._epoch)
            self._order = rng.permutation(self._x.shape[0])

    def reset(self):
        self._i = 0
        self._epoch += 1
        self._maybe_shuffle()

    def get_state(self) -> dict:
        return {"i": int(self._i), "epoch": int(self._epoch)}

    def set_state(self, state: dict) -> None:
        i = int(state["i"])
        if not 0 <= i <= self._x.shape[0]:
            raise ValueError(
                f"iterator state position {i} out of range for "
                f"{self._x.shape[0]} examples (checkpoint from a "
                "different dataset?)")
        self._epoch = int(state["epoch"])
        self._i = i
        # the shuffle order is a pure function of (seed, epoch), so
        # restoring (epoch, i) reproduces the exact batch sequence
        self._maybe_shuffle()

    def hasNext(self) -> bool:
        remaining = self._x.shape[0] - self._i
        if self._drop_last:
            return remaining >= self._bs
        return remaining > 0

    def next(self) -> DataSet:
        j = min(self._i + self._bs, self._x.shape[0])
        idx = self._order[self._i:j]
        self._i = j
        return DataSet(self._x[idx], self._y[idx])

    def batch(self) -> int:
        return self._bs

    def totalExamples(self) -> int:
        return int(self._x.shape[0])
