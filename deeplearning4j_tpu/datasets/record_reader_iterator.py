"""RecordReader → DataSet iterators + async host prefetch.

Reference: deeplearning4j-data RecordReaderDataSetIterator.java,
SequenceRecordReaderDataSetIterator.java, and nd4j
AsyncDataSetIterator.java (background ETL thread + bounded queue that
keeps the device fed — SURVEY.md §2.27, §3.1). The TPU analog of the
reference's workspace-backed prefetch thread is simply: decode/augment
on host threads while the accelerator runs the previous jitted step.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator
from deeplearning4j_tpu.datasets.multi_dataset import (
    MultiDataSet, MultiDataSetIterator,
)
from deeplearning4j_tpu.datavec.records import RecordReader


def _one_hot(labels: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros((labels.shape[0], n), dtype=np.float32)
    out[np.arange(labels.shape[0]), labels.astype(np.int64)] = 1.0
    return out


class RecordReaderDataSetIterator(DataSetIterator):
    """Batch records into DataSets.

    Classification: ``label_index`` + ``num_classes`` → one-hot labels.
    Regression: ``regression=True`` with ``label_index`` (or
    label_index_from/to range). Image readers (records of
    [HWC array, label]) are detected automatically and stacked NHWC.
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_from: Optional[int] = None,
                 label_index_to: Optional[int] = None):
        self.reader = reader
        self._bs = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.label_from = label_index_from
        self.label_to = label_index_to
        self.reader.reset()

    def reset(self):
        self.reader.reset()

    def hasNext(self) -> bool:
        return self.reader.hasNext()

    def batch(self) -> int:
        return self._bs

    def next(self) -> DataSet:
        if not self.reader.hasNext():
            raise StopIteration("iterator exhausted — call reset()")
        recs = []
        while self.reader.hasNext() and len(recs) < self._bs:
            recs.append(self.reader.next())
        first = recs[0]
        if len(first) == 2 and isinstance(first[0], np.ndarray) \
                and first[0].ndim >= 2:
            # image records: [HWC, label]
            x = np.stack([r[0] for r in recs]).astype(np.float32)
            y = np.asarray([r[1] for r in recs])
            if self.num_classes:
                y = _one_hot(y, self.num_classes)
            return DataSet(x, y)
        mat = np.asarray(recs, dtype=np.float32)
        if self.label_from is not None and self.label_to is not None:
            y = mat[:, self.label_from:self.label_to + 1]
            x = np.delete(mat, range(self.label_from, self.label_to + 1),
                          axis=1)
            return DataSet(x, y)
        if self.label_index is None:
            return DataSet(mat, mat)
        y_col = mat[:, self.label_index]
        x = np.delete(mat, self.label_index, axis=1)
        if self.regression:
            return DataSet(x, y_col[:, None])
        if self.num_classes is None:
            raise ValueError("classification needs num_classes")
        return DataSet(x, _one_hot(y_col, self.num_classes))


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """One sequence per record → NTF DataSet batches, right-padded with
    masks (reference: SequenceRecordReaderDataSetIterator ALIGN_END).
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int, num_classes: Optional[int] = None,
                 regression: bool = False):
        self.reader = reader
        self._bs = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.reader.reset()

    def reset(self):
        self.reader.reset()

    def hasNext(self) -> bool:
        return self.reader.hasNext()

    def batch(self) -> int:
        return self._bs

    def next(self) -> DataSet:
        if not self.reader.hasNext():
            raise StopIteration("iterator exhausted — call reset()")
        seqs = []
        while self.reader.hasNext() and len(seqs) < self._bs:
            seqs.append(self.reader.next())
        lengths = [len(s) for s in seqs]
        t_max = max(lengths)
        n_in = len(seqs[0][0]) - 1
        # regression always means one target channel, even if a caller
        # passes num_classes out of reference-API habit
        n_out = self.num_classes \
            if (self.num_classes and not self.regression) else 1
        x = np.zeros((len(seqs), t_max, n_in), np.float32)
        y = np.zeros((len(seqs), t_max, n_out), np.float32)
        mask = np.zeros((len(seqs), t_max), np.float32)
        for i, seq in enumerate(seqs):
            for t, step in enumerate(seq):
                row = list(step)
                label = row.pop(self.label_index)
                x[i, t] = row
                if self.regression or self.num_classes is None:
                    y[i, t, 0] = label
                else:
                    y[i, t, int(label)] = 1.0
                mask[i, t] = 1.0
        return DataSet(x, y, features_mask=mask, labels_mask=mask)


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch wrapper (reference:
    AsyncDataSetIterator with queue size; the device never waits on
    host ETL). Exceptions in the worker re-raise on next()."""

    _SENTINEL = object()

    def __init__(self, underlying: DataSetIterator, queue_size: int = 4):
        self.underlying = underlying
        self.queue_size = queue_size
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._peek = None
        self._exhausted = False  # sentinel consumed; epoch over
        self._stop = threading.Event()
        self._start()

    def _start(self):
        self._error = None
        self._exhausted = False
        self._stop = threading.Event()
        self._q = queue.Queue(maxsize=self.queue_size)
        stop, q = self._stop, self._q

        def worker():
            try:
                self.underlying.reset()
                while not stop.is_set() and self.underlying.hasNext():
                    item = self.underlying.next()
                    # put with a poll so a stop request can't wedge a
                    # producer blocked on a full queue
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
            except BaseException as e:  # propagate to consumer
                self._error = e
            finally:
                while True:
                    try:
                        q.put(self._SENTINEL, timeout=0.5)
                        break
                    except queue.Full:
                        if not stop.is_set():
                            # Live consumer, just slow (e.g. mid jit
                            # compile) — keep waiting for space; a
                            # timeout here used to silently DROP a live
                            # queued batch. Trade-off: an iterator
                            # abandoned without reset()/shutdown()
                            # leaves this daemon thread polling at 2Hz
                            # — that's an API-misuse leak (the test
                            # suite's thread-leak gate catches it), vs
                            # the old behavior's data loss in correct
                            # usage.
                            continue
                        # consumer gone (reset drained); drop one stale
                        # item to make room for the sentinel
                        try:
                            q.get_nowait()
                        except queue.Empty:
                            pass

        self._thread = threading.Thread(target=worker, daemon=True,
                                        name="AsyncDataSet-ETL")
        self._thread.start()

    def _join_worker(self):
        """Stop the producer, drain until its sentinel, join. Gate the
        drain on _exhausted, not thread liveness: the worker may still
        be between put(SENTINEL) and exit."""
        if self._thread is None:
            return
        self._stop.set()
        if not self._exhausted:
            while self._q.get() is not self._SENTINEL:
                pass
        self._thread.join()
        self._thread = None

    def reset(self):
        # Signal the worker to stop producing (don't decode a whole
        # discarded epoch), drain until its sentinel, restart.
        self._join_worker()
        self._peek = None
        self._start()

    def shutdown(self):
        """Stop and join the ETL thread WITHOUT restarting (thread-leak
        hygiene for owners like DevicePrefetchIterator). A later
        reset() reopens the iterator."""
        self._join_worker()
        self._peek = None
        self._exhausted = True

    def hasNext(self) -> bool:
        if self._exhausted:
            return False
        if self._peek is None:
            item = self._q.get()
            if item is self._SENTINEL:
                self._exhausted = True
                if self._error is not None:
                    err, self._error = self._error, None
                    raise err
                return False
            self._peek = item
        return True

    def next(self) -> DataSet:
        if not self.hasNext():
            raise StopIteration
        item, self._peek = self._peek, None
        return item

    def batch(self) -> int:
        return self.underlying.batch()


class RecordReaderMultiDataSetIterator(MultiDataSetIterator):
    """Multi-reader → MultiDataSet builder (reference:
    deeplearning4j-data RecordReaderMultiDataSetIterator — THE canonical
    way to feed multi-input/multi-output ComputationGraphs from
    datavec). Named readers advance in lock-step; each addInput/
    addOutput spec slices columns (inclusive col_from..col_to, the
    reference convention) or one-hots a single column. Sequence
    readers produce [N,T,F] padded to the batch max length with
    [N,T] masks (ALIGN_END pads at the start — the reference default
    for many-to-one setups — ALIGN_START pads at the end).
    """

    def __init__(self, batch_size, readers, seq_readers, inputs, outputs,
                 alignment):
        self._bs = batch_size
        self._readers = readers            # name -> RecordReader
        self._seq = seq_readers            # name -> bool
        self._inputs = inputs              # list of spec tuples
        self._outputs = outputs
        self._align = alignment
        for r in self._readers.values():
            r.reset()

    # -- builder (reference API shape) ---------------------------------
    class Builder:
        def __init__(self, batch_size: int):
            self._bs = batch_size
            self._readers = {}
            self._seq = {}
            self._inputs = []
            self._outputs = []
            self._align = "ALIGN_START"

        def addReader(self, name: str, reader):
            self._readers[name] = reader
            self._seq[name] = False
            return self

        def addSequenceReader(self, name: str, reader):
            self._readers[name] = reader
            self._seq[name] = True
            return self

        def sequenceAlignmentMode(self, mode: str):
            if mode not in ("ALIGN_START", "ALIGN_END", "EQUAL_LENGTH"):
                raise ValueError(f"unknown alignment mode {mode!r}")
            self._align = mode
            return self

        def _spec(self, name, col_from, col_to, one_hot, n):
            if name not in self._readers:
                raise ValueError(f"no reader named {name!r} — call "
                                 "addReader/addSequenceReader first")
            if (col_from is None) != (col_to is None):
                raise ValueError(
                    f"reader {name!r}: give BOTH col_from and col_to "
                    "(inclusive bounds, reference convention) or "
                    "neither (all columns)")
            return (name, col_from, col_to, one_hot, n)

        def addInput(self, name: str, col_from=None, col_to=None):
            self._inputs.append(self._spec(name, col_from, col_to,
                                           False, None))
            return self

        def addInputOneHot(self, name: str, column: int, num_classes: int):
            self._inputs.append(self._spec(name, column, column, True,
                                           num_classes))
            return self

        def addOutput(self, name: str, col_from=None, col_to=None):
            self._outputs.append(self._spec(name, col_from, col_to,
                                            False, None))
            return self

        def addOutputOneHot(self, name: str, column: int,
                            num_classes: int):
            self._outputs.append(self._spec(name, column, column, True,
                                            num_classes))
            return self

        def build(self) -> "RecordReaderMultiDataSetIterator":
            if not self._inputs or not self._outputs:
                raise ValueError("need at least one addInput and one "
                                 "addOutput spec")
            return RecordReaderMultiDataSetIterator(
                self._bs, self._readers, self._seq, self._inputs,
                self._outputs, self._align)

    # -- iteration ------------------------------------------------------
    def reset(self):
        for r in self._readers.values():
            r.reset()

    def hasNext(self) -> bool:
        states = {n: r.hasNext() for n, r in self._readers.items()}
        if any(states.values()) and not all(states.values()):
            # Lock-step exhaustion: a reader running long means the
            # sources are misaligned — surfacing it beats silently
            # dropping the longer readers' tail records (reference
            # RecordReaderMultiDataSetIterator errors here too).
            longer = sorted(n for n, s in states.items() if s)
            done = sorted(n for n, s in states.items() if not s)
            raise ValueError(
                "readers exhausted out of lock-step: "
                f"{done} are done but {longer} still have records — "
                "input sources have unequal record counts")
        return all(states.values())

    def batch(self) -> int:
        return self._bs

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()

    def _pull(self):
        """One lock-step batch of raw records per reader."""
        out = {name: [] for name in self._readers}
        while self.hasNext() and len(next(iter(out.values()))) < self._bs:
            for name, r in self._readers.items():
                out[name].append(r.next())
        return out

    def _slice(self, rows, col_from, col_to, one_hot, n):
        mat = np.asarray(rows, dtype=np.float32)
        if one_hot:
            return _one_hot(mat[:, col_from], n)
        if col_from is None:
            return mat
        return mat[:, col_from:col_to + 1]

    def _build_arrays(self, raw, specs):
        arrays, masks, any_mask = [], [], False
        for name, col_from, col_to, one_hot, n in specs:
            recs = raw[name]
            if not self._seq[name]:
                arrays.append(self._slice(recs, col_from, col_to,
                                          one_hot, n))
                masks.append(None)
                continue
            lens = [len(s) for s in recs]
            t_max = max(lens)
            if self._align == "EQUAL_LENGTH" and len(set(lens)) > 1:
                raise ValueError(
                    f"reader {name!r}: EQUAL_LENGTH alignment but "
                    f"sequence lengths differ ({sorted(set(lens))}); use "
                    "ALIGN_START or ALIGN_END")
            per_seq = [self._slice(s, col_from, col_to, one_hot, n)
                       for s in recs]
            f = per_seq[0].shape[-1]
            x = np.zeros((len(recs), t_max, f), np.float32)
            m = np.zeros((len(recs), t_max), np.float32)
            for i, (s, ln) in enumerate(zip(per_seq, lens)):
                if self._align == "ALIGN_END":
                    x[i, t_max - ln:] = s
                    m[i, t_max - ln:] = 1.0
                else:
                    x[i, :ln] = s
                    m[i, :ln] = 1.0
            arrays.append(x)
            masks.append(m if len(set(lens)) > 1 else None)
            any_mask = any_mask or len(set(lens)) > 1
        return arrays, (masks if any_mask else None)

    def next(self) -> MultiDataSet:
        if not self.hasNext():
            raise StopIteration("iterator exhausted — call reset()")
        raw = self._pull()
        feats, fmasks = self._build_arrays(raw, self._inputs)
        labs, lmasks = self._build_arrays(raw, self._outputs)
        return MultiDataSet(features=feats, labels=labs,
                            features_mask_arrays=fmasks,
                            labels_mask_arrays=lmasks)
