"""SLO engine: windowed metric evaluation, burn-rate alerting, and
control-plane action hooks.

The stack emits rich signals — ``SERVING_*`` latency/TTFT histograms,
``dl4j_tpu_mfu``, queue/KV gauges, ``dl4j_tpu_jobs_*`` — but until this
module nothing watched them: an operator learned about a p99 blow-up or
an MFU collapse by polling the dashboard. Just as the framework owns
its performance substrate (cuDNN-style fused primitives,
arXiv:1410.0759), a production system must own its HEALTH substrate:
windowed objectives evaluated continuously in-process, not in an
external scraper. The pieces:

- **Snapshot ring.** ``SLOEngine`` captures the whole
  ``MetricsRegistry`` (``registry.capture()``) every ``interval_s`` on
  a background thread ("SLOEvaluator") into a bounded ring — or, when
  attached to the shared ``profiler/timeseries.py`` sampler
  (``attach_sampler``), receives the TSDB's capture instead: one
  ``registry.capture()`` per tick for both consumers, and the ring
  sees the same federated ``worker=`` series range queries do. Counter
  windows are value DELTAS between two snapshots (a counter reset —
  e.g. an engine restart — clamps at 0, never negative); histogram
  windows are cumulative-bucket-count deltas, so the windowed quantile
  here and ``histogram_quantile()`` in an external Prometheus share
  one definition (the ``_bucket{le=...}`` series telemetry.py now
  exports).
- **Rules.** Three declarative kinds, each evaluated per label group:
  ``Threshold`` (a gauge — or a windowed histogram quantile — vs a
  bound, breached continuously for ``for_s`` before firing), ``Rate``
  (counter delta/s over ``window_s`` vs a bound), and ``BurnRate``
  (SRE-workbook multi-window error-budget burn: the error fraction —
  bad/total counters, or the fraction of histogram samples over a
  latency target — divided by the budget ``1 - objective``, evaluated
  over a FAST and a SLOW window; the alert condition is both windows
  exceeding ``factor``, i.e. ``min(burn_fast, burn_slow) > factor``,
  which pages quickly on a real burn and un-pages as soon as the fast
  window recovers).
- **Alert lifecycle.** Per (rule, label-group): inactive -> pending
  (condition true, ``for_s`` not yet served) -> firing -> resolved.
  A pending alert whose condition clears before ``for_s`` is
  suppressed (flap), never fired. Every transition emits a flight-
  recorder event and a ``dl4j_tpu_alerts_total{rule,state}`` count;
  ``firing`` with ``severity="page"`` additionally writes a full
  flight-recorder incident dump (digest-valid post-mortem) and every
  ``firing``/``resolved`` optionally POSTs to a webhook sink. A firing
  page (or any rule with ``action="profile"``) also captures a
  bounded device profile via ``programs.ProfileSession`` — rate-
  limited (``profile_min_interval_s``), gated by ``profile_on_page``
  (default: only when the program registry is enabled), with the
  bundle path stamped into the incident dump's manifest context.
- **Action hooks.** ``on_alert(fn)`` subscribes callables to
  transitions — how ``control/scheduler.py`` turns a sustained
  queue-pressure alert into a serve-replica scale-up, replacing its
  one-shot ``queue_pressure()`` poll with real hysteresis
  (pending/firing = hands off the fleet's replicas; resolved = fair
  game for rebalancing).
- **Built-in rule pack.** ``serving_rules()`` (p99 latency burn, TTFT,
  error-rate and 429 burn, KV-page utilization, fleet queue pressure)
  and ``training_rules()`` (MFU floor, watchdog-stall rate,
  divergence-rollback rate, prefetch starvation); ``default_rules()``
  is both.

Off by default and bit/token-identical when disabled: nothing
constructs an ``SLOEngine`` unless the operator does, the serving and
training paths never import this module, and the HTTP/telemetry
surfaces peek ``default_engine()`` (one attribute read when absent).

Overhead when on: one ``registry.capture()`` per ``interval_s``
(a dict copy per metric under its lock — microseconds at this repo's
series cardinality) plus rule evaluation over the ring on the
evaluator thread. Nothing on any training or serving hot path.
"""

from __future__ import annotations

import bisect
import collections
import json
import logging
import threading
import time
import urllib.request
from typing import (
    Any, Callable, Dict, List, Optional, Sequence, Tuple, Union,
)

from deeplearning4j_tpu.profiler import flight_recorder as _flight
from deeplearning4j_tpu.profiler import telemetry as _telemetry
from deeplearning4j_tpu.profiler.timeseries import histogram_quantile

log = logging.getLogger("deeplearning4j_tpu")

#: alert states (lifecycle order)
STATES = ("inactive", "pending", "firing", "resolved")

LabelKey = Tuple[Tuple[str, str], ...]
#: a metric selector: a name, or (name, {label: value, ...}) where the
#: where-dict is a subset match on the series' labels
Selector = Union[str, Tuple[str, Dict[str, str]]]


# ---------------------------------------------------------------- math
# histogram_quantile is imported from profiler/timeseries.py — the one
# windowed-quantile definition the SLO engine, the TSDB's PromQL-lite
# evaluator, and external scrapers all share.


def _match(labels: Dict[str, str], where: Dict[str, str]) -> bool:
    return all(labels.get(k) == str(v) for k, v in where.items())


def _norm_selector(sel: Selector) -> Tuple[str, Dict[str, str]]:
    if isinstance(sel, str):
        return sel, {}
    name, where = sel
    return name, dict(where)


# ------------------------------------------------------------ snapshots
class _Ring:
    """Bounded ring of (monotonic_t, registry capture)."""

    def __init__(self, capacity: int):
        self._buf: collections.deque = collections.deque(
            maxlen=max(int(capacity), 4))

    def append(self, t: float, cap: Dict[str, Any]) -> None:
        self._buf.append((t, cap))

    def __len__(self) -> int:
        return len(self._buf)

    def latest(self) -> Optional[Tuple[float, Dict[str, Any]]]:
        return self._buf[-1] if self._buf else None

    def at_or_before(self, t: float) \
            -> Optional[Tuple[float, Dict[str, Any]]]:
        """The NEWEST snapshot taken at or before ``t`` — the window's
        far edge. None when history doesn't reach back that far (the
        rule is then not evaluable yet: never fire on a half-window)."""
        out = None
        for item in self._buf:
            if item[0] <= t:
                out = item
            else:
                break
        return out


def _counter_series(cap: Dict[str, Any], sel: Selector) \
        -> List[Tuple[Dict[str, str], float]]:
    """Counter-like series from a capture: counters and gauges by
    value; histograms by their cumulative COUNT (so a latency
    histogram doubles as a per-label request counter)."""
    name, where = _norm_selector(sel)
    m = cap.get(name)
    if m is None:
        return []
    if m["kind"] == "histogram":
        items = [(k, cnt) for k, (cnt, _s, _b) in m["series"].items()]
    else:
        items = list(m["values"].items())
    out = []
    for k, v in items:
        labels = dict(k)
        if _match(labels, where):
            out.append((labels, float(v)))
    return out


def _hist_series(cap: Dict[str, Any], sel: Selector) \
        -> Tuple[Tuple[float, ...],
                 List[Tuple[Dict[str, str], Tuple[float, ...]]]]:
    """(bounds, [(labels, bucket_counts)]) from a capture."""
    name, where = _norm_selector(sel)
    m = cap.get(name)
    if m is None or m["kind"] != "histogram":
        return (), []
    out = []
    for k, (_cnt, _sum, buckets) in m["series"].items():
        labels = dict(k)
        if _match(labels, where):
            out.append((labels, buckets))
    return m["bounds"], out


def _grouped_bucket_deltas(rule: "Rule", cap1: Dict[str, Any],
                           cap0: Dict[str, Any], sel: Selector) \
        -> Tuple[Tuple[float, ...], Dict[LabelKey, List[float]]]:
    """Per-group NON-cumulative bucket-count deltas between two
    captures, reset-clamped at 0 — the ONE windowed-histogram read
    Threshold quantile mode and BurnRate histogram mode share (so the
    two cannot silently diverge on delta semantics)."""
    bounds, now_series = _hist_series(cap1, sel)
    if not bounds:
        return (), {}
    _b0, then_series = _hist_series(cap0, sel)

    def acc(series):
        g: Dict[LabelKey, List[float]] = {}
        for labels, buckets in series:
            a = g.setdefault(rule._gkey(labels), [0.0] * len(buckets))
            for i, b in enumerate(buckets):
                a[i] += b
        return g

    now_g, then_g = acc(now_series), acc(then_series)
    out = {}
    for k, a in now_g.items():
        prev = then_g.get(k, [0.0] * len(a))
        out[k] = [max(x - p, 0.0) for x, p in zip(a, prev)]
    return bounds, out


# ----------------------------------------------------------------- rules
class Rule:
    """Base: shared grouping + lifecycle knobs.

    ``where`` filters series by label subset; ``group_by`` names the
    labels that key one alert (None = every distinct label set is its
    own alert — the right default for per-engine gauges); ``for_s`` is
    how long the condition must hold before ``pending`` becomes
    ``firing``; ``action`` is an opaque tag subscribers dispatch on
    (the scheduler watches ``"scale_serve"``)."""

    kind = "rule"

    def __init__(self, name: str, *, severity: str = "ticket",
                 for_s: float = 0.0,
                 where: Optional[Dict[str, str]] = None,
                 group_by: Optional[Sequence[str]] = None,
                 action: Optional[str] = None,
                 description: str = ""):
        if severity not in ("ticket", "page"):
            raise ValueError(f"severity must be 'ticket' or 'page', "
                             f"got {severity!r}")
        self.name = str(name)
        self.severity = severity
        self.for_s = float(for_s)
        self.where = dict(where or {})
        self.group_by = (tuple(group_by)
                         if group_by is not None else None)
        self.action = action
        self.description = description

    # -- grouping -------------------------------------------------------
    def _gkey(self, labels: Dict[str, str]) -> LabelKey:
        if self.group_by is None:
            return tuple(sorted(labels.items()))
        return tuple((k, labels[k]) for k in self.group_by
                     if k in labels)

    def _group_sum(self, entries) -> Dict[LabelKey, float]:
        out: Dict[LabelKey, float] = {}
        for labels, v in entries:
            k = self._gkey(labels)
            out[k] = out.get(k, 0.0) + v
        return out

    # -- evaluation -----------------------------------------------------
    def evaluate(self, ring: _Ring, now: float) \
            -> Dict[LabelKey, Optional[float]]:
        """group-key -> rule value (None = not evaluable this tick:
        no data / empty window / not enough history — never a
        breach)."""
        raise NotImplementedError

    def breached(self, value: float) -> bool:
        raise NotImplementedError

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name, "kind": self.kind,
                "severity": self.severity, "for_s": self.for_s,
                "where": self.where,
                "group_by": list(self.group_by or []) or None,
                "action": self.action,
                "description": self.description}


def _cmp(op: str):
    if op == ">":
        return lambda v, b: v > b
    if op == "<":
        return lambda v, b: v < b
    raise ValueError(f"op must be '>' or '<', got {op!r}")


class Threshold(Rule):
    """A gauge — or a windowed histogram quantile — vs a bound.

    Gauge mode: ``Threshold("kv_hot", metric=GAUGE, bound=0.95)``.
    Within a group the WORST member decides (max for ``>``, min for
    ``<``).
    Quantile mode: pass ``quantile=`` and ``window_s=``; the value is
    the quantile of the samples observed in the window (bucket-count
    deltas), and an empty window means 'no data', not zero."""

    kind = "threshold"

    def __init__(self, name: str, *, metric: Selector, bound: float,
                 op: str = ">", quantile: Optional[float] = None,
                 window_s: Optional[float] = None, **kw):
        super().__init__(name, **kw)
        self.metric = metric
        self.bound = float(bound)
        self.op = op
        self._cmp = _cmp(op)
        self.quantile = None if quantile is None else float(quantile)
        if self.quantile is not None and window_s is None:
            raise ValueError(
                f"rule {name!r}: quantile mode needs window_s")
        self.window_s = None if window_s is None else float(window_s)

    def evaluate(self, ring, now):
        latest = ring.latest()
        if latest is None:
            return {}
        t1, cap1 = latest
        if self.quantile is None:
            worst = max if self.op == ">" else min
            out: Dict[LabelKey, Optional[float]] = {}
            for labels, v in _counter_series(cap1, self.metric):
                k = self._gkey(labels)
                out[k] = v if k not in out else worst(out[k], v)
            return out
        then = ring.at_or_before(now - self.window_s)
        if then is None:
            return {}
        _t0, cap0 = then
        # windowed bucket deltas per group, then ONE quantile per
        # group (None on an empty window — zero samples evaluate
        # nothing)
        bounds, deltas = _grouped_bucket_deltas(
            self, cap1, cap0, self.metric)
        if not bounds:
            return {}
        return {k: histogram_quantile(bounds, d, self.quantile)
                for k, d in deltas.items()}

    def breached(self, value):
        return self._cmp(value, self.bound)

    def describe(self):
        d = super().describe()
        d.update(metric=_norm_selector(self.metric)[0], op=self.op,
                 bound=self.bound, quantile=self.quantile,
                 window_s=self.window_s)
        return d


class Rate(Rule):
    """Counter delta per second over ``window_s`` vs a bound. A
    counter reset (engine restart zeroes its series) clamps the delta
    at 0 — a rate can dip to zero across a restart, never go
    negative."""

    kind = "rate"

    def __init__(self, name: str, *, metric: Selector, bound: float,
                 window_s: float, op: str = ">", **kw):
        super().__init__(name, **kw)
        self.metric = metric
        self.bound = float(bound)
        self.window_s = float(window_s)
        self.op = op
        self._cmp = _cmp(op)

    def evaluate(self, ring, now):
        latest = ring.latest()
        then = ring.at_or_before(now - self.window_s)
        if latest is None or then is None:
            return {}
        t1, cap1 = latest
        t0, cap0 = then
        dt = t1 - t0
        if dt <= 0:
            return {}
        now_g = self._group_sum(_counter_series(cap1, self.metric))
        then_g = self._group_sum(_counter_series(cap0, self.metric))
        return {k: max(v - then_g.get(k, 0.0), 0.0) / dt
                for k, v in now_g.items()}

    def breached(self, value):
        return self._cmp(value, self.bound)

    def describe(self):
        d = super().describe()
        d.update(metric=_norm_selector(self.metric)[0], op=self.op,
                 bound=self.bound, window_s=self.window_s)
        return d


class BurnRate(Rule):
    """Multi-window error-budget burn (SRE workbook).

    The error fraction over a window is either ``numerator`` /
    ``denominator`` counter deltas (e.g. 429s over submissions, error
    finishes over all finishes) or — with ``histogram=`` and
    ``target_s=`` — the fraction of the window's histogram samples
    SLOWER than the latency target (the p99-over-target fraction).
    The burn rate is that fraction divided by the budget
    ``1 - objective``; the rule's value is ``min(burn_fast,
    burn_slow)``, so ``breached`` means BOTH windows exceed
    ``factor``: the slow window proves the burn is sustained, the
    fast window un-pages promptly after recovery. Empty windows
    (denominator delta 0) are 'no data' — nothing fires on silence."""

    kind = "burn_rate"

    def __init__(self, name: str, *, objective: float,
                 fast_window_s: float, slow_window_s: float,
                 factor: float = 4.0,
                 numerator: Optional[Selector] = None,
                 denominator: Optional[Selector] = None,
                 histogram: Optional[Selector] = None,
                 target_s: Optional[float] = None, **kw):
        super().__init__(name, **kw)
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"rule {name!r}: objective must be in (0, 1)")
        if (histogram is None) == (numerator is None):
            raise ValueError(
                f"rule {name!r}: exactly one of histogram= or "
                "numerator=/denominator= must be given")
        if histogram is not None and target_s is None:
            raise ValueError(
                f"rule {name!r}: histogram mode needs target_s")
        if numerator is not None and denominator is None:
            raise ValueError(
                f"rule {name!r}: numerator needs denominator")
        self.objective = float(objective)
        self.budget = 1.0 - self.objective
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.factor = float(factor)
        self.numerator = numerator
        self.denominator = denominator
        self.histogram = histogram
        self.target_s = None if target_s is None else float(target_s)

    def _fractions(self, ring, now, window_s) \
            -> Dict[LabelKey, Optional[float]]:
        latest = ring.latest()
        then = ring.at_or_before(now - window_s)
        if latest is None or then is None:
            return {}
        _t1, cap1 = latest
        _t0, cap0 = then
        if self.histogram is not None:
            bounds, deltas = _grouped_bucket_deltas(
                self, cap1, cap0, self.histogram)
            if not bounds:
                return {}
            # the smallest bound >= target splits good from bad —
            # CONSERVATIVE (samples between target and that bound
            # count as good); align targets to bucket bounds for
            # exactness, same as an external histogram_quantile user
            split = bisect.bisect_left(bounds, self.target_s)
            out: Dict[LabelKey, Optional[float]] = {}
            for k, d in deltas.items():
                total = sum(d)
                if total <= 0:
                    out[k] = None         # empty window: no data
                    continue
                out[k] = sum(d[split + 1:]) / total
            return out
        num1 = self._group_sum(_counter_series(cap1, self.numerator))
        num0 = self._group_sum(_counter_series(cap0, self.numerator))
        den1 = self._group_sum(_counter_series(cap1, self.denominator))
        den0 = self._group_sum(_counter_series(cap0, self.denominator))
        out = {}
        for k, d1 in den1.items():
            den = max(d1 - den0.get(k, 0.0), 0.0)
            if den <= 0:
                out[k] = None
                continue
            num = max(num1.get(k, 0.0) - num0.get(k, 0.0), 0.0)
            out[k] = min(num / den, 1.0)
        return out

    def evaluate(self, ring, now):
        fast = self._fractions(ring, now, self.fast_window_s)
        slow = self._fractions(ring, now, self.slow_window_s)
        out: Dict[LabelKey, Optional[float]] = {}
        for k in set(fast) | set(slow):
            f, s = fast.get(k), slow.get(k)
            if f is None or s is None:
                out[k] = None
                continue
            out[k] = min(f, s) / self.budget
        return out

    def breached(self, value):
        return value > self.factor

    def describe(self):
        d = super().describe()
        d.update(objective=self.objective, factor=self.factor,
                 fast_window_s=self.fast_window_s,
                 slow_window_s=self.slow_window_s,
                 target_s=self.target_s,
                 metric=_norm_selector(
                     self.histogram or self.numerator)[0])
        return d


# ---------------------------------------------------------------- alerts
class Alert:
    """One (rule, label-group)'s live state. Deduplicated: a condition
    that stays breached keeps ONE alert in ``firing``, it does not
    re-fire every tick."""

    def __init__(self, rule: Rule, key: LabelKey):
        self.rule = rule.name
        self.severity = rule.severity
        self.action = rule.action
        self.labels = dict(key)
        self.key = key
        self.state = "inactive"
        self.value: Optional[float] = None
        self.pending_since: Optional[float] = None   # monotonic
        self.fired_at: Optional[float] = None        # wall clock
        self.resolved_at: Optional[float] = None
        self.resolved_mono: Optional[float] = None   # prune clock
        self.transitions = 0
        self.incident_dump: Optional[str] = None
        self.profile_bundle: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"rule": self.rule, "labels": self.labels,
                "state": self.state, "severity": self.severity,
                "action": self.action, "value": self.value,
                "fired_at": self.fired_at,
                "resolved_at": self.resolved_at,
                "transitions": self.transitions,
                "incident_dump": self.incident_dump,
                "profile_bundle": self.profile_bundle}


class SLOEngine:
    """Declarative SLO evaluator over a ``MetricsRegistry`` (module
    docstring). ``start()`` runs the "SLOEvaluator" thread;
    ``tick(now=...)`` evaluates once synchronously (tests drive the
    lifecycle deterministically with a fake clock and never need the
    thread)."""

    #: resolved/suppressed transition records kept for /v1/alerts
    HISTORY = 128
    #: how long a resolved alert whose label group has gone dark (no
    #: data at all) stays visible before its entry is pruned
    RESOLVED_RETENTION = 60.0

    def __init__(self, rules: Optional[Sequence[Rule]] = None, *,
                 registry: Optional[_telemetry.MetricsRegistry] = None,
                 interval_s: float = 1.0,
                 webhook_url: Optional[str] = None,
                 webhook_timeout_s: float = 2.0,
                 flight_dir: Optional[str] = None,
                 profile_on_page: Optional[bool] = None,
                 profile_duration_s: float = 0.25,
                 profile_min_interval_s: float = 120.0,
                 profile_dir: Optional[str] = None,
                 sampler: Optional[Any] = None,
                 make_default: bool = True):
        self.registry = (registry if registry is not None
                         else _telemetry.MetricsRegistry.get_default())
        self.interval_s = float(interval_s)
        self.webhook_url = webhook_url
        self.webhook_timeout_s = float(webhook_timeout_s)
        self.flight_dir = flight_dir
        #: device-profile capture on firing page alerts
        #: (profiler/programs.py ProfileSession). None = auto: capture
        #: only when the program registry is enabled OR the rule says
        #: action="profile" — an SLO-only process keeps its exact
        #: pre-profiling behavior. False disables, True forces.
        self.profile_on_page = profile_on_page
        self.profile_duration_s = float(profile_duration_s)
        self.profile_min_interval_s = float(profile_min_interval_s)
        self.profile_dir = profile_dir
        self._rules: List[Rule] = list(rules or [])
        self._alerts: Dict[Tuple[str, LabelKey], Alert] = {}
        self._history: collections.deque = collections.deque(
            maxlen=self.HISTORY)
        self._subs: List[Tuple[Callable[[Alert], Any],
                               Tuple[str, ...]]] = []
        #: slow side effects (incident dumps, webhook POSTs) queued by
        #: _set_state under the lock, executed by tick() OUTSIDE it —
        #: a stuck webhook must never stall alert_state() readers
        self._pending_io: List[Tuple[str, Alert]] = []
        self._lock = threading.RLock()
        self._ring = _Ring(self._ring_capacity())
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: when attached to a profiler/timeseries.py Sampler, its tick
        #: drives evaluation with the SHARED capture — one
        #: registry.capture() per tick for TSDB + SLO, not two
        self._sampler: Optional[Any] = None
        self._sampler_cb: Optional[Callable] = None
        self.ticks = 0
        if make_default:
            install(self)
        if sampler is not None:
            self.attach_sampler(sampler)

    # ------------------------------------------------------------ rules
    def _ring_capacity(self) -> int:
        horizon = 0.0
        for r in self._rules:
            for attr in ("window_s", "slow_window_s"):
                v = getattr(r, attr, None)
                if v:
                    horizon = max(horizon, float(v))
        if horizon <= 0:
            return 64
        return min(max(int(horizon / max(self.interval_s, 1e-3)) + 8,
                       64), 4096)

    def add_rule(self, rule: Rule) -> None:
        with self._lock:
            if any(r.name == rule.name for r in self._rules):
                raise ValueError(f"duplicate rule name {rule.name!r}")
            self._rules.append(rule)
            # re-home the ring if the new rule needs a longer horizon
            cap = self._ring_capacity()
            if cap != self._ring._buf.maxlen:
                self._ring._buf = collections.deque(
                    self._ring._buf, maxlen=cap)

    def rules(self) -> List[Rule]:
        with self._lock:
            return list(self._rules)

    # ----------------------------------------------------- subscriptions
    def on_alert(self, fn: Callable[[Alert], Any],
                 states: Sequence[str] = ("firing", "resolved")) \
            -> Callable[[Alert], Any]:
        """Subscribe ``fn(alert)`` to lifecycle transitions into
        ``states``. Called on the evaluator thread AFTER the alert's
        own bookkeeping (metrics, flight event, incident dump);
        exceptions are logged, never let near the evaluation loop.
        Returns ``fn`` for decorator use."""
        bad = set(states) - set(STATES)
        if bad:
            raise ValueError(f"unknown alert states: {sorted(bad)}")
        with self._lock:
            self._subs.append((fn, tuple(states)))
        return fn

    # -------------------------------------------------------- lifecycle
    def attach_sampler(self, sampler) -> "SLOEngine":
        """Re-base this engine onto a ``profiler/timeseries.py``
        Sampler: its tick delivers the shared capture (federated
        worker series included) and drives evaluation — ``start()``
        then spawns NO "SLOEvaluator" thread. No-op if this engine is
        already sampler-attached or its own thread is running (a live
        evaluator must not double-tick)."""
        with self._lock:
            if self._sampler is not None \
                    or (self._thread is not None
                        and self._thread.is_alive()):
                return self
            cb = (lambda t_mono, _t_wall, cap:
                  self.tick(now=t_mono, capture=cap))
            self._sampler = sampler
            self._sampler_cb = cb
        sampler.subscribe(cb)
        return self

    def start(self) -> "SLOEngine":
        with self._lock:
            if self._sampler is not None:
                return self        # the shared sampler drives ticks
            if self._thread is not None:
                return self
            if self._stop.is_set():
                raise RuntimeError("SLO engine has been shut down")
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="SLOEvaluator")
            self._thread.start()
        return self

    def shutdown(self, timeout: float = 10.0) -> None:
        self._stop.set()
        with self._lock:
            sampler, cb = self._sampler, self._sampler_cb
            self._sampler = self._sampler_cb = None
        if sampler is not None and cb is not None:
            sampler.unsubscribe(cb)
        t = self._thread
        if t is not None:
            t.join(timeout)
        # stale-series discipline (same as a dying decode engine): the
        # active-alerts gauge describes THIS engine — zero it so a
        # dead engine can't report permanently pending/firing alerts
        g = self.registry.peek(_telemetry.ALERTS_ACTIVE)
        if g is not None:
            for state in ("pending", "firing"):
                g.set(0, state=state)
        if default_engine() is self:
            install(None)

    def __enter__(self) -> "SLOEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                log.exception("SLO evaluator tick failed")
            self._stop.wait(self.interval_s)

    # ------------------------------------------------------- evaluation
    def tick(self, now: Optional[float] = None,
             capture: Optional[Dict[str, Any]] = None) -> None:
        """Capture one registry snapshot and evaluate every rule.
        ``now`` (monotonic seconds) is injectable so tests walk the
        pending->firing->resolved lifecycle with a fake clock.
        ``capture`` injects an already-taken snapshot (the shared
        TSDB sampler's) instead of capturing again — the dedupe that
        keeps two consumers at ONE ``registry.capture()`` per tick."""
        if now is None:
            now = time.monotonic()
        cap = capture if capture is not None else self.registry.capture()
        with self._lock:
            self._ring.append(now, cap)
            self.ticks += 1
            fired: List[Alert] = []
            for rule in self._rules:
                try:
                    results = rule.evaluate(self._ring, now)
                except Exception:
                    log.exception("SLO rule %r evaluation failed",
                                  rule.name)
                    continue
                seen = set(results)
                for key, value in results.items():
                    a = self._transition(rule, key, value, now)
                    if a is not None:
                        fired.append(a)
                # groups that vanished from the data (stale-series
                # expiry dropped a dead engine's gauges): condition
                # false
                for (rname, key), a in list(self._alerts.items()):
                    if rname == rule.name and key not in seen:
                        a2 = self._transition(rule, key, None, now)
                        if a2 is not None:
                            fired.append(a2)
            self._publish_gauges()
            subs = list(self._subs)
            io, self._pending_io = self._pending_io, []
        # slow side effects and notifications OUTSIDE the lock: a
        # stuck webhook or a large incident dump must not stall
        # alert_state()/alerts() readers (the scheduler calls them
        # under its own lock), and a subscriber may call back in.
        # Incident-before-webhook order is preserved per transition,
        # so the firing webhook payload carries incident_dump.
        for kind, a in io:
            if kind == "profile":
                a.profile_bundle = self._profile_capture(a)
            elif kind == "incident":
                ctx = dict(rule=a.rule, labels=dict(a.labels),
                           value=a.value)
                if a.profile_bundle:
                    ctx["profile_bundle"] = a.profile_bundle
                a.incident_dump = _flight.incident(
                    "slo_page", directory=self.flight_dir, **ctx)
            else:
                self._post_webhook(a)
        for a in fired:
            for fn, states in subs:
                if a.state in states:
                    try:
                        fn(a)
                    except Exception:
                        log.exception(
                            "on_alert subscriber failed for %s", a.rule)

    def _transition(self, rule: Rule, key: LabelKey,
                    value: Optional[float], now: float) \
            -> Optional[Alert]:
        """One alert's state machine step. Returns the alert when its
        state CHANGED (the caller notifies subscribers), else None."""
        breached = value is not None and rule.breached(value)
        a = self._alerts.get((rule.name, key))
        if a is None:
            if not breached:
                return None
            a = Alert(rule, key)
            self._alerts[(rule.name, key)] = a
        if value is not None:
            a.value = value
        old = a.state
        if a.state in ("inactive", "resolved"):
            if breached:
                a.pending_since = now
                if rule.for_s <= 0:
                    self._set_state(a, "firing", now)
                else:
                    self._set_state(a, "pending", now)
            elif (a.state == "resolved" and value is None
                  and a.resolved_mono is not None
                  and now - a.resolved_mono >= self.RESOLVED_RETENTION):
                # the group stayed DARK well past resolution: its
                # gauge series vanished (stale-series expiry) or its
                # counter/histogram windows emptied for good (dead
                # engine — counters are retained, so the key stays in
                # results forever with no data). The lifecycle record
                # lives in _history; drop the entry so per-engine
                # label churn (replica restarts mint fresh engine
                # ids) can't grow the alert table without bound. The
                # retention keeps a just-resolved alert visible to
                # operators/drills polling alert_state().
                del self._alerts[(rule.name, key)]
        elif a.state == "pending":
            if not breached:
                # flap: the condition cleared before for_s — suppress,
                # never fire (counted as 'suppressed', visible in
                # history, no page)
                self._set_state(a, "inactive", now, suppressed=True)
                del self._alerts[(rule.name, key)]
            elif now - a.pending_since >= rule.for_s:
                self._set_state(a, "firing", now)
        elif a.state == "firing":
            if not breached:
                self._set_state(a, "resolved", now)
        return a if a.state != old else None

    def _set_state(self, a: Alert, state: str, now: float,
                   suppressed: bool = False) -> None:
        old, a.state = a.state, state
        a.transitions += 1
        wall = time.time()
        if state == "firing":
            a.fired_at = wall
            a.resolved_at = None
        elif state == "resolved":
            a.resolved_at = wall
            a.resolved_mono = now
        counted = "suppressed" if suppressed else state
        _flight.record("alert", rule=a.rule, frm=old, state=counted,
                       labels=dict(a.labels), value=a.value,
                       severity=a.severity)
        if _telemetry.enabled():
            self.registry.counter(
                _telemetry.ALERTS_TOTAL,
                "alert lifecycle transitions (state=pending/firing/"
                "resolved/suppressed)").inc(rule=a.rule, state=counted)
        self._history.append({
            "t": wall, "rule": a.rule, "labels": dict(a.labels),
            "from": old, "to": counted, "value": a.value,
            "severity": a.severity})
        if state == "firing":
            log.warning("SLO ALERT FIRING: %s%s value=%s severity=%s",
                        a.rule, a.labels, a.value, a.severity)
            if a.severity == "page" or a.action == "profile":
                # device-profile capture BEFORE the incident dump so
                # the dump's manifest context carries the bundle path
                # (rate-limited + gated in _profile_capture)
                self._pending_io.append(("profile", a))
            if a.severity == "page":
                # a page is exactly the moment the black box exists
                # for: dump the ring + traces, digest-valid (deferred
                # to tick()'s unlocked phase with the webhook)
                self._pending_io.append(("incident", a))
        elif state == "resolved":
            log.info("SLO alert resolved: %s%s", a.rule, a.labels)
        if state in ("firing", "resolved"):
            self._pending_io.append(("webhook", a))

    def _publish_gauges(self) -> None:
        if not _telemetry.enabled():
            return
        counts = {"pending": 0, "firing": 0}
        for a in self._alerts.values():
            if a.state in counts:
                counts[a.state] += 1
        g = self.registry.gauge(
            _telemetry.ALERTS_ACTIVE,
            "alerts currently pending / firing")
        for state, n in counts.items():
            g.set(n, state=state)

    def _profile_capture(self, a: Alert) -> Optional[str]:
        """Once-per-firing-alert device capture (ISSUE 16): runs in
        tick()'s unlocked io phase, rate-limited across all automated
        triggers by ProfileSession.maybe_capture. Returns the bundle
        path, or None (disabled / gated off / rate-limited / slot
        busy / failed). Never raises."""
        if self.profile_on_page is False:
            return None
        try:
            from deeplearning4j_tpu.profiler import programs as _programs
        except Exception:
            return None
        if (self.profile_on_page is None and a.action != "profile"
                and not _programs.enabled()):
            # auto mode: the device capture rides the program-registry
            # opt-in so an SLO-only process keeps its exact
            # pre-profiling behavior
            return None
        return _programs.profile_session().maybe_capture(
            trigger=f"slo:{a.rule}",
            duration_s=self.profile_duration_s,
            min_interval_s=self.profile_min_interval_s,
            directory=self.profile_dir)

    def _post_webhook(self, a: Alert) -> None:
        url = self.webhook_url
        if not url:
            return
        body = json.dumps(a.to_dict()).encode()
        req = urllib.request.Request(
            url, data=body,
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(
                req, timeout=self.webhook_timeout_s).read()
        except Exception as e:
            log.warning("SLO webhook POST to %s failed: %s", url, e)

    # ---------------------------------------------------------- reading
    def alerts(self, states: Optional[Sequence[str]] = None) \
            -> List[Alert]:
        with self._lock:
            out = list(self._alerts.values())
        if states is not None:
            out = [a for a in out if a.state in states]
        return out

    def alert_state(self, rule: str, **labels) -> str:
        """The state of the alert for ``rule`` whose labels contain
        ``labels`` ("inactive" when none does) — the control plane's
        hysteresis read."""
        with self._lock:
            for (rname, _key), a in self._alerts.items():
                if rname == rule and _match(a.labels, labels):
                    return a.state
        return "inactive"

    def resolved_for(self, rule: str, now: Optional[float] = None,
                     **labels) -> Optional[float]:
        """Seconds the alert for ``rule`` (labels subset-matched, like
        ``alert_state``) has been CONTINUOUSLY quiet — the scale-down
        hysteresis read: None while pending/firing (not quiet at all),
        the age of the resolve while resolved, and ``inf`` when the
        alert never fired (or was pruned after RESOLVED_RETENTION,
        which only happens well past any sane hold window). A caller
        shrinks capacity only once this exceeds its hold time, so one
        noisy resolve/refire flap never thrashes the fleet."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            for (rname, _key), a in self._alerts.items():
                if rname != rule or not _match(a.labels, labels):
                    continue
                if a.state in ("pending", "firing"):
                    return None
                if a.state == "resolved" \
                        and a.resolved_mono is not None:
                    return max(0.0, now - a.resolved_mono)
        return float("inf")

    def alerts_json(self) -> Dict[str, Any]:
        with self._lock:
            alerts = [a.to_dict() for a in self._alerts.values()]
            history = list(self._history)
            rules = [r.describe() for r in self._rules]
        order = {"firing": 0, "pending": 1, "resolved": 2,
                 "inactive": 3}
        alerts.sort(key=lambda a: order.get(a["state"], 9))
        return {"interval_s": self.interval_s,
                "ticks": self.ticks,
                "running": self._thread is not None
                and self._thread.is_alive(),
                "rules": rules, "alerts": alerts,
                "history": history[-64:]}

    def snapshot(self) -> Dict[str, Any]:
        """Compact peek-style embedding for telemetry.snapshot() /
        the dashboard card."""
        with self._lock:
            firing = [a.to_dict() for a in self._alerts.values()
                      if a.state == "firing"]
            pending = [a.to_dict() for a in self._alerts.values()
                       if a.state == "pending"]
            n_rules = len(self._rules)
            history = list(self._history)[-8:]
        return {"rules": n_rules, "ticks": self.ticks,
                "firing": firing, "pending": pending,
                "recent": history}


# ------------------------------------------------------- built-in pack
def serving_rules(*, p99_target_s: float = 1.0,
                  ttft_target_s: float = 0.5,
                  latency_objective: float = 0.99,
                  error_objective: float = 0.999,
                  burn_fast_s: float = 60.0,
                  burn_slow_s: float = 300.0,
                  burn_factor: float = 4.0,
                  kv_util_bound: float = 0.95,
                  queue_pressure_bound: float = 1.0,
                  queue_pressure_for_s: float = 5.0,
                  window_s: float = 60.0,
                  for_s: float = 10.0) -> List[Rule]:
    """The serving pack. Latency burn pages; the rest tickets. The
    queue-pressure rule carries ``action="scale_serve"`` — the
    control plane's scale-up hook."""
    T = _telemetry
    return [
        BurnRate(
            "serving_p99_burn", severity="page",
            histogram=T.SERVING_REQUEST_LATENCY,
            target_s=p99_target_s, objective=latency_objective,
            fast_window_s=burn_fast_s, slow_window_s=burn_slow_s,
            factor=burn_factor, group_by=("engine",),
            description=f"request-latency error budget "
                        f"({latency_objective:.0%} under "
                        f"{p99_target_s}s) burning in both windows"),
        Threshold(
            "serving_ttft_p99", metric=T.SERVING_TTFT,
            quantile=0.99, window_s=window_s, bound=ttft_target_s,
            op=">", for_s=for_s, group_by=("engine",),
            description="windowed TTFT p99 over target"),
        BurnRate(
            "serving_error_rate",
            numerator=(T.SERVING_REQUEST_LATENCY,
                       {"reason": "error"}),
            denominator=T.SERVING_REQUEST_LATENCY,
            objective=error_objective,
            fast_window_s=burn_fast_s, slow_window_s=burn_slow_s,
            factor=burn_factor, group_by=("engine",),
            description="finished-with-error fraction burning the "
                        "availability budget"),
        BurnRate(
            "serving_429_burn",
            numerator=T.SERVING_REJECTS,
            denominator=T.SERVING_REQUESTS,
            objective=error_objective,
            fast_window_s=burn_fast_s, slow_window_s=burn_slow_s,
            factor=burn_factor, group_by=(),
            description="capacity rejects (429s) vs admitted "
                        "submissions, process-wide"),
        Threshold(
            "serving_kv_utilization",
            metric=T.SERVING_KV_PAGE_UTILIZATION,
            bound=kv_util_bound, op=">", for_s=for_s,
            description="KV page pool sustained near capacity"),
        Threshold(
            "serving_queue_pressure",
            metric=T.SERVING_FLEET_PRESSURE,
            bound=queue_pressure_bound, op=">",
            for_s=queue_pressure_for_s, action="scale_serve",
            description="sustained fleet admission pressure "
                        "(queued work per live decode slot) — the "
                        "scheduler's scale-up signal"),
    ]


def training_rules(*, mfu_floor: float = 0.05,
                    mfu_for_s: float = 60.0,
                    stall_window_s: float = 300.0,
                    rollback_window_s: float = 300.0,
                    rollback_rate_bound: float = 1 / 60.0,
                    starvation_for_s: float = 30.0) -> List[Rule]:
    """The training pack: MFU floor, watchdog-stall rate, divergence-
    rollback rate, prefetch starvation (a sustained empty prefetch
    queue = the input pipeline can't keep the chip fed)."""
    T = _telemetry
    return [
        Threshold(
            "train_mfu_drop", metric=T.MFU, bound=mfu_floor,
            op="<", for_s=mfu_for_s,
            description="live MFU below floor (step got slower or "
                        "smaller without anyone asking)"),
        Rate(
            "train_watchdog_stalls", metric=T.WATCHDOG_STALLS,
            bound=0.0, window_s=stall_window_s, group_by=(),
            description="any watchdog stall in the window"),
        Rate(
            "train_divergence_rollbacks", metric=T.FT_ROLLBACKS,
            bound=rollback_rate_bound, window_s=rollback_window_s,
            group_by=(),
            description="divergence rollbacks per second over the "
                        "window (the guard is spending its budget)"),
        Threshold(
            "train_prefetch_starvation",
            metric=T.PREFETCH_QUEUE_DEPTH, bound=0.5, op="<",
            for_s=starvation_for_s,
            description="device prefetch queue sustained empty — "
                        "host ETL is the bottleneck"),
    ]


def default_rules(**overrides) -> List[Rule]:
    """serving_rules() + training_rules(); keyword overrides are
    routed to whichever pack accepts them."""
    import inspect

    s_keys = set(inspect.signature(serving_rules).parameters)
    t_keys = set(inspect.signature(training_rules).parameters)
    unknown = set(overrides) - s_keys - t_keys
    if unknown:
        raise TypeError(f"unknown rule-pack overrides: "
                        f"{sorted(unknown)}")
    return (serving_rules(**{k: v for k, v in overrides.items()
                             if k in s_keys})
            + training_rules(**{k: v for k, v in overrides.items()
                                if k in t_keys}))


# -------------------------------------------- default engine + HTTP
_default: Optional[SLOEngine] = None
_dlock = threading.Lock()


def install(engine: Optional[SLOEngine]) -> None:
    global _default
    with _dlock:
        _default = engine


def default_engine() -> Optional[SLOEngine]:
    return _default


def alerts_snapshot() -> Dict[str, Any]:
    """Peek-style snapshot for telemetry embedding ({} without a live
    engine — an idle process pays one attribute read)."""
    e = _default
    return e.snapshot() if e is not None else {}


def http_alerts() -> Tuple[Dict[str, Any], int]:
    """Shared GET /v1/alerts handling for ui/server.py and
    remote/server.py. Returns (obj, http_code)."""
    e = default_engine()
    if e is None:
        return ({"error": "no SLO engine in this process (construct "
                          "profiler.slo.SLOEngine(rules=slo."
                          "default_rules()) and start() it)"}, 404)
    return (e.alerts_json(), 200)


__all__ = ["SLOEngine", "Rule", "Threshold", "Rate", "BurnRate",
           "Alert", "STATES", "histogram_quantile",
           "serving_rules", "training_rules", "default_rules",
           "install", "default_engine", "alerts_snapshot",
           "http_alerts"]
