"""Black-box flight recorder: a bounded ring of structured step events,
atomically dumped on incidents.

When PR 4's watchdog fires or the divergence guard rolls back, the
operator today gets thread stacks and counters — state at the moment of
failure, not the path INTO it. An aircraft flight recorder solves the
same problem: record everything cheaply all the time, and when the
incident happens the last N minutes are already on disk. Here:

- ``record(kind, **fields)`` appends one structured event (monotonic
  ``seq``, wall-clock ``t``, free-form fields) to a bounded ring.
  The fit loops record a ``train_step`` per step, the guarded
  resilience loop adds per-step loss (it syncs the loss anyway), the
  serving engine records scheduler decisions (admit / burst / evict),
  and the resilience layer records every FT event. Cost per event is a
  perf_counter read + a dict + a deque append under a lock — cheap
  enough to leave on always (``DL4J_TPU_FLIGHT=0`` opts out).
- ``incident(reason, **context)`` appends a terminal event (kind =
  the reason) and atomically dumps the ring: ``events.jsonl`` (one
  event per line), ``trace.json`` (the Chrome-trace snapshot),
  ``requests.json`` (live + recent request timelines from tracing.py),
  ``programs.json`` (the roofline program-registry snapshot, present
  when populated — profiler/programs.py; managed device captures also
  record a ``profile_capture{trigger,bundle}`` event here),
  ``metrics.json`` (the last N minutes of every metric series from the
  time-series store, present when DL4J_TPU_TSDB has a sampler live —
  profiler/timeseries.py)
  and a ``manifest.json`` with sha256 digests of every member —
  written into a dot-tmp dir, fsynced, then renamed into place
  (the same crash-atomic recipe as resilience.write_bundle). The
  terminal event and the ring snapshot happen under ONE lock, so the
  dump's last event is always the incident itself.
- ``load_dump(path)`` verifies the digests and parses everything back
  — the post-mortem loader tests and tooling share.

Dump triggers wired in this repo: watchdog stall, divergence rollback
(and budget-exhausted abort), preemption checkpoint, serving-engine
scheduler death, and — via ``install_excepthook()`` — any unhandled
exception that kills the process.

Dump location: explicit ``directory=`` argument, else ``configure()``d
directory, else ``$DL4J_TPU_FLIGHT_DIR``, else
``<tempdir>/dl4j_tpu_flight``.
"""

from __future__ import annotations

import collections
import hashlib
import json
import logging
import os
import shutil
import sys
import tempfile
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.profiler import telemetry as _telemetry

log = logging.getLogger("deeplearning4j_tpu")

_FORMAT = "dl4j-tpu-flight-1"
_DEFAULT_CAPACITY = 512


def _sanitize(v, depth: int = 0):
    """JSON-safe coercion that never touches a device: numbers become
    plain floats/ints (non-finite floats become their string spelling —
    a NaN loss is exactly what an incident dump must preserve, and bare
    NaN is not JSON), everything unknown becomes a capped repr. The
    depth bound is a runaway guard only — requests.json legitimately
    nests root -> timeline list -> timeline -> events -> event ->
    attr value (depth 5), which must survive as structure."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        return v if v == v and abs(v) != float("inf") else repr(v)
    if isinstance(v, dict) and depth < 8:
        return {str(k): _sanitize(x, depth + 1) for k, x in v.items()}
    if isinstance(v, (list, tuple)) and depth < 8:
        return [_sanitize(x, depth + 1) for x in v]
    item = getattr(v, "item", None)
    if item is not None and getattr(v, "ndim", 1) == 0:
        try:
            return _sanitize(item())
        except Exception:
            pass
    return repr(v)[:200]


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class FlightRecorder:
    """One bounded event ring + its dump machinery. A process normally
    uses the default instance (module-level helpers); tests may build
    private ones."""

    #: dumps retained per directory — a watchdog deadline set slightly
    #: too low must not fill the checkpoint volume with fsynced dumps
    #: (same keep-last discipline as resilience bundles)
    KEEP_DUMPS = 16

    def __init__(self, capacity: int = _DEFAULT_CAPACITY,
                 directory: Optional[str] = None,
                 enabled: Optional[bool] = None):
        self.capacity = int(capacity)
        self.directory = directory
        self.enabled = (os.environ.get("DL4J_TPU_FLIGHT", "1") != "0"
                        if enabled is None else bool(enabled))
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.incidents: collections.deque = collections.deque(maxlen=16)
        self.last_dump: Optional[str] = None

    # ------------------------------------------------------- recording
    def record(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        ev = {"seq": 0, "t": time.time(), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)

    def record_step(self, site: str, iteration: int, t0: float,
                    **fields) -> None:
        """Per-training-step convenience: dispatch time derived from
        the step's start perf_counter reading."""
        if not self.enabled:
            return
        self.record("train_step", site=site, iteration=int(iteration),
                    dispatch_ms=round((time.perf_counter() - t0) * 1e3, 3),
                    **fields)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    # --------------------------------------------------------- dumping
    def incident(self, reason: str, directory: Optional[str] = None,
                 **context) -> Optional[str]:
        """Terminal event + atomic dump. Never raises: a broken disk
        must not take down the training loop the recorder exists to
        explain. Returns the dump path, or None (disabled / failed)."""
        if not self.enabled:
            return None
        term = {"seq": 0, "t": time.time(), "kind": reason}
        term.update({k: _sanitize(v) for k, v in context.items()})
        with self._lock:
            self._seq += 1
            term["seq"] = self._seq
            self._ring.append(term)
            events = list(self._ring)
        try:
            path = self._dump(reason, events, directory, context)
        except Exception:
            log.exception("flight recorder: dump for %r failed", reason)
            return None
        self.incidents.append({"reason": reason, "path": path,
                               "wall_time": term["t"]})
        self.last_dump = path
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.INCIDENT_DUMPS,
                "flight-recorder incident dumps written").inc(
                reason=reason)
        log.error("FLIGHT RECORDER: incident %r — %d events dumped to %s",
                  reason, len(events), path)
        return path

    def _resolve_dir(self, directory: Optional[str]) -> str:
        return (directory or self.directory
                or os.environ.get("DL4J_TPU_FLIGHT_DIR")
                or os.path.join(tempfile.gettempdir(), "dl4j_tpu_flight"))

    def _dump(self, reason: str, events: List[Dict[str, Any]],
              directory: Optional[str], context: Dict[str, Any]) -> str:
        from deeplearning4j_tpu.profiler import tracing as _tracing
        from deeplearning4j_tpu.util.model_serializer import (
            fsync_directory,
        )

        root = self._resolve_dir(directory)
        os.makedirs(root, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        name = (f"incident-{stamp}-{reason}-{os.getpid()}-"
                f"{uuid.uuid4().hex[:6]}")
        final = os.path.join(root, name)
        tmp = os.path.join(root, f".{name}.tmp")
        os.makedirs(tmp)

        def _write(member: str, text: str) -> None:
            with open(os.path.join(tmp, member), "w") as f:
                f.write(text)
                f.flush()
                os.fsync(f.fileno())

        try:
            _write("events.jsonl", "".join(
                json.dumps(_sanitize(ev)) + "\n" for ev in events))
            # cap the trace snapshot: a long-lived process holds up to
            # 50k span events (~10MB of JSON) and the dump must stay
            # fast enough to finish inside a SIGTERM grace period —
            # the newest slice is the forensically relevant one
            trace = _telemetry.chrome_trace()
            tev = trace["traceEvents"]
            if len(tev) > 5000:
                trace = dict(trace, traceEvents=tev[-5000:])
                trace.setdefault("otherData", {})
                trace["otherData"] = dict(
                    trace["otherData"],
                    dump_truncated_events=len(tev) - 5000)
            _write("trace.json", json.dumps(trace))
            try:
                requests = _tracing.snapshot_requests()
            except Exception:
                requests = {"live": [], "recent": []}
            _write("requests.json", json.dumps(_sanitize(requests)))
            members = ["events.jsonl", "trace.json", "requests.json"]
            # the roofline program registry rides along when populated
            # (profiler/programs.py): "what was compiled and where the
            # device time went" is exactly post-mortem signal
            try:
                from deeplearning4j_tpu.profiler import \
                    programs as _programs

                psnap = _programs.snapshot()
            except Exception:
                psnap = {}
            if psnap:
                _write("programs.json", json.dumps(_sanitize(psnap)))
                members.append("programs.json")
            # metrics history rides along when the time-series store
            # is live (profiler/timeseries.py): the last N minutes of
            # every series — the "why", next to the events' "what".
            # sys.modules-guarded: a dump must not import (let alone
            # start) the TSDB in a process that never enabled it.
            try:
                _ts = sys.modules.get(
                    "deeplearning4j_tpu.profiler.timeseries")
                msnap = (_ts.metrics_history_snapshot()
                         if _ts is not None else {})
            except Exception:
                msnap = {}
            if msnap:
                _write("metrics.json", json.dumps(msnap))
                members.append("metrics.json")
            _write("manifest.json", json.dumps({
                "format": _FORMAT,
                "reason": reason,
                "wall_time": time.time(),
                "pid": os.getpid(),
                "host": _tracing.host_id(),
                "event_count": len(events),
                "last_seq": events[-1]["seq"] if events else 0,
                "context": _sanitize(context),
                "digests": {m: _sha256(os.path.join(tmp, m))
                            for m in members},
            }))
            fsync_directory(tmp)
            os.replace(tmp, final)
            fsync_directory(root)
        finally:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp, ignore_errors=True)
        for old in list_dumps(root)[:-self.KEEP_DUMPS]:
            shutil.rmtree(old, ignore_errors=True)
        return final

    def snapshot(self) -> Dict[str, Any]:
        """/telemetry + bench embedding: {} until something recorded
        (peek-style — a process that never records shows nothing)."""
        with self._lock:
            n, last_seq = len(self._ring), self._seq
        if not self.enabled or (last_seq == 0 and not self.incidents):
            return {}
        return {
            "enabled": self.enabled,
            "events": n,
            "capacity": self.capacity,
            "last_seq": last_seq,
            "last_incident": self.last_dump,
            "incidents": list(self.incidents),
        }


# ---------------------------------------------------------- dump loader
def load_dump(path: str) -> Dict[str, Any]:
    """Parse an incident dump back; ``valid`` is True iff the manifest
    parses, the format matches, and every member's sha256 digest
    verifies — the same digest discipline resume bundles use."""
    out: Dict[str, Any] = {"path": path, "valid": False,
                           "manifest": None, "events": [],
                           "trace": None, "requests": None,
                           "programs": None, "metrics": None}
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        out["manifest"] = manifest
        valid = manifest.get("format") == _FORMAT
        for member, digest in manifest.get("digests", {}).items():
            if _sha256(os.path.join(path, member)) != digest:
                valid = False
        out["valid"] = valid
    except (OSError, ValueError, KeyError):
        return out
    try:
        with open(os.path.join(path, "events.jsonl")) as f:
            out["events"] = [json.loads(line) for line in f
                             if line.strip()]
        with open(os.path.join(path, "trace.json")) as f:
            out["trace"] = json.load(f)
        with open(os.path.join(path, "requests.json")) as f:
            out["requests"] = json.load(f)
        if "programs.json" in (out["manifest"].get("digests") or {}):
            with open(os.path.join(path, "programs.json")) as f:
                out["programs"] = json.load(f)
        if "metrics.json" in (out["manifest"].get("digests") or {}):
            with open(os.path.join(path, "metrics.json")) as f:
                out["metrics"] = json.load(f)
    except (OSError, ValueError):
        out["valid"] = False
    return out


def list_dumps(directory: str) -> List[str]:
    """Incident dump dirs under ``directory``, newest name last."""
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith("incident-"))
    except OSError:
        return []
    return [os.path.join(directory, n) for n in names]


# --------------------------------------------------- default instance
_default: Optional[FlightRecorder] = None
_dlock = threading.Lock()


def get_default() -> FlightRecorder:
    global _default
    with _dlock:
        if _default is None:
            _default = FlightRecorder()
        return _default


def configure(directory: Optional[str] = None,
              capacity: Optional[int] = None,
              enabled: Optional[bool] = None) -> FlightRecorder:
    """Adjust the default recorder in place (capacity changes re-home
    the ring, keeping the newest events)."""
    r = get_default()
    with r._lock:
        if directory is not None:
            r.directory = directory
        if enabled is not None:
            r.enabled = bool(enabled)
        if capacity is not None and int(capacity) != r.capacity:
            r.capacity = int(capacity)
            r._ring = collections.deque(r._ring, maxlen=r.capacity)
    return r


def record(kind: str, **fields) -> None:
    # fast path: skip the default-instance lock on the per-step call
    r = _default
    (r if r is not None else get_default()).record(kind, **fields)


def record_step(site: str, iteration: int, t0: float, **fields) -> None:
    r = _default
    (r if r is not None else get_default()).record_step(
        site, iteration, t0, **fields)


def incident(reason: str, directory: Optional[str] = None,
             **context) -> Optional[str]:
    return get_default().incident(reason, directory=directory, **context)


def snapshot() -> Dict[str, Any]:
    return get_default().snapshot()


def reset() -> None:
    """Fresh default recorder (tests / between bench rounds)."""
    global _default
    with _dlock:
        _default = None


# ------------------------------------------------- unhandled exceptions
_hook_installed = False
_in_hook = False


def install_excepthook() -> None:
    """Chain onto ``sys.excepthook`` so a process dying with an
    unhandled exception leaves an incident dump behind. Idempotent;
    the previous hook always runs afterwards."""
    global _hook_installed
    with _dlock:
        if _hook_installed:
            return
        _hook_installed = True
    prev = sys.excepthook

    def _hook(exc_type, exc, tb):
        global _in_hook
        if not _in_hook:
            _in_hook = True
            try:
                incident("unhandled_exception",
                         error=f"{exc_type.__name__}: {exc}"[:400])
            except Exception:
                pass
            finally:
                _in_hook = False
        prev(exc_type, exc, tb)

    sys.excepthook = _hook


__all__ = ["FlightRecorder", "get_default", "configure", "record",
           "record_step", "incident", "snapshot", "reset", "load_dump",
           "list_dumps", "install_excepthook"]
