"""Request-scoped distributed tracing.

The telemetry spine (telemetry.py) answers "how is the PROCESS doing"
— histograms, gauges, a Chrome-trace buffer of host spans. What it
cannot answer is "why was REQUEST 1743 slow" or "which HOST is the
straggler": spans carry no identity that survives aggregation. This
module adds that identity layer:

- ``TraceContext`` — a lightweight per-request (or per-fit-site) trace:
  ``trace_id``, optional ``request_id``, host/process id, and a bounded
  event list. Every event is ALSO emitted into the telemetry span
  buffer tagged ``trace``/``request``/``host``, so one Chrome-trace
  export carries both the process story and the per-request story.
- The serving engine threads a context through a request's whole life:
  ``submit -> queue_wait -> prefill -> decode_burst* -> finish``. The
  finished timeline lands in a bounded registry served at
  ``GET /v1/serving/requests/<id>`` (remote/server.py) and summarized
  on ``/telemetry``.
- The fit loops record one ``train_step`` event per step under a
  long-lived per-site context (``record_train_step``), so a training
  incident dump carries the same timeline shape a serving request does.
- Multi-host: every span is tagged with ``jax.process_index()``. Worker
  hosts ``push_spans(coordinator_url)`` their per-span-name aggregates;
  the coordinator's UI server ingests them at ``POST /telemetry/spans``
  and ``/telemetry`` then shows per-host step totals side by side — a
  straggler host is a visibly fatter ``device_step`` row, not a guess.

Off-mode contract (mirrors PR 5's HealthMonitor): tracing defaults OFF
(``DL4J_TPU_TRACING=1`` or ``set_enabled(True)`` to opt in) and every
hook's disabled path is one module-attribute read — serving and fit
paths are bit-identical with tracing off, and tracing is host-side
only, so enabling it never changes numerics either.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
import urllib.request
import uuid
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.profiler import telemetry as _telemetry

_ENABLED = os.environ.get("DL4J_TPU_TRACING", "0") not in ("0", "")

#: bounded registries: finished timelines kept for /v1/serving/requests
#: lookups, live contexts kept for incident dumps (flight_recorder.py)
_RECENT_MAX = 256
_LIVE_MAX = 1024
#: events retained per context (a request's decode bursts are bounded
#: by max_new_tokens / chunking anyway; train contexts wrap)
_EVENTS_PER_TRACE = 512

_lock = threading.Lock()
_live: "collections.OrderedDict[str, TraceContext]" = \
    collections.OrderedDict()
_recent: "collections.OrderedDict[str, Dict[str, Any]]" = \
    collections.OrderedDict()
_train: Dict[str, "TraceContext"] = {}
#: span aggregates pushed by OTHER hosts (coordinator side)
_remote_hosts: Dict[str, Dict[str, Any]] = {}

_host: Optional[int] = None


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def host_id() -> int:
    """This process's id in the mesh (``jax.process_index()``), cached;
    0 when jax is unavailable or uninitialized."""
    global _host
    if _host is None:
        try:
            import jax

            _host = int(jax.process_index())
        except Exception:
            _host = 0
    return _host


def _key(request_id, trace_id: str) -> str:
    return str(request_id) if request_id is not None else trace_id


class TraceContext:
    """One trace: identity + a bounded, thread-safe event list. Events
    are relative-timestamped (ms since the trace started) so a timeline
    is readable without correlating perf_counter epochs."""

    def __init__(self, kind: str, request_id=None, **attrs):
        self.trace_id = uuid.uuid4().hex[:16]
        self.kind = kind
        self.request_id = request_id
        self.host = host_id()
        self.pid = os.getpid()
        self.started_wall = time.time()
        self._t0 = time.perf_counter()
        self.attrs = {k: v for k, v in attrs.items() if v is not None}
        self.finish_reason: Optional[str] = None
        self._events: collections.deque = collections.deque(
            maxlen=_EVENTS_PER_TRACE)
        self._elock = threading.Lock()

    def event(self, name: str, t0: float, t1: Optional[float] = None,
              **attrs) -> None:
        """Record one completed span: into this trace's timeline AND
        into the process Chrome-trace buffer, tagged with the trace /
        request / host identity."""
        if t1 is None:
            t1 = time.perf_counter()
        ev: Dict[str, Any] = {
            "name": name,
            "ts_ms": round((t0 - self._t0) * 1e3, 3),
            "dur_ms": round(max(t1 - t0, 0.0) * 1e3, 3),
        }
        if attrs:
            ev.update(attrs)
        with self._elock:
            self._events.append(ev)
        tags = dict(attrs)
        tags["trace"] = self.trace_id
        tags["host"] = self.host
        if self.request_id is not None:
            tags["request"] = self.request_id
        _telemetry.record_span(name, t0, t1, **tags)

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.event(name, t0, **attrs)

    def to_dict(self) -> Dict[str, Any]:
        with self._elock:
            events = list(self._events)
        return {
            "trace_id": self.trace_id,
            "kind": self.kind,
            "request_id": self.request_id,
            "host": self.host,
            "pid": self.pid,
            "started_wall": self.started_wall,
            "attrs": dict(self.attrs),
            "finish_reason": self.finish_reason,
            "events": events,
        }


def new_trace(kind: str, request_id=None, **attrs) \
        -> Optional[TraceContext]:
    """Open a trace, or None when tracing is off (callers guard on the
    context, so the disabled path costs one attribute read)."""
    if not _ENABLED:
        return None
    ctx = TraceContext(kind, request_id=request_id, **attrs)
    with _lock:
        _live[_key(request_id, ctx.trace_id)] = ctx
        while len(_live) > _LIVE_MAX:
            _live.popitem(last=False)
    return ctx


def finish_trace(ctx: Optional[TraceContext], reason: Optional[str] = None,
                 **attrs) -> None:
    """Close a trace: out of the live registry, timeline retained in the
    bounded recent registry for /v1/serving/requests/<id> lookups."""
    if ctx is None:
        return
    ctx.finish_reason = reason
    if attrs:
        ctx.attrs.update(attrs)
    d = ctx.to_dict()
    key = _key(ctx.request_id, ctx.trace_id)
    with _lock:
        _live.pop(key, None)
        _recent[key] = d
        _recent.move_to_end(key)
        while len(_recent) > _RECENT_MAX:
            _recent.popitem(last=False)


def timeline(request_id) -> Optional[Dict[str, Any]]:
    """One request's (or train site's) timeline — live or finished —
    or None when unknown (or tracing was off when it ran)."""
    key = str(request_id)
    with _lock:
        ctx = _live.get(key)
        if ctx is not None:
            return ctx.to_dict()
        d = _recent.get(key)
        return dict(d) if d is not None else None


def _summarize(d: Dict[str, Any]) -> Dict[str, Any]:
    phases: Dict[str, List[float]] = {}
    end = 0.0
    for ev in d["events"]:
        p = phases.setdefault(ev["name"], [0, 0.0])
        p[0] += 1
        p[1] += ev["dur_ms"]
        end = max(end, ev["ts_ms"] + ev["dur_ms"])

    def total(name: str) -> float:
        return round(phases.get(name, (0, 0.0))[1], 3)

    return {
        "request_id": d["request_id"],
        "trace_id": d["trace_id"],
        "kind": d["kind"],
        "host": d["host"],
        # per-replica tag (serving fleet: which engine served it)
        "engine": d["attrs"].get("engine"),
        "finish_reason": d["finish_reason"],
        "total_ms": round(end, 3),
        "queue_ms": total("queue_wait"),
        "prefix_lookup_ms": total("prefix_lookup"),
        "prefill_ms": total("prefill"),
        # fleet spans: routing decision + disaggregated-prefill lane
        "route_ms": total("route"),
        "lane_prefill_ms": total("lane_prefill"),
        "decode_ms": total("decode_burst"),
        "events": sum(c for c, _ in phases.values()),
        "spans": {name: {"count": c, "total_ms": round(t, 3)}
                  for name, (c, t) in sorted(phases.items())},
    }


def recent_summaries(n: int = 32) -> List[Dict[str, Any]]:
    with _lock:
        ds = list(_recent.values())[-n:]
    return [_summarize(d) for d in reversed(ds)]


def live_summaries() -> List[Dict[str, Any]]:
    with _lock:
        ctxs = list(_live.values())
    return [_summarize(c.to_dict()) for c in ctxs]


def snapshot_requests() -> Dict[str, Any]:
    """Full timelines, live and recent — what a flight-recorder
    incident dump embeds so the post-mortem shows exactly where every
    in-flight request was when the process went down."""
    with _lock:
        live = [c.to_dict() for c in _live.values()]
        recent = [dict(d) for d in list(_recent.values())[-32:]]
    return {"live": live, "recent": recent}


# ------------------------------------------------------- training steps
def record_train_step(site: str, iteration: int, t0: float,
                      t1: Optional[float] = None, **attrs) -> None:
    """One training step under the long-lived per-site train trace.
    Disabled cost: one module-attribute read."""
    if not _ENABLED:
        return
    with _lock:
        ctx = _train.get(site)
        if ctx is None:
            ctx = _train[site] = TraceContext("train",
                                              request_id=f"train:{site}",
                                              site=site)
        # (re-)insert newest every step: the _live registry evicts
        # oldest-first under request floods, and a never-finishing
        # train context must not be the permanent first casualty
        key = _key(ctx.request_id, ctx.trace_id)
        _live[key] = ctx
        _live.move_to_end(key)
    ctx.event("train_step", t0, t1, iteration=iteration, **attrs)


# --------------------------------------------------- host aggregation
def host_spans(max_events: int = 20_000) -> Dict[str, Any]:
    """Aggregate this host's span buffer per span name (count /
    total_ms / max_ms) — the compact unit that ships to a coordinator.
    Only the newest ``max_events`` trace events are copied and folded,
    bounding the cost of a /telemetry poll on a long-lived process."""
    events = _telemetry.recent_trace_events(max_events)
    agg: Dict[str, List[float]] = {}
    for e in events:
        a = agg.setdefault(e["name"], [0, 0.0, 0.0])
        dur = e.get("dur", 0.0) / 1e3
        a[0] += 1
        a[1] += dur
        a[2] = max(a[2], dur)
    return {
        "host": host_id(),
        "pid": os.getpid(),
        "wall_time": time.time(),
        "spans": {name: {"count": int(c), "total_ms": round(t, 3),
                         "max_ms": round(m, 3)}
                  for name, (c, t, m) in sorted(agg.items())},
    }


#: distinct remote hosts retained (bounded like every other registry
#: here — a restarting worker that changes ids must not grow the
#: coordinator forever)
_REMOTE_MAX = 64


def ingest_host_spans(summary: Dict[str, Any]) -> None:
    """Coordinator side of the aggregation path: store a worker host's
    span summary (POST /telemetry/spans on ui/server.py lands here).
    Oldest-ingested hosts are evicted past ``_REMOTE_MAX``."""
    if not isinstance(summary, dict) or "host" not in summary:
        raise ValueError("span summary must carry a 'host' id")
    key = str(summary["host"])
    with _lock:
        _remote_hosts.pop(key, None)
        _remote_hosts[key] = summary
        while len(_remote_hosts) > _REMOTE_MAX:
            _remote_hosts.pop(next(iter(_remote_hosts)))


def aggregate_hosts() -> Dict[str, Dict[str, Any]]:
    """Local + every ingested remote host, keyed by host id — the
    straggler view: compare each host's device_step / train_step
    totals in one table."""
    out = {str(host_id()): host_spans()}
    with _lock:
        for h, s in _remote_hosts.items():
            out.setdefault(h, s)
    return out


def push_spans(coordinator_url: str, host: Optional[int] = None,
               timeout: float = 10.0) -> None:
    """Worker-side push: POST this host's span aggregate to the
    coordinator's UI server (``/telemetry/spans``)."""
    summary = host_spans()
    if host is not None:
        summary["host"] = int(host)
    body = json.dumps(summary).encode()
    req = urllib.request.Request(
        coordinator_url.rstrip("/") + "/telemetry/spans", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        r.read()


# ------------------------------------------------------------ snapshot
def snapshot() -> Dict[str, Any]:
    """/telemetry + bench embedding: {} unless tracing is on or a
    remote host has pushed spans (peek-style — an untraced process
    pays nothing and shows nothing)."""
    with _lock:
        has_remote = bool(_remote_hosts)
    if not _ENABLED and not has_remote:
        return {}
    return {
        "enabled": _ENABLED,
        "host": host_id(),
        "live_requests": live_summaries(),
        "recent_requests": recent_summaries(16),
        "hosts": aggregate_hosts(),
    }


def reset() -> None:
    """Drop every registry (tests / between bench rounds). Leaves the
    enabled flag as configured."""
    with _lock:
        _live.clear()
        _recent.clear()
        _train.clear()
        _remote_hosts.clear()


__all__ = ["TraceContext", "new_trace", "finish_trace", "timeline",
           "recent_summaries", "live_summaries", "snapshot_requests",
           "record_train_step", "host_spans", "ingest_host_spans",
           "aggregate_hosts", "push_spans", "snapshot", "reset",
           "enabled", "set_enabled", "host_id"]
