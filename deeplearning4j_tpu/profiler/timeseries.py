"""Embedded time-series store: metrics history, PromQL-lite queries,
and worker metric federation.

Every earlier observability layer is point-in-time: ``/metrics`` shows
"now", the SLO engine's snapshot ring is private, PR 15 worker OS
processes are metric-invisible to their coordinator, and an incident
dump says *what* fired with no history to show *why*. This module
closes those gaps with a bounded in-process TSDB:

- **Sampler.** One daemon thread ("TSDBSampler") captures the whole
  ``MetricsRegistry`` (``registry.capture()``) at a fixed cadence and
  ingests it into the store. The capture is SHARED: the SLO engine
  subscribes to the sampler (``SLOEngine.attach_sampler``) instead of
  capturing privately, so two consumers cost one ``capture()`` per
  tick, and both see exactly the same snapshot (federated worker
  series included).
- **Store.** Per-series rings with downsampling tiers — raw (sampler
  cadence, ~10 min), 10 s (~1 h), 1 m (~24 h) — each tier keeps the
  LAST sample per aligned bucket (counters/gauges are level signals;
  last-wins loses no monotonic information and rate() still sees every
  reset that survives a tier's resolution). Histograms store the full
  ``(count, sum, bucket-counts)`` capture tuple per point, so windowed
  quantiles are exact bucket-delta reads at any tier. Retention is
  ring-bounded per tier; memory is O(series x points), independent of
  runtime.
- **PromQL-lite.** ``query()`` / ``query_range()`` evaluate a small
  expression grammar over the store:

  ``name{label="v",l2!="v",l3=~"regex",l4!~"re"}``
      instant vector — the newest sample per matching series within
      the staleness lookback (300 s), tombstoned series excluded.
  ``rate(sel[30s])``
      per-second increase over the window, counter-reset clamped
      (a reset contributes the post-reset value, never a negative) —
      the SAME delta semantics as the SLO engine's ``Rate`` rule.
      Needs >= 2 samples in the window. ``increase(sel[d])`` is the
      un-divided sum. On a histogram name, rates the cumulative count.
  ``histogram_quantile(0.99, name[60s])``
      windowed quantile over the PR 14 cumulative-bucket deltas
      (reset-clamped per pair of adjacent samples), computed by the
      ONE ``histogram_quantile()`` definition this module now owns
      and ``profiler/slo.py`` imports.
  ``avg by (engine) (expr)`` / ``sum``/``max``/``min``
      label-grouped aggregation over any of the above.

  ``name_count`` / ``name_sum`` resolve against a histogram series'
  cumulative count/sum (so ``rate(x_count[30s])`` is request rate and
  ``rate(x_sum[30s]) / rate(x_count[30s])`` is mean latency), exactly
  like the ``_count``/``_sum`` series ``to_prometheus`` exports.
- **Federation.** A PR 15 worker process publishes periodic encoded
  captures through its file-lease control dir (``metrics.json`` next
  to ``heartbeat.json``; ``push_metrics()`` is the HTTP fallback, like
  spans). The coordinator's WorkerSupervisor hands them to the
  sampler (``Sampler.ingest_remote``), which merges every fresh remote
  capture into each tick's snapshot under added ``worker=``/``host=``
  labels — so range queries AND SLO rules see the whole cluster, not
  just the coordinator process.
- **Tombstones.** ``telemetry.retire_engine_series`` tombstones the
  dead engine's gauge series here too (``tombstone_series``): history
  BEFORE the tombstone stays queryable, instant reads at or after it
  return nothing — a removed replica stops flat-lining in range
  queries instead of ghosting at its last value for the lookback.
- **Black box.** ``metrics_history_snapshot()`` exports the last N
  minutes of every series; ``profiler/flight_recorder.py`` embeds it
  as a digest-valid ``metrics.json`` member in every incident dump.

Served as ``GET /v1/query`` + ``GET /v1/query_range`` (Prometheus
HTTP API response shape) on both ``ui/server.py`` and
``remote/server.py``, with ``POST /v1/metrics/push`` for federation.

Off by default: ``DL4J_TPU_TSDB=0`` (the default) means
``ensure_default()`` is a no-op — zero sampler threads, zero ingest,
serving token-identical and fit loops bit-identical. ``=1`` opts in.
Overhead when on: one ``registry.capture()`` per cadence (shared with
the SLO engine) plus O(series) deque appends on the sampler thread —
nothing on any training or serving hot path.
"""

from __future__ import annotations

import collections
import json
import logging
import math
import os
import re
import threading
import time
import urllib.parse
import urllib.request
from typing import (
    Any, Callable, Dict, List, Optional, Sequence, Tuple,
)

from deeplearning4j_tpu.profiler import telemetry as _telemetry

log = logging.getLogger("deeplearning4j_tpu")

LabelKey = Tuple[Tuple[str, str], ...]

#: staleness lookback for instant vectors (Prometheus convention)
LOOKBACK_S = 300.0
#: downsampling tiers: (bucket resolution seconds, points kept).
#: 0 = raw (sampler cadence). At the default 1 s cadence: ~10 min raw,
#: ~1 h at 10 s, ~24 h at 1 m.
TIERS = ((0.0, 600), (10.0, 360), (60.0, 1440))
#: a worker capture older than this is ignored at merge time (a dead
#: or wedged worker must not freeze its last reading into every tick)
REMOTE_TTL_S = 15.0

_ENV = "DL4J_TPU_TSDB"
_enabled = os.environ.get(_ENV, "0") not in ("0", "", "false")


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


# ---------------------------------------------------------------- math
def histogram_quantile(bounds: Sequence[float],
                       counts: Sequence[float], q: float) \
        -> Optional[float]:
    """Prometheus-style quantile over NON-cumulative bucket counts
    (``counts`` has ``len(bounds) + 1`` entries; the last is the +Inf
    overflow). Linear interpolation inside the winning bucket; the
    +Inf bucket clamps to the top finite bound. None on an empty
    window. This is the ONE quantile definition in the repo —
    ``profiler/slo.py`` imports it, external scrapers reproduce it
    from the exported ``_bucket`` series."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        prev_cum, cum = cum, cum + c
        if cum >= rank:
            if i >= len(bounds):          # +Inf bucket
                return float(bounds[-1])
            lo = float(bounds[i - 1]) if i > 0 else 0.0
            hi = float(bounds[i])
            return lo + (hi - lo) * (rank - prev_cum) / c
    return float(bounds[-1])


# ---------------------------------------------------------------- store
class _Series:
    """One (metric, label-set)'s tiered history. Scalar series store
    floats; histogram series store (count, sum, bucket-counts)."""

    __slots__ = ("name", "key", "kind", "bounds", "tiers", "tomb")

    def __init__(self, name: str, key: LabelKey, kind: str,
                 bounds: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.key = key
        self.kind = kind
        self.bounds = bounds
        self.tiers: List[Tuple[float, collections.deque]] = [
            (res, collections.deque(maxlen=n)) for res, n in TIERS]
        self.tomb: Optional[float] = None

    def add(self, t: float, v: Any) -> None:
        for res, dq in self.tiers:
            if res <= 0:
                dq.append((t, v))
            elif dq and int(dq[-1][0] // res) == int(t // res):
                dq[-1] = (t, v)           # last sample wins the bucket
            else:
                dq.append((t, v))

    def samples(self, t0: float, t1: float) -> List[Tuple[float, Any]]:
        """Merged tier view over [t0, t1]: each finer tier masks the
        coarser ones over the span it still covers, so a query sees
        raw-resolution recent history backed by downsampled tails."""
        out: List[Tuple[float, Any]] = []
        cut = t1 + 1.0   # finer-tier coverage start; coarser fills below
        for _res, dq in self.tiers:
            pts = [p for p in dq if t0 <= p[0] <= t1 and p[0] < cut]
            out.extend(pts)
            if dq:
                cut = min(cut, dq[0][0])
        out.sort(key=lambda p: p[0])
        return out


class _Matcher:
    __slots__ = ("label", "op", "value", "rx")

    def __init__(self, label: str, op: str, value: str):
        self.label, self.op, self.value = label, op, value
        self.rx = (re.compile(value) if op in ("=~", "!~") else None)

    def ok(self, labels: Dict[str, str]) -> bool:
        got = labels.get(self.label, "")
        if self.op == "=":
            return got == self.value
        if self.op == "!=":
            return got != self.value
        if self.op == "=~":
            return self.rx.fullmatch(got) is not None
        return self.rx.fullmatch(got) is None     # !~


class TimeSeriesDB:
    """Bounded multi-tier store of registry captures (module
    docstring). Thread-safe: the sampler writes, HTTP handlers read."""

    def __init__(self):
        self._lock = threading.RLock()
        self._series: "collections.OrderedDict[Tuple[str, LabelKey], _Series]" = \
            collections.OrderedDict()

    # ---------------------------------------------------------- ingest
    def ingest(self, t: float, cap: Dict[str, Any]) -> None:
        """One registry capture (``MetricsRegistry.capture()`` shape)
        at wall-clock ``t``."""
        with self._lock:
            for name, m in cap.items():
                kind = m.get("kind")
                if kind == "histogram":
                    bounds = tuple(m["bounds"])
                    for key, tup in m["series"].items():
                        self._get(name, key, kind, bounds).add(
                            t, (float(tup[0]), float(tup[1]),
                                tuple(tup[2])))
                elif kind in ("counter", "gauge"):
                    for key, v in m["values"].items():
                        self._get(name, key, kind).add(t, float(v))

    def _get(self, name: str, key: LabelKey, kind: str,
             bounds: Optional[Tuple[float, ...]] = None) -> _Series:
        s = self._series.get((name, key))
        if s is None:
            s = self._series[(name, key)] = _Series(
                name, key, kind, bounds)
        elif s.tomb is not None:
            # the label set came back (a replica slot reused an id):
            # the series is live again from here on
            s.tomb = None
        return s

    # ------------------------------------------------------- tombstone
    def tombstone(self, label: str, value: str,
                  kinds: Tuple[str, ...] = ("gauge",),
                  t: Optional[float] = None) -> int:
        """Mark every series (of ``kinds``) whose labels carry
        ``label == value`` dead at ``t``: pre-death history stays
        queryable, instant reads at/after ``t`` return nothing."""
        if t is None:
            t = time.time()
        n = 0
        with self._lock:
            for (_name, key), s in self._series.items():
                if s.kind not in kinds or s.tomb is not None:
                    continue
                if dict(key).get(label) == str(value):
                    s.tomb = t
                    n += 1
        return n

    # --------------------------------------------------------- reading
    def select(self, name: str, matchers: Sequence[_Matcher],
               t0: float, t1: float, at: Optional[float] = None) \
            -> List[Tuple[Dict[str, str], str,
                          Optional[Tuple[float, ...]],
                          List[Tuple[float, Any]]]]:
        """[(labels, kind, bounds, samples in [t0, t1])] for every
        matching series; ``at`` (the evaluation instant) excludes
        series tombstoned at or before it."""
        out = []
        with self._lock:
            for (n, key), s in self._series.items():
                if n != name:
                    continue
                if at is not None and s.tomb is not None \
                        and at >= s.tomb:
                    continue
                labels = dict(key)
                if all(m.ok(labels) for m in matchers):
                    pts = s.samples(t0, t1)
                    if pts:
                        out.append((labels, s.kind, s.bounds, pts))
        return out

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def export(self, window_s: float = 300.0,
               now: Optional[float] = None,
               max_series: int = 256) -> Dict[str, Any]:
        """JSON-serializable slice of the last ``window_s`` seconds of
        every series — the flight recorder's ``metrics.json`` member.
        Bounded: at most ``max_series`` series (newest-registered
        last, which is what gets kept), ring-bounded points each."""
        if now is None:
            now = time.time()
        t0 = now - window_s
        out: List[Dict[str, Any]] = []
        with self._lock:
            items = list(self._series.items())
        truncated = max(len(items) - max_series, 0)
        for (name, key), s in items[-max_series:]:
            pts = s.samples(t0, now)
            if not pts:
                continue
            entry: Dict[str, Any] = {
                "name": name, "labels": dict(key), "kind": s.kind}
            if s.kind == "histogram":
                entry["bounds"] = list(s.bounds or ())
                entry["points"] = [
                    [t, [c, sm, list(b)]] for t, (c, sm, b) in pts]
            else:
                entry["points"] = [[t, v] for t, v in pts]
            if s.tomb is not None:
                entry["tombstone"] = s.tomb
            out.append(entry)
        res = {"window_s": window_s, "now": now, "series": out}
        if truncated:
            res["series_truncated"] = truncated
        return res

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


# --------------------------------------------------------------- parser
_TOKEN_RE = re.compile(
    r'\s*(=~|!~|!=|[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'|\d+\.?\d*(?:[eE][+-]?\d+)?'
    r'|"(?:[^"\\]|\\.)*"'
    r'|.)')
_AGG_OPS = ("sum", "avg", "max", "min")
_DUR_UNITS = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


class QueryError(ValueError):
    """Malformed PromQL-lite expression (HTTP 400)."""


def _tokenize(text: str) -> List[str]:
    out, pos = [], 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            break
        tok = m.group(1)
        pos = m.end()
        if tok.strip():
            out.append(tok)
    return out


class _Parser:
    def __init__(self, text: str):
        self.toks = _tokenize(text)
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> str:
        t = self.peek()
        if t is None:
            raise QueryError("unexpected end of query")
        self.i += 1
        return t

    def expect(self, tok: str) -> None:
        got = self.next()
        if got != tok:
            raise QueryError(f"expected {tok!r}, got {got!r}")

    # grammar: expr := agg | func | selector
    def parse(self) -> Tuple:
        node = self.expr()
        if self.peek() is not None:
            raise QueryError(f"trailing tokens at {self.peek()!r}")
        return node

    def expr(self) -> Tuple:
        t = self.peek()
        if t in _AGG_OPS:
            return self.agg()
        if t in ("rate", "increase"):
            op = self.next()
            self.expect("(")
            rng = self.range_selector()
            self.expect(")")
            return (op, rng)
        if t == "histogram_quantile":
            self.next()
            self.expect("(")
            q = self.number()
            self.expect(",")
            rng = self.range_selector()
            self.expect(")")
            if not 0.0 <= q <= 1.0:
                raise QueryError(f"quantile must be in [0, 1], got {q}")
            return ("quantile", q, rng)
        return ("selector",) + self.selector()

    def agg(self) -> Tuple:
        op = self.next()
        by: Optional[List[str]] = None
        if self.peek() == "by":
            self.next()
            self.expect("(")
            by = []
            while True:
                by.append(self.ident())
                if self.peek() == ",":
                    self.next()
                    continue
                break
            self.expect(")")
        self.expect("(")
        inner = self.expr()
        self.expect(")")
        return ("agg", op, by, inner)

    def range_selector(self) -> Tuple:
        name, matchers = self.selector()
        self.expect("[")
        dur = self.duration()
        self.expect("]")
        return (name, matchers, dur)

    def selector(self) -> Tuple[str, List[_Matcher]]:
        name = self.ident()
        matchers: List[_Matcher] = []
        if self.peek() == "{":
            self.next()
            while self.peek() != "}":
                label = self.ident()
                op = self.next()
                if op not in ("=", "!=", "=~", "!~"):
                    raise QueryError(f"bad label op {op!r}")
                val = self.string()
                try:
                    matchers.append(_Matcher(label, op, val))
                except re.error as e:
                    raise QueryError(f"bad regex {val!r}: {e}")
                if self.peek() == ",":
                    self.next()
            self.expect("}")
        return name, matchers

    def ident(self) -> str:
        t = self.next()
        if not re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", t):
            raise QueryError(f"expected identifier, got {t!r}")
        return t

    def number(self) -> float:
        t = self.next()
        try:
            return float(t)
        except ValueError:
            raise QueryError(f"expected number, got {t!r}")

    def string(self) -> str:
        t = self.next()
        if len(t) < 2 or t[0] != '"' or t[-1] != '"':
            raise QueryError(f"expected string, got {t!r}")
        return t[1:-1].replace('\\"', '"').replace("\\\\", "\\")

    def duration(self) -> float:
        n = self.number()
        if self.peek() in _DUR_UNITS:
            n *= _DUR_UNITS[self.next()]
        return n


def parse(text: str) -> Tuple:
    """Parse a PromQL-lite expression to an AST (raises QueryError)."""
    if not text or not text.strip():
        raise QueryError("empty query")
    return _Parser(text).parse()


# ------------------------------------------------------------ evaluator
def _series_for(db: TimeSeriesDB, name: str,
                matchers: Sequence[_Matcher], t0: float, t1: float,
                at: float):
    """Resolve ``name`` to scalar-valued series: direct counters /
    gauges as-is; ``X_count`` / ``X_sum`` against histogram ``X``'s
    cumulative count / sum. Returns [(labels, pts-of-float)]."""
    rows = db.select(name, matchers, t0, t1, at=at)
    out = [(labels, [(t, v) for t, v in pts])
           for labels, kind, _b, pts in rows if kind != "histogram"]
    for suffix, idx in (("_count", 0), ("_sum", 1)):
        if name.endswith(suffix):
            base = name[:-len(suffix)]
            for labels, kind, _b, pts in db.select(
                    base, matchers, t0, t1, at=at):
                if kind == "histogram":
                    out.append((labels,
                                [(t, v[idx]) for t, v in pts]))
    return out


def _hist_for(db: TimeSeriesDB, name: str,
              matchers: Sequence[_Matcher], t0: float, t1: float,
              at: float):
    return [(labels, bounds, pts)
            for labels, kind, bounds, pts in db.select(
                name, matchers, t0, t1, at=at)
            if kind == "histogram"]


def _increase(pts: List[Tuple[float, float]]) -> Optional[float]:
    """Counter increase over >= 2 samples, reset-clamped: a reset
    contributes the post-reset value (the counter restarted from 0),
    never a negative."""
    if len(pts) < 2:
        return None
    inc, prev = 0.0, pts[0][1]
    for _t, v in pts[1:]:
        inc += (v - prev) if v >= prev else v
        prev = v
    return inc


def _bucket_increase(pts) -> Optional[Tuple[float, float, List[float]]]:
    """(count-delta, sum-delta, per-bucket deltas) over a histogram
    window, walking adjacent samples so a mid-window counter reset
    clamps exactly like ``_increase``."""
    if len(pts) < 2:
        return None
    nb = len(pts[0][1][2])
    dcount = dsum = 0.0
    dbuckets = [0.0] * nb
    prev = pts[0][1]
    for _t, cur in pts[1:]:
        if cur[0] >= prev[0]:
            dcount += cur[0] - prev[0]
            dsum += max(cur[1] - prev[1], 0.0)
            for i in range(nb):
                dbuckets[i] += max(cur[2][i] - prev[2][i], 0.0)
        else:                               # reset: count restarted
            dcount += cur[0]
            dsum += cur[1]
            for i in range(nb):
                dbuckets[i] += cur[2][i]
        prev = cur
    return dcount, dsum, dbuckets


def _eval_instant(db: TimeSeriesDB, node: Tuple, t: float) \
        -> List[Tuple[Dict[str, str], float]]:
    op = node[0]
    if op == "selector":
        _op, name, matchers = node
        out = []
        for labels, pts in _series_for(
                db, name, matchers, t - LOOKBACK_S, t, t):
            out.append((labels, pts[-1][1]))
        return out
    if op in ("rate", "increase"):
        name, matchers, dur = node[1]
        out = []
        for labels, pts in _series_for(
                db, name, matchers, t - dur, t, t):
            inc = _increase(pts)
            if inc is None:
                continue
            if op == "rate":
                dt = pts[-1][0] - pts[0][0]
                if dt <= 0:
                    continue
                inc = inc / dt
            out.append((labels, inc))
        # a histogram name with no suffix rates its cumulative count
        for labels, _bounds, pts in _hist_for(
                db, name, matchers, t - dur, t, t):
            d = _bucket_increase(pts)
            if d is None:
                continue
            v = d[0]
            if op == "rate":
                dt = pts[-1][0] - pts[0][0]
                if dt <= 0:
                    continue
                v = v / dt
            out.append((labels, v))
        return out
    if op == "quantile":
        _op, q, (name, matchers, dur) = node
        out = []
        for labels, bounds, pts in _hist_for(
                db, name, matchers, t - dur, t, t):
            d = _bucket_increase(pts)
            if d is None or not bounds:
                continue
            v = histogram_quantile(bounds, d[2], q)
            if v is not None:
                out.append((labels, v))
        return out
    if op == "agg":
        _op, fn, by, inner = node
        vec = _eval_instant(db, inner, t)
        groups: Dict[LabelKey, List[float]] = {}
        for labels, v in vec:
            if by is None:
                key: LabelKey = ()
            else:
                key = tuple((b, labels.get(b, "")) for b in by)
            groups.setdefault(key, []).append(v)
        agg = {"sum": sum, "max": max, "min": min,
               "avg": lambda vs: sum(vs) / len(vs)}[fn]
        return [(dict(k), float(agg(vs))) for k, vs in groups.items()]
    raise QueryError(f"unknown node {op!r}")


def query(expr: str, t: Optional[float] = None,
          db: Optional[TimeSeriesDB] = None) \
        -> List[Tuple[Dict[str, str], float]]:
    """Instant query: [(labels, value)] at wall-clock ``t`` (now)."""
    if db is None:
        db = default_db()
    if db is None:
        return []
    if t is None:
        t = time.time()
    return _eval_instant(db, parse(expr), t)


def query_range(expr: str, start: float, end: float, step: float,
                db: Optional[TimeSeriesDB] = None) \
        -> List[Tuple[Dict[str, str], List[Tuple[float, float]]]]:
    """Range query: the instant expression evaluated at each step in
    [start, end]; series keyed by label set."""
    if db is None:
        db = default_db()
    if db is None:
        return []
    if step <= 0:
        raise QueryError("step must be > 0")
    if end < start:
        raise QueryError("end < start")
    if (end - start) / step > 11_000:
        raise QueryError("too many steps (limit 11000)")
    node = parse(expr)
    acc: "collections.OrderedDict[LabelKey, List[Tuple[float, float]]]" = \
        collections.OrderedDict()
    t = start
    while t <= end + 1e-9:
        for labels, v in _eval_instant(db, node, t):
            acc.setdefault(tuple(sorted(labels.items())), []).append(
                (t, v))
        t += step
    return [(dict(k), pts) for k, pts in acc.items()]


# ------------------------------------------------------------- sampler
class Sampler:
    """The one capture loop: ticks ``registry.capture()`` at
    ``interval_s``, merges fresh federated worker captures, ingests
    into the store, and fans the SAME snapshot out to subscribers
    (the SLO engine) — one capture per tick, however many consumers."""

    THREAD_NAME = "TSDBSampler"

    def __init__(self, db: Optional[TimeSeriesDB] = None,
                 registry: Optional[_telemetry.MetricsRegistry] = None,
                 interval_s: float = 1.0,
                 remote_ttl_s: float = REMOTE_TTL_S):
        self.db = db if db is not None else TimeSeriesDB()
        self.registry = (registry if registry is not None
                         else _telemetry.MetricsRegistry.get_default())
        self.interval_s = float(interval_s)
        self.remote_ttl_s = float(remote_ttl_s)
        self._lock = threading.Lock()
        self._subs: List[Callable[[float, float, Dict[str, Any]],
                                  Any]] = []
        #: worker -> (received_wall_t, extra_labels, capture)
        self._remote: Dict[str, Tuple[float, Dict[str, str],
                                      Dict[str, Any]]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.ticks = 0

    # -------------------------------------------------- subscriptions
    def subscribe(self, fn: Callable[[float, float, Dict[str, Any]],
                                     Any]) -> None:
        """``fn(t_monotonic, t_wall, capture)`` after every tick's
        ingest — the capture includes merged federated series."""
        with self._lock:
            if fn not in self._subs:
                self._subs.append(fn)

    def unsubscribe(self, fn) -> None:
        with self._lock:
            if fn in self._subs:
                self._subs.remove(fn)

    # ----------------------------------------------------- federation
    def ingest_remote(self, capture: Dict[str, Any], worker: str,
                      host: Optional[str] = None,
                      t: Optional[float] = None) -> None:
        """Accept one worker-process registry capture (control-dir
        file or HTTP push). Merged into subsequent ticks under added
        ``worker=`` / ``host=`` labels while fresher than the TTL."""
        if t is None:
            t = time.time()
        extra = {"worker": str(worker)}
        if host:
            extra["host"] = str(host)
        with self._lock:
            self._remote[str(worker)] = (t, extra, capture)

    def remote_workers(self) -> List[str]:
        with self._lock:
            return sorted(self._remote)

    def _merge_remote(self, cap: Dict[str, Any], now: float) \
            -> Dict[str, Any]:
        with self._lock:
            fresh = [(extra, rcap)
                     for _w, (t, extra, rcap) in self._remote.items()
                     if now - t <= self.remote_ttl_s]
        if not fresh:
            return cap
        merged: Dict[str, Any] = {}
        for name, m in cap.items():
            if m.get("kind") == "histogram":
                merged[name] = {"kind": "histogram",
                                "bounds": m["bounds"],
                                "series": dict(m["series"])}
            else:
                merged[name] = {"kind": m["kind"],
                                "values": dict(m["values"])}
        for extra, rcap in fresh:
            for name, m in rcap.items():
                kind = m.get("kind")
                dst = merged.get(name)
                if dst is None:
                    dst = merged[name] = (
                        {"kind": "histogram", "bounds": m["bounds"],
                         "series": {}}
                        if kind == "histogram"
                        else {"kind": kind, "values": {}})
                if dst["kind"] != kind:
                    continue            # cross-process kind clash
                if kind == "histogram":
                    if tuple(dst["bounds"]) != tuple(m["bounds"]):
                        continue        # incompatible bucket layouts
                    for key, tup in m["series"].items():
                        k2 = tuple(sorted(dict(key, **extra).items()))
                        dst["series"][k2] = tup
                else:
                    for key, v in m["values"].items():
                        k2 = tuple(sorted(dict(key, **extra).items()))
                        dst["values"][k2] = v
        return merged

    # ------------------------------------------------------ lifecycle
    def tick_once(self, now_mono: Optional[float] = None,
                  now_wall: Optional[float] = None) -> Dict[str, Any]:
        """One synchronous sample: capture, merge, ingest, notify.
        Tests drive the whole pipeline with fake clocks through this."""
        if now_mono is None:
            now_mono = time.monotonic()
        if now_wall is None:
            now_wall = time.time()
        cap = self._merge_remote(self.registry.capture(), now_wall)
        self.db.ingest(now_wall, cap)
        self.ticks += 1
        with self._lock:
            subs = list(self._subs)
        for fn in subs:
            try:
                fn(now_mono, now_wall, cap)
            except Exception:
                log.exception("TSDB sampler subscriber failed")
        return cap

    def start(self) -> "Sampler":
        with self._lock:
            if self._thread is not None:
                return self
            if self._stop.is_set():
                raise RuntimeError("sampler has been shut down")
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name=self.THREAD_NAME)
            self._thread.start()
        return self

    def shutdown(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick_once()
            except Exception:
                log.exception("TSDB sampler tick failed")
            self._stop.wait(self.interval_s)


# --------------------------------------------- capture (de)serialization
def encode_capture(cap: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-shaped registry capture (tuple label keys become pair
    lists) — the federation wire/file format."""
    out: Dict[str, Any] = {}
    for name, m in cap.items():
        if m.get("kind") == "histogram":
            out[name] = {
                "kind": "histogram", "bounds": list(m["bounds"]),
                "series": [[[list(p) for p in k],
                            [c, s, list(b)]]
                           for k, (c, s, b) in m["series"].items()]}
        else:
            out[name] = {
                "kind": m["kind"],
                "values": [[[list(p) for p in k], v]
                           for k, v in m["values"].items()]}
    return out


def decode_capture(obj: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of ``encode_capture`` (back to registry-capture shape).
    Malformed metrics are skipped, never raised — a torn federation
    file must not take down the coordinator's sampler."""
    out: Dict[str, Any] = {}
    for name, m in obj.items():
        try:
            kind = m["kind"]
            if kind == "histogram":
                out[name] = {
                    "kind": "histogram",
                    "bounds": tuple(float(b) for b in m["bounds"]),
                    "series": {
                        tuple(tuple(p) for p in k):
                        (float(v[0]), float(v[1]),
                         tuple(float(x) for x in v[2]))
                        for k, v in m["series"]}}
            elif kind in ("counter", "gauge"):
                out[name] = {
                    "kind": kind,
                    "values": {tuple(tuple(p) for p in k): float(v)
                               for k, v in m["values"]}}
        except Exception:
            continue
    return out


def push_metrics(coordinator_url: str, worker: str,
                 host: Optional[str] = None,
                 registry: Optional[_telemetry.MetricsRegistry] = None,
                 timeout: float = 2.0) -> bool:
    """HTTP federation fallback (like ``tracing.push_spans``): POST
    this process's capture to the coordinator's
    ``POST /v1/metrics/push``. Returns success; never raises."""
    reg = (registry if registry is not None
           else _telemetry.MetricsRegistry.get_default())
    body = json.dumps({
        "worker": str(worker), "host": host, "t": time.time(),
        "capture": encode_capture(reg.capture())}).encode()
    url = coordinator_url.rstrip("/") + "/v1/metrics/push"
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"})
    try:
        urllib.request.urlopen(req, timeout=timeout).read()
        return True
    except Exception as e:
        log.debug("metrics push to %s failed: %s", url, e)
        return False


def ingest_push(payload: Dict[str, Any]) -> bool:
    """Coordinator-side handler body for ``POST /v1/metrics/push``:
    hand the pushed capture to the default sampler. False when no
    sampler is live (TSDB off) or the payload is malformed."""
    sampler = default_sampler()
    if sampler is None:
        return False
    try:
        worker = str(payload["worker"])
        cap = decode_capture(payload.get("capture") or {})
    except Exception:
        return False
    if not cap:
        return False
    sampler.ingest_remote(cap, worker, host=payload.get("host"))
    return True


# ----------------------------------------------------- default instance
_default_db: Optional[TimeSeriesDB] = None
_default_sampler: Optional[Sampler] = None
_dlock = threading.Lock()


def default_db() -> Optional[TimeSeriesDB]:
    return _default_db


def default_sampler() -> Optional[Sampler]:
    return _default_sampler


def install(db: Optional[TimeSeriesDB],
            sampler: Optional[Sampler] = None) -> None:
    """Make (db, sampler) the process defaults (tests / embedders)."""
    global _default_db, _default_sampler
    with _dlock:
        _default_db = db
        _default_sampler = sampler


def ensure_default(registry=None, interval_s: float = 1.0) \
        -> Optional[Sampler]:
    """Start the default sampler if the TSDB is enabled — idempotent;
    a no-op returning None when ``DL4J_TPU_TSDB`` is off (zero new
    threads, bit-identical hot paths). Called by the ui / remote
    servers at start so an opted-in process gets history without
    extra wiring. If a default SLO engine exists, it is attached to
    the sampler so both share one capture per tick."""
    global _default_db, _default_sampler
    if not enabled():
        return None
    with _dlock:
        if _default_sampler is None:
            db = _default_db if _default_db is not None \
                else TimeSeriesDB()
            _default_db = db
            _default_sampler = Sampler(
                db=db, registry=registry, interval_s=interval_s)
        sampler = _default_sampler
    try:
        from deeplearning4j_tpu.profiler import slo as _slo
        eng = _slo.default_engine()
        if eng is not None:
            eng.attach_sampler(sampler)
    except Exception:
        pass
    sampler.start()
    return sampler


def shutdown_default(timeout: float = 10.0) -> None:
    global _default_db, _default_sampler
    with _dlock:
        sampler, _default_sampler = _default_sampler, None
        _default_db = None
    if sampler is not None:
        sampler.shutdown(timeout)


def tombstone_series(label: str, value: str,
                     kinds: Tuple[str, ...] = ("gauge",)) -> int:
    """Tombstone matching series in the default store (the
    ``telemetry.retire_engine_series`` hook). 0 when no store."""
    db = default_db()
    if db is None:
        return 0
    return db.tombstone(label, value, kinds=kinds)


def metrics_history_snapshot(window_s: float = 300.0) \
        -> Dict[str, Any]:
    """Peek-style export of the default store's recent history ({}
    when the TSDB is off) — what the flight recorder embeds as
    ``metrics.json`` in incident dumps."""
    db = default_db()
    if db is None:
        return {}
    snap = db.export(window_s=window_s)
    return snap if snap.get("series") else {}


# ------------------------------------------------------------ HTTP glue
def _json_num(v: float) -> str:
    # Prometheus renders sample values as strings (lossless for NaN
    # and infinities, which JSON numbers can't carry)
    if v != v:
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    return repr(float(v))


def _http_error(msg: str, code: int) -> Tuple[Dict[str, Any], int]:
    return ({"status": "error", "error": msg}, code)


def _parse_qs(qs: str) -> Dict[str, str]:
    return {k: v[-1] for k, v in
            urllib.parse.parse_qs(qs, keep_blank_values=True).items()}


def http_query(qs: str) -> Tuple[Dict[str, Any], int]:
    """Shared ``GET /v1/query`` handling for ui/server.py and
    remote/server.py: ``query=<expr>&time=<unix>``. Prometheus HTTP
    API response shape. Returns (obj, http_code)."""
    if default_db() is None:
        return _http_error(
            "time-series store is off (set DL4J_TPU_TSDB=1 and "
            "restart, or call profiler.timeseries.ensure_default())",
            404)
    params = _parse_qs(qs)
    expr = params.get("query", "")
    try:
        node = parse(expr)
        t = float(params["time"]) if "time" in params else time.time()
        vec = _eval_instant(default_db(), node, t)
    except QueryError as e:
        return _http_error(str(e), 400)
    except ValueError:
        return _http_error("bad time parameter", 400)
    name = node[1] if node[0] == "selector" else None
    result = []
    for labels, v in vec:
        metric = dict(labels)
        if name:
            metric["__name__"] = name
        result.append({"metric": metric, "value": [t, _json_num(v)]})
    return ({"status": "success",
             "data": {"resultType": "vector", "result": result}}, 200)


def http_query_range(qs: str) -> Tuple[Dict[str, Any], int]:
    """Shared ``GET /v1/query_range``:
    ``query=<expr>&start=<unix>&end=<unix>&step=<s>``."""
    if default_db() is None:
        return _http_error(
            "time-series store is off (set DL4J_TPU_TSDB=1 and "
            "restart, or call profiler.timeseries.ensure_default())",
            404)
    params = _parse_qs(qs)
    expr = params.get("query", "")
    try:
        start = float(params["start"])
        end = float(params["end"])
        step = float(params.get("step", "1"))
    except (KeyError, ValueError):
        return _http_error(
            "query_range needs start=, end= (unix seconds) and "
            "numeric step=", 400)
    try:
        rows = query_range(expr, start, end, step)
    except QueryError as e:
        return _http_error(str(e), 400)
    result = [{"metric": dict(labels),
               "values": [[t, _json_num(v)] for t, v in pts]}
              for labels, pts in rows]
    return ({"status": "success",
             "data": {"resultType": "matrix", "result": result}}, 200)


def snapshot() -> Dict[str, Any]:
    """Peek-style embedding for telemetry.snapshot() ({} when off)."""
    db, sampler = default_db(), default_sampler()
    if db is None:
        return {}
    out: Dict[str, Any] = {"series": db.series_count()}
    if sampler is not None:
        out["ticks"] = sampler.ticks
        out["interval_s"] = sampler.interval_s
        workers = sampler.remote_workers()
        if workers:
            out["federated_workers"] = workers
    return out


__all__ = [
    "TimeSeriesDB", "Sampler", "QueryError",
    "histogram_quantile", "parse", "query", "query_range",
    "encode_capture", "decode_capture", "push_metrics", "ingest_push",
    "default_db", "default_sampler", "install", "ensure_default",
    "shutdown_default", "tombstone_series", "metrics_history_snapshot",
    "http_query", "http_query_range", "snapshot",
    "enabled", "set_enabled", "LOOKBACK_S", "TIERS", "REMOTE_TTL_S",
]
