"""Profiling / numerics-panic instrumentation.

Reference: org/nd4j/linalg/profiler/{OpProfiler,ProfilerConfig} — per-op
timing histograms and NAN_PANIC/INF_PANIC/ANY_PANIC modes that assert-
check every op output (SURVEY.md §5); libnd4j graph/profiling/
{GraphProfile,NodeProfile}.

TPU redesign: the reference times each op at its JNI dispatch; here the
whole step is ONE XLA executable, so "per-op wall time" lives in the
XLA profile, not Python. The split is:

- `start_trace`/`stop_trace` — jax.profiler traces (XLA per-op/HLO
  timing; open in TensorBoard/xprof). This is the NodeProfile analog.
- `OpProfiler` — graph-dispatch census (how many times each registered
  op is EMITTED/traced; invocation counts + host wall-clock of eager
  registry calls) plus step-level timings via `ProfilerListener`.
- panic modes — `check_numerics(tree)` host assertion used by the
  training front-ends each iteration when enabled (the score is always
  checked; ANY_PANIC also sweeps params — documented cost).
"""

from __future__ import annotations

import collections
import contextlib
import enum
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops import registry as _registry
from deeplearning4j_tpu.profiler import flight_recorder, telemetry, tracing
from deeplearning4j_tpu.profiler.model_health import HealthMonitor


def __getattr__(name):
    # slo/programs/timeseries are LAZY attributes (PEP 562): the fit
    # loops and serving engines import this package for telemetry, and
    # the off-mode contract is that they never pull in the SLO engine,
    # the program registry, or the time-series store
    if name in ("slo", "programs", "timeseries"):
        import importlib

        return importlib.import_module(
            f"deeplearning4j_tpu.profiler.{name}")
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


class ProfilerMode(enum.Enum):
    DISABLED = "disabled"
    OPERATIONS = "operations"     # count + time registry dispatches
    NAN_PANIC = "nan_panic"
    INF_PANIC = "inf_panic"
    ANY_PANIC = "any_panic"       # NaN or Inf, sweep params too


class ProfilerConfig:
    def __init__(self, mode: ProfilerMode = ProfilerMode.DISABLED,
                 check_params: bool = False):
        self.mode = mode
        self.check_params = check_params or mode is ProfilerMode.ANY_PANIC


class OpProfiler:
    """Singleton (reference: OpProfiler.getInstance())."""

    _instance: Optional["OpProfiler"] = None

    def __init__(self):
        self.config = ProfilerConfig()
        # honor the DL4J_TPU_PANIC env default (reference: ND4J panic
        # modes via system properties — see common/environment.py)
        from deeplearning4j_tpu.common.environment import Environment

        panic = Environment.getInstance().panicMode()
        if panic:
            mode = {"nan": ProfilerMode.NAN_PANIC,
                    "inf": ProfilerMode.INF_PANIC,
                    "any": ProfilerMode.ANY_PANIC}.get(panic)
            if mode is not None:
                self.config = ProfilerConfig(mode=mode)
        self.invocations: Dict[str, int] = collections.Counter()
        self.total_time: Dict[str, float] = collections.defaultdict(float)
        self._orig_get_op = None

    @classmethod
    def getInstance(cls) -> "OpProfiler":
        if cls._instance is None:
            cls._instance = OpProfiler()
        return cls._instance

    # -- registry instrumentation --------------------------------------
    def applyConfig(self, config: ProfilerConfig) -> None:
        self.config = config
        if config.mode is ProfilerMode.OPERATIONS:
            self._install()
        else:
            self._uninstall()

    def _install(self) -> None:
        if self._orig_get_op is not None:
            return
        self._orig_get_op = _registry.get_op
        prof = self

        def profiled_get_op(name):
            fn = prof._orig_get_op(name)

            def wrapped(*a, **k):
                t0 = time.perf_counter()
                out = fn(*a, **k)
                prof.invocations[name] += 1
                prof.total_time[name] += time.perf_counter() - t0
                return out

            return wrapped

        _registry.get_op = profiled_get_op

    def _uninstall(self) -> None:
        if self._orig_get_op is not None:
            _registry.get_op = self._orig_get_op
            self._orig_get_op = None

    def reset(self) -> None:
        self.invocations.clear()
        self.total_time.clear()

    def printOutDashboard(self) -> str:
        lines = ["OpProfiler (dispatch census — XLA fuses execution; "
                 "use start_trace for device timing):"]
        for name, n in sorted(self.invocations.items(),
                              key=lambda kv: -self.total_time[kv[0]]):
            lines.append(f"  {name:<30} calls={n:<8} "
                         f"host_time={self.total_time[name]*1e3:.2f}ms")
        return "\n".join(lines)


# ---------------------------------------------------------------- panics
class NumericsException(ArithmeticError):
    pass


def check_numerics(tree, mode: ProfilerMode, context: str = "") -> None:
    """Host-side NaN/Inf assertion over a pytree (reference: the panic
    modes' per-op output checks, applied per-step here).

    Panic-mode cost is ONE device->host transfer per call: floating
    leaves are fetched together via a single ``jax.device_get`` (a
    per-leaf ``np.asarray`` would sync the pipeline once per leaf —
    ruinous over a remote/tunneled accelerator), and the NaN/Inf flags
    are reduced across all leaves before raising."""
    if mode in (ProfilerMode.DISABLED, ProfilerMode.OPERATIONS):
        return
    float_leaves = []
    for leaf in jax.tree_util.tree_leaves(tree):
        dt = getattr(leaf, "dtype", None)
        if dt is None:
            leaf = np.asarray(leaf)
            dt = leaf.dtype
        # jnp.issubdtype, not np: bfloat16 (ml_dtypes) must be swept too
        if jnp.issubdtype(dt, jnp.floating):
            float_leaves.append(leaf)
    if not float_leaves:
        return
    host = jax.device_get(float_leaves)
    check_nan = mode in (ProfilerMode.NAN_PANIC, ProfilerMode.ANY_PANIC)
    check_inf = mode in (ProfilerMode.INF_PANIC, ProfilerMode.ANY_PANIC)
    has_nan = has_inf = False
    for a in host:
        a = np.asarray(a)
        if a.dtype not in (np.float16, np.float32, np.float64):
            a = a.astype(np.float32)   # extended dtypes lack isnan ufuncs
        if check_nan and np.isnan(a).any():
            has_nan = True
        if check_inf and np.isinf(a).any():
            has_inf = True
    if has_nan:
        raise NumericsException(f"NaN detected {context}")
    if has_inf:
        raise NumericsException(f"Inf detected {context}")


# ------------------------------------------------------------ XLA traces
def start_trace(log_dir: str) -> bool:
    """Start a jax.profiler trace (per-op HLO timing — the reference's
    NodeProfile equivalent, viewable in xprof/TensorBoard).

    Routed through ``programs.ProfileSession`` — the process has ONE
    jax.profiler trace slot shared with managed ``capture()`` bundles,
    and a second ``jax.profiler.start_trace`` raises RuntimeError from
    inside XLA. Idempotent-with-warning: returns False (no-op) when a
    trace or managed capture is already active, True when this call
    started the trace."""
    from deeplearning4j_tpu.profiler import programs

    return programs.profile_session().start_manual(log_dir)


def stop_trace() -> bool:
    """Stop an ad-hoc trace started by :func:`start_trace`. No-op
    (False) when none is active — a managed capture in flight is never
    stopped from here."""
    from deeplearning4j_tpu.profiler import programs

    return programs.profile_session().stop_manual()


@contextlib.contextmanager
def trace(log_dir: str):
    """Context-managed trace, exception-safe against a FAILED start: a
    start that did not take the trace slot (already active, or
    jax.profiler refused) is not stopped on exit, so an outer trace or
    capture keeps running."""
    started = start_trace(log_dir)
    try:
        yield
    finally:
        if started:
            stop_trace()


__all__ = ["OpProfiler", "ProfilerConfig", "ProfilerMode",
           "NumericsException", "check_numerics", "start_trace",
           "stop_trace", "trace", "telemetry", "HealthMonitor",
           "tracing", "flight_recorder", "slo", "programs",
           "timeseries"]
