"""Peak-FLOPs table + dtype-aware lookup — the MFU denominator.

Hoisted out of bench_common so the LIVE fit loops (profiler/
model_health.py) and the bench scripts share one table: an MFU number
is only comparable if both sides divide by the same peak.
bench_common re-exports ``PEAK_FLOPS``/``peak_flops`` from here, so
existing imports keep working.

A single bf16 number would silently inflate (f32 workload / bf16 peak)
or deflate MFU; the dtype key makes the denominator match the
numerator's math. f32 on the v5e MXU runs at ~half bf16 rate
(multi-pass emulation).
"""

from __future__ import annotations

import logging

log = logging.getLogger("deeplearning4j_tpu")

#: per-chip peak FLOPs keyed by device kind AND compute dtype.
PEAK_FLOPS = {
    "TPU v5 lite": {"bf16": 197e12, "f32": 98.5e12},
}

#: per-chip peak HBM bandwidth in GB/s (decimal GB) keyed by device
#: kind — the roofline's second axis (profiler/programs.py). Bandwidth
#: does not depend on compute dtype, so this table is flat.
PEAK_HBM_GBPS = {
    "TPU v5 lite": 819.0,
}

_warned_unknown_peak = set()
_warned_unknown_hbm = set()


def peak_flops(dtype="bf16"):
    """Peak FLOPs of device 0 for a compute dtype ("bf16"/"f32", any
    DataType.from_any spelling). Unknown devices return None with a
    logged warning — callers then skip MFU (the measured
    cost_analysis FLOPs still get reported), rather than dividing by a
    wrong peak and publishing a silently bogus MFU."""
    import jax

    from deeplearning4j_tpu.ndarray.dtypes import DataType

    kind = jax.devices()[0].device_kind
    entry = PEAK_FLOPS.get(kind)
    if entry is None:
        if kind not in _warned_unknown_peak:
            _warned_unknown_peak.add(kind)
            log.warning(
                "no peak-FLOPs entry for device kind %r — MFU will be "
                "omitted (cost_analysis FLOPs are still measured); add "
                "the chip to profiler.flops.PEAK_FLOPS to enable it",
                kind)
        return None
    dt = DataType.from_any(dtype)
    key = "bf16" if dt.width_bytes() == 2 else "f32"
    return entry.get(key)


def peak_hbm_gbps():
    """Peak HBM bandwidth (GB/s) of device 0. Same contract as
    ``peak_flops``: unknown devices return None with a warn-once log —
    callers then omit achieved-bandwidth/roofline numbers rather than
    publish them against a wrong peak."""
    import jax

    kind = jax.devices()[0].device_kind
    bw = PEAK_HBM_GBPS.get(kind)
    if bw is None and kind not in _warned_unknown_hbm:
        _warned_unknown_hbm.add(kind)
        log.warning(
            "no peak-HBM-bandwidth entry for device kind %r — roofline "
            "verdicts fall back to nominal ratios and achieved-GB/s is "
            "reported without a utilization figure; add the chip to "
            "profiler.flops.PEAK_HBM_GBPS to enable it", kind)
    return bw


__all__ = ["PEAK_FLOPS", "PEAK_HBM_GBPS", "peak_flops", "peak_hbm_gbps"]
