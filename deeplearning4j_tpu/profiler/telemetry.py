"""Unified telemetry spine: metrics registry, host spans, recompile
detector, device-memory watermarks.

Reference mapping (SURVEY.md §2.23, §5): the reference spreads
observability over OpProfiler/ProfilerConfig (per-op timing),
PerformanceListener (throughput lines) and the UI StatsListener
(histograms to StatsStorage). On TPU the facts that matter most are
different — jit-cache misses and compile time, device-memory
watermarks, and the ETL-wait vs device-step split — so this module is
the single process-wide sink every layer reports through:

- ``MetricsRegistry`` — thread-safe counters / gauges / bounded
  histograms with percentile summaries; Prometheus text exposition
  (``to_prometheus``) and JSON dump (``to_json``). Served by
  ``ui/server.py`` at ``/metrics`` and ``/telemetry``.
- ``span()`` — nestable host-side timing context manager. Events land
  in a bounded trace buffer and export as Chrome trace-event JSON
  (``export_chrome_trace``; loadable in perfetto / chrome://tracing),
  complementing ``jax.profiler`` DEVICE traces with the HOST story.
- ``instrument_jit(site, fn)`` — recompilation detector. Wraps a
  jitted callable; a growing executable cache (``_cache_size``) marks
  a compile, which is counted + timed per site, and shape/dtype churn
  (a "recompile storm") logs a loud warning with the offending
  signatures.
- ``sample_device_memory()`` — per-step device watermark gauges from
  ``device.memory_stats()`` (graceful no-op on backends that don't
  report, e.g. CPU).

Everything here is host-side and cheap by construction: a disabled
check is one attribute read; an enabled step records a few
``perf_counter`` deltas and deque appends — never a device sync.

Env: ``DL4J_TPU_TELEMETRY=0`` disables recording (default on; ``=1``
forces on), ``DL4J_TPU_RECOMPILE_STORM_THRESHOLD`` (default 5) sets
the distinct-signature count per site that triggers the storm warning.
"""

from __future__ import annotations

import bisect
import collections
import contextlib
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

log = logging.getLogger("deeplearning4j_tpu")

_ENABLED = os.environ.get("DL4J_TPU_TELEMETRY", "1") != "0"
_T0 = time.perf_counter()   # trace-timestamp epoch (µs since import)

#: canonical metric names (acceptance surface — keep stable)
JIT_COMPILES = "dl4j_tpu_jit_compiles_total"
JIT_COMPILE_SECONDS = "dl4j_tpu_jit_compile_seconds"
STEP_PHASE_SECONDS = "dl4j_tpu_step_phase_seconds"
DEVICE_BYTES_IN_USE = "dl4j_tpu_device_bytes_in_use"
DEVICE_PEAK_BYTES = "dl4j_tpu_device_peak_bytes_in_use"
#: device input pipeline (datasets/device_prefetch.py)
PREFETCH_QUEUE_DEPTH = "dl4j_tpu_prefetch_queue_depth"
TRANSFER_OVERLAP_MS = "dl4j_tpu_prefetch_transfer_overlap_ms"
PREFETCH_PADDED_EXAMPLES = "dl4j_tpu_prefetch_padded_examples_total"
BUCKET_HITS = "dl4j_tpu_shape_bucket_hits_total"
BUCKET_MISSES = "dl4j_tpu_shape_bucket_misses_total"
ON_DEVICE_BATCHES = "dl4j_tpu_on_device_batches_total"
#: mixed-precision engine (nn/precision.py)
LOSS_SCALE = "dl4j_tpu_loss_scale"
LOSS_SCALE_OVERFLOWS = "dl4j_tpu_loss_scale_overflows_total"
LOSS_SCALE_SKIPPED_STEPS = "dl4j_tpu_loss_scale_skipped_steps_total"
PRECISION_CASTS = "dl4j_tpu_precision_casts_per_step"
#: fault tolerance (util/resilience.py, profiler/chaos.py)
FT_ROLLBACKS = "dl4j_tpu_ft_rollbacks_total"
FT_SKIPPED_BATCHES = "dl4j_tpu_ft_skipped_batches_total"
FT_PREEMPTION_CHECKPOINTS = "dl4j_tpu_ft_preemption_checkpoints_total"
FT_PERIODIC_CHECKPOINTS = "dl4j_tpu_ft_periodic_checkpoints_total"
FT_AUTO_RESUMES = "dl4j_tpu_ft_auto_resumes_total"
TRANSFER_RETRIES = "dl4j_tpu_transfer_retries_total"
TRANSFER_QUARANTINES = "dl4j_tpu_transfer_quarantined_batches_total"
WATCHDOG_STALLS = "dl4j_tpu_watchdog_stalls_total"
CHAOS_INJECTED = "dl4j_tpu_chaos_injected_total"
#: cross-replica update sharding (parallel/zero.py)
MASTER_PARAM_BYTES = "dl4j_tpu_master_param_bytes"
OPT_STATE_BYTES = "dl4j_tpu_opt_state_bytes"
#: in-step model health (profiler/model_health.py)
LAYER_GRAD_NORM = "dl4j_tpu_layer_grad_norm"
LAYER_PARAM_NORM = "dl4j_tpu_layer_param_norm"
UPDATE_RATIO = "dl4j_tpu_update_ratio"
NONFINITE_FIRST_LAYER = "dl4j_tpu_nonfinite_first_layer"
MFU = "dl4j_tpu_mfu"
STEP_FLOPS = "dl4j_tpu_step_flops"
HEALTH_FETCHES = "dl4j_tpu_health_fetches_total"
#: continuous-batching decode engine (serving/engine.py)
SERVING_REQUESTS = "dl4j_tpu_serving_requests_total"
SERVING_TOKENS = "dl4j_tpu_serving_tokens_total"
SERVING_REQUEST_LATENCY = "dl4j_tpu_serving_request_latency_seconds"
SERVING_TTFT = "dl4j_tpu_serving_ttft_seconds"
SERVING_QUEUE_DEPTH = "dl4j_tpu_serving_queue_depth"
SERVING_SLOT_OCCUPANCY = "dl4j_tpu_serving_slot_occupancy"
SERVING_KV_PAGE_UTILIZATION = "dl4j_tpu_serving_kv_page_utilization"
SERVING_KV_PAGE_BYTES = "dl4j_tpu_serving_kv_page_bytes"
SERVING_WARM_HITS = "dl4j_tpu_serving_warm_pool_hits_total"
SERVING_WARM_MISSES = "dl4j_tpu_serving_warm_pool_misses_total"
SERVING_DECODE_STEPS = "dl4j_tpu_serving_decode_steps_total"
SERVING_DECODE_STEP_SECONDS = "dl4j_tpu_serving_decode_step_seconds"
SERVING_PREFILL_SECONDS = "dl4j_tpu_serving_prefill_seconds"
#: speculative decoding (serving/spec_decode.py): drafts proposed /
#: accepted, the cumulative acceptance-rate + tokens-per-weight-read
#: gauges, and the verify-dispatch latency histogram
SERVING_SPEC_PROPOSED = "dl4j_tpu_serving_spec_proposed_tokens_total"
SERVING_SPEC_ACCEPTED = "dl4j_tpu_serving_spec_accepted_tokens_total"
SERVING_SPEC_ACCEPTANCE = "dl4j_tpu_serving_spec_acceptance_rate"
SERVING_TOKENS_PER_DISPATCH = "dl4j_tpu_serving_tokens_per_dispatch"
SERVING_VERIFY_SECONDS = "dl4j_tpu_serving_verify_seconds"
#: cross-request KV reuse (serving/prefix_cache.py, sessions.py)
SERVING_PREFIX_HITS = "dl4j_tpu_serving_prefix_cache_hits_total"
SERVING_PREFIX_MISSES = "dl4j_tpu_serving_prefix_cache_misses_total"
SERVING_PREFIX_HIT_TOKENS = \
    "dl4j_tpu_serving_prefix_cache_hit_tokens_total"
SERVING_PREFIX_EVICTED_PAGES = \
    "dl4j_tpu_serving_prefix_cache_evicted_pages_total"
SERVING_PREFIX_CACHED_PAGES = "dl4j_tpu_serving_prefix_cached_pages"
SERVING_SHARED_PAGES = "dl4j_tpu_serving_shared_kv_pages"
SERVING_PINNED_PAGES = "dl4j_tpu_serving_session_pinned_pages"
SERVING_SESSION_EVICTIONS = \
    "dl4j_tpu_serving_session_evictions_total"
SERVING_WARM_TTFT = "dl4j_tpu_serving_warm_ttft_seconds"
#: serving fleet (serving/fleet.py) — every SERVING_* series above is
#: also labelled ``engine=<id>`` so N engines in one process stay
#: distinguishable; these are the fleet-level series
SERVING_REJECTS = "dl4j_tpu_serving_capacity_rejects_total"
SERVING_FLEET_ROUTED = "dl4j_tpu_serving_fleet_routed_total"
SERVING_FLEET_REROUTES = "dl4j_tpu_serving_fleet_reroutes_total"
SERVING_FLEET_REPLICAS = "dl4j_tpu_serving_fleet_live_replicas"
SERVING_LANE_PREFILLS = "dl4j_tpu_serving_prefill_lane_prefills_total"
SERVING_LANE_SECONDS = "dl4j_tpu_serving_prefill_lane_seconds"
SERVING_HANDOFF_SECONDS = "dl4j_tpu_serving_handoff_seconds"
SERVING_FLEET_PRESSURE = "dl4j_tpu_serving_fleet_queue_pressure"
SERVING_FLEET_SIZE = "dl4j_tpu_serving_fleet_size"
SERVING_FLEET_PENDING_SCALE = "dl4j_tpu_serving_fleet_pending_scale"
#: queued dynamic-batching inference (parallel/wrapper.py)
INFERENCE_REQUEST_LATENCY = "dl4j_tpu_inference_request_latency_seconds"
INFERENCE_QUEUE_DEPTH = "dl4j_tpu_inference_queue_depth"
INFERENCE_BATCH_OCCUPANCY = "dl4j_tpu_inference_batch_occupancy"
#: tracing + flight recorder (profiler/tracing.py, flight_recorder.py)
SPANS_DROPPED = "dl4j_tpu_spans_dropped_total"
INCIDENT_DUMPS = "dl4j_tpu_incident_dumps_total"
#: elastic control plane (control/scheduler.py) — one JobScheduler
#: owning a device fleet and running many train/serve jobs over it
JOBS_SUBMITTED = "dl4j_tpu_jobs_submitted_total"
JOBS_FINISHED = "dl4j_tpu_jobs_finished_total"
JOBS_RESTARTS = "dl4j_tpu_jobs_restarts_total"
JOBS_MIGRATIONS = "dl4j_tpu_jobs_migrations_total"
JOBS_RUNNING = "dl4j_tpu_jobs_running"
JOBS_DEVICES = "dl4j_tpu_jobs_devices"
JOBS_THROUGHPUT = "dl4j_tpu_job_throughput"
JOBS_MFU = "dl4j_tpu_job_mfu"
JOBS_LATENCY_P50 = "dl4j_tpu_job_request_p50_ms"
#: control plane phase 2 (control/worker.py, preemption notices)
JOBS_PREEMPTIONS = "dl4j_tpu_jobs_preemptions_total"
#: control plane phase 3 (alert-driven fleet elasticity)
FLEET_SCALE_UP = "dl4j_tpu_fleet_scale_up_total"
FLEET_SCALE_DOWN = "dl4j_tpu_fleet_scale_down_total"
WORKER_PROCESSES = "dl4j_tpu_worker_processes"
WORKER_HEARTBEAT_AGE = "dl4j_tpu_worker_heartbeat_age_seconds"
FT_BUNDLE_IO_RETRIES = "dl4j_tpu_ft_bundle_io_retries_total"
#: SLO / alerting engine (profiler/slo.py)
ALERTS_TOTAL = "dl4j_tpu_alerts_total"
ALERTS_ACTIVE = "dl4j_tpu_alerts_active"
#: managed device-profile captures (profiler/programs.py)
PROFILE_CAPTURES = "dl4j_tpu_profile_captures_total"


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


# ---------------------------------------------------------------- utils
def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_labels(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    esc = lambda v: v.replace("\\", "\\\\").replace('"', '\\"') \
                     .replace("\n", "\\n")
    return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in key) + "}"


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _fmt_value(v: float) -> str:
    """Prometheus sample-value rendering: the exposition format spells
    non-finite values ``NaN`` / ``+Inf`` / ``-Inf`` (python's ``%g``
    gives ``nan`` / ``inf``, which real scrapers reject)."""
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return f"{v:g}"


def _escape_help(text: str) -> str:
    """# HELP escaping per the exposition format: backslash and
    newline only (quotes are NOT escaped in help text)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


# -------------------------------------------------------------- metrics
class Counter:
    """Monotonic counter, optionally labelled (one value per label set)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[Tuple, float] = collections.defaultdict(float)

    def inc(self, n: float = 1.0, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] += n

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def values(self) -> Dict[Tuple[Tuple[str, str], ...], float]:
        """Copy of every label set's value, insertion-ordered."""
        with self._lock:
            return dict(self._values)

    def remove_matching(self, label: str, value: str) -> int:
        """Drop every label set where ``label == value`` (stale-series
        expiry: a shut-down engine's gauges must not stay frozen at
        their last reading forever). Returns the number dropped."""
        with self._lock:
            dead = [k for k in self._values
                    if dict(k).get(label) == value]
            for k in dead:
                del self._values[k]
            return len(dead)

    def _capture(self) -> Dict[str, Any]:
        """Point-in-time raw values for windowed evaluation
        (profiler/slo.py)."""
        with self._lock:
            return {"kind": self.kind, "values": dict(self._values)}

    def _expose(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [f"{self.name}{_fmt_labels(k)} {_fmt_value(v)}"
                for k, v in items]

    def _json(self) -> Any:
        with self._lock:
            return {(_fmt_labels(k) or "total"): v
                    for k, v in self._values.items()}


class Gauge(Counter):
    """Last-write-wins value; supports inc/dec via set()."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(v)


#: default cumulative-bucket bounds (seconds — latency-shaped; +Inf is
#: implicit). Shared by external scrapers and the SLO engine, so both
#: compute the SAME quantile from the same bucket counts.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class Histogram:
    """Cumulative-bucket histogram with a bounded sample reservoir.

    Exposed as a proper Prometheus ``histogram``: cumulative
    ``_bucket{le=...}`` series (``+Inf`` = count) plus ``_sum`` /
    ``_count`` — external scrapers run ``histogram_quantile()`` over
    exactly the bucket counts the in-process SLO engine windows
    (profiler/slo.py), so there is ONE quantile definition, not two.
    The reservoir (last ``max_samples`` observations per label set)
    additionally feeds the exact-percentile summaries in
    ``percentiles()`` / the JSON dump, which dashboards read."""

    kind = "histogram"
    QUANTILES = (0.5, 0.9, 0.99)

    def __init__(self, name: str, help: str = "", max_samples: int = 2048,
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.help = help
        self.max_samples = max_samples
        self.bounds: Tuple[float, ...] = tuple(
            sorted(buckets if buckets is not None else DEFAULT_BUCKETS))
        self._lock = threading.Lock()
        self._buf: Dict[Tuple, collections.deque] = {}
        self._count: Dict[Tuple, int] = collections.defaultdict(int)
        self._sum: Dict[Tuple, float] = collections.defaultdict(float)
        #: per-label NON-cumulative bucket counts, len(bounds)+1 (the
        #: last slot is the +Inf overflow); cumulated at exposure
        self._buckets: Dict[Tuple, List[int]] = {}

    def _bucket_index(self, v: float) -> int:
        return bisect.bisect_left(self.bounds, v)

    def observe(self, v: float, **labels) -> None:
        key = _label_key(labels)
        v = float(v)
        with self._lock:
            buf = self._buf.get(key)
            if buf is None:
                buf = self._buf[key] = collections.deque(
                    maxlen=self.max_samples)
                self._buckets[key] = [0] * (len(self.bounds) + 1)
            buf.append(v)
            self._count[key] += 1
            self._sum[key] += v
            # NaN never lands in a le-bound bucket (Prometheus drops it
            # from _bucket too); it still counts in _count/_sum
            if v == v:
                self._buckets[key][self._bucket_index(v)] += 1

    def count(self, **labels) -> int:
        with self._lock:
            return self._count.get(_label_key(labels), 0)

    def sum(self, **labels) -> float:
        with self._lock:
            return self._sum.get(_label_key(labels), 0.0)

    def percentiles(self, **labels) -> Dict[str, float]:
        key = _label_key(labels)
        with self._lock:
            vals = sorted(self._buf.get(key, ()))
        return {f"p{int(q * 100)}": _percentile(vals, q)
                for q in self.QUANTILES}

    def remove_matching(self, label: str, value: str) -> int:
        """Drop every label set where ``label == value`` (see
        Counter.remove_matching)."""
        with self._lock:
            dead = [k for k in self._buf
                    if dict(k).get(label) == value]
            for k in dead:
                del self._buf[k]
                self._count.pop(k, None)
                self._sum.pop(k, None)
                self._buckets.pop(k, None)
            return len(dead)

    def _capture(self) -> Dict[str, Any]:
        """Point-in-time (count, sum, bucket-counts) per label set for
        windowed evaluation (profiler/slo.py). Bucket counts are the
        NON-cumulative per-bucket tallies; windowed quantiles come from
        their deltas between two captures."""
        with self._lock:
            return {"kind": self.kind, "bounds": self.bounds,
                    "series": {k: (self._count[k], self._sum[k],
                                   tuple(self._buckets.get(
                                       k, (0,) * (len(self.bounds) + 1))))
                               for k in self._buf}}

    def _expose(self) -> List[str]:
        out: List[str] = []
        with self._lock:
            keys = sorted(self._buf)
            snap = {k: (list(self._buckets.get(
                            k, (0,) * (len(self.bounds) + 1))),
                        self._count[k], self._sum[k])
                    for k in keys}
        for k, (buckets, cnt, tot) in snap.items():
            cum = 0
            for bound, n in zip(self.bounds, buckets):
                cum += n
                bk = k + (("le", f"{bound:g}"),)
                out.append(f"{self.name}_bucket{_fmt_labels(bk)} {cum}")
            bk = k + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_fmt_labels(bk)} "
                       f"{cum + buckets[-1]}")
            out.append(f"{self.name}_count{_fmt_labels(k)} {cnt}")
            out.append(f"{self.name}_sum{_fmt_labels(k)} {_fmt_value(tot)}")
        return out

    def _json(self) -> Any:
        with self._lock:
            keys = sorted(self._buf)
            snap = {k: (sorted(self._buf[k]), self._count[k], self._sum[k])
                    for k in keys}
        return {(_fmt_labels(k) or "total"): dict(
                    count=cnt, sum=tot,
                    **{f"p{int(q * 100)}": _percentile(vals, q)
                       for q in self.QUANTILES})
                for k, (vals, cnt, tot) in snap.items()}


class MetricsRegistry:
    """Process-wide named-metric registry (one default instance; tests
    may build private ones). get-or-create accessors are idempotent and
    thread-safe; a name registered as one kind cannot be re-registered
    as another."""

    _default: Optional["MetricsRegistry"] = None
    _default_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()

    @classmethod
    def get_default(cls) -> "MetricsRegistry":
        with cls._default_lock:
            if cls._default is None:
                cls._default = MetricsRegistry()
            return cls._default

    def _get(self, name: str, factory: Callable, kind: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif m.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"not {kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help), "counter")

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help), "gauge")

    def histogram(self, name: str, help: str = "",
                  max_samples: int = 2048,
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        return self._get(
            name, lambda: Histogram(name, help, max_samples, buckets),
            "histogram")

    def peek(self, name: str):
        """The metric if it exists, else None — a read that never
        creates (snapshot assembly must not pollute /metrics with
        empty series)."""
        with self._lock:
            return self._metrics.get(name)

    def capture(self) -> Dict[str, Any]:
        """Point-in-time raw capture of every metric — counters/gauges
        as per-label values, histograms as (count, sum, bucket counts)
        — the SLO engine's snapshot-ring unit (profiler/slo.py).
        Each metric is captured under its own lock; the capture is
        internally consistent per metric, not across metrics (windowed
        deltas don't need cross-metric atomicity)."""
        with self._lock:
            metrics = list(self._metrics.items())
        return {name: m._capture() for name, m in metrics}

    def remove_matching(self, label: str, value: str,
                        kinds: Optional[Tuple[str, ...]] = None) -> int:
        """Drop every label set with ``label == value`` across all
        metrics (optionally restricted to ``kinds``). Returns the
        number of series removed — the stale-series expiry a dying
        engine runs so its gauges don't haunt /metrics forever."""
        with self._lock:
            metrics = list(self._metrics.values())
        n = 0
        for m in metrics:
            if kinds is not None and m.kind not in kinds:
                continue
            n += m.remove_matching(label, str(value))
        return n

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4: every metric gets
        a ``# HELP`` (escaped) and ``# TYPE`` pair, label values are
        escaped, non-finite samples render as NaN/+Inf/-Inf — real
        scrapers ingest the output unmodified."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.append(f"# HELP {m.name} "
                         f"{_escape_help(m.help)}".rstrip())
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m._expose())
        return "\n".join(lines) + "\n"

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            metrics = list(self._metrics.items())
        return {name: {"kind": m.kind, "help": m.help, "values": m._json()}
                for name, m in metrics}

    def reset(self) -> None:
        """Drop every metric (tests / between bench rounds)."""
        with self._lock:
            self._metrics.clear()


# ---------------------------------------------------------------- spans
_trace_lock = threading.Lock()
_trace_events: collections.deque = collections.deque(maxlen=50_000)
_span_stack = threading.local()
#: events evicted by the bounded buffer since the last flush into the
#: SPANS_DROPPED counter (guarded by _trace_lock — the hot path pays
#: one int increment, not a registry lookup per wrapped span)
_spans_dropped_pending = 0


def _now_us() -> float:
    return (time.perf_counter() - _T0) * 1e6


def record_span(name: str, t0: float, t1: Optional[float] = None,
                metric: Optional[str] = None, **attrs) -> None:
    """Record one completed host span. ``t0``/``t1`` are
    ``time.perf_counter()`` readings; ``metric`` names a histogram in
    the default registry that receives the duration in SECONDS, with
    ``attrs`` as its labels."""
    if not _ENABLED:
        return
    if t1 is None:
        t1 = time.perf_counter()
    ev = {
        "name": name,
        "ph": "X",
        "ts": (t0 - _T0) * 1e6,
        "dur": max(t1 - t0, 0.0) * 1e6,
        "pid": os.getpid(),
        "tid": threading.get_ident(),
    }
    if attrs:
        ev["args"] = {k: v for k, v in attrs.items()}
    global _spans_dropped_pending
    with _trace_lock:
        if _trace_events.maxlen is not None \
                and len(_trace_events) == _trace_events.maxlen:
            # the bounded buffer wrapped: the oldest event is gone, so
            # exports from here on are TRUNCATED — count it (flushed
            # into the SPANS_DROPPED counter at export time) so an
            # incomplete trace is attributable, not silently short
            _spans_dropped_pending += 1
        _trace_events.append(ev)
    if metric is not None:
        # depth/parent describe span nesting, not a metric dimension —
        # letting them through would explode the label cardinality
        labels = {k: str(v) for k, v in attrs.items()
                  if k not in ("depth", "parent")}
        MetricsRegistry.get_default().histogram(metric).observe(
            t1 - t0, **labels)


@contextlib.contextmanager
def span(name: str, metric: Optional[str] = None, **attrs):
    """Nestable host-side timing span. Nesting is tracked per thread
    and recorded as a ``depth``/``parent`` arg on the trace event, so
    perfetto's flame view reconstructs the stack from the complete
    ('X') events."""
    if not _ENABLED:
        yield
        return
    stack = getattr(_span_stack, "names", None)
    if stack is None:
        stack = _span_stack.names = []
    attrs = dict(attrs)
    attrs["depth"] = len(stack)
    if stack:
        attrs["parent"] = stack[-1]
    stack.append(name)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        stack.pop()
        record_span(name, t0, metric=metric, **attrs)


def record_phase(phase: str, t0: float, t1: Optional[float] = None,
                 **attrs) -> None:
    """Step-phase helper: span + ``dl4j_tpu_step_phase_seconds`` sample
    labelled ``phase=...`` (etl_wait / device_step / listener_host)."""
    record_span(phase, t0, t1, metric=STEP_PHASE_SECONDS, phase=phase,
                **attrs)


def record_on_device_batch(site: str) -> None:
    """Count a batch that arrived in a fit loop already device-resident
    (the device prefetcher transferred it ahead of time), so the
    per-step host->device copy was skipped."""
    if not _ENABLED:
        return
    MetricsRegistry.get_default().counter(
        ON_DEVICE_BATCHES,
        "batches that arrived already device-resident (prefetched) and "
        "skipped the fit loop's host->device copy").inc(site=site)


def record_state_bytes(master_bytes: int, opt_bytes: int, mode: str,
                       site: str = "sharded") -> None:
    """Per-device fp32-master and optimizer-state byte gauges, labelled
    by sharding mode ('replicated' vs 'update_sharded') — set once at
    trainer placement time, so the 1/N memory win of update sharding is
    a measured number on /telemetry, not a claim."""
    if not _ENABLED:
        return
    reg = MetricsRegistry.get_default()
    reg.gauge(MASTER_PARAM_BYTES,
              "per-device bytes of master (update-precision) params"
              ).set(master_bytes, mode=mode, site=site)
    reg.gauge(OPT_STATE_BYTES,
              "per-device bytes of optimizer (updater) state"
              ).set(opt_bytes, mode=mode, site=site)


#: engines whose per-engine series were retired at shutdown — keeps
#: serving_snapshot()'s live-engine list honest while their COUNTERS
#: (monotonic history) stay in the registry so fleet aggregates remain
#: correct
_retired_engines: set = set()
_retired_lock = threading.Lock()


def retire_engine_series(engine_id: str) -> int:
    """Stale-series expiry for a shut-down decode engine: drop every
    GAUGE series labelled ``engine=<id>`` (queue depth, slot occupancy,
    KV-page utilization, shared/pinned pages — values that are only
    meaningful for a LIVE engine and would otherwise stay frozen at
    their last reading forever, poisoning ``serving_snapshot()``,
    ``/metrics`` scrapes, and SLO threshold rules with ghost engines).
    Counters and histograms are retained: they are cumulative history,
    so fleet-level aggregates (requests served, latency distributions)
    stay correct, and windowed SLO rules see zero deltas from a dead
    engine — which is exactly 'no data', not a stuck value.
    Idempotent. Returns the number of series dropped."""
    eid = str(engine_id)
    n = MetricsRegistry.get_default().remove_matching(
        "engine", eid, kinds=("gauge",))
    with _retired_lock:
        _retired_engines.add(eid)
    # tombstone the same series in the time-series store, if one is
    # live in this process — without this a removed replica's gauges
    # keep answering instant/range queries at their last reading for
    # the whole staleness lookback (the zombie the registry expiry
    # above exists to kill). sys.modules-guarded: retiring an engine
    # must not pay the import when nothing ever enabled the TSDB.
    import sys
    _ts = sys.modules.get("deeplearning4j_tpu.profiler.timeseries")
    if _ts is not None:
        try:
            _ts.tombstone_series("engine", eid, kinds=("gauge",))
        except Exception:
            pass
    return n


def retired_engines() -> frozenset:
    with _retired_lock:
        return frozenset(_retired_engines)


def timed_batches(iterable):
    """Iterate, recording time blocked on ``next()`` as the
    ``etl_wait`` phase — the one ETL-timing loop every fit front-end
    shares (MultiLayerNetwork keeps its own variant because it also
    feeds the UI's ``_last_etl_ms``)."""
    it = iter(iterable)
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            return
        record_phase("etl_wait", t0)
        yield item


def flush_dropped_spans() -> None:
    """Fold pending buffer-wrap evictions into the SPANS_DROPPED
    counter. Called by every export path (chrome_trace, snapshot, the
    UI's /metrics handler) so scrapes are exact without the record
    hot path paying a registry lookup per wrapped span."""
    global _spans_dropped_pending
    with _trace_lock:
        n, _spans_dropped_pending = _spans_dropped_pending, 0
    if n:
        MetricsRegistry.get_default().counter(
            SPANS_DROPPED,
            "trace events evicted when the bounded span buffer "
            "wrapped (exports are truncated past this point)").inc(n)


def recent_trace_events(n: int) -> List[Dict[str, Any]]:
    """The newest ``n`` trace events, copying only that slice (a full
    ``chrome_trace()`` copies the whole 50k-event buffer under the
    lock — too heavy for per-poll aggregation)."""
    import itertools

    with _trace_lock:
        k = len(_trace_events)
        if k <= n:
            return list(_trace_events)
        return list(itertools.islice(_trace_events, k - n, k))


def chrome_trace() -> Dict[str, Any]:
    """Chrome trace-event JSON object (perfetto / chrome://tracing).
    When the bounded buffer has wrapped, ``otherData.spans_dropped``
    says how many events the export is missing."""
    flush_dropped_spans()
    with _trace_lock:
        events = list(_trace_events)
    out: Dict[str, Any] = {"traceEvents": events, "displayTimeUnit": "ms"}
    m = MetricsRegistry.get_default().peek(SPANS_DROPPED)
    if m is not None and m.total() > 0:
        out["otherData"] = {"spans_dropped": m.total()}
    return out


def export_chrome_trace(path: str) -> str:
    with open(path, "w") as f:
        json.dump(chrome_trace(), f)
    return path


def clear_trace() -> None:
    with _trace_lock:
        _trace_events.clear()


# -------------------------------------------------- recompile detector
def _storm_threshold() -> int:
    try:
        return max(2, int(os.environ.get(
            "DL4J_TPU_RECOMPILE_STORM_THRESHOLD", "5")))
    except ValueError:
        return 5


def _arg_signature(args, kwargs) -> str:
    """Compact shape/dtype signature of a call, for storm diagnostics
    (NOT the compile trigger — the executable cache is). Arrays render
    as dtype[shape]; everything else by type."""
    import jax

    parts = []
    leaves = jax.tree_util.tree_leaves((args, kwargs))
    for l in leaves[:64]:
        if hasattr(l, "shape") and hasattr(l, "dtype"):
            parts.append(f"{l.dtype}{list(l.shape)}")
        else:
            parts.append(type(l).__name__)
    if len(leaves) > 64:
        parts.append(f"...+{len(leaves) - 64}")
    return ",".join(parts)


class _InstrumentedJit:
    """Transparent wrapper around a ``jax.jit`` callable that counts
    and times executable-cache misses (trace + XLA compile + first
    run). Attribute access (``lower``, ``clear_cache``, …) passes
    through, so AOT cost analysis and existing callers see the
    underlying jitted function unchanged.

    Probes: ``cache`` (default) reads the pjit executable-cache size —
    exact, but inert when the callable is only ever invoked under a
    transformation trace (``jax.vjp`` over the jitted fn never grows
    it); ``signature`` counts the first call per distinct shape/dtype
    signature instead — use it for sites that are exclusively
    vjp/grad-driven."""

    def __init__(self, site: str, fn: Callable, probe: str = "cache"):
        self._site = site
        self._fn = fn
        self._sigs: List[str] = []
        self._sig_flops: Dict[str, float] = {}   # per-executable FLOPs
        self._warned_at = 0
        self._has_cache_probe = (probe == "cache"
                                 and hasattr(fn, "_cache_size"))

    # pass-through for .lower(), .clear_cache(), etc.
    def __getattr__(self, name):
        return getattr(self._fn, name)

    @property
    def compiles(self) -> int:
        return len(self._sigs)

    def __call__(self, *args, **kwargs):
        if not _ENABLED:
            return self._fn(*args, **kwargs)
        from deeplearning4j_tpu.profiler import model_health, programs

        # FLOPs attribution (the MFU numerator) is off — one bool + a
        # set lookup — until a HealthMonitor exists, and limited to the
        # train-step sites MFU reads; when on, the call's signature
        # keys the per-EXECUTABLE cost so coexisting executables (shape
        # buckets, ragged batches) each charge their own FLOPs
        capture = model_health.wants_flops(self._site)
        # program registry (roofline attribution): opt-in, any site
        prog_on = programs.enabled()
        sig = (_arg_signature(args, kwargs)
               if (capture or prog_on or not self._has_cache_probe)
               else None)
        before = self._fn._cache_size() if self._has_cache_probe else -1
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        t1 = time.perf_counter()
        if self._has_cache_probe:
            compiled = self._fn._cache_size() > before
        else:
            # fallback probe: first call with an unseen signature
            compiled = sig not in self._sigs
        if compiled:
            if sig is None:
                sig = _arg_signature(args, kwargs)
            self._record_compile(t0, t1, sig)
            if capture:
                # the lower().compile() inside hits the XLA cache the
                # call above just populated: one abstract trace, not a
                # second compile
                f = model_health.capture_flops(
                    self._site, self._fn, args, kwargs)
                if f:
                    self._sig_flops[sig] = f
            if prog_on:
                # same cache-hitting relower as capture_flops
                programs.on_jit_compile(self._site, self._fn, args,
                                        kwargs, sig, t1 - t0)
        if capture:
            # executables compiled before capture was enabled have no
            # per-sig entry; the site's latest capture is the best
            # remaining estimate (None only before any capture)
            f = self._sig_flops.get(sig) \
                or model_health.site_flops(self._site)
            if f:
                model_health.add_dispatched_flops(self._site, f)
        if prog_on:
            # the compile call's wall time is compile, not execution —
            # count it but don't time it
            programs.record_dispatch(
                self._site, sig, None if compiled else t1 - t0)
        return out

    def _record_compile(self, t0: float, t1: float, sig: str) -> None:
        self._sigs.append(sig)
        reg = MetricsRegistry.get_default()
        reg.counter(JIT_COMPILES,
                    "jit executable-cache misses (trace+compile)"
                    ).inc(site=self._site)
        reg.histogram(JIT_COMPILE_SECONDS,
                      "wall time of jit-cache-miss calls "
                      "(trace + XLA compile + first run)"
                      ).observe(t1 - t0, site=self._site)
        record_span(f"jit_compile:{self._site}", t0, t1, site=self._site,
                    signature=sig)
        n = len(self._sigs)
        threshold = _storm_threshold()
        # warn at the threshold, then at every doubling (storms keep
        # shouting; a stable site that legitimately sees a handful of
        # shapes goes quiet)
        if n >= threshold and n >= max(self._warned_at * 2, threshold):
            self._warned_at = n
            recent = "; ".join(self._sigs[-3:])
            bucket_hits = reg.counter(BUCKET_HITS).total()
            bucket_misses = reg.counter(BUCKET_MISSES).total()
            if bucket_hits + bucket_misses == 0:
                remedy = ("input-pipeline shape bucketing is OFF — "
                          "enable it: wrap the iterator in "
                          "DevicePrefetchIterator(policy=BatchShape"
                          "Policy('bucket')) (docs/INPUT_PIPELINE.md)")
            else:
                # the bucket counters are process-global, not per-site:
                # another model's pipeline may be the bucketed one
                remedy = ("shape bucketing is active SOMEWHERE in this "
                          "process (%d bucket misses / %d hits) but "
                          "this site still churns — if this site's "
                          "iterator is not behind a bucketed "
                          "DevicePrefetchIterator, enable it there; "
                          "otherwise check the bucket boundaries"
                          % (bucket_misses, bucket_hits))
            log.warning(
                "RECOMPILE STORM at jit site %r: %d compiles (shape/"
                "dtype churn). Each distinct input shape/dtype traces "
                "and compiles a fresh XLA executable — pad or bucket "
                "batches to stable shapes: %s. Recent signatures: %s",
                self._site, n, remedy, recent)


def instrument_jit(site: str, fn: Callable,
                   probe: str = "cache") -> Callable:
    """Wrap a jitted callable with the recompilation detector."""
    return _InstrumentedJit(site, fn, probe)


# ------------------------------------------------ device-memory marks
_mem_supported: Optional[bool] = None


def sample_device_memory(device=None, force=False) -> Dict[str, Any]:
    """Read ``device.memory_stats()`` into watermark gauges. Returns
    the raw sample, or {} when the backend doesn't report (CPU) — the
    not-supported verdict is cached (default device only) so the
    steady-state no-op is one attribute read. An EXCEPTION from the
    probe is treated as transient and never latches the verdict; an
    explicit ``device`` argument bypasses the cache entirely.

    ``force=True`` samples even with telemetry disabled (the gauges are
    then left untouched) — for callers like StatsListener whose memory
    report must survive DL4J_TPU_TELEMETRY=0."""
    global _mem_supported
    if (not _ENABLED and not force) \
            or (device is None and _mem_supported is False):
        return {}
    import jax

    try:
        d = device if device is not None else jax.local_devices()[0]
        ms = d.memory_stats()
    except Exception:
        return {}
    if not ms:
        if device is None:
            _mem_supported = False   # backend affirmatively reports none
        return {}
    if device is None:
        _mem_supported = True
    if _ENABLED:
        reg = MetricsRegistry.get_default()
        dev = str(getattr(d, "id", 0))
        if ms.get("bytes_in_use") is not None:
            reg.gauge(DEVICE_BYTES_IN_USE,
                      "current device bytes in use").set(
                ms["bytes_in_use"], device=dev)
        if ms.get("peak_bytes_in_use") is not None:
            reg.gauge(DEVICE_PEAK_BYTES,
                      "peak device bytes in use (watermark)").set(
                ms["peak_bytes_in_use"], device=dev)
    return dict(ms)


# ------------------------------------------------------------ snapshot
def snapshot() -> Dict[str, Any]:
    """Compile counts/times + memory watermarks for embedding in bench
    rounds (BENCH_*.json) and the ``/telemetry`` endpoint."""
    reg = MetricsRegistry.get_default()
    compiles = reg.counter(JIT_COMPILES)
    seconds = reg.histogram(JIT_COMPILE_SECONDS)
    with compiles._lock:
        sites = [dict(k) for k in compiles._values]
    per_site = {}
    for labels in sites:
        site = labels.get("site", "?")
        per_site[site] = {
            "compiles": compiles.value(site=site),
            "compile_seconds": seconds.sum(site=site),
        }
    flush_dropped_spans()
    out: Dict[str, Any] = {
        "jit_compiles_total": compiles.total(),
        "jit_compile_seconds_total": sum(
            s["compile_seconds"] for s in per_site.values()),
        "per_site": per_site,
    }
    mem = sample_device_memory()
    if mem:
        out["device_memory"] = {
            "bytes_in_use": mem.get("bytes_in_use"),
            "peak_bytes_in_use": mem.get("peak_bytes_in_use"),
        }
    health = model_health_snapshot()
    if health:
        out["model_health"] = health
    state_bytes = {}
    for key, name in (("master_param_bytes", MASTER_PARAM_BYTES),
                      ("opt_state_bytes", OPT_STATE_BYTES)):
        m = reg.peek(name)
        if m is not None:
            state_bytes[key] = m._json()
    if state_bytes:
        out["state_bytes"] = state_bytes
    serving = serving_snapshot()
    if serving:
        out["serving"] = serving
    # per-request tracing + flight recorder (lazy imports: both modules
    # import telemetry; both snapshots are peek-style {} when inactive)
    try:
        from deeplearning4j_tpu.profiler import (
            flight_recorder as _flight, tracing as _tracing,
        )

        tr = _tracing.snapshot()
        if tr:
            out["tracing"] = tr
        fl = _flight.snapshot()
        if fl:
            out["flight_recorder"] = fl
    except Exception:
        pass
    # control plane (lazy + peek-style like tracing/flight: {} unless a
    # JobScheduler is live in this process)
    try:
        from deeplearning4j_tpu import control as _control

        js = _control.jobs_snapshot()
        if js:
            out["jobs"] = js
    except Exception:
        pass
    # SLO / alerting engine (lazy + peek-style: {} unless an SLOEngine
    # is live in this process)
    try:
        from deeplearning4j_tpu.profiler import slo as _slo

        al = _slo.alerts_snapshot()
        if al:
            out["alerts"] = al
    except Exception:
        pass
    # roofline program registry (lazy + peek-style: {} until a program
    # has registered — see profiler/programs.py)
    try:
        from deeplearning4j_tpu.profiler import programs as _programs

        pr = _programs.snapshot()
        if pr:
            out["programs"] = pr
    except Exception:
        pass
    # time-series store (lazy + peek-style: {} unless DL4J_TPU_TSDB
    # opted a sampler in — see profiler/timeseries.py)
    try:
        from deeplearning4j_tpu.profiler import timeseries as _ts

        th = _ts.snapshot()
        if th:
            out["timeseries"] = th
    except Exception:
        pass
    m = reg.peek(PROFILE_CAPTURES)
    if m is not None:
        out["profile_captures"] = m._json()
    return out


def serving_snapshot() -> Dict[str, Any]:
    """Latest serving-engine metrics (request latency / TTFT summaries,
    queue depth, slot occupancy, KV-page utilization, warm-pool hit
    rate) as plain JSON, or {} when no engine has published. peek-only:
    assembling the snapshot never creates empty series."""
    reg = MetricsRegistry.get_default()
    out: Dict[str, Any] = {}
    for key, name in (("requests_total", SERVING_REQUESTS),
                      ("tokens_total", SERVING_TOKENS),
                      ("request_latency", SERVING_REQUEST_LATENCY),
                      ("ttft", SERVING_TTFT),
                      ("queue_depth", SERVING_QUEUE_DEPTH),
                      ("slot_occupancy", SERVING_SLOT_OCCUPANCY),
                      ("kv_page_utilization",
                       SERVING_KV_PAGE_UTILIZATION),
                      ("kv_page_bytes", SERVING_KV_PAGE_BYTES),
                      ("warm_pool_hits", SERVING_WARM_HITS),
                      ("warm_pool_misses", SERVING_WARM_MISSES),
                      ("decode_steps", SERVING_DECODE_STEPS),
                      ("spec_proposed_tokens", SERVING_SPEC_PROPOSED),
                      ("spec_accepted_tokens", SERVING_SPEC_ACCEPTED),
                      ("spec_acceptance_rate", SERVING_SPEC_ACCEPTANCE),
                      ("tokens_per_dispatch",
                       SERVING_TOKENS_PER_DISPATCH),
                      ("verify_seconds", SERVING_VERIFY_SECONDS),
                      ("prefix_cache_hits", SERVING_PREFIX_HITS),
                      ("prefix_cache_misses", SERVING_PREFIX_MISSES),
                      ("prefix_cache_hit_tokens",
                       SERVING_PREFIX_HIT_TOKENS),
                      ("prefix_cached_pages",
                       SERVING_PREFIX_CACHED_PAGES),
                      ("prefix_cache_evicted_pages",
                       SERVING_PREFIX_EVICTED_PAGES),
                      ("shared_kv_pages", SERVING_SHARED_PAGES),
                      ("session_pinned_pages", SERVING_PINNED_PAGES),
                      ("session_evictions", SERVING_SESSION_EVICTIONS),
                      ("warm_ttft", SERVING_WARM_TTFT),
                      ("capacity_rejects", SERVING_REJECTS),
                      ("fleet_routed", SERVING_FLEET_ROUTED),
                      ("fleet_reroutes", SERVING_FLEET_REROUTES),
                      ("fleet_live_replicas", SERVING_FLEET_REPLICAS),
                      ("lane_prefills", SERVING_LANE_PREFILLS),
                      ("lane_prefill_seconds", SERVING_LANE_SECONDS),
                      ("handoff_seconds", SERVING_HANDOFF_SECONDS)):
        m = reg.peek(name)
        if m is not None:
            out[key] = m._json()
    # every SERVING_* series carries an ``engine=<id>`` label; fold the
    # per-engine counters back into fleet-level aggregates so "how much
    # traffic is this PROCESS serving" stays a one-key read even with N
    # engines resident (two engines used to merge into one
    # indistinguishable series — now they are separable AND summed)
    req_c = reg.peek(SERVING_REQUESTS)
    if req_c is not None:
        # live engines only: an engine retired at shutdown keeps its
        # counters (history feeds the aggregates below) but drops out
        # of the engine roster — ghost replicas must not look alive
        retired = retired_engines()
        engines = sorted({dict(k).get("engine", "")
                          for k in req_c.values()} - set(retired))
        agg: Dict[str, float] = {}
        for key, name in (("requests_total", SERVING_REQUESTS),
                          ("tokens_total", SERVING_TOKENS),
                          ("decode_steps_total", SERVING_DECODE_STEPS),
                          ("spec_proposed_tokens_total",
                           SERVING_SPEC_PROPOSED),
                          ("spec_accepted_tokens_total",
                           SERVING_SPEC_ACCEPTED),
                          ("capacity_rejects_total", SERVING_REJECTS),
                          ("prefix_cache_hits_total",
                           SERVING_PREFIX_HITS),
                          ("prefix_cache_hit_tokens_total",
                           SERVING_PREFIX_HIT_TOKENS)):
            m = reg.peek(name)
            if m is not None:
                agg[key] = m.total()
        out["engines"] = engines
        out["aggregate"] = agg
    return out


def model_health_snapshot() -> Dict[str, Any]:
    """Latest model-health gauge values (per-layer grad norms, update
    ratios, NaN provenance, MFU, step FLOPs) as plain JSON, or {} when
    no HealthMonitor has published yet. peek-only: assembling the
    snapshot never creates empty series."""
    reg = MetricsRegistry.get_default()
    out: Dict[str, Any] = {}
    for key, name in (("layer_grad_norm", LAYER_GRAD_NORM),
                      ("layer_param_norm", LAYER_PARAM_NORM),
                      ("update_ratio", UPDATE_RATIO),
                      ("nonfinite_first_layer", NONFINITE_FIRST_LAYER),
                      ("mfu", MFU),
                      ("step_flops", STEP_FLOPS),
                      ("health_fetches", HEALTH_FETCHES)):
        m = reg.peek(name)
        if m is not None:
            out[key] = m._json()
    return out


def reset() -> None:
    """Full telemetry reset: metrics, trace buffer, memory probe cache.
    (Instrumented-jit signature lists live on the network instances and
    reset with them.)"""
    global _mem_supported, _spans_dropped_pending
    MetricsRegistry.get_default().reset()
    clear_trace()
    with _trace_lock:
        _spans_dropped_pending = 0
    with _retired_lock:
        _retired_engines.clear()
    _mem_supported = None


__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "span", "record_span", "record_phase", "flush_dropped_spans",
    "chrome_trace", "recent_trace_events", "export_chrome_trace",
    "clear_trace",
    "instrument_jit", "sample_device_memory", "snapshot",
    "model_health_snapshot", "serving_snapshot", "reset",
    "enabled", "set_enabled", "record_on_device_batch",
    "record_state_bytes", "retire_engine_series", "retired_engines",
    "DEFAULT_BUCKETS",
    "MASTER_PARAM_BYTES", "OPT_STATE_BYTES",
    "JIT_COMPILES", "JIT_COMPILE_SECONDS", "STEP_PHASE_SECONDS",
    "DEVICE_BYTES_IN_USE", "DEVICE_PEAK_BYTES",
    "PREFETCH_QUEUE_DEPTH", "TRANSFER_OVERLAP_MS",
    "PREFETCH_PADDED_EXAMPLES", "BUCKET_HITS", "BUCKET_MISSES",
    "ON_DEVICE_BATCHES",
    "LOSS_SCALE", "LOSS_SCALE_OVERFLOWS", "LOSS_SCALE_SKIPPED_STEPS",
    "PRECISION_CASTS",
    "FT_ROLLBACKS", "FT_SKIPPED_BATCHES", "FT_PREEMPTION_CHECKPOINTS",
    "FT_PERIODIC_CHECKPOINTS",
    "FT_AUTO_RESUMES", "TRANSFER_RETRIES", "TRANSFER_QUARANTINES",
    "WATCHDOG_STALLS", "CHAOS_INJECTED",
    "LAYER_GRAD_NORM", "LAYER_PARAM_NORM", "UPDATE_RATIO",
    "NONFINITE_FIRST_LAYER", "MFU", "STEP_FLOPS", "HEALTH_FETCHES",
    "PROFILE_CAPTURES",
    "SERVING_REQUESTS", "SERVING_TOKENS", "SERVING_REQUEST_LATENCY",
    "SERVING_TTFT", "SERVING_QUEUE_DEPTH", "SERVING_SLOT_OCCUPANCY",
    "SERVING_KV_PAGE_UTILIZATION", "SERVING_KV_PAGE_BYTES",
    "SERVING_WARM_HITS",
    "SERVING_WARM_MISSES", "SERVING_DECODE_STEPS",
    "SERVING_DECODE_STEP_SECONDS", "SERVING_PREFILL_SECONDS",
    "SERVING_SPEC_PROPOSED", "SERVING_SPEC_ACCEPTED",
    "SERVING_SPEC_ACCEPTANCE", "SERVING_TOKENS_PER_DISPATCH",
    "SERVING_VERIFY_SECONDS",
    "SERVING_PREFIX_HITS", "SERVING_PREFIX_MISSES",
    "SERVING_PREFIX_HIT_TOKENS", "SERVING_PREFIX_EVICTED_PAGES",
    "SERVING_PREFIX_CACHED_PAGES", "SERVING_SHARED_PAGES",
    "SERVING_PINNED_PAGES", "SERVING_SESSION_EVICTIONS",
    "SERVING_WARM_TTFT",
    "SERVING_REJECTS", "SERVING_FLEET_ROUTED",
    "SERVING_FLEET_REROUTES", "SERVING_FLEET_REPLICAS",
    "SERVING_LANE_PREFILLS", "SERVING_LANE_SECONDS",
    "SERVING_HANDOFF_SECONDS", "SERVING_FLEET_PRESSURE",
    "INFERENCE_REQUEST_LATENCY", "INFERENCE_QUEUE_DEPTH",
    "INFERENCE_BATCH_OCCUPANCY",
    "SPANS_DROPPED", "INCIDENT_DUMPS",
    "JOBS_SUBMITTED", "JOBS_FINISHED", "JOBS_RESTARTS",
    "JOBS_MIGRATIONS", "JOBS_RUNNING", "JOBS_DEVICES",
    "JOBS_THROUGHPUT", "JOBS_MFU", "JOBS_LATENCY_P50",
    "JOBS_PREEMPTIONS", "WORKER_PROCESSES", "WORKER_HEARTBEAT_AGE",
    "FT_BUNDLE_IO_RETRIES",
    "ALERTS_TOTAL", "ALERTS_ACTIVE",
]
