"""Fault-injection harness for the resilience layer (util/resilience.py).

Production training on preemptible accelerators sees three recurring
failure shapes: a NaN batch poisoning the loss, a flaky host->device
transfer, and a SIGTERM landing mid-epoch. None of them reproduce on
demand, so the recovery code paths that handle them rot unless
something exercises them deliberately. This module is that something:
a deterministic (seeded) chaos monkey the fit loops and the device
prefetcher consult at their fault-sensitive seams.

Injection points:

- ``corrupt_batch(ds, ordinal)`` — called by the FaultTolerance fit
  loop on every batch; batches whose global ordinal is listed in
  ``nan_steps`` get their features replaced with NaN (exercises the
  divergence guard's rollback-and-skip).
- ``maybe_fail_transfer()`` — called by ``DevicePrefetchIterator``
  before each device transfer attempt; raises
  ``ChaosTransferError`` with probability ``transfer_error_rate``
  (exercises the exponential-backoff retry + quarantine path). Each
  RETRY re-rolls, so transient errors clear and rate=1.0 forces the
  quarantine.
- ``maybe_preempt(steps_done)`` — called by the FaultTolerance loop
  after each step; raises SIGTERM against this process once when the
  step count hits ``preempt_at_step`` (exercises the preemption
  checkpoint + auto-resume cycle end to end, real signal included).

Activation, in priority order:

1. programmatic — ``install(ChaosMonkey(cfg))`` or the ``installed``
   context manager (tests);
2. environment — ``DL4J_TPU_CHAOS=1`` plus the ``DL4J_TPU_CHAOS_*``
   knobs below (the CI chaos smoke gate in run_tests.sh);
3. otherwise ``active()`` returns None and every hook is a no-op
   (a single attribute read on the hot path).

Env knobs (read once, on first ``active()`` call):
``DL4J_TPU_CHAOS_NAN_STEPS`` (comma-separated batch ordinals),
``DL4J_TPU_CHAOS_TRANSFER_P`` (float probability),
``DL4J_TPU_CHAOS_PREEMPT_AT`` (``<step>`` = raise SIGTERM;
``<step>,<deadline_s>`` = deliver a fake maintenance NOTICE with that
grace window — the phase-2 notice drill), ``DL4J_TPU_CHAOS_SEED``.

Every injection lands in the telemetry registry as
``dl4j_tpu_chaos_injected_total{kind=...}`` so a chaos run's report
shows what was thrown at the model next to how it recovered.
"""

from __future__ import annotations

import contextlib
import logging
import os
import signal
import threading
from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.profiler import telemetry as _telemetry

log = logging.getLogger("deeplearning4j_tpu")


def _flight_record(kind: str, **fields) -> None:
    """Lazy flight-recorder bridge (chaos loads very early; the
    recorder import stays off the no-chaos path)."""
    from deeplearning4j_tpu.profiler import flight_recorder

    flight_recorder.record(kind, **fields)


class ChaosTransferError(RuntimeError):
    """Injected transient host->device transfer failure."""


class WorkerKilledError(RuntimeError):
    """Injected SIGKILL-equivalent worker death: the fit loop dies
    mid-step with NO checkpoint, exactly as a host losing power (or a
    platform hard-kill after the grace period) would. The control
    plane's migration path recovers the newest digest-valid bundle;
    nothing in the dying run gets to clean up."""


@dataclass
class ChaosConfig:
    """What to inject and when. All fields default to 'inject nothing'
    so a config only lists the faults a test actually wants."""

    #: global batch ordinals (0-based, counted per fit run) whose
    #: features are replaced with NaN
    nan_steps: Tuple[int, ...] = ()
    #: probability each transfer ATTEMPT raises ChaosTransferError
    #: (retries re-roll; 1.0 makes a batch un-transferable -> quarantine)
    transfer_error_rate: float = 0.0
    #: raise SIGTERM in-process once this many steps have completed
    preempt_at_step: Optional[int] = None
    #: with a deadline, ``preempt_at_step`` delivers a FAKE MAINTENANCE
    #: EVENT instead of a signal: ``ft.request_preemption(deadline_s,
    #: kind="chaos_notice")`` — the notice→checkpoint→drain path is
    #: drillable without a real cluster (env spelling:
    #: ``DL4J_TPU_CHAOS_PREEMPT_AT=<step>,<deadline_s>``)
    preempt_deadline_s: Optional[float] = None
    #: stall the training step with this ordinal for ``hang_seconds``
    #: INSIDE the watchdog scope — the hung-not-dead failure mode only
    #: real hardware (a wedged collective, a dead host link) otherwise
    #: produces; the watchdog must fire, the step must then complete
    hang_step: Optional[int] = None
    hang_seconds: float = 2.0
    #: raise WorkerKilledError once this many steps have completed —
    #: SIGKILL-equivalent (no checkpoint, no cleanup), the control
    #: plane's recover-newest-bundle-and-migrate drill
    kill_at_step: Optional[int] = None
    seed: int = 20260803

    @staticmethod
    def from_env() -> Optional["ChaosConfig"]:
        if os.environ.get("DL4J_TPU_CHAOS", "0") in ("0", ""):
            return None
        raw = os.environ.get("DL4J_TPU_CHAOS_NAN_STEPS", "")
        nan_steps = tuple(int(v) for v in raw.split(",") if v.strip())
        preempt = os.environ.get("DL4J_TPU_CHAOS_PREEMPT_AT")
        hang = os.environ.get("DL4J_TPU_CHAOS_HANG_STEP")
        kill = os.environ.get("DL4J_TPU_CHAOS_KILL_AT")
        # "<step>" = raise SIGTERM; "<step>,<deadline_s>" = deliver a
        # fake maintenance NOTICE with that grace window
        preempt_deadline = None
        if preempt and "," in preempt:
            preempt, deadline_raw = preempt.split(",", 1)
            preempt_deadline = float(deadline_raw)
        return ChaosConfig(
            nan_steps=nan_steps,
            transfer_error_rate=float(
                os.environ.get("DL4J_TPU_CHAOS_TRANSFER_P", "0") or 0),
            preempt_at_step=int(preempt) if preempt else None,
            preempt_deadline_s=preempt_deadline,
            hang_step=int(hang) if hang else None,
            hang_seconds=float(
                os.environ.get("DL4J_TPU_CHAOS_HANG_SECONDS", "2") or 2),
            kill_at_step=int(kill) if kill else None,
            seed=int(os.environ.get("DL4J_TPU_CHAOS_SEED", "20260803")),
        )


class ChaosMonkey:
    """Stateful injector for one ChaosConfig. Thread-safe: the transfer
    hook runs on the prefetcher's worker thread while the batch/preempt
    hooks run on the fit-loop thread."""

    def __init__(self, config: ChaosConfig):
        self.config = config
        self._rng = np.random.default_rng(config.seed)
        self._lock = threading.Lock()
        self._preempted = False
        self._hung = False
        self._killed = False

    def _record(self, kind: str) -> None:
        if not _telemetry.enabled():
            return
        _telemetry.MetricsRegistry.get_default().counter(
            _telemetry.CHAOS_INJECTED,
            "faults injected by the chaos harness").inc(kind=kind)

    # ------------------------------------------------------------ hooks
    def corrupt_batch(self, ds, ordinal: int):
        """Return ``ds`` with NaN features when ``ordinal`` is a
        configured NaN step; otherwise ``ds`` unchanged. Never mutates
        the caller's batch — the original survives for a post-rollback
        inspection."""
        if ordinal not in self.config.nan_steps:
            return ds
        self._record("nan_batch")
        log.warning("CHAOS: injecting NaN batch at ordinal %d", ordinal)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.multi_dataset import MultiDataSet

        nan_like = lambda a: np.full(np.asarray(a).shape, np.nan,
                                     np.asarray(a).dtype
                                     if np.asarray(a).dtype.kind == "f"
                                     else np.float32)
        if isinstance(ds, MultiDataSet):
            return MultiDataSet([nan_like(a) for a in ds.features],
                                list(ds.labels),
                                ds.features_mask_arrays or None,
                                ds.labels_mask_arrays or None)
        if isinstance(ds, DataSet):
            return DataSet(nan_like(ds.features), ds.labels,
                           ds.features_mask, ds.labels_mask)
        return nan_like(ds)

    def maybe_fail_transfer(self) -> None:
        """Raise ChaosTransferError with the configured probability."""
        p = self.config.transfer_error_rate
        if p <= 0.0:
            return
        with self._lock:   # default_rng is not thread-safe
            roll = self._rng.random()
        if roll < p:
            self._record("transfer_error")
            raise ChaosTransferError(
                "injected transient host->device transfer failure "
                f"(p={p})")

    def maybe_hang(self, steps_done: int) -> None:
        """Stall the current step once, for ``hang_seconds``, when the
        configured step count is reached. Called INSIDE the fit loop's
        watchdog scope, so a deadline shorter than the hang sees a real
        stall verdict (watchdog fires, stall counter bumps, incident
        dump) while the step itself eventually completes — hung, not
        dead."""
        at = self.config.hang_step
        if at is None or self._hung or steps_done < at:
            return
        self._hung = True
        self._record("hang")
        log.warning("CHAOS: hanging step %d for %.1fs (watchdog drill)",
                    steps_done, self.config.hang_seconds)
        import time

        time.sleep(self.config.hang_seconds)

    def maybe_kill(self, steps_done: int) -> None:
        """Raise WorkerKilledError once at the configured step count —
        the SIGKILL-equivalent death: the exception escapes the fit
        loop with no checkpoint written, and recovery is whatever the
        newest periodic bundle holds."""
        at = self.config.kill_at_step
        if at is None or self._killed or steps_done < at:
            return
        self._killed = True
        self._record("worker_kill")
        log.warning("CHAOS: killing worker after %d steps (no "
                    "checkpoint — SIGKILL-equivalent)", steps_done)
        raise WorkerKilledError(
            f"chaos worker kill after {steps_done} steps")

    def maybe_preempt(self, steps_done: int, ft=None) -> None:
        """Deliver one preemption at the configured step count. With
        no ``preempt_deadline_s``: one real SIGTERM — the fit loop's
        installed handler turns it into a clean checkpoint-and-exit.
        With a deadline (and the loop's FaultTolerance passed in): a
        FAKE MAINTENANCE EVENT — ``ft.request_preemption(deadline_s,
        kind="chaos_notice")`` — so the notice→checkpoint→drain path
        is drillable without a real cluster."""
        at = self.config.preempt_at_step
        if at is None or self._preempted or steps_done < at:
            return
        self._preempted = True
        deadline = self.config.preempt_deadline_s
        if deadline is not None and ft is not None:
            self._record("preempt_notice")
            _flight_record("chaos_injected", fault="preempt_notice",
                           deadline_s=deadline, step=steps_done)
            log.warning("CHAOS: delivering fake maintenance notice "
                        "after %d steps (deadline %.1fs)", steps_done,
                        deadline)
            ft.request_preemption(deadline_s=deadline,
                                  kind="chaos_notice")
            return
        self._record("preemption")
        log.warning("CHAOS: simulating preemption after %d steps "
                    "(raising SIGTERM)", steps_done)
        signal.raise_signal(signal.SIGTERM)


# ------------------------------------------------------------ activation
_active: Optional[ChaosMonkey] = None
_env_checked = False
_lock = threading.Lock()


def active() -> Optional[ChaosMonkey]:
    """The installed monkey, the env-configured one, or None."""
    global _active, _env_checked
    if _active is not None:
        return _active
    if not _env_checked:
        with _lock:
            if not _env_checked:
                cfg = ChaosConfig.from_env()
                if cfg is not None:
                    _active = ChaosMonkey(cfg)
                    log.warning("CHAOS harness active from env: %s", cfg)
                _env_checked = True
    return _active


def install(monkey: Optional[ChaosMonkey]) -> None:
    """Install (or with None, remove) the process-wide chaos monkey."""
    global _active
    _active = monkey


@contextlib.contextmanager
def installed(config: ChaosConfig):
    """Scoped activation for tests: install a fresh monkey for the
    block, restore whatever was active before on exit."""
    global _active
    prev = _active
    monkey = ChaosMonkey(config)
    _active = monkey
    try:
        yield monkey
    finally:
        _active = prev


def preempt_worker(worker, deadline_s: float = 30.0,
                   target=None) -> None:
    """Fake GCE/Borg maintenance event for drills: deliver a
    preemption NOTICE (grace deadline included) for ``worker`` so the
    notice→checkpoint→drain path runs without a real cluster.

    ``worker`` is a fleet worker name routed through ``target`` — a
    ``JobScheduler``, a ``WorkerSupervisor``, or (default) the
    process's default scheduler — or a ``FaultTolerance`` policy
    passed directly (the notice lands on it without any control
    plane). Emits ``chaos_injected{kind=preempt_notice}``."""
    if _telemetry.enabled():
        _telemetry.MetricsRegistry.get_default().counter(
            _telemetry.CHAOS_INJECTED,
            "faults injected by the chaos harness").inc(
            kind="preempt_notice")
    _flight_record("chaos_injected", fault="preempt_notice",
                   worker=str(worker), deadline_s=deadline_s)
    log.warning("CHAOS: fake maintenance notice for worker %s "
                "(deadline %.1fs)", worker, deadline_s)
    if hasattr(worker, "request_preemption"):   # a FaultTolerance
        worker.request_preemption(deadline_s=deadline_s,
                                  kind="chaos_notice")
        return
    if target is None:
        from deeplearning4j_tpu.control import default_scheduler

        target = default_scheduler()
        if target is None:
            from deeplearning4j_tpu.control.worker import (
                default_supervisor,
            )

            target = default_supervisor()
    if target is None:
        raise RuntimeError(
            "chaos.preempt_worker: no JobScheduler/WorkerSupervisor "
            "in this process — pass target= (or a FaultTolerance "
            "directly)")
    if hasattr(target, "preempt_worker"):       # JobScheduler
        target.preempt_worker(worker, deadline_s=deadline_s)
    else:                                       # WorkerSupervisor
        target.preempt(worker, deadline_s=deadline_s)


class FaultyObjectStore:
    """Chaos wrapper for object-store clients
    (``util.resilience.InMemoryObjectStore`` and friends): injects the
    failure shapes real S3/GCS traffic sees — per-op transient errors
    and TORN UPLOADS (the connection dies mid-PUT, leaving a truncated
    blob under the key) — so the rename-less commit protocol's claims
    (write retry, partial-upload invisibility, digest-based fallback)
    are proven, not assumed.

    - ``error_rate``: probability each op in ``ops`` raises OSError
      before touching the inner store. A rate ``>= 1.0`` switches to
      the deterministic drill mode: the FIRST attempt of every
      distinct ``(op, key)`` fails and the retry succeeds — every
      bundle op retries at least once, nothing ever wedges (what the
      CI drill sets via ``DL4J_TPU_CHAOS_STORE_ERROR_RATE=1``).
    - ``torn_rate``: probability (same ``>= 1.0`` drill semantics) a
      PUT writes only the first half of the payload to the inner
      store and then raises — the torn blob EXISTS remotely; only
      digest validation can tell. A retried put overwrites it whole
      (last-write-wins, the S3 model).
    - ``ops``: which of put/get/list/delete inject errors (default
      all).

    Env activation (``from_env``): ``DL4J_TPU_CHAOS_STORE_ERROR_RATE``,
    ``DL4J_TPU_CHAOS_STORE_TORN_RATE``, ``DL4J_TPU_CHAOS_STORE_OPS``
    (comma-separated), ``DL4J_TPU_CHAOS_STORE_SEED``. Standalone knobs
    — no ``DL4J_TPU_CHAOS`` master switch needed: a store-chaos drill
    should not also enable NaN/preemption injection. Every injection
    lands in ``dl4j_tpu_chaos_injected_total{kind=store_*}`` plus a
    flight-recorder event."""

    _OPS = ("put", "get", "list", "delete")

    def __init__(self, inner, *, error_rate: float = 0.0,
                 torn_rate: float = 0.0, ops=None,
                 seed: Optional[int] = None):
        self.inner = inner
        self.error_rate = float(error_rate)
        self.torn_rate = float(torn_rate)
        self.ops = tuple(ops) if ops else self._OPS
        self._rng = np.random.default_rng(
            20260807 if seed is None else seed)
        self._fault_lock = threading.Lock()
        #: (kind, op, key) triples already failed once — the
        #: deterministic rate>=1.0 drill mode's memory
        self._failed_once = set()
        self.injected = 0

    @staticmethod
    def from_env(inner, environ=None):
        """Wrap ``inner`` per the ``DL4J_TPU_CHAOS_STORE_*`` env knobs
        — or return it untouched when none are set."""
        env = os.environ if environ is None else environ
        err = float(
            env.get("DL4J_TPU_CHAOS_STORE_ERROR_RATE", "0") or 0)
        torn = float(
            env.get("DL4J_TPU_CHAOS_STORE_TORN_RATE", "0") or 0)
        if err <= 0.0 and torn <= 0.0:
            return inner
        ops = tuple(
            v.strip()
            for v in env.get("DL4J_TPU_CHAOS_STORE_OPS", "").split(",")
            if v.strip()) or None
        seed = env.get("DL4J_TPU_CHAOS_STORE_SEED")
        store = FaultyObjectStore(
            inner, error_rate=err, torn_rate=torn, ops=ops,
            seed=int(seed) if seed else None)
        log.warning("CHAOS: object-store fault injection active "
                    "(error_rate=%s, torn_rate=%s, ops=%s)",
                    err, torn, store.ops)
        return store

    # ------------------------------------------------------- injection
    def _roll(self, kind: str, op: str, key, rate: float) -> bool:
        if rate <= 0.0 or op not in self.ops:
            return False
        if rate >= 1.0:
            with self._fault_lock:
                mark = (kind, op, str(key))
                if mark in self._failed_once:
                    return False
                self._failed_once.add(mark)
                return True
        with self._fault_lock:       # default_rng is not thread-safe
            return float(self._rng.random()) < rate

    def _inject(self, kind: str, op: str, key) -> None:
        self.injected += 1
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.CHAOS_INJECTED,
                "faults injected by the chaos harness").inc(kind=kind)
        _flight_record("chaos_injected", fault=kind, op=op,
                       key=str(key)[-160:])
        log.warning("CHAOS: injected %s on object-store %s %s",
                    kind, op, key)

    # ------------------------------------------------------ client api
    def put(self, key, data) -> None:
        if self._roll("store_torn", "put", key, self.torn_rate):
            self._inject("store_torn", "put", key)
            # the half-written blob LANDS — invisible only because no
            # commit/digest ever blesses it
            self.inner.put(key, bytes(data[:max(1, len(data) // 2)]))
            raise OSError(f"chaos: torn upload of {key}")
        if self._roll("store_error", "put", key, self.error_rate):
            self._inject("store_error", "put", key)
            raise OSError(f"chaos: injected put failure for {key}")
        return self.inner.put(key, data)

    def get(self, key):
        if self._roll("store_error", "get", key, self.error_rate):
            self._inject("store_error", "get", key)
            raise OSError(f"chaos: injected get failure for {key}")
        return self.inner.get(key)

    def list(self, prefix):
        if self._roll("store_error", "list", prefix, self.error_rate):
            self._inject("store_error", "list", prefix)
            raise OSError(
                f"chaos: injected list failure for {prefix}")
        return self.inner.list(prefix)

    def delete(self, key) -> None:
        if self._roll("store_error", "delete", key, self.error_rate):
            self._inject("store_error", "delete", key)
            raise OSError(
                f"chaos: injected delete failure for {key}")
        return self.inner.delete(key)

    def describe(self) -> str:
        inner = getattr(self.inner, "describe", None)
        return (f"faulty({inner() if callable(inner) else self.inner},"
                f" err={self.error_rate}, torn={self.torn_rate})")


def hang_replica(engine, seconds: float = 2.0) -> None:
    """Stall a decode engine's scheduler for ``seconds`` at its next
    loop pass — a decode burst that stops making progress without the
    thread dying, which is how a wedged device or a hung collective
    presents in production serving. The engine records a
    ``chaos_hang`` flight event when the stall begins; the control
    plane's health loop sees the replica's progress clock stop. Works
    on a solo ``DecodeEngine`` or any fleet replica's ``.engine``."""
    if _telemetry.enabled():
        _telemetry.MetricsRegistry.get_default().counter(
            _telemetry.CHAOS_INJECTED,
            "faults injected by the chaos harness").inc(
            kind="hang_replica")
    log.warning("CHAOS: hanging decode engine %s for %.1fs",
                getattr(engine, "engine_id", "?"), seconds)
    engine._hang_s = float(seconds)


__all__ = ["ChaosConfig", "ChaosMonkey", "ChaosTransferError",
           "FaultyObjectStore", "WorkerKilledError", "hang_replica",
           "preempt_worker", "active", "install", "installed"]
