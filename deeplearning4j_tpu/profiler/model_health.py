"""In-step model-health monitoring: jit-threaded per-layer gradient /
update statistics, NaN provenance, and live MFU attribution.

PR 1's telemetry spine answers "is the machine healthy" (compiles,
memory, step phases); this module answers "is the *model* healthy" —
from inside the one compiled train step, with no second backward pass:

- **Per-layer scalars, computed on device**: gradient L2 norms (raw
  master-precision grads, pre-clipping — the diagnostic signal),
  post-update parameter norms, and update-to-param ratios (the
  learning-rate sanity check: healthy nets sit around 1e-4..1e-2).
  All of it reduces to one small pytree (a handful of length-L arrays)
  returned as an extra step output; the fit loop fetches it with a
  single ``device_get`` every ``frequency`` steps.
- **Non-finite provenance**: a per-layer bitmask of "this layer's
  training-forward activation or gradient went NaN/Inf", plus the
  FIRST offending layer index. Activation flags take priority — NaN
  propagates downstream, so the first non-finite activation localizes
  the source (a poisoned input reads layer 0; a blown-up layer k reads
  k), where gradient flags alone cannot (a NaN loss makes every
  layer's gradient NaN). Loss-scale-aware: a mixed_float16 overflow
  the precision engine already handled (step skipped, scale halved) is
  reported as CLEAN (-1) — that's the engine working, not the model
  sickening; the raw index stays available as
  ``last["handled_overflow_layer"]`` for scale debugging.
- **Live MFU**: ``instrument_jit`` captures each executable's
  ``cost_analysis()`` FLOPs at compile time (an AOT lower+compile that
  hits the XLA compile cache — one trace, not a second compile);
  each health sample divides FLOPs/step by the wall clock since the
  previous sample and by the dtype-aware peak (profiler/flops.py) into
  the ``dl4j_tpu_mfu`` gauge. Devices without a ``PEAK_FLOPS`` entry
  log one warning and omit the gauge — never a silently wrong MFU.

Cost model (docs/OBSERVABILITY.md "Model health"):

- monitoring OFF: the step builders take the exact legacy code path —
  bit-identical executables, regression-gated in run_tests.sh;
- monitoring ON: one extra compile per jit site (the step signature
  gains one static flag), a few fused reductions inside the step
  (O(params) reads the step already does), and ONE small device->host
  transfer per sampled step. No extra backward pass, ever.

Wiring: ``net.setHealthMonitor(HealthMonitor(frequency=10))`` on
MultiLayerNetwork or ComputationGraph; ShardedTrainer (mode='sharing')
picks the model's monitor up automatically — GSPMD's compiler-inserted
psum makes the in-step norms mesh-global for free. The shard_map modes
(sharing_compressed / averaging) warn once and skip monitoring, like
they do for mask arrays.
"""

from __future__ import annotations

import logging
import time
import weakref
from typing import Any, Dict, List, Optional, Sequence

log = logging.getLogger("deeplearning4j_tpu")

#: latest captured cost_analysis FLOPs per jit site (one entry per
#: site; the per-EXECUTABLE values live in each _InstrumentedJit's
#: signature map and feed the dispatched counter below)
_site_flops: Dict[str, float] = {}
#: cumulative FLOPs DISPATCHED per site — instrument_jit adds the
#: running executable's own FLOPs on every call, so the MFU numerator
#: is exact even when several executables coexist at one site (shape
#: buckets, ragged final batches, mask variants): each monitor sample
#: reads the delta over its window instead of latest-compile-wins
_site_dispatched: Dict[str, float] = {}
_capture_enabled = False
#: live HealthMonitor objects — FLOPs capture/attribution is gated on
#: this being non-empty, so the per-call cost ends when the last
#: monitor is garbage-collected (not merely detached: a detached but
#: referenced monitor can be re-attached and expects MFU to resume)
_live_monitors = weakref.WeakSet()

#: the only jit sites whose FLOPs ever feed an MFU sample — capture is
#: limited to these so the forward/eval/pretrain sites never pay the
#: extra compile-time trace
_MFU_SITES = frozenset({
    "mln_step", "mln_tbptt_step", "cg_step", "parallel_sharing_step",
})


# ======================================================================
# device-side reducers (called at TRACE time, inside the jitted step)
# ======================================================================
def _l2sq(tree):
    """Sum of squares over every floating leaf, accumulated in fp32.
    Doubles as the non-finite probe: any NaN/Inf leaf makes the sum
    non-finite."""
    import jax
    import jax.numpy as jnp

    s = jnp.asarray(0.0, jnp.float32)
    for l in jax.tree_util.tree_leaves(tree):
        if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating):
            s = s + jnp.sum(jnp.square(l.astype(jnp.float32)))
    return s


def _l2sq_diff(new_tree, old_tree):
    import jax
    import jax.numpy as jnp

    s = jnp.asarray(0.0, jnp.float32)
    new_leaves = jax.tree_util.tree_leaves(new_tree)
    old_leaves = jax.tree_util.tree_leaves(old_tree)
    for n, o in zip(new_leaves, old_leaves):
        if hasattr(n, "dtype") and jnp.issubdtype(n.dtype, jnp.floating):
            d = n.astype(jnp.float32) - o.astype(jnp.float32)
            s = s + jnp.sum(jnp.square(d))
    return s


def act_flag(a):
    """Per-layer forward provenance bit: True when this activation (or
    loss value) contains any NaN/Inf. Called by the loss forwards when
    ``collect_acts=True``."""
    import jax.numpy as jnp

    if not (hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)):
        return jnp.asarray(False)
    return jnp.logical_not(jnp.all(jnp.isfinite(a)))


def device_stats(keys: Sequence, grads, new_params, old_params,
                 act_bad: Optional[List], handled=None) -> Dict[str, Any]:
    """Reduce one step's health into a small pytree, on device.

    ``keys`` fixes the layer order (list indices for MultiLayerNetwork,
    conf.nodes order for ComputationGraph); ``grads`` are the RAW
    (pre-clip, master-precision) gradients; ``new_params``/
    ``old_params`` the post-/pre-update param containers (post-guard
    under loss scaling, so a skipped step reads update_ratio 0);
    ``act_bad`` the per-layer forward flags (or None); ``handled`` a
    scalar bool marking a loss-scale-handled overflow."""
    import jax.numpy as jnp

    gn, pn, ur, bad = [], [], [], []
    for i, k in enumerate(keys):
        gsq = _l2sq(grads[k])
        psq = _l2sq(new_params[k])
        usq = _l2sq_diff(new_params[k], old_params[k])
        gn.append(jnp.sqrt(gsq))
        pn.append(jnp.sqrt(psq))
        ur.append(jnp.sqrt(usq) / (jnp.sqrt(psq) + 1e-12))
        g_bad = jnp.logical_not(jnp.isfinite(gsq))
        a_bad = act_bad[i] if act_bad is not None else jnp.asarray(False)
        bad.append(jnp.logical_or(a_bad, g_bad))
    act_vec = (jnp.stack(act_bad) if act_bad
               else jnp.zeros((len(gn),), bool))
    bad_vec = jnp.stack(bad)
    # first offender: activation flags take priority (they localize the
    # source — see module docstring), gradient flags break the tie when
    # the forward was clean (e.g. an inf*0 born in the backward)
    first = jnp.where(
        jnp.any(act_vec), jnp.argmax(act_vec),
        jnp.where(jnp.any(bad_vec), jnp.argmax(bad_vec), -1)
    ).astype(jnp.int32)
    if handled is None:
        handled = jnp.asarray(False)
    return {
        "grad_norm": jnp.stack(gn),
        "param_norm": jnp.stack(pn),
        "update_ratio": jnp.stack(ur),
        "nonfinite": bad_vec,
        "first_nonfinite": first,
        "handled": handled,
    }


# ======================================================================
# model seam helpers
# ======================================================================
def split_health(res, monitored: bool):
    """Split a train step's outputs into ``(core, health_tree)`` —
    the monitored step appends the health pytree as its LAST output;
    unmonitored steps pass through with health None. The one place the
    fit loops' unpack convention lives."""
    if monitored:
        return res[:-1], res[-1]
    return res, None


def layer_keys(model) -> List:
    """Container keys in canonical layer order: list indices for
    MultiLayerNetwork, conf.nodes names for ComputationGraph."""
    if hasattr(model, "params_map"):
        return [n.name for n in model.conf.nodes]
    return list(range(len(model.conf.layers)))


def layer_names(model) -> List[str]:
    """Human-readable layer labels, aligned with ``layer_keys``."""
    if hasattr(model, "params_map"):
        return [n.name for n in model.conf.nodes]
    return [f"{i}:{type(l).__name__}"
            for i, l in enumerate(model.conf.layers)]


# ======================================================================
# compile-time FLOPs capture (fed by telemetry.instrument_jit)
# ======================================================================
def enable_flops_capture() -> None:
    """Turn on per-compile cost_analysis capture in instrument_jit.
    Flipped (never cleared) by the first HealthMonitor constructed —
    compiles are rare, but the capture still costs a trace each, so it
    stays off until someone wants MFU."""
    global _capture_enabled
    _capture_enabled = True


def flops_capture_enabled() -> bool:
    return _capture_enabled


def wants_flops(site: str) -> bool:
    """Should instrument_jit capture/attribute cost_analysis FLOPs for
    ``site``? Only while at least one HealthMonitor object is alive,
    and only for the train-step sites an MFU sample can ever read — a
    compile at any other site (forwards, pretrain, eval) never pays
    the capture trace, and once the last monitor is collected the
    per-call attribution cost disappears too."""
    return _capture_enabled and site in _MFU_SITES \
        and bool(_live_monitors)


def capture_flops(site: str, fn, args, kwargs) -> Optional[float]:
    """Record the freshly compiled executable's cost_analysis FLOPs for
    ``site``. Called by instrument_jit right after it detects a
    compile; the ``lower().compile()`` here hits the XLA compile cache
    the real call just populated (~ms), so the extra cost is one
    abstract trace. Any failure (deleted donated buffers, backends
    without cost analysis) leaves the previous capture in place."""
    from deeplearning4j_tpu.profiler import telemetry

    try:
        compiled = fn.lower(*args, **kwargs).compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        flops = float(ca.get("flops", 0.0)) or None
    except Exception:
        return None
    if flops is None:
        return None
    _site_flops[site] = flops
    telemetry.MetricsRegistry.get_default().gauge(
        telemetry.STEP_FLOPS,
        "XLA cost_analysis FLOPs of the live compiled step executable"
    ).set(flops, site=site)
    return flops


def add_dispatched_flops(site: str, flops: float) -> None:
    """Called by instrument_jit on EVERY call at an MFU site: add the
    executable-that-just-ran's FLOPs to the site's cumulative
    dispatched counter (the exact MFU numerator)."""
    _site_dispatched[site] = _site_dispatched.get(site, 0.0) + flops


def dispatched_flops(site: str) -> float:
    return _site_dispatched.get(site, 0.0)


def site_flops(site: str) -> Optional[float]:
    return _site_flops.get(site)


# ======================================================================
# the monitor
# ======================================================================
class HealthMonitor:
    """In-step model-health policy. Attach with
    ``net.setHealthMonitor(HealthMonitor(frequency=N))``; detach with
    ``setHealthMonitor(None)``. Toggling costs exactly one extra
    compile per jit site (the step cache is keyed on the flag).

    Every step the jitted step returns the device-side health pytree;
    every ``frequency`` steps the monitor fetches it (ONE device_get),
    publishes the per-layer gauges + ``dl4j_tpu_mfu`` into the
    MetricsRegistry, and refreshes ``last`` — the host-side dict
    StatsListener and the divergence guard read."""

    def __init__(self, frequency: int = 10, mfu: bool = True):
        self.frequency = max(int(frequency), 1)
        self.mfu = bool(mfu)
        #: latest host-side sample (None until the first fetch)
        self.last: Optional[Dict[str, Any]] = None
        self._device = None          # latest device-side health pytree
        self._names: Optional[List[str]] = None
        self._model_ref = None       # identity key for the label set
        self._site = "?"
        self._jit_site: Optional[str] = None
        self._compute_dtype = "float32"
        self._iteration = 0
        self._steps = 0              # steps observed by this monitor
        self._fetches = 0
        self._last_t: Optional[float] = None
        self._last_steps = 0
        self._last_disp = 0.0        # dispatched-FLOPs anchor ...
        self._last_disp_site = None  # ... and the site it was read at
        _live_monitors.add(self)
        enable_flops_capture()

    # ------------------------------------------------------------ wiring
    def on_step(self, model, health_tree, site: str,
                jit_site: Optional[str] = None) -> None:
        """Fit-loop hook: record this step's device-side health output;
        fetch + publish every ``frequency`` steps. Never syncs except
        on the sampled step."""
        self._device = health_tree
        self._site = site
        self._jit_site = jit_site
        if self._model_ref is None or self._model_ref() is not model:
            # (re-)attached: refresh the label set — a monitor moved to
            # a different model must not index with the old layer list
            self._model_ref = weakref.ref(model)
            self._names = layer_names(model)
        policy = getattr(model, "_policy", None)
        if policy is not None:
            self._compute_dtype = policy.compute_dtype
        self._iteration = int(getattr(model, "_iteration", 0))
        self._steps += 1
        if self._steps % self.frequency == 0:
            self.sample()

    # ---------------------------------------------------------- sampling
    def sample(self) -> Optional[Dict[str, Any]]:
        """Fetch the latest device-side health pytree (one device_get —
        this is the monitor's entire per-sample transfer), publish
        gauges, refresh ``last``. Returns the sample dict."""
        if self._device is None:
            return None
        import jax

        from deeplearning4j_tpu.profiler import telemetry

        host = jax.device_get(self._device)
        self._fetches += 1
        now = time.perf_counter()
        names = self._names or []
        handled = bool(host["handled"])
        first = int(host["first_nonfinite"])
        report = -1 if handled else first
        sample: Dict[str, Any] = {
            "iteration": self._iteration,
            "grad_norms": {}, "param_norms": {}, "update_ratios": {},
            "nonfinite_layers": [],
            "nonfinite_first_layer": report,
            "nonfinite_layer_name": (names[report]
                                     if 0 <= report < len(names) else None),
            "handled_overflow": handled,
            "handled_overflow_layer": first if handled else -1,
        }
        reg = telemetry.MetricsRegistry.get_default() \
            if telemetry.enabled() else None
        if reg is not None:
            reg.counter(
                telemetry.HEALTH_FETCHES,
                "model-health device->host fetches (one per sampled "
                "step)").inc(site=self._site)
        gn = reg.gauge(telemetry.LAYER_GRAD_NORM,
                       "per-layer gradient L2 norm (raw master-"
                       "precision grads, pre-clip)") if reg else None
        pn = reg.gauge(telemetry.LAYER_PARAM_NORM,
                       "per-layer parameter L2 norm (post-update)") \
            if reg else None
        ur = reg.gauge(telemetry.UPDATE_RATIO,
                       "per-layer update-to-param L2 ratio") if reg else None
        for i, nm in enumerate(names):
            g = float(host["grad_norm"][i])
            p = float(host["param_norm"][i])
            r = float(host["update_ratio"][i])
            sample["grad_norms"][nm] = g
            sample["param_norms"][nm] = p
            sample["update_ratios"][nm] = r
            if bool(host["nonfinite"][i]) and not handled:
                sample["nonfinite_layers"].append(nm)
            if reg is not None:
                gn.set(g, layer=nm, site=self._site)
                pn.set(p, layer=nm, site=self._site)
                ur.set(r, layer=nm, site=self._site)
        if reg is not None:
            reg.gauge(telemetry.NONFINITE_FIRST_LAYER,
                      "index of the first layer whose activation/"
                      "gradient went NaN/Inf (-1 = clean; loss-scale-"
                      "handled overflows report clean)"
                      ).set(report, site=self._site)
        mfu = self._compute_mfu(now)
        if mfu is not None:
            sample["mfu"] = mfu
            if reg is not None:
                reg.gauge(telemetry.MFU,
                          "model FLOPs utilization of the live step "
                          "(cost_analysis FLOPs / wall clock / dtype-"
                          "aware peak)").set(mfu, site=self._site)
        self._last_t = now
        self._last_steps = self._steps
        self._last_disp_site = self._jit_site
        self._last_disp = _site_dispatched.get(self._jit_site or "", 0.0)
        self.last = sample
        return sample

    def _compute_mfu(self, now: float) -> Optional[float]:
        """FLOPs dispatched at the step's jit site since the last
        sample / elapsed wall clock / dtype-aware peak. The dispatched
        counter sums each call's OWN executable cost, so the numerator
        stays exact when several executables coexist at the site
        (shape buckets, ragged final batches). Semantics are
        SITE-level: all work dispatched at the jit site in the window
        counts, so two models training interleaved at one site read
        the site's combined utilization. The device_get that preceded
        this call synced the pipeline, so the elapsed window covers
        real device execution, not just dispatch."""
        if not self.mfu or self._last_t is None:
            return None
        site = self._jit_site or ""
        if self._last_disp_site != self._jit_site:
            # the fit loop switched jit sites mid-window (e.g. a
            # tbptt-length boundary): the anchor belongs to another
            # counter, so this window has no sound numerator — skip;
            # sample() re-anchors at the current site for the next one
            return None
        num = _site_dispatched.get(site, 0.0) - self._last_disp
        if num <= 0:
            # no per-dispatch data (capture found no cost analysis):
            # latest-executable x steps is the best remaining estimate
            flops = _site_flops.get(site)
            steps = self._steps - self._last_steps
            if not flops or steps <= 0:
                return None
            num = flops * steps
        elapsed = now - self._last_t
        if elapsed <= 0:
            return None
        from deeplearning4j_tpu.profiler.flops import peak_flops

        peak = peak_flops(self._compute_dtype)
        if not peak:
            return None   # unknown device: peak_flops warned already
        return num / (elapsed * peak)

    def latest(self) -> Optional[Dict[str, Any]]:
        """The sample for the MOST RECENT step: ``last`` when the
        sampled step IS the latest observed step, else a fresh fetch
        (one device_get, same cost as a scheduled sample). StatsListener
        reads this so a monitor sampling on a coarser cadence than the
        listener never serves stale stats as a current report."""
        if self.last is not None \
                and self.last["iteration"] == self._iteration:
            return self.last
        return self.sample()

    # ------------------------------------------------------- provenance
    def nonfinite_label(self) -> Optional[str]:
        """Name of the first non-finite layer of the MOST RECENT step
        (fetching just the provenance scalars, not the full tree), or
        None when clean / handled / nothing recorded. Used by the
        divergence guard to label its rollback telemetry."""
        if self._device is None:
            return None
        import jax

        first, handled = jax.device_get(
            [self._device["first_nonfinite"], self._device["handled"]])
        first = int(first)
        if handled or first < 0:
            return None
        names = self._names or []
        return names[first] if first < len(names) else str(first)

    @property
    def fetches(self) -> int:
        """Device->host health transfers so far (test seam: exactly one
        per sampled step)."""
        return self._fetches


__all__ = ["HealthMonitor", "device_stats", "act_flag", "split_health",
           "layer_keys", "layer_names", "capture_flops",
           "add_dispatched_flops", "dispatched_flops", "site_flops",
           "enable_flops_capture", "flops_capture_enabled",
           "wants_flops"]
