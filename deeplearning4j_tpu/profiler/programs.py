"""Roofline program registry + managed device profiling.

The rest of the profiler records *how fast* each site is (step
latencies, one cost_analysis FLOPs number for MFU); this module records
*where the time goes*. Two layers:

**ProgramRegistry** — a process-wide table of every compiled XLA
executable the stack runs: site label, input shape/dtype signature,
compile wall time, HLO digest, ``cost_analysis()`` (flops, bytes
accessed, transcendentals) and ``memory_analysis()`` (temp / argument /
output / generated-code bytes). Per-dispatch accounting (count + host
wall time) turns the static numbers into achieved FLOP/s, achieved
GB/s, arithmetic intensity and a **roofline verdict** per program:

- ``compute_bound``  — AI ≥ ridge point (peak_flops / peak_bandwidth)
- ``memory_bound``   — AI below the ridge: fuse or re-layout, don't
  look for a faster MXU schedule
- ``dispatch_bound`` — the program is too small for the device: its
  roofline-model runtime is under ``DISPATCH_FLOOR_S``, or (when real
  peaks are known) measured dispatch wall time exceeds
  ``DISPATCH_FACTOR``× the roofline time — launch/host overhead
  dominates and kernel tuning is pointless
- ``unknown``        — XLA reported no flops/bytes for the program

Peaks come from ``profiler/flops.py`` (``PEAK_FLOPS`` +
``PEAK_HBM_GBPS``); an unknown device kind is warned-and-omitted, never
guessed — classification then falls back to NOMINAL_* v5e-class ratios
(labeled ``"nominal"`` in snapshots) so verdicts stay available on CPU
smoke runs without publishing bogus utilization numbers.

Populated from ``telemetry.instrument_jit`` (training/eval step sites)
and the serving engines' AOT warm pools (decode/prefill/adopt sites).
The registry is OFF by default (``DL4J_TPU_PROGRAMS=1`` env or
``set_enabled(True)``): registration re-lowers the jitted call once at
compile time (abstract trace, hits the executable cache — no second
XLA compile) and per-dispatch accounting is one enabled-check when off,
so off-mode hot paths are bit-identical.

**ProfileSession** — makes device capture a managed artifact instead of
a bare ``jax.profiler`` wrapper. One session per process interlocks
ad-hoc traces (``profiler.start_trace``/``trace()``) and bounded
``capture()`` runs, since jax.profiler supports exactly one active
trace. ``capture()`` writes a digest-valid bundle::

    profile-<utc-stamp>-<trigger>-<pid>-<nonce>/
        trace/...            # raw jax.profiler output (xplane/trace.json)
        programs.json        # registry snapshot at capture time
        manifest.json        # format + trigger + per-file sha256 digests

written atomically (tmp dir → fsync → rename, same recipe as flight
incident dumps), pruned keep-newest (``KEEP_CAPTURES``). Triggers:
``POST /v1/profile`` on the ui/remote servers, the SLO engine's
firing-page hook (``slo.SLOEngine`` — rate-limited via
``maybe_capture``), and ``bench.py --profile``. Every capture emits a
``profile_capture`` flight event and bumps
``dl4j_tpu_profile_captures_total{trigger}``.

Host-wall caveat: dispatch seconds are measured on the host around the
executable call; with async device dispatch they are an upper bound on
launch cost and a lower bound on device occupancy. The training fronts
block per step (score fetch), so step sites are accurate there; decode
sites include queueing slack. The trace bundle is the ground truth.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import tempfile
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.profiler import flight_recorder as _flight
from deeplearning4j_tpu.profiler import telemetry as _telemetry
from deeplearning4j_tpu.profiler.flops import (
    PEAK_FLOPS, PEAK_HBM_GBPS, peak_flops, peak_hbm_gbps,
)

log = logging.getLogger("deeplearning4j_tpu")

_FORMAT = "dl4j-tpu-profile-1"

#: classification fallbacks when device 0 has no peak-table entry
#: (v5e-class bf16 ratios); used for the VERDICT only, never for
#: published utilization numbers.
NOMINAL_PEAK_FLOPS = 197e12
NOMINAL_PEAK_HBM_GBPS = 819.0

#: roofline-model runtime below this is launch overhead territory on
#: any real accelerator (grid launch + host sync ~O(100µs)).
DISPATCH_FLOOR_S = 1e-4
#: measured avg dispatch this many times over the roofline-model time
#: (known peaks only) also reads dispatch_bound.
DISPATCH_FACTOR = 10.0

VERDICTS = ("compute_bound", "memory_bound", "dispatch_bound", "unknown")

_ENABLED = os.environ.get("DL4J_TPU_PROGRAMS", "0") == "1"


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


# ------------------------------------------------------------- verdicts
def roofline_verdict(flops: Optional[float],
                     bytes_accessed: Optional[float],
                     avg_dispatch_s: Optional[float] = None,
                     peak_fl: Optional[float] = None,
                     peak_bw_gbps: Optional[float] = None) -> str:
    """Classify a program against the roofline. ``peak_fl`` /
    ``peak_bw_gbps`` None → nominal ratios (classification only; the
    measured-dispatch test is skipped because comparing CPU wall time
    against a TPU roofline would mislabel everything dispatch_bound)."""
    if not flops or not bytes_accessed:
        return "unknown"
    nominal = peak_fl is None or peak_bw_gbps is None
    pf = peak_fl if peak_fl else NOMINAL_PEAK_FLOPS
    bw = (peak_bw_gbps if peak_bw_gbps else NOMINAL_PEAK_HBM_GBPS) * 1e9
    roofline_s = max(flops / pf, bytes_accessed / bw)
    if roofline_s < DISPATCH_FLOOR_S:
        return "dispatch_bound"
    if (not nominal and avg_dispatch_s is not None
            and avg_dispatch_s > DISPATCH_FACTOR * roofline_s):
        return "dispatch_bound"
    if flops / bytes_accessed < pf / bw:
        return "memory_bound"
    return "compute_bound"


# ------------------------------------------------------------- registry
class _Program:
    __slots__ = ("site", "signature", "source", "engine",
                 "compile_seconds", "hlo_digest", "flops",
                 "bytes_accessed", "transcendentals", "memory",
                 "dispatches", "timed_dispatches", "dispatch_seconds")

    def __init__(self, site, signature, source, engine, compile_seconds):
        self.site = site
        self.signature = signature
        self.source = source
        self.engine = engine
        self.compile_seconds = compile_seconds
        self.hlo_digest = None
        self.flops = None
        self.bytes_accessed = None
        self.transcendentals = None
        self.memory: Dict[str, int] = {}
        self.dispatches = 0
        self.timed_dispatches = 0
        self.dispatch_seconds = 0.0


def _extract(prog: _Program, compiled) -> None:
    """Pull cost/memory analysis + HLO digest off a compiled
    executable; every probe is individually guarded — a backend that
    can't answer one question must not lose the others."""
    try:
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        if ca:
            f = ca.get("flops")
            b = ca.get("bytes accessed")
            t = ca.get("transcendentals")
            prog.flops = float(f) if f else None
            prog.bytes_accessed = float(b) if b else None
            prog.transcendentals = float(t) if t else None
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            prog.memory = {
                "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
                "argument_bytes": int(
                    getattr(ma, "argument_size_in_bytes", 0)),
                "output_bytes": int(
                    getattr(ma, "output_size_in_bytes", 0)),
                "generated_code_bytes": int(
                    getattr(ma, "generated_code_size_in_bytes", 0)),
            }
    except Exception:
        pass
    try:
        prog.hlo_digest = hashlib.sha256(
            compiled.as_text().encode()).hexdigest()[:16]
    except Exception:
        pass


class ProgramRegistry:
    """Process-wide table of compiled executables, keyed
    ``(site, signature)`` — a site that recompiles per shape (TBPTT
    tails, serving batch tiers) gets one row per signature."""

    def __init__(self):
        self._lock = threading.Lock()
        self._programs: Dict[Tuple[str, str], _Program] = {}

    def register(self, site: str, signature: str, compiled, *,
                 source: str = "jit", engine: Optional[str] = None,
                 compile_seconds: Optional[float] = None) -> None:
        """Record (or refresh) one compiled executable. Never raises."""
        try:
            prog = _Program(site, signature, source, engine,
                            compile_seconds)
            _extract(prog, compiled)
            with self._lock:
                old = self._programs.get((site, signature))
                if old is not None:
                    # recompile of a known shape: keep the dispatch
                    # history, refresh the analysis
                    prog.dispatches = old.dispatches
                    prog.timed_dispatches = old.timed_dispatches
                    prog.dispatch_seconds = old.dispatch_seconds
                self._programs[(site, signature)] = prog
        except Exception:
            log.exception("program registry: register(%s) failed", site)

    def record_dispatch(self, site: str, signature: Optional[str],
                        seconds: Optional[float]) -> None:
        """Count one dispatch. ``seconds=None`` counts without timing
        (the compile call's wall time is compile, not execution).
        Unknown (site, signature) dispatches are dropped — a program
        must register before it is accounted."""
        if signature is None:
            return
        with self._lock:
            prog = self._programs.get((site, signature))
            if prog is None:
                return
            prog.dispatches += 1
            if seconds is not None:
                prog.timed_dispatches += 1
                prog.dispatch_seconds += seconds

    def size(self) -> int:
        with self._lock:
            return len(self._programs)

    def reset(self) -> None:
        with self._lock:
            self._programs.clear()

    # ------------------------------------------------------- snapshot
    @staticmethod
    def _device_peaks() -> Dict[str, Any]:
        """Device-0 peak entries, warn-once omitted when unknown (same
        contract as ``peak_flops``)."""
        dev: Dict[str, Any] = {}
        try:
            import jax

            dev["kind"] = jax.devices()[0].device_kind
        except Exception:
            return dev
        fl = PEAK_FLOPS.get(dev["kind"])
        if fl is not None:
            dev["peak_flops"] = dict(fl)
        bw = peak_hbm_gbps()
        if bw is not None:
            dev["peak_hbm_gbps"] = bw
        return dev

    @staticmethod
    def _program_dict(p: _Program, peak_fl, peak_bw) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "site": p.site, "signature": p.signature,
            "source": p.source, "engine": p.engine,
            "compile_seconds": p.compile_seconds,
            "hlo_digest": p.hlo_digest,
            "flops": p.flops, "bytes_accessed": p.bytes_accessed,
            "transcendentals": p.transcendentals,
            "memory": dict(p.memory),
            "dispatches": p.dispatches,
            "dispatch_seconds": round(p.dispatch_seconds, 6),
        }
        if p.flops and p.bytes_accessed:
            d["arithmetic_intensity"] = p.flops / p.bytes_accessed
        avg = (p.dispatch_seconds / p.timed_dispatches
               if p.timed_dispatches else None)
        if avg and p.flops:
            d["achieved_flops_per_s"] = p.flops / avg
            if peak_fl:
                d["mfu"] = round(p.flops / avg / peak_fl, 4)
        if avg and p.bytes_accessed:
            d["achieved_gbps"] = p.bytes_accessed / avg / 1e9
            if peak_bw:
                d["hbm_utilization"] = round(
                    p.bytes_accessed / avg / 1e9 / peak_bw, 4)
        d["verdict"] = roofline_verdict(
            p.flops, p.bytes_accessed, avg_dispatch_s=avg,
            peak_fl=peak_fl, peak_bw_gbps=peak_bw)
        return d

    def snapshot(self, top_n: Optional[int] = None) -> Dict[str, Any]:
        """JSON-ready registry view: per-program rows (sorted by total
        dispatch wall time, descending — "top N by device time") plus
        per-site aggregates with their own roofline verdict."""
        with self._lock:
            progs = list(self._programs.values())
        dev = self._device_peaks()
        kind = dev.get("kind")
        peak_bw = PEAK_HBM_GBPS.get(kind) if kind else None

        def _peak_fl(p: _Program) -> Optional[float]:
            entry = PEAK_FLOPS.get(kind) if kind else None
            if entry is None:
                return None
            key = ("bf16" if p.signature and "bfloat16" in p.signature
                   else "f32")
            return entry.get(key)

        rows = [self._program_dict(p, _peak_fl(p), peak_bw)
                for p in progs]
        rows.sort(key=lambda d: (-d["dispatch_seconds"], d["site"]))

        sites: Dict[str, Dict[str, Any]] = {}
        for d in rows:
            s = sites.setdefault(d["site"], {
                "programs": 0, "dispatches": 0, "dispatch_seconds": 0.0,
                "flops": 0.0, "bytes_accessed": 0.0, "verdict": "unknown",
            })
            s["programs"] += 1
            s["dispatches"] += d["dispatches"]
            s["dispatch_seconds"] = round(
                s["dispatch_seconds"] + d["dispatch_seconds"], 6)
            s["flops"] += (d["flops"] or 0.0) * d["dispatches"]
            s["bytes_accessed"] += \
                (d["bytes_accessed"] or 0.0) * d["dispatches"]
            if s["verdict"] == "unknown":
                # rows arrive sorted by device time: the first program
                # with a verdict is the site's dominant one
                s["verdict"] = d["verdict"]
        for s in sites.values():
            if s["flops"] and s["bytes_accessed"]:
                s["arithmetic_intensity"] = \
                    s["flops"] / s["bytes_accessed"]

        if top_n is not None:
            rows = rows[:max(0, int(top_n))]
        out: Dict[str, Any] = {
            "enabled": _ENABLED,
            "peak_source": ("table" if kind in PEAK_FLOPS
                            else "nominal"),
            "programs": rows,
            "sites": sites,
        }
        if dev:
            out["device"] = dev
        return out


_default: Optional[ProgramRegistry] = None
_dlock = threading.Lock()


def get_default() -> ProgramRegistry:
    global _default
    if _default is None:
        with _dlock:
            if _default is None:
                _default = ProgramRegistry()
    return _default


def snapshot(top_n: Optional[int] = None) -> Dict[str, Any]:
    """Peek-style snapshot for ``telemetry.snapshot()`` embedding: {}
    unless at least one program has registered (so off-mode snapshots
    are unchanged)."""
    r = _default
    if r is None or not r.size():
        return {}
    return r.snapshot(top_n)


def on_jit_compile(site: str, fn, args, kwargs, signature: str,
                   compile_seconds: float) -> None:
    """``telemetry.instrument_jit`` hook, called on a detected compile.
    Re-lowers the call abstractly — this hits the executable cache the
    compile just populated, so there is no second XLA compile. Never
    raises."""
    if not _ENABLED:
        return
    try:
        compiled = fn.lower(*args, **kwargs).compile()
    except Exception:
        log.debug("program registry: lower(%s) failed", site,
                  exc_info=True)
        return
    get_default().register(site, signature, compiled, source="jit",
                           compile_seconds=compile_seconds)


def record_dispatch(site: str, signature: Optional[str],
                    seconds: Optional[float]) -> None:
    if not _ENABLED:
        return
    r = _default
    if r is not None:
        r.record_dispatch(site, signature, seconds)


# ------------------------------------------------------ profile session
def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _slug(s: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "-"
                   for c in str(s))[:48] or "manual"


def list_captures(root: str) -> List[str]:
    """Capture bundle dirs under ``root``, oldest first (the UTC stamp
    prefix makes name order time order)."""
    try:
        names = sorted(n for n in os.listdir(root)
                       if n.startswith("profile-")
                       and not n.startswith(".")
                       and os.path.isfile(
                           os.path.join(root, n, "manifest.json")))
    except OSError:
        return []
    return [os.path.join(root, n) for n in names]


class ProfileSession:
    """Single owner of the process's jax.profiler trace slot.

    jax.profiler supports ONE active trace per process (a second
    ``start_trace`` raises RuntimeError from inside XLA); this class is
    the interlock between ad-hoc traces (``profiler.start_trace`` /
    ``trace()``) and managed bounded ``capture()`` bundles, so they can
    never interleave. All entry points are no-op-with-warning rather
    than raising when the slot is busy."""

    KEEP_CAPTURES = 8
    MAX_DURATION_S = 60.0

    def __init__(self, directory: Optional[str] = None):
        self._lock = threading.Lock()
        self._owner: Optional[str] = None      # "manual" | "capture"
        self.directory = directory
        self.last_bundle: Optional[str] = None
        self._last_capture_mono: Optional[float] = None

    def active(self) -> Optional[str]:
        """Current owner ("manual"/"capture") or None when idle."""
        with self._lock:
            return self._owner

    def _resolve_dir(self, directory: Optional[str]) -> str:
        return (directory or self.directory
                or os.environ.get("DL4J_TPU_PROFILE_DIR")
                or os.path.join(tempfile.gettempdir(),
                                "dl4j_tpu_profiles"))

    # ------------------------------------------------- ad-hoc traces
    def start_manual(self, log_dir: str) -> bool:
        """Idempotent-with-warning start. False when a trace or capture
        is already active (the old code called jax.profiler.start_trace
        again and got RuntimeError)."""
        import jax

        with self._lock:
            if self._owner is not None:
                log.warning(
                    "profiler: a %s trace is already active — ignoring "
                    "start_trace(%r)", self._owner, log_dir)
                return False
            # failure (bad dir, backend refusal) propagates and leaves
            # the slot free — trace() then knows not to stop
            jax.profiler.start_trace(log_dir)
            self._owner = "manual"
            return True

    def stop_manual(self) -> bool:
        """Stop an ad-hoc trace; False (no-op) unless one is active —
        a managed capture in flight is never stopped from here."""
        import jax

        with self._lock:
            if self._owner != "manual":
                return False
            try:
                jax.profiler.stop_trace()
            finally:
                self._owner = None
            return True

    # ---------------------------------------------- managed captures
    def capture(self, duration_s: float = 0.5, *,
                trigger: str = "manual",
                directory: Optional[str] = None,
                work=None) -> Optional[str]:
        """Bounded jax.profiler capture → digest-valid bundle path, or
        None (slot busy / capture failed). Never raises. ``work`` (a
        nullary callable, e.g. one training step) runs inside the trace
        before the residual ``duration_s`` sleep, so there is always
        device activity on the timeline."""
        import jax

        try:
            duration_s = min(max(float(duration_s), 0.0),
                             self.MAX_DURATION_S)
        except (TypeError, ValueError):
            return None
        with self._lock:
            if self._owner is not None:
                log.warning(
                    "profiler: capture(%s) skipped — a %s trace is "
                    "already active", trigger, self._owner)
                return None
            self._owner = "capture"
        root = self._resolve_dir(directory)
        name = (f"profile-{time.strftime('%Y%m%dT%H%M%S', time.gmtime())}"
                f"-{_slug(trigger)}-{os.getpid()}-{uuid.uuid4().hex[:6]}")
        final = os.path.join(root, name)
        tmp = os.path.join(root, f".{name}.tmp")
        try:
            os.makedirs(os.path.join(tmp, "trace"), exist_ok=True)
            t0 = time.perf_counter()
            jax.profiler.start_trace(os.path.join(tmp, "trace"))
            try:
                if work is not None:
                    work()
                if duration_s > 0:
                    time.sleep(duration_s)
            finally:
                jax.profiler.stop_trace()
            path = self._write_bundle(
                tmp, final, root, trigger,
                measured_s=time.perf_counter() - t0)
        except Exception:
            log.exception("profiler: capture(%s) failed", trigger)
            shutil.rmtree(tmp, ignore_errors=True)
            return None
        finally:
            with self._lock:
                self._owner = None
        self.last_bundle = path
        _flight.record("profile_capture", trigger=trigger, bundle=path)
        _telemetry.MetricsRegistry.get_default().counter(
            _telemetry.PROFILE_CAPTURES,
            "managed device-profile captures written").inc(
            trigger=trigger)
        log.info("profiler: capture(%s) wrote %s", trigger, path)
        return path

    def maybe_capture(self, *, trigger: str, duration_s: float = 0.25,
                      min_interval_s: float = 120.0,
                      directory: Optional[str] = None,
                      work=None) -> Optional[str]:
        """Rate-limited capture for automated triggers (the SLO page
        hook): at most one bundle per ``min_interval_s`` across ALL
        automated triggers, None when inside the window or the slot is
        busy. Manual ``capture()`` calls do NOT advance the limiter —
        an operator forcing a bundle must not suppress the next
        alert-triggered diagnostic. Never raises."""
        with self._lock:
            last = self._last_capture_mono
        if last is not None and \
                time.monotonic() - last < min_interval_s:
            log.info("profiler: capture(%s) rate-limited "
                     "(min_interval_s=%s)", trigger, min_interval_s)
            return None
        path = self.capture(duration_s, trigger=trigger,
                            directory=directory, work=work)
        if path is not None:
            self._last_capture_mono = time.monotonic()
        return path

    # ------------------------------------------------------- bundles
    def _write_bundle(self, tmp: str, final: str, root: str,
                      trigger: str, measured_s: float) -> str:
        from deeplearning4j_tpu.util.model_serializer import \
            fsync_directory

        with open(os.path.join(tmp, "programs.json"), "w") as f:
            f.write(json.dumps(get_default().snapshot()))
        digests = {}
        for dirpath, _dirnames, filenames in os.walk(tmp):
            for fn in filenames:
                p = os.path.join(dirpath, fn)
                rel = os.path.relpath(p, tmp)
                digests[rel] = _sha256(p)
                with open(p, "rb") as f:
                    os.fsync(f.fileno())
        manifest = {"format": _FORMAT, "trigger": trigger,
                    "created_unix": time.time(),
                    "duration_s": round(measured_s, 6),
                    "pid": os.getpid(), "digests": digests}
        mpath = os.path.join(tmp, "manifest.json")
        with open(mpath, "w") as f:
            f.write(json.dumps(manifest, indent=1, sort_keys=True))
            f.flush()
            os.fsync(f.fileno())
        fsync_directory(tmp)
        os.replace(tmp, final)
        fsync_directory(root)
        for old in list_captures(root)[:-self.KEEP_CAPTURES]:
            shutil.rmtree(old, ignore_errors=True)
        return final


_session: Optional[ProfileSession] = None


def profile_session() -> ProfileSession:
    global _session
    if _session is None:
        with _dlock:
            if _session is None:
                _session = ProfileSession()
    return _session


def load_capture(path: str) -> Dict[str, Any]:
    """Read a capture bundle back, verifying every manifest digest.
    Returns {"path", "valid", "manifest", "programs"}; ``valid`` False
    on format mismatch, a missing member, or a digest mismatch."""
    out: Dict[str, Any] = {"path": path, "valid": False,
                           "manifest": None, "programs": None}
    try:
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return out
    out["manifest"] = manifest
    if manifest.get("format") != _FORMAT:
        return out
    ok = True
    for rel, want in (manifest.get("digests") or {}).items():
        p = os.path.join(path, rel)
        try:
            ok = ok and _sha256(p) == want
        except OSError:
            ok = False
    out["valid"] = ok
    if "programs.json" in (manifest.get("digests") or {}):
        try:
            with open(os.path.join(path, "programs.json")) as f:
                out["programs"] = json.load(f)
        except (OSError, ValueError):
            out["valid"] = False
    return out


# ----------------------------------------------------------------- http
def http_programs(query: str = "") -> Tuple[Dict[str, Any], int]:
    """GET /v1/programs handler shared by the ui and remote servers:
    registry snapshot, top-N programs by device time (?n=, default 50).
    Returns (json-ready object, http status)."""
    import urllib.parse

    n = 50
    try:
        q = urllib.parse.parse_qs(query or "")
        if "n" in q:
            n = max(1, min(500, int(q["n"][0])))
    except ValueError:
        return {"error": "n must be an integer"}, 400
    return get_default().snapshot(top_n=n), 200


def http_profile(payload: Any) -> Tuple[Dict[str, Any], int]:
    """POST /v1/profile handler: forced (non-rate-limited) capture.
    Body: {"duration_s": 0.5, "trigger": "...", "directory": "..."} —
    all optional. 409 when a trace/capture is already active, 500 when
    the capture itself failed."""
    if not isinstance(payload, dict):
        return {"error": "body must be a JSON object"}, 400
    try:
        duration = float(payload.get("duration_s", 0.5))
    except (TypeError, ValueError):
        return {"error": "duration_s must be a number"}, 400
    if not 0.0 <= duration <= ProfileSession.MAX_DURATION_S:
        return {"error": "duration_s must be in "
                         f"[0, {ProfileSession.MAX_DURATION_S}]"}, 400
    sess = profile_session()
    if sess.active() is not None:
        return {"error": "a profiler trace/capture is already "
                         "active"}, 409
    path = sess.capture(duration,
                        trigger=_slug(payload.get("trigger") or "http"),
                        directory=payload.get("directory"))
    if path is None:
        return {"error": "profile capture failed (see logs)"}, 500
    return {"bundle": path,
            "programs": get_default().size()}, 200


def reset() -> None:
    """Fresh default registry + session rate-limit state (tests /
    between bench rounds). An ACTIVE session is left untouched."""
    global _default, _session
    with _dlock:
        _default = None
        s = _session
        if s is not None and s.active() is None:
            _session = None


__all__ = [
    "ProgramRegistry", "ProfileSession", "roofline_verdict", "VERDICTS",
    "NOMINAL_PEAK_FLOPS", "NOMINAL_PEAK_HBM_GBPS", "DISPATCH_FLOOR_S",
    "DISPATCH_FACTOR", "enabled", "set_enabled", "get_default",
    "snapshot", "on_jit_compile", "record_dispatch", "profile_session",
    "load_capture", "list_captures", "http_programs", "http_profile",
    "reset", "PEAK_FLOPS", "PEAK_HBM_GBPS", "peak_flops",
    "peak_hbm_gbps",
]
