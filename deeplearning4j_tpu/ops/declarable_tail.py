"""Final declarable-op tail: losses, RNN cells, updater ops, NN
helpers, shape utilities, image ops, moments, and merge/bitpack
stragglers (reference: libnd4j/include/ops/declarable/generic/** —
the remaining families from SURVEY.md §2.6's ~500-op inventory:
generic/loss/*, generic/recurrent/{sruCell,lstmCell,gruCell}.cpp,
generic/updaters/*.cpp, generic/nn/{dilation2d,col2im,...}.cpp,
generic/parity_ops, generic/images, generic/broadcastable).

All compute is jax/lax composition: under jit XLA fuses these into the
surrounding program, so "one declarable op = one C++ kernel" becomes
"one declarable op = one traced region" with no dispatch cost.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.ops.registry import register_op


# ---------------------------------------------------------------- losses
# (reference: ops/declarable/generic/loss/*.cpp)
def _weighted_mean(per_elem, weights):
    w = jnp.broadcast_to(jnp.asarray(weights, per_elem.dtype),
                         per_elem.shape)
    denom = jnp.maximum(jnp.sum(w), 1e-12)
    return jnp.sum(per_elem * w) / denom


@register_op("l2_loss")
def l2_loss(x):
    """sum(x**2) / 2 (reference: loss/l2_loss.cpp; TF tf.nn.l2_loss)."""
    return jnp.sum(jnp.square(x)) / 2


@register_op("mean_squared_error")
def mean_squared_error(labels, predictions, weights=1.0):
    """Weighted-mean squared error (loss/mean_sqerr_loss.cpp)."""
    return _weighted_mean(jnp.square(predictions - labels), weights)


@register_op("smooth_l1_loss")
def smooth_l1_loss(predictions, labels, delta=1.0):
    """Elementwise smooth-L1 (huber without slope rescale), mean."""
    d = jnp.abs(predictions - labels)
    per = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return jnp.mean(per)


@register_op("sparse_softmax_cross_entropy")
def sparse_softmax_cross_entropy(logits, labels):
    """Integer-label softmax CE (loss/sparseSoftmaxCrossEntropy...)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(
        logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return -picked


@register_op("weighted_cross_entropy_with_logits")
def weighted_cross_entropy_with_logits(targets, logits, pos_weight):
    """TF-stable formulation: (1-t)x + (1+(pw-1)t)(log1p(e^-|x|)+max(-x,0))."""
    x, t = logits, targets
    log_weight = 1.0 + (pos_weight - 1.0) * t
    return (1.0 - t) * x + log_weight * (
        jnp.log1p(jnp.exp(-jnp.abs(x))) + jax.nn.relu(-x))


@register_op("log_poisson_loss")
def log_poisson_loss(log_input, targets, compute_full_loss=False):
    """exp(log_input) - targets*log_input (+ Stirling when full)."""
    loss = jnp.exp(log_input) - targets * log_input
    if compute_full_loss:
        stirling = (targets * jnp.log(jnp.maximum(targets, 1e-12))
                    - targets
                    + 0.5 * jnp.log(2.0 * np.pi
                                    * jnp.maximum(targets, 1e-12)))
        loss = loss + jnp.where(targets >= 1.0, stirling, 0.0)
    return loss


# ------------------------------------------------------------- RNN cells
# (reference: generic/recurrent/{lstmCell,gruCell,sru,sruCell}.cpp —
# single-step cells; the fused sequence layers live in ops/nn.py)
@register_op("lstm_cell")
def lstm_cell(x, h_prev, c_prev, w, b):
    """One LSTM step. w: [(in+h), 4h] gate order i,f,g,o; returns (h, c)."""
    z = jnp.concatenate([x, h_prev], axis=-1) @ w + b
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c = jax.nn.sigmoid(f) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, c


@register_op("gru_cell")
def gru_cell(x, h_prev, w, b):
    """One GRU step. w: [(in+h), 3h] gate order z,r,n; returns h."""
    hsz = h_prev.shape[-1]
    cat = jnp.concatenate([x, h_prev], axis=-1)
    zr = jax.nn.sigmoid(cat @ w[:, :2 * hsz] + b[:2 * hsz])
    z, r = jnp.split(zr, 2, axis=-1)
    n = jnp.tanh(jnp.concatenate([x, r * h_prev], axis=-1)
                 @ w[:, 2 * hsz:] + b[2 * hsz:])
    return (1.0 - z) * n + z * h_prev


@register_op("sru_cell")
def sru_cell(x, c_prev, w, b):
    """One SRU step (reference: sruCell.cpp). w: [d, 3d], b: [2d]."""
    d = x.shape[-1]
    u = x @ w
    xt, fp, rp = u[..., :d], u[..., d:2 * d], u[..., 2 * d:]
    f = jax.nn.sigmoid(fp + b[:d])
    r = jax.nn.sigmoid(rp + b[d:])
    c = f * c_prev + (1.0 - f) * xt
    h = r * jnp.tanh(c) + (1.0 - r) * x
    return h, c


@register_op("sru")
def sru(x, w, b, c0):
    """Simple Recurrent Unit over a sequence [N, T, d] via lax.scan —
    the recurrence is elementwise, so the matmul is hoisted out of the
    loop (the property SRU was designed for; generic/recurrent/sru.cpp).
    Returns (h_seq, c_last)."""
    d = x.shape[-1]
    u = x @ w
    f = jax.nn.sigmoid(u[..., d:2 * d] + b[:d])
    r = jax.nn.sigmoid(u[..., 2 * d:] + b[d:])
    xt = u[..., :d]

    def step(c, inp):
        xt_t, f_t, r_t, x_t = inp
        c_new = f_t * c + (1.0 - f_t) * xt_t
        h_t = r_t * jnp.tanh(c_new) + (1.0 - r_t) * x_t
        return c_new, h_t

    tm = lambda a: jnp.moveaxis(a, 1, 0)  # noqa: E731
    c_last, h_seq = lax.scan(step, c0, (tm(xt), tm(f), tm(r), tm(x)))
    return jnp.moveaxis(h_seq, 0, 1), c_last


# ------------------------------------------------------------ updater ops
# (reference: generic/updaters/*.cpp — the updater math as declarable
# ops, separate from the object-level API in learning/updaters.py).
# Uniform contract: (update_to_subtract, *new_states).
@register_op("sgd_updater")
def sgd_updater(g, lr=0.01):
    return g * lr


@register_op("nesterovs_updater")
def nesterovs_updater(g, v, lr=0.01, momentum=0.9):
    v_new = momentum * v - lr * g
    return -(momentum * v_new - lr * g), v_new


@register_op("ada_grad_updater")
def ada_grad_updater(g, acc, lr=0.01, eps=1e-6):
    acc_new = acc + jnp.square(g)
    return lr * g / (jnp.sqrt(acc_new) + eps), acc_new


@register_op("rms_prop_updater")
def rms_prop_updater(g, acc, lr=0.01, decay=0.95, eps=1e-8):
    acc_new = decay * acc + (1.0 - decay) * jnp.square(g)
    return lr * g / (jnp.sqrt(acc_new) + eps), acc_new


@register_op("ada_delta_updater")
def ada_delta_updater(g, msg, msdx, rho=0.95, eps=1e-6):
    msg_new = rho * msg + (1.0 - rho) * jnp.square(g)
    dx = g * jnp.sqrt(msdx + eps) / jnp.sqrt(msg_new + eps)
    msdx_new = rho * msdx + (1.0 - rho) * jnp.square(dx)
    return dx, msg_new, msdx_new


def _adam_moments(g, m, v, beta1, beta2, step):
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * jnp.square(g)
    t = jnp.asarray(step, g.dtype) + 1.0
    mhat = m_new / (1.0 - beta1 ** t)
    vhat = v_new / (1.0 - beta2 ** t)
    return m_new, v_new, mhat, vhat


def _adam_alpha(g, lr, beta1, beta2, step):
    """DL4J/libnd4j formulation (generic/updaters/adamUpdater.cpp):
    alpha = lr * sqrt(1-b2^t) / (1-b1^t), applied to RAW moments —
    algebraically Adam's bias correction folded into the step size."""
    t = jnp.asarray(step, g.dtype) + 1.0
    return lr * jnp.sqrt(1.0 - beta2 ** t) / (1.0 - beta1 ** t)


@register_op("adam_updater")
def adam_updater(g, m, v, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
                 step=0):
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * jnp.square(g)
    alpha = _adam_alpha(g, lr, beta1, beta2, step)
    return alpha * m_new / (jnp.sqrt(v_new) + eps), m_new, v_new


@register_op("ada_max_updater")
def ada_max_updater(g, m, u, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
                    step=0):
    m_new = beta1 * m + (1.0 - beta1) * g
    u_new = jnp.maximum(beta2 * u, jnp.abs(g))
    t = jnp.asarray(step, g.dtype) + 1.0
    return lr * m_new / ((1.0 - beta1 ** t) * (u_new + eps)), m_new, u_new


@register_op("nadam_updater")
def nadam_updater(g, m, v, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
                  step=0):
    m_new, v_new, mhat, vhat = _adam_moments(g, m, v, beta1, beta2, step)
    t = jnp.asarray(step, g.dtype) + 1.0
    upd = (beta1 * mhat + (1.0 - beta1) * g / (1.0 - beta1 ** t))
    return lr * upd / (jnp.sqrt(vhat) + eps), m_new, v_new


@register_op("ams_grad_updater")
def ams_grad_updater(g, m, v, vhat, lr=1e-3, beta1=0.9, beta2=0.999,
                     eps=1e-8, step=0):
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * jnp.square(g)
    vhat_new = jnp.maximum(vhat, v_new)
    alpha = _adam_alpha(g, lr, beta1, beta2, step)
    return (alpha * m_new / (jnp.sqrt(vhat_new) + eps),
            m_new, v_new, vhat_new)


# --------------------------------------------------------- abs reductions
# (reference: legacy reduce loops amax/amin/amean/asum)
def _reduce(fn, x, dimensions=None, keep_dims=False):
    ax = tuple(dimensions) if dimensions is not None else None
    return fn(x, axis=ax, keepdims=keep_dims)


@register_op("amax")
def amax(x, dimensions=None, keep_dims=False):
    return _reduce(jnp.max, jnp.abs(x), dimensions, keep_dims)


@register_op("amin")
def amin(x, dimensions=None, keep_dims=False):
    return _reduce(jnp.min, jnp.abs(x), dimensions, keep_dims)


@register_op("amean")
def amean(x, dimensions=None, keep_dims=False):
    return _reduce(jnp.mean, jnp.abs(x), dimensions, keep_dims)


@register_op("asum")
def asum(x, dimensions=None, keep_dims=False):
    return _reduce(jnp.sum, jnp.abs(x), dimensions, keep_dims)


# -------------------------------------------------------------- NN extras
@register_op("bias_add")
def bias_add(x, bias):
    """Channel-last bias broadcast (generic/broadcastable/bias_add)."""
    return x + bias


@register_op("relu_layer")
def relu_layer(x, w, b):
    """relu(x @ w + b) (generic/nn/relu_layer.cpp)."""
    return jax.nn.relu(x @ w + b)


@register_op("pointwise_conv2d")
def pointwise_conv2d(x, w):
    """1x1 conv = channel mixing einsum (generic/nn/convo/pointwise)."""
    if w.ndim == 4:
        w = w[0, 0]
    return jnp.einsum("nhwc,co->nhwo", x, w)


@register_op("deconv3d")
def deconv3d(x, w, strides=(1, 1, 1), padding="VALID"):
    """3-D transposed conv, NDHWC x DHWIO (generic/nn/convo/deconv3d)."""
    return lax.conv_transpose(
        x, w, tuple(strides), padding,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))


@register_op("upsampling3d")
def upsampling3d(x, scale=2):
    s = (scale, scale, scale) if np.isscalar(scale) else tuple(scale)
    for ax, k in zip((1, 2, 3), s):
        x = jnp.repeat(x, k, axis=ax)
    return x


@register_op("dilation2d")
def dilation2d(x, filt, strides=(1, 1), rates=(1, 1), padding="VALID"):
    """Grayscale morphological dilation (generic/nn/dilation2d.cpp):
    out = max over window of (x + filt). Patches come channel-major
    from conv_general_dilated_patches; filt is [kh, kw, C]."""
    kh, kw, c = filt.shape
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), tuple(strides), padding,
        rhs_dilation=tuple(rates),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    n, oh, ow, _ = patches.shape
    patches = patches.reshape(n, oh, ow, c, kh * kw)
    f = jnp.transpose(filt, (2, 0, 1)).reshape(c, kh * kw)
    return jnp.max(patches + f, axis=-1)


@register_op("max_pool_with_argmax")
def max_pool_with_argmax(x, kernel=(2, 2), strides=None,
                         padding="VALID"):
    """Max pool + TF-layout flat argmax ((h*W + w)*C + c) —
    generic/nn/max_pool_with_argmax.cpp."""
    kh, kw = kernel
    sh, sw = strides if strides is not None else kernel
    n, h, w, c = x.shape
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    _, oh, ow, _ = patches.shape
    patches = patches.reshape(n, oh, ow, c, kh, kw)
    vals = jnp.max(patches, axis=(-2, -1))
    flat_k = jnp.argmax(patches.reshape(n, oh, ow, c, kh * kw), axis=-1)
    ki, kj = flat_k // kw, flat_k % kw
    hh = jnp.arange(oh)[None, :, None, None] * sh + ki
    ww = jnp.arange(ow)[None, None, :, None] * sw + kj
    cc = jnp.arange(c)[None, None, None, :]
    idx = (hh * w + ww) * c + cc
    return vals, idx.astype(jnp.int64)


@register_op("col2im")
def col2im(cols, out_size, kernel, strides=(1, 1)):
    """Inverse of im2col (generic/nn/col2im.cpp): scatter-add patches
    [N, oH, oW, C*kH*kW] (channel-major, matching ops/nn.py im2col)
    back onto [N, H, W, C]."""
    n, oh, ow, f = cols.shape
    kh, kw = kernel
    sh, sw = strides
    c = f // (kh * kw)
    h, w = out_size
    cols = cols.reshape(n, oh, ow, c, kh, kw)
    out = jnp.zeros((n, h, w, c), cols.dtype)
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, i:i + sh * oh:sh, j:j + sw * ow:sw, :].add(
                cols[:, :, :, :, i, j])
    return out


@register_op("precise_gelu")
def precise_gelu(x):
    """Exact erf-based GELU (reference precise_gelu vs tanh approx)."""
    return 0.5 * x * (1.0 + lax.erf(x / np.sqrt(2.0)))


# ------------------------------------------------------- shape/transform
@register_op("invert_permutation")
def invert_permutation(p):
    return jnp.argsort(p)


@register_op("parallel_stack")
def parallel_stack(*xs):
    return jnp.stack(xs, axis=0)


@register_op("identity_n")
def identity_n(*xs):
    return tuple(xs)


@register_op("dynamic_partition")
def dynamic_partition(x, partitions, num_partitions):
    """Eager-only (outputs are data-dependently sized, like the
    reference op); the jit-safe masked variant is
    dynamic_partition_masks."""
    parts = np.asarray(partitions)
    xs = np.asarray(x)
    return tuple(jnp.asarray(xs[parts == i])
                 for i in range(int(num_partitions)))


@register_op("unique")
def unique(x):
    """Eager-only (dynamic output size): values + inverse indices."""
    vals, inv = np.unique(np.asarray(x), return_inverse=True)
    return jnp.asarray(vals), jnp.asarray(inv.astype(np.int32))


@register_op("setdiff1d")
def setdiff1d(x, y):
    """TF ListDiff: values of x not in y, plus their indices (eager)."""
    xs, ys = np.asarray(x), np.asarray(y)
    mask = ~np.isin(xs, ys)
    return (jnp.asarray(xs[mask]),
            jnp.asarray(np.nonzero(mask)[0].astype(np.int32)))


@register_op("broadcast_dynamic_shape")
def broadcast_dynamic_shape(s1, s2):
    out = np.broadcast_shapes(tuple(np.asarray(s1).tolist()),
                              tuple(np.asarray(s2).tolist()))
    return jnp.asarray(np.asarray(out, np.int32))


@register_op("size_at")
def size_at(x, dim):
    return jnp.asarray(x.shape[int(dim)], jnp.int64)


@register_op("tile_to_shape")
def tile_to_shape(x, shape):
    return jnp.broadcast_to(x, tuple(int(d) for d in shape))


@register_op("assign")
def assign(x, y):
    """Functional assign: y broadcast into x's shape/dtype
    (generic/broadcastable/assign.cpp — no in-place under XLA)."""
    return jnp.broadcast_to(jnp.asarray(y, x.dtype), x.shape)


@register_op("create")
def create(shape, dtype="float32"):
    return jnp.zeros(tuple(int(d) for d in shape), jnp.dtype(dtype))


@register_op("clip_by_global_norm")
def clip_by_global_norm(*tensors, clip_norm=1.0):
    gn = jnp.sqrt(sum(jnp.sum(jnp.square(t)) for t in tensors))
    scale = clip_norm / jnp.maximum(gn, clip_norm)
    return tuple(t * scale for t in tensors) + (gn,)


@register_op("clip_by_avg_norm")
def clip_by_avg_norm(t, clip_norm=1.0):
    avg = jnp.sqrt(jnp.sum(jnp.square(t))) / t.size
    return t * (clip_norm / jnp.maximum(avg, clip_norm))


@register_op("space_to_batch_nd")
def space_to_batch_nd(x, block_shape, paddings):
    """N-D generalization (generic/parity_ops/space_to_batch_nd)."""
    block = [int(b) for b in block_shape]
    m = len(block)
    pads = [(0, 0)] + [(int(a), int(b)) for a, b in paddings] + \
        [(0, 0)] * (x.ndim - 1 - m)
    x = jnp.pad(x, pads)
    n = x.shape[0]
    rest = x.shape[1 + m:]
    shp = [n]
    for i in range(m):
        shp += [x.shape[1 + i] // block[i], block[i]]
    x = x.reshape(shp + list(rest))
    perm = [2 * i + 2 for i in range(m)] + [0] + \
        [2 * i + 1 for i in range(m)] + \
        list(range(1 + 2 * m, x.ndim))
    x = jnp.transpose(x, perm)
    out_n = n * int(np.prod(block))
    out_sp = [x.shape[m + 1 + i] for i in range(m)]
    return x.reshape([out_n] + out_sp + list(rest))


@register_op("batch_to_space_nd")
def batch_to_space_nd(x, block_shape, crops):
    block = [int(b) for b in block_shape]
    m = len(block)
    n = x.shape[0] // int(np.prod(block))
    sp = list(x.shape[1:1 + m])
    rest = x.shape[1 + m:]
    x = x.reshape(block + [n] + sp + list(rest))
    perm = [m]
    for i in range(m):
        perm += [m + 1 + i, i]
    perm += list(range(2 * m + 1, x.ndim))
    x = jnp.transpose(x, perm)
    x = x.reshape([n] + [sp[i] * block[i] for i in range(m)] +
                  list(rest))
    for i in range(m):
        c0, c1 = int(crops[i][0]), int(crops[i][1])
        x = lax.slice_in_dim(x, c0, x.shape[1 + i] - c1, axis=1 + i)
    return x


# ---------------------------------------------------------------- moments
@register_op("sufficient_statistics")
def sufficient_statistics(x, axes, shift=None):
    ax = tuple(int(a) for a in axes)
    count = jnp.asarray(np.prod([x.shape[a] for a in ax]), x.dtype)
    xs = x - shift if shift is not None else x
    return (count, jnp.sum(xs, axis=ax), jnp.sum(jnp.square(xs), axis=ax),
            shift if shift is not None else jnp.zeros((), x.dtype))


@register_op("normalize_moments")
def normalize_moments(count, mean_ss, variance_ss, shift=0.0):
    mean = mean_ss / count + shift
    variance = variance_ss / count - jnp.square(mean_ss / count)
    return mean, variance


@register_op("weighted_moments")
def weighted_moments(x, axes, weights):
    ax = tuple(int(a) for a in axes)
    w = jnp.broadcast_to(weights, x.shape)
    wsum = jnp.sum(w, axis=ax)
    mean = jnp.sum(x * w, axis=ax) / wsum
    var = jnp.sum(w * jnp.square(x - jnp.expand_dims(mean, ax)),
                  axis=ax) / wsum
    return mean, var


# ------------------------------------------------------------------ image
_YIQ = np.array([[0.299, 0.587, 0.114],
                 [0.59590059, -0.27455667, -0.32134392],
                 [0.21153661, -0.52273617, 0.31119955]], np.float32)


@register_op("rgb_to_yiq")
def rgb_to_yiq(x):
    return jnp.einsum("...c,dc->...d", x, jnp.asarray(_YIQ, x.dtype))


@register_op("yiq_to_rgb")
def yiq_to_rgb(x):
    inv = jnp.asarray(np.linalg.inv(_YIQ), x.dtype)
    return jnp.einsum("...c,dc->...d", x, inv)


@register_op("image_resize")
def image_resize(x, size, method="bilinear", antialias=False):
    """Generic resize dispatcher (generic/parity_ops/image_resize).
    'area' follows ops/image.py resize_area: antialiased linear is the
    box-filter approximation jax.image offers (no 'area' kernel)."""
    h, w = int(size[0]), int(size[1])
    if method == "area":
        method, antialias = "linear", True
    else:
        method = {"bicubic": "cubic", "bilinear": "linear",
                  "nearest": "nearest", "lanczos3": "lanczos3",
                  "lanczos5": "lanczos5", "cubic": "cubic",
                  "linear": "linear"}[method]
    shape = x.shape[:-3] + (h, w, x.shape[-1])
    if method == "nearest":
        return jax.image.resize(x, shape, "nearest")
    return jax.image.resize(x, shape, method, antialias=antialias)


@register_op("random_crop")
def random_crop(x, size, seed=0):
    key = jax.random.key(int(seed))
    size = tuple(int(d) for d in size)
    starts = []
    for i, (dim, out) in enumerate(zip(x.shape, size)):
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, dim - out + 1))
    return lax.dynamic_slice(x, tuple(starts), size)


@register_op("non_max_suppression_overlaps")
def non_max_suppression_overlaps(overlaps, scores, max_output_size,
                                 overlap_threshold=0.5,
                                 score_threshold=float("-inf")):
    """Greedy NMS over a PRE-COMPUTED overlap matrix (eager; reference
    generic/parity_ops/non_max_suppression_overlaps.cpp)."""
    ov = np.asarray(overlaps)
    sc = np.asarray(scores)
    order = np.argsort(-sc)
    keep = []
    for i in order:
        if sc[i] < score_threshold or len(keep) >= int(max_output_size):
            break
        if all(ov[i, j] <= overlap_threshold for j in keep):
            keep.append(int(i))
    return jnp.asarray(np.asarray(keep, np.int32))


@register_op("draw_bounding_boxes")
def draw_bounding_boxes(images, boxes, colors=None):
    """Paint 1-px box borders (generic/parity_ops/draw_bounding_boxes).
    boxes: [N, B, 4] normalized (y1, x1, y2, x2)."""
    n, h, w, c = images.shape
    nb = boxes.shape[1]
    if colors is None:
        colors = jnp.ones((1, c), images.dtype)
    colors = jnp.asarray(colors, images.dtype)
    ys = jnp.arange(h, dtype=jnp.float32)[None, :, None] / max(h - 1, 1)
    xs = jnp.arange(w, dtype=jnp.float32)[None, None, :] / max(w - 1, 1)
    out = images
    for b in range(nb):
        y1, x1, y2, x2 = (boxes[:, b, i][:, None, None] for i in range(4))
        inside = (ys >= y1) & (ys <= y2) & (xs >= x1) & (xs <= x2)
        eps_y = 1.0 / max(h - 1, 1)
        eps_x = 1.0 / max(w - 1, 1)
        interior = (ys >= y1 + eps_y) & (ys <= y2 - eps_y) & \
                   (xs >= x1 + eps_x) & (xs <= x2 - eps_x)
        border = (inside & ~interior)[..., None]
        color = colors[b % colors.shape[0]]
        out = jnp.where(border, color, out)
    return out


@register_op("total_variation")
def total_variation(images):
    """Sum of absolute neighbor diffs per image (tf.image parity)."""
    dh = jnp.abs(images[:, 1:, :, :] - images[:, :-1, :, :])
    dw = jnp.abs(images[:, :, 1:, :] - images[:, :, :-1, :])
    return (jnp.sum(dh, axis=(1, 2, 3)) + jnp.sum(dw, axis=(1, 2, 3)))


@register_op("psnr")
def psnr(a, b, max_val=1.0):
    mse = jnp.mean(jnp.square(a - b), axis=(-3, -2, -1))
    return 10.0 * jnp.log10(max_val ** 2 / mse)


# ------------------------------------------------------------- stragglers
@register_op("zeta")
def zeta(x, q):
    return jax.scipy.special.zeta(x, q)


@register_op("lbeta")
def lbeta(x):
    gl = jax.scipy.special.gammaln
    return jnp.sum(gl(x), axis=-1) - gl(jnp.sum(x, axis=-1))


@register_op("axpy")
def axpy(a, x, y):
    return a * x + y


@register_op("histogram")
def histogram(x, nbins=10):
    """Counts over [min(x), max(x)] (generic/parity_ops/histogram)."""
    lo, hi = jnp.min(x), jnp.max(x)
    width = jnp.maximum(hi - lo, 1e-12)
    idx = jnp.clip(((x.reshape(-1) - lo) / width * nbins).astype(
        jnp.int32), 0, nbins - 1)
    return jax.ops.segment_sum(jnp.ones_like(idx, jnp.int32), idx,
                               num_segments=int(nbins))


@register_op("compare_and_bitpack")
def compare_and_bitpack(x, threshold):
    """(x > threshold) packed 8 bools/byte along the last axis."""
    bits = (x > threshold).astype(jnp.uint8)
    shp = bits.shape[:-1] + (bits.shape[-1] // 8, 8)
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint8)
    return jnp.sum(bits.reshape(shp) * weights, axis=-1,
                   dtype=jnp.uint8)


@register_op("is_non_decreasing")
def is_non_decreasing(x):
    f = x.reshape(-1)
    return jnp.all(f[1:] >= f[:-1]) if f.size > 1 \
        else jnp.asarray(True)


@register_op("is_strictly_increasing")
def is_strictly_increasing(x):
    f = x.reshape(-1)
    return jnp.all(f[1:] > f[:-1]) if f.size > 1 else jnp.asarray(True)


@register_op("is_numeric_tensor")
def is_numeric_tensor(x):
    return jnp.asarray(jnp.issubdtype(x.dtype, jnp.number))


@register_op("matrix_diag_part")
def matrix_diag_part(x):
    """Reference op name for the batched main diagonal."""
    return jnp.diagonal(x, axis1=-2, axis2=-1)


@register_op("mergemax")
def mergemax(*xs):
    return jnp.max(jnp.stack(xs), axis=0)


@register_op("mergeadd")
def mergeadd(*xs):
    return jnp.sum(jnp.stack(xs), axis=0)


@register_op("mergeavg")
def mergeavg(*xs):
    return jnp.mean(jnp.stack(xs), axis=0)


@register_op("mergemaxindex")
def mergemaxindex(*xs):
    return jnp.argmax(jnp.stack(xs), axis=0).astype(jnp.int32)


def _nudged_quant(x, mn, mx, num_bits, narrow_range):
    qmin = 1.0 if narrow_range else 0.0
    qmax = float(2 ** num_bits - 1)
    scale = (mx - mn) / (qmax - qmin)
    zp = qmin - mn / scale
    nudged_zp = jnp.clip(jnp.round(zp), qmin, qmax)
    nudged_min = (qmin - nudged_zp) * scale
    nudged_max = (qmax - nudged_zp) * scale
    clamped = jnp.clip(x, nudged_min, nudged_max)
    q = jnp.round((clamped - nudged_min) / scale)
    return q * scale + nudged_min


@register_op("fake_quant_with_min_max_vars")
def fake_quant_with_min_max_vars(x, mn, mx, num_bits=8,
                                 narrow_range=False):
    return _nudged_quant(x, mn, mx, int(num_bits), narrow_range)


@register_op("fake_quant_with_min_max_args")
def fake_quant_with_min_max_args(x, min=-6.0, max=6.0, num_bits=8,
                                 narrow_range=False):
    return _nudged_quant(x, jnp.asarray(min, x.dtype),
                         jnp.asarray(max, x.dtype), int(num_bits),
                         narrow_range)


# -------------------------------------------------------------- word2vec
# (reference: generic/nn/embeddings/{skipgram,cbow}.cpp — the negative-
# sampling SGD step as a single fused op; nlp/word2vec.py drives these)
@register_op("skipgram")
def skipgram(h, ctx_rows, labels, lr=0.025):
    """One negative-sampling step: h [d] center vector, ctx_rows [k, d]
    context/negative output vectors, labels [k] (1=positive).
    Returns (new_h, new_ctx_rows)."""
    logits = ctx_rows @ h
    g = (jax.nn.sigmoid(logits) - labels) * lr
    new_ctx = ctx_rows - g[:, None] * h[None, :]
    new_h = h - g @ ctx_rows
    return new_h, new_ctx


@register_op("cbow")
def cbow(ctx_in_rows, target_rows, labels, lr=0.025):
    """CBOW step: hidden = mean of context input vectors; the input
    gradient is shared equally across the context window."""
    k = ctx_in_rows.shape[0]
    h = jnp.mean(ctx_in_rows, axis=0)
    logits = target_rows @ h
    g = (jax.nn.sigmoid(logits) - labels) * lr
    new_targets = target_rows - g[:, None] * h[None, :]
    dh = g @ target_rows
    new_ctx = ctx_in_rows - dh[None, :] / k
    return new_ctx, new_targets


# ------------------------------------------------- recurrent declarables
# (reference: generic/recurrent/{staticRNN,dynamicRNN,
# staticBidirectionalRNN,dynamicBidirectionalRNN}.cpp — full-sequence
# simple-RNN drivers; the fused layer ops live in ops/nn.py)
def _rnn_seq(x, wx, wh, b, h0=None):
    n, t, _ = x.shape
    hidden = wh.shape[0]
    if h0 is None:
        h0 = jnp.zeros((n, hidden), x.dtype)
    xp = (x.reshape(n * t, -1) @ wx + b).reshape(n, t, hidden)
    xp = jnp.moveaxis(xp, 1, 0)

    def step(h, p):
        h2 = jnp.tanh(p + h @ wh)
        return h2, h2

    hT, ys = lax.scan(step, h0, xp)
    return jnp.moveaxis(ys, 0, 1), hT


@register_op("static_rnn")
def static_rnn(x, wx, wh, b, h0=None):
    """tanh RNN over the full (static-length) sequence; returns
    (h_seq [N,T,H], h_last)."""
    return _rnn_seq(x, wx, wh, b, h0)


@register_op("dynamic_rnn")
def dynamic_rnn(x, wx, wh, b, h0=None, seq_lengths=None):
    """Like static_rnn, but positions past seq_lengths are zeroed and
    h_last is the state at each row's true final step (zero-length
    rows return their INITIAL state, matching TF dynamic_rnn)."""
    ys, hT = _rnn_seq(x, wx, wh, b, h0)
    if seq_lengths is None:
        return ys, hT
    t = x.shape[1]
    lens = jnp.asarray(seq_lengths, jnp.int32)
    valid = (jnp.arange(t)[None, :] < lens[:, None])[..., None]
    ys = jnp.where(valid, ys, 0)
    idx = jnp.clip(lens - 1, 0, t - 1)
    h_last = jnp.take_along_axis(
        ys, idx[:, None, None].astype(jnp.int32).repeat(ys.shape[-1], -1),
        axis=1)[:, 0]
    h_init = h0 if h0 is not None \
        else jnp.zeros((x.shape[0], wh.shape[0]), x.dtype)
    h_last = jnp.where((lens == 0)[:, None], h_init, h_last)
    return ys, h_last


@register_op("static_bidirectional_rnn")
def static_bidirectional_rnn(x, wx_f, wh_f, b_f, wx_b, wh_b, b_b,
                             h0_f=None, h0_b=None):
    """Forward + time-reversed backward tanh RNNs, outputs
    CONCATENATED on the feature axis (the reference op's layout);
    returns (y [N,T,2H], h_last_f, h_last_b)."""
    yf, hf = _rnn_seq(x, wx_f, wh_f, b_f, h0_f)
    yb, hb = _rnn_seq(jnp.flip(x, axis=1), wx_b, wh_b, b_b, h0_b)
    return (jnp.concatenate([yf, jnp.flip(yb, axis=1)], axis=-1),
            hf, hb)


@register_op("dynamic_bidirectional_rnn")
def dynamic_bidirectional_rnn(x, wx_f, wh_f, b_f, wx_b, wh_b, b_b,
                              seq_lengths=None, h0_f=None, h0_b=None):
    """Bidirectional with per-row lengths: the backward direction
    reverses only each row's VALID prefix (reverse_sequence
    semantics), matching the reference/TF dynamic bidirectional op."""
    if seq_lengths is None:
        return static_bidirectional_rnn(x, wx_f, wh_f, b_f, wx_b, wh_b,
                                        b_b, h0_f, h0_b)
    from deeplearning4j_tpu.ops.registry import get_op
    reverse_sequence = get_op("reverse_sequence")

    lens = jnp.asarray(seq_lengths, jnp.int32)
    pos = jnp.arange(x.shape[1])[None, :]
    # forward half IS dynamic_rnn (masking + last-step + zero-length
    # fallback live in one place)
    yf, hf = dynamic_rnn(x, wx_f, wh_f, b_f, h0_f, seq_lengths=lens)
    xr = reverse_sequence(x, lens, seq_axis=1)
    yb_r, _ = _rnn_seq(xr, wx_b, wh_b, b_b, h0_b)
    yb = reverse_sequence(yb_r, lens, seq_axis=1)
    yb = jnp.where((pos < lens[:, None])[..., None], yb, 0)
    hb = yb[:, 0]
    hb_init = h0_b if h0_b is not None \
        else jnp.zeros((x.shape[0], wh_b.shape[0]), x.dtype)
    hb = jnp.where((lens == 0)[:, None], hb_init, hb)
    return jnp.concatenate([yf, yb], axis=-1), hf, hb


# --------------------------------------------------------- CTC decoders
# (reference: generic/loss/ctcLoss.cpp's decode companions —
# parity_ops ctc_greedy_decoder / ctc_beam.cpp)
@register_op("ctc_greedy_decoder")
def ctc_greedy_decoder(log_probs, seq_lengths=None, blank=0,
                       merge_repeated=True):
    """Best-path decode: argmax per frame, collapse repeats, drop
    blanks. Returns (dense [B, T] with -1 padding, lengths [B])."""
    b, t, _ = log_probs.shape
    ids = jnp.argmax(log_probs, axis=-1).astype(jnp.int32)
    if seq_lengths is not None:
        valid = jnp.arange(t)[None, :] < \
            jnp.asarray(seq_lengths, jnp.int32)[:, None]
        ids = jnp.where(valid, ids, blank)
    if merge_repeated:
        keep = jnp.concatenate(
            [jnp.ones((b, 1), bool), ids[:, 1:] != ids[:, :-1]], axis=1)
    else:
        keep = jnp.ones((b, t), bool)
    keep = keep & (ids != blank)
    # stable left-compaction: target position = cumsum of keeps
    pos = jnp.cumsum(keep, axis=1) - 1
    out = jnp.full((b, t), -1, jnp.int32)
    rows = jnp.arange(b)[:, None].repeat(t, 1)
    out = out.at[rows, jnp.where(keep, pos, t - 1)].set(
        jnp.where(keep, ids, -1), mode="drop")
    # re-assert padding beyond each row's count (a dropped write may
    # have left t-1 untouched; set explicitly)
    counts = jnp.sum(keep, axis=1)
    out = jnp.where(jnp.arange(t)[None, :] < counts[:, None], out, -1)
    return out, counts.astype(jnp.int32)


@register_op("ctc_beam_search_decoder")
def ctc_beam_search_decoder(log_probs, seq_lengths=None, beam_width=8,
                            blank=0, top_paths=1):
    """Small-vocab CTC prefix beam search (eager, host-side — decode is
    an inference utility, not a training hot path; reference:
    ctc_beam.cpp). Returns (paths list of [B, L_i] int32 lists,
    log_probs [B, top_paths])."""
    import numpy as np

    lp = np.asarray(log_probs, np.float64)
    b, t, c = lp.shape
    lens = (np.asarray(seq_lengths, np.int64)
            if seq_lengths is not None else np.full(b, t))
    all_paths, all_scores = [], []
    for bi in range(b):
        beams = {(): (0.0, -np.inf)}   # prefix -> (logp_blank, logp_nb)
        for ti in range(int(lens[bi])):
            nxt = {}

            def add(pfx, pb, pnb):
                opb, opnb = nxt.get(pfx, (-np.inf, -np.inf))
                nxt[pfx] = (np.logaddexp(opb, pb),
                            np.logaddexp(opnb, pnb))

            for pfx, (pb, pnb) in beams.items():
                total = np.logaddexp(pb, pnb)
                add(pfx, total + lp[bi, ti, blank], -np.inf)
                for s in range(c):
                    if s == blank:
                        continue
                    p = lp[bi, ti, s]
                    if pfx and pfx[-1] == s:
                        add(pfx, -np.inf, pnb + p)          # repeat
                        add(pfx + (s,), -np.inf, pb + p)    # after blank
                    else:
                        add(pfx + (s,), -np.inf, total + p)
            beams = dict(sorted(
                nxt.items(),
                key=lambda kv: -np.logaddexp(*kv[1]))[:int(beam_width)])
        ranked = sorted(beams.items(),
                        key=lambda kv: -np.logaddexp(*kv[1]))
        paths = [list(p) for p, _ in ranked[:int(top_paths)]]
        scores = [float(np.logaddexp(*s)) for _, s in
                  ranked[:int(top_paths)]]
        while len(paths) < int(top_paths):
            paths.append([])
            scores.append(float("-inf"))
        all_paths.append(paths)
        all_scores.append(scores)
    return all_paths, jnp.asarray(np.asarray(all_scores, np.float32))


@register_op("apply_sgd")
def apply_sgd(params, grad, lr=0.01):
    """Functional p - lr*g (generic/optimizer/sgd.cpp apply_sgd)."""
    return params - lr * grad


@register_op("print_variable")
def print_variable(x, message=""):
    """Debug identity (generic/util/print_variable.cpp): prints eagerly,
    passes through under jit via jax.debug.print."""
    jax.debug.print("{m}{v}", m=message, v=x)
    return x
