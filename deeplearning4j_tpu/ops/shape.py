"""Shape / gather-scatter / broadcast manipulation ops.

Reference: nd4j ``org/nd4j/linalg/api/ops/impl/shape/**`` (Concat,
Stack, Gather, ScatterUpdate, Tile, ...) and libnd4j
``ops/declarable/generic/shape/**`` + ``transforms/**`` (SURVEY.md
§2.2, §2.6). Pure jax; static shapes by design — ops whose output
shape is data-dependent in the reference (``unique``, boolean
``where``) take explicit size arguments or return masks, the XLA-
compatible formulation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import register_op


# -- pure shape --------------------------------------------------------
@register_op("permute")
def permute(x, axes):
    return jnp.transpose(x, axes)


@register_op("flatten_2d")
def flatten_2d(x, axis=1):
    """Collapse to 2D around `axis` (reference: Flatten2D)."""
    lead = 1
    for s in x.shape[:axis]:
        lead *= s
    return jnp.reshape(x, (lead, -1))


@register_op("broadcast_to")
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, shape)


@register_op("rank")
def rank(x):
    return jnp.asarray(jnp.ndim(x), jnp.int32)


# -- concat / split family ---------------------------------------------
@register_op("split_v")
def split_v(x, sizes, axis=0):
    idx = []
    acc = 0
    for s in sizes[:-1]:
        acc += s
        idx.append(acc)
    return jnp.split(x, idx, axis=axis)


@register_op("reverse_sequence")
def reverse_sequence(x, seq_lengths, seq_axis=1, batch_axis=0):
    """Per-row partial reversal (reference: reverse_sequence.cpp)."""
    t = x.shape[seq_axis]
    idx = jnp.arange(t)

    def per_row(row, n):
        rev = jnp.where(idx < n, n - 1 - idx, idx)
        return jnp.take(row, rev, axis=seq_axis - 1 if seq_axis > batch_axis
                        else seq_axis)

    return jax.vmap(per_row, in_axes=(batch_axis, 0),
                    out_axes=batch_axis)(x, seq_lengths)


# -- pad ---------------------------------------------------------------
@register_op("mirror_pad")
def mirror_pad(x, paddings, reflect=True):
    return jnp.pad(x, paddings, mode="reflect" if reflect else "symmetric")


# -- gather / scatter --------------------------------------------------
@register_op("take_along_axis")
def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


@register_op("scatter_sub")
def scatter_sub(ref, indices, updates):
    return ref.at[indices].add(-updates)


@register_op("scatter_mul")
def scatter_mul(ref, indices, updates):
    return ref.at[indices].multiply(updates)


@register_op("scatter_div")
def scatter_div(ref, indices, updates):
    return ref.at[indices].divide(updates)


@register_op("scatter_max")
def scatter_max(ref, indices, updates):
    return ref.at[indices].max(updates)


@register_op("scatter_min")
def scatter_min(ref, indices, updates):
    return ref.at[indices].min(updates)


@register_op("scatter_nd")
def scatter_nd(indices, updates, shape):
    """Build zeros(shape) scattered with updates (reference:
    scatter_nd.cpp)."""
    zeros = jnp.zeros(shape, updates.dtype)
    idx = tuple(jnp.moveaxis(indices, -1, 0))
    return zeros.at[idx].add(updates)


# -- slicing -----------------------------------------------------------
@register_op("dynamic_update_slice")
def dynamic_update_slice(x, update, start_indices):
    return lax.dynamic_update_slice(x, update, start_indices)


# -- construction ------------------------------------------------------
@register_op("fill")
def fill(shape, value, dtype=None):
    return jnp.full(shape, value, dtype)


@register_op("meshgrid")
def meshgrid(*arrays, indexing="xy"):
    return jnp.meshgrid(*arrays, indexing=indexing)


@register_op("diag_part")
def diag_part(x):
    return jnp.diagonal(x, axis1=-2, axis2=-1)


@register_op("matrix_diag")
def matrix_diag(x):
    return jnp.zeros(x.shape + (x.shape[-1],), x.dtype) \
        .at[..., jnp.arange(x.shape[-1]), jnp.arange(x.shape[-1])].set(x)


@register_op("matrix_set_diag")
def matrix_set_diag(x, diagonal):
    n = min(x.shape[-2], x.shape[-1])
    i = jnp.arange(n)
    return x.at[..., i, i].set(diagonal[..., :n])


# -- data movement / layout --------------------------------------------
@register_op("space_to_depth")
def space_to_depth(x, block_size):
    """NHWC [N,H,W,C] -> [N,H/b,W/b,C*b*b]."""
    n, h, w, c = x.shape
    b = block_size
    x = x.reshape(n, h // b, b, w // b, b, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // b, w // b,
                                                 c * b * b)


@register_op("depth_to_space")
def depth_to_space(x, block_size):
    n, h, w, c = x.shape
    b = block_size
    x = x.reshape(n, h, w, b, b, c // (b * b))
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h * b, w * b,
                                                 c // (b * b))


@register_op("batch_to_space")
def batch_to_space(x, block_shape, crops):
    b0, b1 = block_shape
    n, h, w, c = x.shape
    r = x.reshape(b0, b1, n // (b0 * b1), h, w, c)
    r = r.transpose(2, 3, 0, 4, 1, 5).reshape(n // (b0 * b1), h * b0,
                                              w * b1, c)
    (ct0, cb0), (ct1, cb1) = crops
    return r[:, ct0:h * b0 - cb0, ct1:w * b1 - cb1, :]


@register_op("space_to_batch")
def space_to_batch(x, block_shape, paddings):
    b0, b1 = block_shape
    (pt0, pb0), (pt1, pb1) = paddings
    x = jnp.pad(x, ((0, 0), (pt0, pb0), (pt1, pb1), (0, 0)))
    n, h, w, c = x.shape
    x = x.reshape(n, h // b0, b0, w // b1, b1, c)
    return x.transpose(2, 4, 0, 1, 3, 5).reshape(n * b0 * b1, h // b0,
                                                 w // b1, c)


# -- selection ---------------------------------------------------------
@register_op("select")
def select(condition, x, y):
    return jnp.where(condition, x, y)


@register_op("masked_fill")
def masked_fill(x, mask, value):
    return jnp.where(mask.astype(bool), jnp.asarray(value, x.dtype), x)


@register_op("compress")
def compress(x, mask, size, axis=0, fill_value=0):
    """Static-size boolean selection: first `size` kept slices along
    `axis`, surplus slots filled with fill_value (XLA formulation of
    data-dependent-shape `compress`)."""
    idx = jnp.nonzero(mask, size=size, fill_value=x.shape[axis])[0]
    pad_shape = list(x.shape)
    pad_shape[axis] = 1
    padded = jnp.concatenate(
        [x, jnp.full(pad_shape, fill_value, x.dtype)], axis=axis)
    return jnp.take(padded, idx, axis=axis)


@register_op("unique_with_counts")
def unique_with_counts(x, size):
    """Static-size unique (XLA-safe): returns (values, counts) with
    `size` slots, surplus filled with the max value."""
    vals, counts = jnp.unique(x, size=size, return_counts=True,
                              fill_value=jnp.max(x))
    return vals, counts
