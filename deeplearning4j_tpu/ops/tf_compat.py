"""Ops with TF-attr calling conventions used by imported graphs.

These live in the ops package (not the importer) so a process that
LOADS a saved imported graph — e.g. a TF-less inference host running
``SameDiff.load`` — has them registered without ever importing the
tensorflow package (reference: the imported graph executes on plain
nd4j ops, org/nd4j/linalg/api/ops/impl/shape/StridedSlice etc.,
SURVEY.md §2.14).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import register_op


@register_op("tf_strided_slice")
def tf_strided_slice(x, begin=None, end=None, strides=None, begin_mask=0,
                     end_mask=0, shrink_axis_mask=0, new_axis_mask=0):
    """TF StridedSlice subset: begin/end/shrink/new-axis masks, no
    ellipsis. A new_axis position consumes one spec entry (its
    begin/end/stride are ignored) and inserts a length-1 axis there."""
    slices = []
    shrink_axes = []
    new_axes = []
    out_pos = 0
    for i in range(len(begin)):
        if new_axis_mask & (1 << i):
            new_axes.append(out_pos)
            out_pos += 1
            continue
        if shrink_axis_mask & (1 << i):
            # begin=-1 means "last element": end must be None, not 0
            e = begin[i] + 1 if begin[i] != -1 else None
            slices.append(slice(begin[i], e, 1))
            shrink_axes.append(len(slices) - 1)
            continue
        b = None if begin_mask & (1 << i) else begin[i]
        e = None if end_mask & (1 << i) else end[i]
        slices.append(slice(b, e, strides[i]))
        out_pos += 1
    out = x[tuple(slices)]
    if shrink_axes:
        out = jnp.squeeze(out, axis=tuple(shrink_axes))
    for pos in new_axes:
        out = jnp.expand_dims(out, pos)
    return out


@register_op("tf_strided_slice_dyn")
def tf_strided_slice_dyn(x, begin_t, begin=None, end=None, begin_mask=0,
                         end_mask=0, shrink_axis_mask=0):
    """StridedSlice with runtime begin entries (None in ``begin``/
    ``end``); unit strides. Dynamic entries are only legal on shrink
    dims (checked at import) — the op lowers to one lax.dynamic_slice
    plus a static squeeze, so loop-counter indexing stays on-device."""
    from jax import lax

    starts: list = []
    sizes: list = []
    squeeze: list = []
    nspec = len(begin)
    for d in range(nspec):
        dim = x.shape[d]
        if shrink_axis_mask & (1 << d):
            if begin[d] is None:
                st = begin_t[d].astype(jnp.int32)
                st = jnp.where(st < 0, st + dim, st)
            else:
                st = begin[d] + dim if begin[d] < 0 else begin[d]
            starts.append(st)
            sizes.append(1)
            squeeze.append(d)
        else:
            b = 0 if (begin_mask & (1 << d)) else begin[d]
            e = dim if (end_mask & (1 << d)) else end[d]
            b = b + dim if b < 0 else b
            e = e + dim if e < 0 else e
            b = max(0, min(b, dim))
            e = max(0, min(e, dim))
            starts.append(b)
            sizes.append(max(0, e - b))
    for d in range(nspec, x.ndim):
        starts.append(0)
        sizes.append(x.shape[d])
    out = lax.dynamic_slice(
        x, tuple(jnp.asarray(s, jnp.int32) for s in starts), tuple(sizes))
    return jnp.squeeze(out, axis=tuple(squeeze)) if squeeze else out


@register_op("tf_fill")
def tf_fill(shape=None, value=0.0):
    return jnp.full(tuple(shape), value)


@register_op("erfc")
def erfc(x):
    return jax.scipy.special.erfc(x)
