"""Pallas conv + BN-epilogue kernels for the ResNet fast path.

Reference role: CudnnConvolutionHelper / the cuDNN fused
conv+bias+act paths (SURVEY.md §2.8-2.9). The round-3 byte ledger
(BASELINE.md) showed the ResNet-50 step is HBM-bound with ~46 ms of
non-conv traffic, and named moving BN statistics and residual adds
into conv epilogues — a Pallas conv framework — as the one untaken
lever. These kernels implement that class for the shapes where the
MXU mapping is clean:

- ``conv1x1_bn_stats``: a 1x1/stride-1 conv IS a [rows, Cin] @
  [Cin, Cout] matmul (rows = N*H*W). The kernel tiles it over a
  (cols, rows) grid and accumulates per-channel sum/sum-of-squares in
  the SAME pass, in f32, from the pre-cast MXU accumulator — saving
  the separate full re-read of the conv output that XLA's batch-norm
  stats reduction costs today.
- ``conv3x3_bn_stats``: same contract for 3x3/stride-1 SAME convs as
  9 shifted matmuls accumulated in f32. The grid runs one step per
  image; the block is the whole zero-padded image (fits VMEM for
  every ResNet-50 3x3 shape: 58x58x64 ... 9x9x512), which sidesteps
  halo blocks — overlapping reads can't be expressed in blocked
  BlockSpec indexing.

Stats blocks are indexed by the column block only, so every row-step
revisits them; TPU Pallas grids execute sequentially, which makes the
revisit-accumulate pattern exact (pallas guide: grids/BlockSpecs).
Divisibility is asserted, not padded: a partial edge block would feed
garbage rows into the stats accumulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import register_op

# jax.experimental.pallas is imported inside each function body — the
# package-wide convention (see lstm_pallas/flash_attention): the import
# costs ~2.3s and must not tax a bare `import deeplearning4j_tpu.ops`.


def _pick_block(total, cap):
    """Largest divisor of `total` that is <= cap (stats correctness
    needs exact tiling; see module docstring)."""
    b = min(cap, total)
    while total % b:
        b -= 1
    return b


def _k_conv1x1(x_ref, w_ref, o_ref, sum_ref, sq_ref):
    from jax.experimental import pallas as pl

    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)

    acc = jnp.dot(x_ref[...], w_ref[...],
                  preferred_element_type=jnp.float32)
    o_ref[...] = acc.astype(o_ref.dtype)
    sum_ref[...] += jnp.sum(acc, axis=0, keepdims=True)
    sq_ref[...] += jnp.sum(acc * acc, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("bm", "bn", "interpret"))
def conv1x1_bn_stats(x, w, bm=512, bn=256, interpret=False):
    """x: [N,H,W,Cin] (or [rows,Cin]); w: [Cin,Cout].
    Returns (y raw conv output, mean [Cout], var [Cout]) with biased
    variance (batch-norm convention), stats accumulated in f32."""
    from jax.experimental import pallas as pl

    shp = x.shape
    rows = 1
    for d in shp[:-1]:
        rows *= d
    cin = shp[-1]
    cout = w.shape[1]
    x2 = x.reshape(rows, cin)
    bm = _pick_block(rows, bm)
    bn = _pick_block(cout, bn)
    grid = (cout // bn, rows // bm)
    y, s, sq = pl.pallas_call(
        _k_conv1x1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, cin), lambda j, i: (i, 0)),
            pl.BlockSpec((cin, bn), lambda j, i: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
            pl.BlockSpec((1, bn), lambda j, i: (0, j)),
            pl.BlockSpec((1, bn), lambda j, i: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, cout), x.dtype),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w)
    mean = s[0] / rows
    # E[x^2]-E[x]^2 in f32: clamped because cancellation can push it
    # slightly negative for high-mean/low-variance channels (and the
    # relative error grows as mean^2/var — documented limitation of the
    # single-pass form; the network's XLA BN path uses two-pass var)
    var = jnp.maximum(sq[0] / rows - mean * mean, 0.0)
    return y.reshape(shp[:-1] + (cout,)), mean, var


def _k_conv3x3(x_ref, w_ref, o_ref, sum_ref, sq_ref, *, h, wd, cin,
               cout):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)

    acc = jnp.zeros((h * wd, cout), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            patch = x_ref[dy:dy + h, dx:dx + wd, :]
            acc += jnp.dot(patch.reshape(h * wd, cin), w_ref[dy, dx],
                           preferred_element_type=jnp.float32)
    o_ref[...] = acc.reshape(h, wd, cout).astype(o_ref.dtype)
    sum_ref[...] += jnp.sum(acc, axis=0, keepdims=True)
    sq_ref[...] += jnp.sum(acc * acc, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def conv3x3_bn_stats(x, w, interpret=False):
    """3x3 stride-1 SAME conv + fused BN stats.

    x: [N,H,W,Cin]; w: [3,3,Cin,Cout]. One grid step per image: the
    block is the whole zero-padded image [(H+2), (W+2), Cin] viewed as
    row-blocks of [N*(H+2), W+2, Cin], so no halo crosses a block
    boundary. Returns (y [N,H,W,Cout], mean [Cout], var [Cout])."""
    from jax.experimental import pallas as pl

    n, h, wd, cin = x.shape
    cout = w.shape[3]
    # One grid step holds the whole padded image + f32 accumulator in
    # VMEM; that is the design envelope (every ResNet-50 3x3 shape).
    # Guard it so out-of-envelope dispatch fails with a clear error,
    # not an opaque Mosaic allocation failure.
    block_bytes = ((h + 2) * (wd + 2) * cin * x.dtype.itemsize
                   + 9 * cin * cout * w.dtype.itemsize
                   + h * wd * cout * (x.dtype.itemsize + 4))
    if block_bytes > 24 * 1024 * 1024:
        raise ValueError(
            f"conv3x3_bn_stats: per-image block needs ~{block_bytes >> 20}MB "
            f"VMEM for shape {x.shape}->{cout}ch; the kernel's envelope is "
            "the ResNet-50 3x3 shapes (<=58x58x64 ... 9x9x512). Use the "
            "XLA conv path (ops.nn.conv2d + batch-norm) for this shape.")
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    rows_v = xp.reshape(n * (h + 2), wd + 2, cin)
    kernel = functools.partial(_k_conv3x3, h=h, wd=wd, cin=cin,
                               cout=cout)
    y, s, sq = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((h + 2, wd + 2, cin), lambda i: (i, 0, 0)),
            pl.BlockSpec((3, 3, cin, cout), lambda i: (0, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((h, wd, cout), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n * h, wd, cout), x.dtype),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        interpret=interpret,
    )(rows_v, w)
    rows = n * h * wd
    mean = s[0] / rows
    var = jnp.maximum(sq[0] / rows - mean * mean, 0.0)  # see conv1x1
    return y.reshape(n, h, wd, cout), mean, var


@register_op("conv1x1_bn_stats")
def conv1x1_bn_stats_op(x, w, bm=512, bn=256):
    return conv1x1_bn_stats(x, w, bm=bm, bn=bn,
                            interpret=jax.default_backend() != "tpu")


@register_op("conv3x3_bn_stats")
def conv3x3_bn_stats_op(x, w):
    return conv3x3_bn_stats(x, w,
                            interpret=jax.default_backend() != "tpu")
