"""Neural-net ops (reference: libnd4j ops/declarable/generic/nn/** —
conv2d.cpp, pooling, batchnorm.cpp, recurrent/lstmLayer.cpp, attention
ops — plus the cuDNN/oneDNN platform fast paths, SURVEY.md §2.6-2.9).

TPU-first design notes:
- Layout is **NHWC** (TPU/XLA's preferred conv layout; the reference
  defaults to NCHW for cuDNN). Config-level code converts if users ask
  for NCHW.
- Convs lower to ``lax.conv_general_dilated`` which XLA tiles onto the
  MXU; there is no cuDNN-helper-style dispatch seam needed — XLA *is*
  the fast path. The LayerHelper seam from the reference survives only
  as the ability to swap a reference (naive) impl in tests.
- The LSTM is a single fused ``lax.scan`` over time with one big gate
  matmul per step (reference: CudnnLSTMHelper / lstmLayer.cpp). Under
  jit, XLA unrolls/pipelines this; weights stay resident in VMEM/HBM.
- Attention is jax-native; a Pallas flash-attention path can be slotted
  under the same op name later without touching callers.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import register_op


# ----------------------------------------------------------------------
# convolution (reference: ops/declarable/generic/nn/convo/conv2d.cpp)
# ----------------------------------------------------------------------
def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _conv_padding(padding, kernel, strides, dilation):
    """Map reference padding modes to lax padding.

    The reference uses ConvolutionMode {Same, Truncate, Causal} plus
    explicit pad values. Strings 'SAME'/'VALID' map straight to lax;
    explicit ints become symmetric pads; ((lo,hi),(lo,hi)) pairs pass
    through asymmetric (TF EXPLICIT padding).
    """
    if isinstance(padding, str):
        return padding.upper()
    if (isinstance(padding, (tuple, list)) and len(padding) == 2
            and all(isinstance(q, (tuple, list)) and len(q) == 2
                    for q in padding)):
        return [tuple(int(v) for v in q) for q in padding]
    p = _pair(padding)
    return [(p[0], p[0]), (p[1], p[1])]


@register_op("conv2d")
def conv2d(x, w, b=None, strides=(1, 1), padding="SAME", dilation=(1, 1),
           groups=1):
    """2D convolution, NHWC x HWIO -> NHWC.

    x: [N,H,W,C_in]; w: [kH,kW,C_in/groups,C_out]; b: [C_out] or None.
    groups>1 = grouped conv (ONNX Conv group attr, ResNeXt/MobileNet);
    XLA's feature_group_count does the channel partitioning.
    """
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=_pair(strides),
        padding=_conv_padding(padding, w.shape[:2], strides, dilation),
        rhs_dilation=_pair(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=int(groups),
    )
    if b is not None:
        out = out + b
    return out


@register_op("conv1d")
def conv1d(x, w, b=None, stride=1, padding="SAME", dilation=1):
    """1D convolution, NWC x WIO -> NWC."""
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=(stride,),
        padding=padding.upper() if isinstance(padding, str) else [(padding, padding)],
        rhs_dilation=(dilation,),
        dimension_numbers=("NWC", "WIO", "NWC"),
    )
    if b is not None:
        out = out + b
    return out


@register_op("conv3d")
def conv3d(x, w, b=None, strides=(1, 1, 1), padding="SAME", dilation=(1, 1, 1)):
    """3D convolution, NDHWC x DHWIO -> NDHWC."""
    s = (strides, strides, strides) if isinstance(strides, int) else tuple(strides)
    d = (dilation, dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    if not isinstance(padding, str):
        p = (padding, padding, padding) if isinstance(padding, int) else tuple(padding)
        padding = [(v, v) for v in p]
    else:
        padding = padding.upper()
    out = lax.conv_general_dilated(
        x, w, window_strides=s, padding=padding, rhs_dilation=d,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
    )
    if b is not None:
        out = out + b
    return out


@register_op("depthwise_conv2d")
def depthwise_conv2d(x, w, b=None, strides=(1, 1), padding="SAME", dilation=(1, 1)):
    """Depthwise conv. w: [kH,kW,C_in,mult] -> feature grouping by C_in."""
    c_in = x.shape[-1]
    mult = w.shape[-1]
    w2 = w.reshape(w.shape[0], w.shape[1], 1, c_in * mult)
    out = lax.conv_general_dilated(
        x,
        w2,
        window_strides=_pair(strides),
        padding=_conv_padding(padding, w.shape[:2], strides, dilation),
        rhs_dilation=_pair(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c_in,
    )
    if b is not None:
        out = out + b
    return out


@register_op("separable_conv2d")
def separable_conv2d(x, depth_w, point_w, b=None, strides=(1, 1),
                     padding="SAME", dilation=(1, 1)):
    out = depthwise_conv2d(x, depth_w, None, strides, padding, dilation)
    out = conv2d(out, point_w, b, (1, 1), "SAME")
    return out


@register_op("deconv2d")
def deconv2d(x, w, b=None, strides=(2, 2), padding="SAME"):
    """Transposed conv (reference: deconv2d.cpp). w: [kH,kW,C_in,C_out].

    ``padding``: 'SAME'/'VALID', an int applied to both spatial dims, or
    a per-dim (pH, pW) pair. Integer padding follows reference deconv
    semantics out = s*(in-1) + k - 2p (the gradient of a forward conv
    padded by p), which for ``lax.conv_transpose`` — whose integer
    padding pads the stride-dilated input directly — means low = high =
    k - 1 - p per spatial dim.
    """
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        pad = [(k - 1 - p, k - 1 - p)
               for k, p in zip(w.shape[:2], _pair(padding))]
    out = lax.conv_transpose(
        x,
        w,
        strides=_pair(strides),
        padding=pad,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if b is not None:
        out = out + b
    return out


@register_op("upsampling2d")
def upsampling2d(x, scale=2):
    s = _pair(scale)
    return jnp.repeat(jnp.repeat(x, s[0], axis=1), s[1], axis=2)


@register_op("im2col")
def im2col(x, kernel, strides=(1, 1), padding="VALID"):
    """Patch extraction (reference: im2col in libnd4j helpers).

    Returns [N, outH, outW, C*kH*kW]. NOTE: the feature axis is
    CHANNEL-MAJOR — ordered (C, kH, kW), as produced by
    ``lax.conv_general_dilated_patches`` — not Keras's (kH, kW, C).
    """
    kh, kw = _pair(kernel)
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), _pair(strides),
        padding if isinstance(padding, str) else [(padding, padding)] * 2,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return patches


# ----------------------------------------------------------------------
# pooling (reference: ops/declarable/generic/nn/pooling)
# ----------------------------------------------------------------------
def _pool_pad(padding):
    """Pooling padding arg: 'SAME'/'VALID' or per-dim symmetric (pH, pW)."""
    if isinstance(padding, str):
        return padding.upper()
    p = _pair(padding)
    return [(0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0)]


@register_op("maxpool2d")
def maxpool2d(x, kernel=(2, 2), strides=None, padding="VALID"):
    k = _pair(kernel)
    s = _pair(strides) if strides is not None else k
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, k[0], k[1], 1), (1, s[0], s[1], 1),
        _pool_pad(padding)
    )


@register_op("sumpool2d")
def sumpool2d(x, kernel=(2, 2), strides=None, padding="VALID"):
    k = _pair(kernel)
    s = _pair(strides) if strides is not None else k
    return lax.reduce_window(
        x, 0.0, lax.add, (1, k[0], k[1], 1), (1, s[0], s[1], 1),
        _pool_pad(padding)
    )


@register_op("avgpool2d")
def avgpool2d(x, kernel=(2, 2), strides=None, padding="VALID"):
    k = _pair(kernel)
    s = _pair(strides) if strides is not None else k
    pad = _pool_pad(padding)
    summed = lax.reduce_window(
        x, 0.0, lax.add, (1, k[0], k[1], 1), (1, s[0], s[1], 1), pad
    )
    if pad == "VALID":
        return summed / (k[0] * k[1])
    # SAME / explicit: divide by actual (edge-clipped) window sizes
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(
        ones, 0.0, lax.add, (1, k[0], k[1], 1), (1, s[0], s[1], 1), pad
    )
    return summed / counts


@register_op("pnormpool2d")
def pnormpool2d(x, kernel=(2, 2), strides=None, padding="VALID", p=2):
    k = _pair(kernel)
    s = _pair(strides) if strides is not None else k
    summed = lax.reduce_window(
        jnp.abs(x) ** p, 0.0, lax.add, (1, k[0], k[1], 1), (1, s[0], s[1], 1),
        _pool_pad(padding)
    )
    return summed ** (1.0 / p)


@register_op("maxpool1d")
def maxpool1d(x, kernel=2, stride=None, padding="VALID"):
    s = stride if stride is not None else kernel
    pad = (padding.upper() if isinstance(padding, str)
           else [(0, 0), (padding, padding), (0, 0)])
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, kernel, 1), (1, s, 1), pad,
    )


@register_op("avgpool1d")
def avgpool1d(x, kernel=2, stride=None, padding="VALID"):
    s = stride if stride is not None else kernel
    pad = (padding.upper() if isinstance(padding, str)
           else [(0, 0), (padding, padding), (0, 0)])
    summed = lax.reduce_window(x, 0.0, lax.add, (1, kernel, 1), (1, s, 1), pad)
    if pad == "VALID":
        return summed / kernel
    counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                               (1, kernel, 1), (1, s, 1), pad)
    return summed / counts


@register_op("sumpool1d")
def sumpool1d(x, kernel=2, stride=None, padding="VALID"):
    s = stride if stride is not None else kernel
    pad = (padding.upper() if isinstance(padding, str)
           else [(0, 0), (padding, padding), (0, 0)])
    return lax.reduce_window(x, 0.0, lax.add, (1, kernel, 1), (1, s, 1), pad)


@register_op("pnormpool1d")
def pnormpool1d(x, kernel=2, stride=None, padding="VALID", p=2):
    s = stride if stride is not None else kernel
    pad = (padding.upper() if isinstance(padding, str)
           else [(0, 0), (padding, padding), (0, 0)])
    summed = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add,
                               (1, kernel, 1), (1, s, 1), pad)
    return summed ** (1.0 / p)


def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


def _pool3d_pad(padding):
    if isinstance(padding, str):
        return padding.upper()
    p = _triple(padding)
    return [(0, 0), (p[0], p[0]), (p[1], p[1]), (p[2], p[2]), (0, 0)]


@register_op("maxpool3d")
def maxpool3d(x, kernel=(2, 2, 2), strides=None, padding="VALID"):
    """3D max pool over NDHWC (reference: maxpool3dnew.cpp)."""
    k = _triple(kernel)
    s = _triple(strides) if strides is not None else k
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, k[0], k[1], k[2], 1),
        (1, s[0], s[1], s[2], 1), _pool3d_pad(padding))


@register_op("avgpool3d")
def avgpool3d(x, kernel=(2, 2, 2), strides=None, padding="VALID"):
    """3D avg pool over NDHWC (reference: avgpool3dnew.cpp)."""
    k = _triple(kernel)
    s = _triple(strides) if strides is not None else k
    pad = _pool3d_pad(padding)
    summed = lax.reduce_window(
        x, 0.0, lax.add, (1, k[0], k[1], k[2], 1),
        (1, s[0], s[1], s[2], 1), pad)
    if pad == "VALID":
        return summed / (k[0] * k[1] * k[2])
    counts = lax.reduce_window(
        jnp.ones_like(x), 0.0, lax.add, (1, k[0], k[1], k[2], 1),
        (1, s[0], s[1], s[2], 1), pad)
    return summed / counts


@register_op("locally_connected2d")
def locally_connected2d(x, w, b=None, kernel=(2, 2), strides=(1, 1),
                        padding="VALID"):
    """Unshared-weight conv (reference: LocallyConnected2D samediff layer).

    x: [N,H,W,C]; w: [outH*outW, C*kH*kW, C_out] — one filter bank per
    output position, with the patch axis CHANNEL-MAJOR (C, kH, kW) to
    match :func:`im2col` (NOT Keras's (kH, kW, C) order — permute when
    converting Keras weights). im2col + batched einsum stays on the MXU.
    """
    patches = im2col(x, kernel, strides, padding)      # [N,oh,ow,kH*kW*C]
    n, oh, ow, kc = patches.shape
    out = jnp.einsum("npk,pko->npo", patches.reshape(n, oh * ow, kc), w)
    out = out.reshape(n, oh, ow, w.shape[-1])
    if b is not None:
        out = out + b
    return out


@register_op("locally_connected1d")
def locally_connected1d(x, w, b=None, kernel=2, stride=1, padding="VALID"):
    """1D unshared conv (reference: LocallyConnected1D samediff layer).

    x: [N,T,F]; w: [outT, F*k, C_out] — patch axis channel-major (F, k),
    as produced by ``lax.conv_general_dilated_patches``.
    """
    patches = lax.conv_general_dilated_patches(
        x, (kernel,), (stride,),
        padding if isinstance(padding, str) else [(padding, padding)],
        dimension_numbers=("NWC", "WIO", "NWC"))       # [N,oT,k*F]
    out = jnp.einsum("npk,pko->npo", patches, w)
    if b is not None:
        out = out + b
    return out


@register_op("global_avg_pool")
def global_avg_pool(x, spatial_axes=(1, 2)):
    return jnp.mean(x, axis=spatial_axes)


@register_op("global_max_pool")
def global_max_pool(x, spatial_axes=(1, 2)):
    return jnp.max(x, axis=spatial_axes)


# ----------------------------------------------------------------------
# normalization (reference: batchnorm.cpp, cuDNN BatchNormalizationHelper)
# ----------------------------------------------------------------------
@register_op("batch_norm")
def batch_norm(x, gamma, beta, mean, var, eps=1e-5):
    """Inference-mode batchnorm over trailing channel axis."""
    inv = lax.rsqrt(var + eps)
    return (x - mean) * inv * gamma + beta


def _bn_stats(x, axes):
    """One-pass f32 batch statistics over ``axes`` -> (mean, var).

    sum(d) and sum(d*d) fuse into a single read of x (jnp.var's two-pass
    formulation re-reads the activation after the mean — BN stat passes
    dominate ResNet step time on TPU, so halving the reads matters).
    The per-channel shift (first sample) keeps E[d^2]-E[d]^2 free of
    catastrophic cancellation when the activation mean is large relative
    to its spread.
    """
    xf = x.astype(jnp.float32)
    shift = lax.stop_gradient(xf[tuple(0 for _ in axes)])
    d = xf - shift
    md = jnp.mean(d, axis=axes)
    v = jnp.mean(d * d, axis=axes) - md * md
    return shift + md, jnp.maximum(v, 0.0)


@register_op("batch_norm_train")
def batch_norm_train(x, gamma, beta, eps=1e-5, axes=None):
    """Training-mode batchnorm. Returns (y, batch_mean, batch_var).

    axes: reduction axes; defaults to all but the last (channel) axis.
    Backward is XLA autodiff of the one-pass stats formulation. Two
    hand-written fused VJPs (canonical cuDNN-style BN backward, both
    with minimal bf16 residuals) were A/B-measured in-process against
    autodiff on the v5e ResNet-50 train step and BOTH lost by ~4-5%
    (2030-2050 vs 2140-2150 img/s) — XLA schedules the autodiff
    backward better than the hand formulation, so it stays.
    """
    if axes is None:
        axes = tuple(range(x.ndim - 1))
    m, v = _bn_stats(x, tuple(axes))
    scale = lax.rsqrt(v + eps) * gamma.astype(jnp.float32)
    shift = beta.astype(jnp.float32) - m * scale
    y = x * scale.astype(x.dtype) + shift.astype(x.dtype)
    return y, m.astype(x.dtype), v.astype(x.dtype)


# Trace-time toggle for the fused BN+ReLU backward (A/B-able in one
# process by flipping between traces; see bench_bn_fused_ab.py).
FUSED_BN_RELU_BWD = os.environ.get("DL4J_TPU_FUSED_BN_RELU", "0") == "1"


def _bn_bcast(c, ndim, axes):
    """Broadcast a per-channel [C] vector over the reduce axes of x."""
    shape = [1] * ndim
    for ax in range(ndim):
        if ax not in axes:
            shape[ax] = c.shape[0]
    return c.reshape(shape)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_relu_core(x, gamma, beta, eps, axes):
    y, _, _ = _bn_relu_fwd(x, gamma, beta, eps, axes)[0]
    return y


def _bn_relu_fwd(x, gamma, beta, eps, axes):
    m, v = _bn_stats(x, axes)
    inv = lax.rsqrt(v + eps)
    scale = inv * gamma.astype(jnp.float32)
    shift = beta.astype(jnp.float32) - m * scale
    sb = _bn_bcast(scale, x.ndim, axes).astype(x.dtype)
    hb = _bn_bcast(shift, x.ndim, axes).astype(x.dtype)
    y = jnp.maximum(x * sb + hb, 0)
    return (y, m, v), (x, gamma, beta, m, v)


def _bn_relu_bwd(eps, axes, res, gy):
    """Two-pass fused backward: ReLU mask + BN reductions + dx, with the
    masked gradient recomputed inline in each pass so it is NEVER
    materialized to HBM (the relu-backward select category in the
    ResNet-50 byte ledger, BASELINE.md). Residuals are x plus the
    C-sized stats — no extra activation saves vs autodiff.

    The batch-stat outputs (m, v) are treated as stop_gradient: they
    only ever feed the running-stat EMA, never the loss (same contract
    the one-pass ``_bn_stats`` shift trick already assumes).
    """
    x, gamma, beta, m, v = res
    ndim = x.ndim
    inv = lax.rsqrt(v + eps)
    gamma_f = gamma.astype(jnp.float32)
    m_b = _bn_bcast(m, ndim, axes)
    inv_b = _bn_bcast(inv, ndim, axes)
    sc_b = _bn_bcast(gamma_f * inv, ndim, axes)
    sh_b = _bn_bcast(beta.astype(jnp.float32) - m * gamma_f * inv, ndim, axes)
    count = 1
    for ax in axes:
        count *= x.shape[ax]

    def masked_pieces():
        xf = x.astype(jnp.float32)
        xhat = (xf - m_b) * inv_b
        mask = xf * sc_b + sh_b > 0
        gp = jnp.where(mask, gy.astype(jnp.float32), 0.0)
        return xhat, gp

    # pass A: per-channel reductions (mask/xhat recomputed in-fusion)
    xhat, gp = masked_pieces()
    s1 = jnp.sum(gp, axis=axes)
    s2 = jnp.sum(gp * xhat, axis=axes)
    # pass B: dx elementwise (XLA duplicates the cheap producers into
    # this fusion rather than round-tripping them through HBM)
    xhat2, gp2 = masked_pieces()
    dx = sc_b * (gp2 - (_bn_bcast(s1, ndim, axes)
                        + xhat2 * _bn_bcast(s2, ndim, axes)) / count)
    return dx.astype(x.dtype), s2.astype(gamma.dtype), s1.astype(beta.dtype)


def _bn_relu_core_fwd(x, gamma, beta, eps, axes):
    (y, _, _), res = _bn_relu_fwd(x, gamma, beta, eps, axes)
    return y, res


_bn_relu_core.defvjp(_bn_relu_core_fwd, _bn_relu_bwd)


@register_op("batch_norm_relu_train")
def batch_norm_relu_train(x, gamma, beta, eps=1e-5, axes=None):
    """Fused training-mode BN + ReLU with a hand-written two-pass
    backward (custom_vjp). Same signature contract as
    ``batch_norm_train`` but the activation is applied inside, and the
    returned batch stats are stop_gradient (EMA consumers only).

    Round-4 ResNet-50 attack on the byte ledger's relu-mask category:
    autodiff emits relu-bwd (read y, read g, write g') then BN
    reductions (read g', read x) then dx (read g', read x, write dx) —
    ~16 B/elem; this backward reads (x, g) twice and writes dx once —
    ~10 B/elem — by recomputing the mask from the saved conv output
    instead of materializing the masked gradient.
    """
    if axes is None:
        axes = tuple(range(x.ndim - 1))
    axes = tuple(axes)
    y = _bn_relu_core(x, gamma, beta, eps, axes)
    m, v = _bn_stats(x, axes)
    return (y, lax.stop_gradient(m).astype(x.dtype),
            lax.stop_gradient(v).astype(x.dtype))


@register_op("layer_norm")
def layer_norm(x, gamma, beta=None, axis=-1, eps=1e-5):
    m = jnp.mean(x, axis=axis, keepdims=True)
    v = jnp.var(x, axis=axis, keepdims=True)
    y = (x - m) * lax.rsqrt(v + eps) * gamma
    if beta is not None:
        y = y + beta
    return y


@register_op("lrn")
def local_response_normalization(x, depth_radius=5, bias=1.0, alpha=1.0, beta=0.5):
    """LRN over channels (reference: lrn.cpp; used by AlexNet)."""
    sq = jnp.square(x)
    # sum over a window along the channel axis
    pad = [(0, 0)] * (x.ndim - 1) + [(depth_radius, depth_radius)]
    sq = jnp.pad(sq, pad)
    win = 2 * depth_radius + 1
    acc = sum(
        lax.slice_in_dim(sq, i, i + x.shape[-1], axis=-1) for i in range(win)
    )
    return x / jnp.power(bias + alpha * acc, beta)


@register_op("dropout")
def dropout(x, rate, rng, deterministic=False):
    """Inverted dropout (reference: dropout as multiplicative noise)."""
    if deterministic or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


# ----------------------------------------------------------------------
# linear / embedding
# ----------------------------------------------------------------------
@register_op("xw_plus_b")
def xw_plus_b(x, w, b):
    return x @ w + b


@register_op("embedding_lookup")
def embedding_lookup(table, ids):
    return jnp.take(table, ids, axis=0)


@register_op("one_hot")
def one_hot(ids, depth, dtype=jnp.float32, on_value=1.0, off_value=0.0,
            axis=-1):
    hot = jax.nn.one_hot(ids, depth, dtype=dtype, axis=axis)
    if on_value == 1.0 and off_value == 0.0:
        return hot
    return off_value + hot * (on_value - off_value)


# ----------------------------------------------------------------------
# recurrent (reference: lstmLayer.cpp, CudnnLSTMHelper; gruCell.cpp)
# ----------------------------------------------------------------------
@register_op("lstm_layer")
def lstm_layer(x, w_ih, w_hh, b, h0=None, c0=None, reverse=False,
               impl="scan"):
    """Fused LSTM over time via lax.scan.

    x: [N, T, in]; w_ih: [in, 4H]; w_hh: [H, 4H]; b: [4H].
    Gate order: i, f, g(cell), o (reference lstmLayer uses IFGO-configurable;
    we fix IFGO). Returns (outputs [N,T,H], (hT, cT)).

    Design: the input projection for ALL timesteps is one big [N*T, in] x
    [in, 4H] matmul (MXU-friendly), the scan carries only the recurrent
    matmul — this is the standard TPU RNN decomposition and is what the
    reference's cuDNN fast path does internally.

    impl="pallas" swaps the recurrence for the persistent-VMEM Pallas
    kernel (ops/lstm_pallas.py) — measured ~par at H=256 and ~1.3x at
    H=512 on v5e (BASELINE.md); differentiable via a custom VJP
    (reverse-time recompute scan), so it works for training too. Scan
    remains the default.
    """
    if impl not in ("scan", "pallas"):
        raise ValueError(f"lstm_layer impl={impl!r}: 'scan' or 'pallas'")
    n, t, _ = x.shape
    hidden = w_hh.shape[0]
    if h0 is None:
        h0 = jnp.zeros((n, hidden), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((n, hidden), x.dtype)

    x_proj = x.reshape(n * t, -1) @ w_ih + b  # one large MXU matmul
    x_proj = x_proj.reshape(n, t, 4 * hidden).transpose(1, 0, 2)  # [T,N,4H]
    if reverse:
        x_proj = jnp.flip(x_proj, axis=0)

    if impl == "pallas":
        from deeplearning4j_tpu.ops.lstm_pallas import (
            pallas_lstm_recurrence,
        )

        ys, hT, cT = pallas_lstm_recurrence(x_proj, w_hh, h0, c0)
        if reverse:
            ys = jnp.flip(ys, axis=0)
        return ys.transpose(1, 0, 2), (hT, cT)

    def step(carry, xp):
        h, c = carry
        gates = xp + h @ w_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        return (h2, c2), h2

    (hT, cT), ys = lax.scan(step, (h0, c0), x_proj)
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys.transpose(1, 0, 2), (hT, cT)


@register_op("gru_layer")
def gru_layer(x, w_ih, w_hh, b, h0=None, rb=None):
    """GRU over time. x: [N,T,in]; w_ih: [in,3H]; w_hh: [H,3H]; b: [3H].

    Gate order: r (reset), z (update), n (candidate). The candidate uses
    r*(h@Whh_n) — "reset after" form. ``rb`` is an optional recurrent
    bias [3H] added to the h projection (Keras reset_after parity).
    """
    n, t, _ = x.shape
    hidden = w_hh.shape[0]
    if h0 is None:
        h0 = jnp.zeros((n, hidden), x.dtype)
    x_proj = (x.reshape(n * t, -1) @ w_ih + b).reshape(n, t, 3 * hidden).transpose(1, 0, 2)

    def step(h, xp):
        hp = h @ w_hh
        if rb is not None:
            hp = hp + rb
        xr, xz, xn = jnp.split(xp, 3, axis=-1)
        hr, hz, hn = jnp.split(hp, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        nn_ = jnp.tanh(xn + r * hn)
        h2 = (1 - z) * nn_ + z * h
        return h2, h2

    hT, ys = lax.scan(step, h0, x_proj)
    return ys.transpose(1, 0, 2), hT


@register_op("lstm_seq")
def lstm_seq(x, w_ih, w_hh, b, h0=None, c0=None, reverse=False):
    """lstm_layer with a FLAT (ys, hT, cT) return for graph executors.
    Kept for serde back-compat: SameDiff graphs saved by earlier ONNX
    imports reference this op name (new imports emit onnx_lstm_seq,
    whose default flags are a superset)."""
    ys, (hT, cT) = lstm_layer(x, w_ih, w_hh, b, h0=h0, c0=c0,
                              reverse=reverse)
    return ys, hT, cT


@register_op("gru_seq")
def gru_seq(x, w_ih, w_hh, b, rb, h0=None, reverse=False):
    """gru_layer with rb POSITIONAL and a reverse flag. Kept for serde
    back-compat: SameDiff graphs saved by earlier ONNX imports
    reference this op name (new imports emit onnx_gru_seq)."""
    if reverse:
        x = jnp.flip(x, axis=1)
    ys, hT = gru_layer(x, w_ih, w_hh, b, h0=h0, rb=rb)
    if reverse:
        ys = jnp.flip(ys, axis=1)
    return ys, hT


@register_op("simple_rnn_layer")
def simple_rnn_layer(x, w_ih, w_hh, b, h0=None, activation=jnp.tanh):
    n, t, _ = x.shape
    hidden = w_hh.shape[0]
    if h0 is None:
        h0 = jnp.zeros((n, hidden), x.dtype)
    x_proj = (x.reshape(n * t, -1) @ w_ih + b).reshape(n, t, hidden).transpose(1, 0, 2)

    def step(h, xp):
        h2 = activation(xp + h @ w_hh)
        return h2, h2

    hT, ys = lax.scan(step, h0, x_proj)
    return ys.transpose(1, 0, 2), hT


# ----------------------------------------------------------------------
# ONNX-semantics recurrent ops (reference: the reference's flexible
# lstmLayer mapping in samediff-import-onnx covers cell clip, coupled
# gates, per-gate activations and ragged sequence lengths — SURVEY.md
# §2.14). Same TPU decomposition as lstm_layer (one big MXU input
# projection + lax.scan recurrence), with the full ONNX option set.
# ----------------------------------------------------------------------
def _onnx_act(spec):
    """ONNX activation spec (name, alpha, beta) -> elementwise fn."""
    name, alpha, beta = spec
    n = name.lower()
    if n == "sigmoid":
        return jax.nn.sigmoid
    if n == "tanh":
        return jnp.tanh
    if n == "relu":
        return jax.nn.relu
    if n == "leakyrelu":
        a = 0.01 if alpha is None else alpha
        return lambda v: jnp.where(v >= 0, v, a * v)
    if n == "hardsigmoid":
        a = 0.2 if alpha is None else alpha
        c = 0.5 if beta is None else beta
        return lambda v: jnp.clip(a * v + c, 0.0, 1.0)
    if n == "elu":
        a = 1.0 if alpha is None else alpha
        return lambda v: jnp.where(v > 0, v, a * (jnp.exp(v) - 1.0))
    if n == "softsign":
        return jax.nn.soft_sign
    if n == "softplus":
        return jax.nn.softplus
    if n == "affine":
        a = 1.0 if alpha is None else alpha
        c = 0.0 if beta is None else beta
        return lambda v: a * v + c
    if n == "thresholdedrelu":
        a = 1.0 if alpha is None else alpha
        return lambda v: jnp.where(v > a, v, jnp.zeros_like(v))
    raise ValueError(f"unsupported RNN activation {name!r}")


def _rev_seq(x, lens):
    """Per-element time reversal within each sequence's length:
    x [N, T, ...], lens [N]. Delegates to the canonical
    reverse_sequence op (ops/shape.py) — ONE implementation of the
    tf.reverse_sequence semantics in the codebase."""
    from deeplearning4j_tpu.ops.shape import reverse_sequence
    return reverse_sequence(x, lens.astype(jnp.int32), seq_axis=1,
                            batch_axis=0)


def _maybe_clip(v, clip):
    return jnp.clip(v, -clip, clip) if clip else v


@register_op("onnx_lstm_seq")
def onnx_lstm_seq(x, w_ih, w_hh, b, *rest, has_state=False,
                  has_lens=False, has_peep=False, reverse=False,
                  cell_clip=0.0, input_forget=False, acts=None):
    """ONNX LSTM for one direction, full option set.

    x: [N,T,in]; w_ih: [in,4H]; w_hh: [H,4H]; b: [4H] (our i,f,g,o gate
    order — the importer re-packs from ONNX iofc). `rest` holds the
    optional traced inputs in order, gated by the has_* flags (graph
    executors pass a flat positional input list): h0 [N,H] + c0 [N,H]
    if has_state, seq_lens [N] int if has_lens, peep [3,H] as
    (p_i, p_f, p_o) if has_peep.

    acts: 3 (name, alpha, beta) triples for (f, g, h); default
    (sigmoid, tanh, tanh). cell_clip clamps every gate pre-activation
    to [-clip, clip] (the ONNX "applied to input of activations" rule);
    input_forget=True couples f = 1 - i.

    seq_lens semantics match onnxruntime: Y rows at t >= len are 0,
    the returned h/c freeze at each element's last valid step, and the
    reverse direction runs over each element's OWN prefix reversed.
    Returns (ys [N,T,H], hT [N,H], cT [N,H]).
    """
    n, t, _ = x.shape
    hidden = w_hh.shape[0]
    k = 0
    if has_state:
        h0, c0 = rest[0], rest[1]
        k = 2
    else:
        h0 = jnp.zeros((n, hidden), x.dtype)
        c0 = jnp.zeros((n, hidden), x.dtype)
    seq_lens = None
    if has_lens:
        seq_lens = rest[k]
        k += 1
    peep = rest[k] if has_peep else None
    f_act, g_act, h_act = [
        _onnx_act(s) for s in (acts or
                               (("sigmoid", None, None),
                                ("tanh", None, None),
                                ("tanh", None, None)))]
    lens = None if seq_lens is None else seq_lens.astype(jnp.int32)
    if reverse:
        x = _rev_seq(x, lens) if lens is not None else jnp.flip(x, 1)
    x_proj = (x.reshape(n * t, -1) @ w_ih + b) \
        .reshape(n, t, 4 * hidden).transpose(1, 0, 2)
    tt = jnp.arange(t, dtype=jnp.int32)

    def step(carry, inp):
        h, c = carry
        xp, ti = inp
        gates = xp + h @ w_hh
        gi, gf, gg, go = jnp.split(gates, 4, axis=-1)
        if peep is not None:
            gi = gi + peep[0] * c
            gf = gf + peep[1] * c
        i = f_act(_maybe_clip(gi, cell_clip))
        f = (1.0 - i) if input_forget \
            else f_act(_maybe_clip(gf, cell_clip))
        g = g_act(_maybe_clip(gg, cell_clip))
        c2 = f * c + i * g
        if peep is not None:
            go = go + peep[2] * c2
        o = f_act(_maybe_clip(go, cell_clip))
        h2 = o * h_act(c2)
        if lens is not None:
            valid = (ti < lens)[:, None]
            y = jnp.where(valid, h2, jnp.zeros_like(h2))
            h2 = jnp.where(valid, h2, h)
            c2 = jnp.where(valid, c2, c)
        else:
            y = h2
        return (h2, c2), y

    (hT, cT), ys = lax.scan(step, (h0, c0), (x_proj, tt))
    ys = ys.transpose(1, 0, 2)
    if reverse:
        ys = _rev_seq(ys, lens) if lens is not None else jnp.flip(ys, 1)
    return ys, hT, cT


@register_op("onnx_gru_seq")
def onnx_gru_seq(x, w_ih, w_hh, wb, rb, *rest, has_state=False,
                 has_lens=False, reverse=False,
                 linear_before_reset=True, cell_clip=0.0, acts=None):
    """ONNX GRU for one direction, full option set.

    x: [N,T,in]; w_ih: [in,3H]; w_hh: [H,3H]; wb/rb: [3H] (our r,z,n
    gate order — importer re-packs from ONNX zrh). `rest` holds h0
    [N,H] if has_state then seq_lens [N] if has_lens. acts: 2 triples
    for (f, g); default (sigmoid, tanh).

    linear_before_reset=True (torch's form): n = g(xn + r*(h@Rn + rbn)).
    linear_before_reset=False (the ONNX DEFAULT, what keras/sklearn
    exporters emit): n = g(xn + (r*h)@Rn + rbn) — the reset gate is
    applied to the state BEFORE the recurrent matmul.
    Returns (ys [N,T,H], hT [N,H]).
    """
    n, t, _ = x.shape
    hidden = w_hh.shape[0]
    k = 0
    if has_state:
        h0 = rest[0]
        k = 1
    else:
        h0 = jnp.zeros((n, hidden), x.dtype)
    seq_lens = rest[k] if has_lens else None
    f_act, g_act = [
        _onnx_act(s) for s in (acts or (("sigmoid", None, None),
                                        ("tanh", None, None)))]
    lens = None if seq_lens is None else seq_lens.astype(jnp.int32)
    if reverse:
        x = _rev_seq(x, lens) if lens is not None else jnp.flip(x, 1)
    x_proj = (x.reshape(n * t, -1) @ w_ih + wb) \
        .reshape(n, t, 3 * hidden).transpose(1, 0, 2)
    w_hh_rz = w_hh[:, :2 * hidden]
    rb_rz = rb[:2 * hidden]
    w_hh_n = w_hh[:, 2 * hidden:]
    rb_n = rb[2 * hidden:]
    tt = jnp.arange(t, dtype=jnp.int32)

    def step(h, inp):
        xp, ti = inp
        xr, xz, xn = jnp.split(xp, 3, axis=-1)
        if linear_before_reset:
            hp = h @ w_hh + rb
            hr, hz, hn = jnp.split(hp, 3, axis=-1)
        else:
            # reset-before form recomputes the n projection on r*h, so
            # projecting the n column here would be a wasted matmul
            hr, hz = jnp.split(h @ w_hh_rz + rb_rz, 2, axis=-1)
        r = f_act(_maybe_clip(xr + hr, cell_clip))
        z = f_act(_maybe_clip(xz + hz, cell_clip))
        if linear_before_reset:
            n_pre = xn + r * hn
        else:
            n_pre = xn + (r * h) @ w_hh_n + rb_n
        nn_ = g_act(_maybe_clip(n_pre, cell_clip))
        h2 = (1.0 - z) * nn_ + z * h
        if lens is not None:
            valid = (ti < lens)[:, None]
            y = jnp.where(valid, h2, jnp.zeros_like(h2))
            h2 = jnp.where(valid, h2, h)
        else:
            y = h2
        return h2, y

    hT, ys = lax.scan(step, h0, (x_proj, tt))
    ys = ys.transpose(1, 0, 2)
    if reverse:
        ys = _rev_seq(ys, lens) if lens is not None else jnp.flip(ys, 1)
    return ys, hT


@register_op("onnx_rnn_seq")
def onnx_rnn_seq(x, w_ih, w_hh, b, *rest, has_state=False,
                 has_lens=False, reverse=False, cell_clip=0.0,
                 acts=None):
    """ONNX vanilla RNN for one direction: h2 = f(x@W + h@R + b), with
    the same rest/has_* convention and seq_lens / clip / activation
    handling as the LSTM/GRU ops. Returns (ys [N,T,H], hT [N,H])."""
    n, t, _ = x.shape
    hidden = w_hh.shape[0]
    k = 0
    if has_state:
        h0 = rest[0]
        k = 1
    else:
        h0 = jnp.zeros((n, hidden), x.dtype)
    seq_lens = rest[k] if has_lens else None
    f_act = _onnx_act(acts[0] if acts else ("tanh", None, None))
    lens = None if seq_lens is None else seq_lens.astype(jnp.int32)
    if reverse:
        x = _rev_seq(x, lens) if lens is not None else jnp.flip(x, 1)
    x_proj = (x.reshape(n * t, -1) @ w_ih + b) \
        .reshape(n, t, hidden).transpose(1, 0, 2)
    tt = jnp.arange(t, dtype=jnp.int32)

    def step(h, inp):
        xp, ti = inp
        h2 = f_act(_maybe_clip(xp + h @ w_hh, cell_clip))
        if lens is not None:
            valid = (ti < lens)[:, None]
            y = jnp.where(valid, h2, jnp.zeros_like(h2))
            h2 = jnp.where(valid, h2, h)
        else:
            y = h2
        return h2, y

    hT, ys = lax.scan(step, h0, (x_proj, tt))
    ys = ys.transpose(1, 0, 2)
    if reverse:
        ys = _rev_seq(ys, lens) if lens is not None else jnp.flip(ys, 1)
    return ys, hT


# ----------------------------------------------------------------------
# attention (reference: multiHeadDotProductAttention / dotProductAttention
# ops backing SelfAttentionLayer et al., SURVEY.md §5 long-context notes)
# ----------------------------------------------------------------------
@register_op("dot_product_attention")
def dot_product_attention(q, k, v, mask=None, scale=None):
    """Scaled dot-product attention.

    q: [..., Tq, d]; k: [..., Tk, d]; v: [..., Tk, dv].
    mask: broadcastable to [..., Tq, Tk]; 1 = attend, 0 = masked.
    """
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / jnp.sqrt(jnp.asarray(d, q.dtype))
    logits = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if mask is not None:
        big_neg = jnp.asarray(jnp.finfo(logits.dtype).min, logits.dtype)
        logits = jnp.where(mask.astype(bool), logits, big_neg)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("...qk,...kv->...qv", weights, v)


@register_op("multi_head_dot_product_attention")
def multi_head_dot_product_attention(x_q, x_kv, wq, wk, wv, wo, mask=None, num_heads=None):
    """Full MHA (reference: multiHeadDotProductAttention op).

    x_q: [N, Tq, D]; x_kv: [N, Tk, D]; wq/wk/wv: [D, H*dh]; wo: [H*dh, D].
    """
    n, tq, d = x_q.shape
    proj_dim = wq.shape[-1]
    h = num_heads if num_heads else max(1, proj_dim // 64)
    dh = proj_dim // h

    def split_heads(y):
        return y.reshape(n, -1, h, dh).transpose(0, 2, 1, 3)  # [N,H,T,dh]

    q = split_heads(x_q @ wq)
    k = split_heads(x_kv @ wk)
    v = split_heads(x_kv @ wv)
    if mask is not None and mask.ndim == 2:  # [N, Tk] key-padding mask
        mask = mask[:, None, None, :]
    out = dot_product_attention(q, k, v, mask)
    out = out.transpose(0, 2, 1, 3).reshape(n, tq, proj_dim)
    return out @ wo


# ----------------------------------------------------------------------
# losses-adjacent ops used by layers
# ----------------------------------------------------------------------
@register_op("softmax_cross_entropy")
def softmax_cross_entropy(logits, labels, axis=-1):
    """Per-example CE with probabilistic labels."""
    logp = jax.nn.log_softmax(logits, axis=axis)
    return -jnp.sum(labels * logp, axis=axis)


@register_op("sigmoid_cross_entropy")
def sigmoid_cross_entropy(logits, labels):
    return jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))


@register_op("log_loss")
def log_loss(probs, labels, eps=1e-7):
    p = jnp.clip(probs, eps, 1 - eps)
    return -(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p))


# ----------------------------------------------------------------------
# activation / normalization long tail (reference: generic/nn/activations
# + contrib norms; VERDICT r1 #5 breadth)
# ----------------------------------------------------------------------
@register_op("celu")
def celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha=alpha)


@register_op("glu")
def glu(x, axis=-1):
    return jax.nn.glu(x, axis=axis)


@register_op("log_sigmoid")
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@register_op("hard_swish")
def hard_swish(x):
    return jax.nn.hard_swish(x)


@register_op("group_norm")
def group_norm(x, gamma, beta, num_groups, eps=1e-5):
    """Channel-last group norm; stats per (group, sample)."""
    c = x.shape[-1]
    g = num_groups
    xs = x.reshape(x.shape[:-1] + (g, c // g))
    axes = tuple(range(1, x.ndim - 1)) + (x.ndim,)
    m = jnp.mean(xs, axis=axes, keepdims=True)
    v = jnp.var(xs, axis=axes, keepdims=True)
    xs = (xs - m) * lax.rsqrt(v + eps)
    return xs.reshape(x.shape) * gamma + beta


@register_op("instance_norm")
def instance_norm(x, gamma, beta, eps=1e-5):
    """Per-sample, per-channel spatial norm (NHWC)."""
    axes = tuple(range(1, x.ndim - 1))
    m = jnp.mean(x, axis=axes, keepdims=True)
    v = jnp.var(x, axis=axes, keepdims=True)
    return (x - m) * lax.rsqrt(v + eps) * gamma + beta


@register_op("rms_norm")
def rms_norm(x, gamma, eps=1e-6):
    """RMSNorm (no mean subtraction) — the transformer-era layer norm."""
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * lax.rsqrt(ms + eps) * gamma


# ----------------------------------------------------------------------
# loss long tail (reference: org/nd4j/linalg/lossfunctions kin as ops)
# ----------------------------------------------------------------------
@register_op("huber_loss")
def huber_loss(labels, predictions, delta=1.0):
    err = jnp.abs(predictions - labels)
    quad = jnp.minimum(err, delta)
    return 0.5 * quad * quad + delta * (err - quad)


@register_op("hinge_loss")
def hinge_loss(labels, logits):
    """labels in {0,1} -> {-1,1} (reference: hinge_loss.cpp)."""
    all_ones = 2.0 * labels - 1.0
    return jnp.maximum(0.0, 1.0 - all_ones * logits)


@register_op("kl_divergence")
def kl_divergence(p, q, axis=-1, eps=1e-12):
    return jnp.sum(p * (jnp.log(p + eps) - jnp.log(q + eps)), axis=axis)


@register_op("poisson_nll_loss")
def poisson_nll_loss(targets, log_input):
    return jnp.exp(log_input) - targets * log_input


@register_op("mean_pairwise_squared_error")
def mean_pairwise_squared_error(labels, predictions):
    """MSE of all element-pair DIFFERENCES per sample (reference:
    mean_pairwise_squared_error.cpp)."""
    d = (predictions - labels).reshape(labels.shape[0], -1)
    n = d.shape[-1]
    s1 = jnp.sum(d, axis=-1)
    s2 = jnp.sum(d * d, axis=-1)
    # TF normalizes over ordered pairs: n*(n-1), not n^2
    return 2.0 * (n * s2 - s1 * s1) / (n * max(n - 1, 1))


@register_op("ctc_loss")
def ctc_loss(log_probs, labels, logit_lengths, label_lengths, blank=0):
    """CTC via optax (reference: ctc_loss.cpp / CudnnCTCLossHelper role).

    log_probs: [B, T, C] log-softmax outputs; labels: [B, L] int32.
    """
    import optax

    logits = log_probs
    b, t, _ = logits.shape
    lpad = jnp.arange(t)[None, :] >= logit_lengths[:, None]
    label_pad = jnp.arange(labels.shape[1])[None, :] >= \
        label_lengths[:, None]
    return optax.ctc_loss(logits, lpad.astype(jnp.float32), labels,
                          label_pad.astype(jnp.float32), blank_id=blank)
