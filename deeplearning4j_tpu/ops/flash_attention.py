"""Flash / memory-efficient attention for the transformer hot path.

Reference: the reference's attention is the vanilla O(L^2)-memory
multiHeadDotProductAttention op (SURVEY.md §5 long-context: "vanilla
O(L²)"); it has no flash path at all. This module is the TPU-native
upgrade that slots under the same seam (TransformerEncoder attn_fn,
SelfAttentionLayer op):

Three implementations, one dispatcher:

1. `pallas_flash_forward` — in-repo Pallas TPU kernel: online-softmax
   streaming over K/V blocks, one grid step per (batch*head, q-block),
   K/V resident in VMEM, logits never materialized in HBM. Used via
   `flash_attention` (custom_vjp) whose backward recomputes with the
   blockwise path (O(T) memory both directions).
2. `blockwise_attention` — pure-jax lax.scan online softmax. Same
   memory behavior (XLA keeps only one [bq, bk] logits tile live per
   step), runs on any backend; it is both the CPU fallback and the
   recompute backward.
3. jax's library Pallas kernel (jax.experimental.pallas.ops.tpu.
   flash_attention) — fwd AND bwd as tuned kernels; preferred on TPU
   when shapes meet its block constraints.

`attention(q, k, v, mask, impl="auto")` picks: library kernel on TPU
(aligned shapes) → in-repo flash → blockwise.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import register_op

_NEG_INF = -1e30  # large-but-finite: keeps masked softmax NaN-free


# ======================================================================
# 1. blockwise (pure jax) — fallback + recompute backward
# ======================================================================
def blockwise_attention(q, k, v, mask=None, causal: bool = False,
                        block_k: int = 256, scale: Optional[float] = None):
    """Online-softmax attention scanning K/V in blocks.

    q,k,v: [N,H,T,dh]; mask: [N,Tk] key-padding (1=valid) or
    broadcastable [N,1,1,Tk]. Returns [N,H,Tq,dh].
    """
    n, h, tq, dh = q.shape
    tk = k.shape[2]
    if scale is None:
        scale = 1.0 / (dh ** 0.5)
    orig_dtype = q.dtype
    qf = (q * scale).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    pad = (-tk) % block_k
    if pad:
        kf = jnp.pad(kf, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, 0), (0, pad), (0, 0)))
    nblk = (tk + pad) // block_k

    if mask is not None and mask.ndim == 4:
        mask = mask[:, 0, 0, :]
    key_valid = jnp.ones((n, tk), jnp.float32) if mask is None \
        else mask.astype(jnp.float32)
    if pad:
        key_valid = jnp.pad(key_valid, ((0, 0), (0, pad)))

    kf = kf.reshape(n, h, nblk, block_k, dh).transpose(2, 0, 1, 3, 4)
    vf = vf.reshape(n, h, nblk, block_k, dh).transpose(2, 0, 1, 3, 4)
    key_valid = key_valid.reshape(n, nblk, block_k).transpose(1, 0, 2)

    q_pos = jnp.arange(tq)[:, None]

    def step(carry, blk):
        m, l, acc = carry
        kb, vb, valid, bi = blk
        s = jnp.einsum("nhqd,nhkd->nhqk", qf, kb)
        s = jnp.where(valid[:, None, None, :] > 0, s, _NEG_INF)
        if causal:
            k_pos = bi * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum("nhqk,nhkd->nhqd", p, vb)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((n, h, tq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((n, h, tq, 1), jnp.float32)
    a0 = jnp.zeros((n, h, tq, dh), jnp.float32)
    (m, l, acc), _ = lax.scan(
        step, (m0, l0, a0),
        (kf, vf, key_valid, jnp.arange(nblk)))
    return (acc / jnp.maximum(l, 1e-30)).astype(orig_dtype)


# ======================================================================
# 2. in-repo Pallas forward kernel
# ======================================================================
def _flash_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *,
                      block_k: int, scale: float, causal: bool,
                      block_q: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [bq, dh]
    tk = k_ref.shape[1]
    nblk = tk // block_k
    bq = q.shape[0]

    def body(i, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)
        valid = mask_ref[0, pl.ds(i * block_k, block_k)] > 0
        s = jnp.where(valid[None, :], s, _NEG_INF)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = i * block_k + lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.dot(p, vb,
                                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    dh = q.shape[-1]
    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, dh), jnp.float32)
    m, l, acc = lax.fori_loop(0, nblk, body, (m0, l0, a0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


try:  # pallas import is cheap; kernels only build when called
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False


def pallas_flash_forward(q, k, v, mask=None, causal: bool = False,
                         block_q: int = 128, block_k: int = 128,
                         scale: Optional[float] = None,
                         interpret: bool = False):
    """Forward-only Pallas flash attention (see module docstring).

    Requires Tq % block_q == 0 and Tk % block_k == 0 (dispatcher pads);
    grid = (N*H, Tq/block_q); each step streams K/V of one (n,h) pair
    through VMEM in block_k chunks.
    """
    n, h, tq, dh = q.shape
    tk = k.shape[2]
    if scale is None:
        scale = 1.0 / (dh ** 0.5)
    if tq % block_q or tk % block_k:
        raise ValueError(f"shape not block-aligned: Tq={tq}, Tk={tk}")
    if mask is None:
        mask_arr = jnp.ones((n, tk), jnp.float32)
    else:
        mask_arr = (mask[:, 0, 0, :] if mask.ndim == 4 else mask) \
            .astype(jnp.float32)

    qr = q.reshape(n * h, tq, dh)
    kr = k.reshape(n * h, tk, dh)
    vr = v.reshape(n * h, tk, dh)

    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               scale=scale, causal=causal, block_q=block_q)
    out = pl.pallas_call(
        kernel,
        grid=(n * h, tq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tk, dh), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tk, dh), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tk), lambda b, i: (b // h, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, dh), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((n * h, tq, dh), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, mask_arr)
    return out.reshape(n, h, tq, dh)


# custom_vjp: Pallas forward, blockwise recompute backward
@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flash(q, k, v, mask, causal):
    return pallas_flash_forward(q, k, v, mask, causal=causal)


def _flash_fwd(q, k, v, mask, causal):
    return pallas_flash_forward(q, k, v, mask, causal=causal), \
        (q, k, v, mask)


def _flash_bwd(causal, res, g):
    q, k, v, mask = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(q_, k_, v_, mask,
                                               causal=causal), q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


# ======================================================================
# 3. dispatcher
# ======================================================================
def _library_block_sizes(tq, tk):
    """Tuned block sizes for the library kernel. Its built-in defaults
    collapse at long sequence (measured on v5e, T=2048 batch 24:
    44.2 ms default vs 10.1 ms with these blocks vs 16.5 ms XLA naive
    — the default-blocks kernel LOSES 2.7x to XLA, the tuned one wins
    1.6x; BASELINE.md 'flash attention re-measured, round 5')."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
    )

    # the kernel requires seq_len % block == 0 — take the largest
    # power-of-two block that divides (T=1536 must get 512/256, not
    # crash on 1024)
    bq = next(b for b in (512, 256, 128) if tq % b == 0)
    bkv = next(b for b in (1024, 512, 256, 128) if tk % b == 0)
    return BlockSizes(
        block_q=bq, block_k_major=bkv, block_k=bkv, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bkv,
        block_k_dkv=bkv, block_q_dkv=bq,
        block_k_major_dq=bkv, block_k_dq=bkv, block_q_dq=bq)


def _library_flash(q, k, v, mask, causal):
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        SegmentIds, flash_attention as lib_flash,
    )
    dh = q.shape[-1]
    seg = None
    if mask is not None:
        m2 = (mask[:, 0, 0, :] if mask.ndim == 4 else mask)
        kv_seg = jnp.where(m2.astype(bool), 0, 1).astype(jnp.int32)
        q_seg = jnp.zeros((q.shape[0], q.shape[2]), jnp.int32)
        seg = SegmentIds(q=q_seg, kv=kv_seg)
    return lib_flash(q, k, v, segment_ids=seg, causal=causal,
                     sm_scale=1.0 / (dh ** 0.5),
                     block_sizes=_library_block_sizes(q.shape[2],
                                                      k.shape[2]))


def _xla_attention(q, k, v, mask, causal):
    """Plain fused-softmax attention — what XLA compiles best at short
    sequence (measured: beats every flash variant below ~1024 on v5e)."""
    dh = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(dh, q.dtype))
    logits = jnp.einsum("nhqd,nhkd->nhqk", q, k) * scale
    neg = jnp.asarray(_NEG_INF, logits.dtype)
    if mask is not None:
        m4 = mask if mask.ndim == 4 else mask[:, None, None, :]
        logits = jnp.where(m4.astype(bool), logits, neg)
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        cm = jnp.tril(jnp.ones((tq, tk), bool), tk - tq)
        logits = jnp.where(cm[None, None], logits, neg)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("nhqk,nhkd->nhqd", w, v)


@register_op("flash_attention")
def attention(q, k, v, mask=None, causal: bool = False,
              impl: str = "auto"):
    """Dispatching flash attention. q,k,v: [N,H,T,dh]; mask: [N,Tk]
    key-padding (1 = attend). impl: auto | library | pallas | blockwise.
    """
    n, h, tq, dh = q.shape
    tk = k.shape[2]
    on_tpu = jax.default_backend() == "tpu"

    if impl == "auto":
        aligned = tq % 128 == 0 and tk % 128 == 0 and dh >= 64
        if on_tpu and tk < 1024:
            # short-KV: XLA's fused softmax attention beats every
            # flash variant (v5e measurements, BASELINE.md round 5 —
            # T=512 fwd: 4.0 ms XLA vs 6.1 ms best-tuned flash), and
            # the O(tq*tk) logits stay small when the KV side is
            # short. Routing is on KV length: a short-QUERY call
            # against a long KV (cross-attention, cached decode) is
            # exactly where flash's no-materialization matters, so it
            # must NOT fall through to the einsum path.
            impl = "xla"
        elif on_tpu and aligned:
            impl = "library"
        elif on_tpu and tq % 128 == 0 and tk % 128 == 0:
            impl = "pallas"
        else:
            impl = "blockwise"
    if impl == "xla":
        return _xla_attention(q, k, v, mask, causal)
    if impl == "library":
        return _library_flash(q, k, v, mask, causal)
    if impl == "pallas":
        return _flash(q, k, v, mask, causal)
    if impl == "blockwise":
        return blockwise_attention(q, k, v, mask, causal=causal)
    raise ValueError(f"unknown impl {impl!r}")


__all__ = ["attention", "blockwise_attention", "pallas_flash_forward"]
