"""Linear-algebra ops.

Reference: libnd4j ``ops/declarable/generic/blas/**`` (matmul, batched
gemm, tensormmul) and ``generic/linalg/**`` (cholesky, qr, svd, solve,
triangular_solve, lup, matrix_inverse, matrix_determinant, ...) —
SURVEY.md §2.6. The decompositions lower to XLA custom calls (LAPACK on
CPU, specialized kernels on TPU); matmuls are the MXU path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import register_op


@register_op("batched_gemm")
def batched_gemm(a, b, alpha=1.0, beta=0.0, c=None):
    out = alpha * (a @ b)
    if c is not None and beta != 0.0:
        out = out + beta * c
    return out


@register_op("tensormmul")
def tensormmul(a, b, axes_a, axes_b):
    """Tensor contraction (reference: tensormmul / TensorMmul)."""
    return jnp.tensordot(a, b, axes=(axes_a, axes_b))


@register_op("outer")
def outer(a, b):
    return jnp.outer(a, b)


@register_op("cholesky")
def cholesky(x):
    return jnp.linalg.cholesky(x)


@register_op("qr")
def qr(x, full_matrices=False):
    return jnp.linalg.qr(x, mode="complete" if full_matrices else
                         "reduced")


@register_op("svd")
def svd(x, full_matrices=False, compute_uv=True):
    return jnp.linalg.svd(x, full_matrices=full_matrices,
                          compute_uv=compute_uv)


@register_op("eigh")
def eigh(x):
    """Symmetric eigendecomposition (reference: self_adjoint_eig)."""
    return jnp.linalg.eigh(x)


@register_op("solve")
def solve(a, b):
    return jnp.linalg.solve(a, b)


@register_op("triangular_solve")
def triangular_solve(a, b, lower=True, adjoint=False):
    return jax.scipy.linalg.solve_triangular(
        a, b, lower=lower, trans="T" if adjoint else "N")


@register_op("lstsq")
def lstsq(a, b, rcond=None):
    return jnp.linalg.lstsq(a, b, rcond=rcond)[0]


@register_op("lu")
def lu(x):
    return jax.scipy.linalg.lu(x)


@register_op("matrix_inverse")
def matrix_inverse(x):
    return jnp.linalg.inv(x)


@register_op("pinv")
def pinv(x):
    return jnp.linalg.pinv(x)


@register_op("matrix_determinant")
def matrix_determinant(x):
    return jnp.linalg.det(x)


@register_op("log_matrix_determinant")
def log_matrix_determinant(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return sign, logdet


@register_op("matrix_band_part")
def matrix_band_part(x, num_lower, num_upper):
    m, n = x.shape[-2], x.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep = jnp.ones((m, n), bool)
    if num_lower >= 0:
        keep = keep & (i - j <= num_lower)
    if num_upper >= 0:
        keep = keep & (j - i <= num_upper)
    return jnp.where(keep, x, jnp.zeros((), x.dtype))


@register_op("tri")
def tri(n, m=None, k=0, dtype=jnp.float32):
    return jnp.tri(n, m, k, dtype=dtype)


@register_op("triu")
def triu(x, k=0):
    return jnp.triu(x, k)


@register_op("tril")
def tril(x, k=0):
    return jnp.tril(x, k)


@register_op("cross")
def cross(a, b, axis=-1):
    return jnp.cross(a, b, axis=axis)


@register_op("kron")
def kron(a, b):
    return jnp.kron(a, b)


@register_op("norm_fro")
def norm_fro(x):
    return jnp.linalg.norm(x)


@register_op("l2_normalize")
def l2_normalize(x, axis=-1, eps=1e-12):
    return x * lax.rsqrt(jnp.maximum(
        jnp.sum(x * x, axis=axis, keepdims=True), eps))


@register_op("slogdet")
def slogdet(x):
    """(sign, log|det|) (reference: log_matrix_determinant kin)."""
    sign, logabs = jnp.linalg.slogdet(x)
    return sign, logabs


@register_op("matrix_power")
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


@register_op("matrix_rank")
def matrix_rank(x, tol=None):
    """``tol`` is an ABSOLUTE singular-value threshold (numpy
    semantics), not jnp's relative rtol."""
    if tol is None:
        return jnp.linalg.matrix_rank(x)
    s = jnp.linalg.svd(x, compute_uv=False)
    return jnp.sum(s > tol, axis=-1)


@register_op("eigvalsh")
def eigvalsh(x):
    return jnp.linalg.eigvalsh(x)


@register_op("expm")
def expm(x):
    """Matrix exponential (jax.scipy.linalg.expm; Pade on the MXU)."""
    import jax.scipy.linalg as jsl
    return jsl.expm(x)


@register_op("cond_number")
def cond_number(x, p=None):
    return jnp.linalg.cond(x, p=p)


@register_op("multi_dot")
def multi_dot(mats):
    return jnp.linalg.multi_dot(list(mats))


@register_op("adjoint")
def adjoint(x):
    return jnp.conjugate(jnp.swapaxes(x, -1, -2))
