"""Op registry (reference: libnd4j OpRegistrator + nd4j DynamicCustomOp).

The reference hashes op names to C++ implementations and exposes
``Nd4j.exec(CustomOp)``. Here registration is a decorator; lookup is by
name. Registered ops are pure jax functions — safe to call inside jit.
"""

from __future__ import annotations

from typing import Callable, Dict

_REGISTRY: Dict[str, Callable] = {}


def register_op(name: str):
    """Register a pure-jax op under `name` (and return it unchanged)."""

    def deco(fn: Callable) -> Callable:
        if name in _REGISTRY:
            raise ValueError(f"op already registered: {name}")
        _REGISTRY[name] = fn
        return fn

    return deco


def get_op(name: str) -> Callable:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"Unknown op '{name}'. Registered: {sorted(_REGISTRY)[:20]}..."
        ) from None


def list_ops() -> list[str]:
    return sorted(_REGISTRY)


def has_op(name: str) -> bool:
    return name in _REGISTRY
