"""Op registry (reference: libnd4j OpRegistrator + nd4j DynamicCustomOp).

The reference hashes op names to C++ implementations and exposes
``Nd4j.exec(CustomOp)``. Here registration is a decorator; lookup is by
name. Registered ops are pure jax functions — safe to call inside jit.

Execution accounting (reference: OpValidation tracks which ops the test
suite actually EXERCISED and fails the build otherwise, SURVEY.md §4):
every dispatch records the op name into an in-process set; when
``DL4J_TPU_OP_TRACE_FILE`` is set the set is appended to that file at
interpreter exit, so subprocess-heavy tests (multi-process distributed
drives) contribute to the same accounting. Recording happens at trace
time under jit (once per compilation, not per step) and on each eager
edge call — a set.add, negligible either way.
"""

from __future__ import annotations

import atexit
import functools
import os
from typing import Callable, Dict, Set

_REGISTRY: Dict[str, Callable] = {}
_EXECUTED: Set[str] = set()


def register_op(name: str):
    """Register a pure-jax op under `name`. Returns the dispatch
    wrapper (records execution) so direct calls to the decorated name
    count toward coverage exactly like registry dispatch."""

    def deco(fn: Callable) -> Callable:
        if name in _REGISTRY:
            raise ValueError(f"op already registered: {name}")

        @functools.wraps(fn)
        def dispatch(*args, **kwargs):
            _EXECUTED.add(name)
            return fn(*args, **kwargs)

        _REGISTRY[name] = dispatch
        return dispatch

    return deco


def get_op(name: str) -> Callable:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"Unknown op '{name}'. Registered: {sorted(_REGISTRY)[:20]}..."
        ) from None


def list_ops() -> list[str]:
    return sorted(_REGISTRY)


def has_op(name: str) -> bool:
    return name in _REGISTRY


def executed_ops() -> Set[str]:
    """Ops dispatched so far in THIS process, merged with any trace
    file written by (sub)processes sharing DL4J_TPU_OP_TRACE_FILE."""
    out = set(_EXECUTED)
    path = os.environ.get("DL4J_TPU_OP_TRACE_FILE")
    if path and os.path.exists(path):
        with open(path) as f:
            out.update(ln.strip() for ln in f if ln.strip())
    return out


@atexit.register
def _dump_trace() -> None:
    path = os.environ.get("DL4J_TPU_OP_TRACE_FILE")
    if path and _EXECUTED:
        try:
            with open(path, "a") as f:
                f.write("\n".join(sorted(_EXECUTED)) + "\n")
        except OSError:
            pass
