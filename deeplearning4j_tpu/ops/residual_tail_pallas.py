"""Pallas residual-block TAIL kernel: BN-apply + ReLU + residual add
in one elementwise pass over the conv output.

Reference role: the cuDNN fused conv+BN+add+act epilogues
(SURVEY.md §2.8-2.9). This is the round-5 probe VERDICT r4 #1(b) asked
for — the ONE fusion class the ResNet-50 byte ledger left untried:
the ledger's 11 ms residual-add category plus a share of the 17.4 ms
mask traffic exists because XLA schedules BN-apply(+ReLU) and the
residual add in separate fusions, each round-tripping the [N,H,W,C]
tensor through HBM. This kernel reads the conv output and the residual
ONCE and writes the activated sum ONCE.

Training integration: `bn_relu_residual` is a jax.custom_vjp — the
forward runs the kernel; the backward recomputes via jax autodiff of
the reference formula (the recompute trade the Pallas LSTM's VJP
makes, BASELINE.md round 4), so gradients flow through x, residual,
gamma/beta AND the batch-stat inputs (the mean/var chain stays intact
for upstream autodiff).

A/B results live in BASELINE.md ("residual-tail fusion probe");
bench_residual_tail.py is the measurement harness.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import register_op

# pallas imported inside fn bodies (package convention — see
# conv_pallas module docstring)


def _pick_block(total, cap):
    b = min(cap, total)
    while total % b:
        b -= 1
    return b


def _k_tail(x_ref, r_ref, mean_ref, var_ref, g_ref, b_ref, o_ref, *,
            eps):
    x = x_ref[...].astype(jnp.float32)
    r = r_ref[...].astype(jnp.float32)
    inv = jax.lax.rsqrt(var_ref[...].astype(jnp.float32) + eps)
    z = (x - mean_ref[...]) * inv * g_ref[...] + b_ref[...] + r
    o_ref[...] = jnp.maximum(z, 0.0).astype(o_ref.dtype)


def _tail_kernel(x2, r2, mean, var, gamma, beta, eps, interpret):
    from jax.experimental import pallas as pl

    rows, c = x2.shape
    # VMEM budget: 3 row-blocks (x, res, out) double-buffered + f32
    # temps; cap the block at ~256 KB per buffer so C=2048 still fits
    bm = _pick_block(rows, max(8, (256 * 1024) // (2 * c)))
    grid = (rows // bm,)
    row_spec = pl.BlockSpec((bm, c), lambda i: (i, 0))
    chan_spec = pl.BlockSpec((1, c), lambda i: (0, 0))
    return pl.pallas_call(
        functools.partial(_k_tail, eps=eps),
        grid=grid,
        in_specs=[row_spec, row_spec, chan_spec, chan_spec, chan_spec,
                  chan_spec],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((rows, c), x2.dtype),
        interpret=interpret,
    )(x2, r2, mean.reshape(1, c).astype(jnp.float32),
      var.reshape(1, c).astype(jnp.float32),
      gamma.reshape(1, c).astype(jnp.float32),
      beta.reshape(1, c).astype(jnp.float32))


def _ref_formula(x, res, mean, var, gamma, beta, eps):
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    z = (x.astype(jnp.float32) - mean) * inv * gamma + beta \
        + res.astype(jnp.float32)
    return jnp.maximum(z, 0.0).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def bn_relu_residual(x, res, mean, var, gamma, beta, eps=1e-5):
    """relu(batchnorm_apply(x; mean,var,gamma,beta) + res) in one HBM
    pass. x/res: [N,H,W,C] (or any [..., C]); stats/params: [C].
    Off-TPU falls back to the jnp formula (numerics identical)."""
    if jax.default_backend() != "tpu":
        return _ref_formula(x, res, mean, var, gamma, beta, eps)
    c = x.shape[-1]
    x2 = x.reshape(-1, c)
    r2 = res.reshape(-1, c)
    out = _tail_kernel(x2, r2, mean, var, gamma, beta, eps,
                       interpret=False)
    return out.reshape(x.shape)


def _fwd(x, res, mean, var, gamma, beta, eps):
    return (bn_relu_residual(x, res, mean, var, gamma, beta, eps),
            (x, res, mean, var, gamma, beta))


def _bwd(eps, saved, g):
    x, res, mean, var, gamma, beta = saved
    _, vjp = jax.vjp(
        lambda *a: _ref_formula(*a, eps), x, res, mean, var, gamma,
        beta)
    return vjp(g)


bn_relu_residual.defvjp(_fwd, _bwd)


@register_op("bn_relu_residual")
def _op(x, res, mean, var, gamma, beta, eps=1e-5):
    return bn_relu_residual(x, res, mean, var, gamma, beta, eps)
