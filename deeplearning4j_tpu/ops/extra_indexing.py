"""Indexing / segment / scatter long-tail ops (reference: libnd4j
ops/declarable/generic/parity_ops — segment_*.cpp, scatter_*.cpp,
dynamic ops — the families VERDICT r1 #5 called out).

Static-shape discipline: anything whose output size depends on VALUES
(unique, nonzero) either takes a static size argument or is documented
host-side-only; everything here is jit-safe.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import register_op


# ------------------------------------------------------------- segments
@register_op("unsorted_segment_max")
def unsorted_segment_max(data, segment_ids, num_segments):
    return jax.ops.segment_max(data, segment_ids,
                               num_segments=num_segments)


@register_op("unsorted_segment_min")
def unsorted_segment_min(data, segment_ids, num_segments):
    return jax.ops.segment_min(data, segment_ids,
                               num_segments=num_segments)


@register_op("unsorted_segment_prod")
def unsorted_segment_prod(data, segment_ids, num_segments):
    return jax.ops.segment_prod(data, segment_ids,
                                num_segments=num_segments)


@register_op("unsorted_segment_sqrt_n")
def unsorted_segment_sqrt_n(data, segment_ids, num_segments):
    """sum / sqrt(count) per segment (reference:
    unsorted_segment_sqrt_n.cpp)."""
    s = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    n = jax.ops.segment_sum(jnp.ones_like(data), segment_ids,
                            num_segments=num_segments)
    return s / jnp.sqrt(jnp.maximum(n, 1.0))


# ------------------------------------------------------------- scatter
@register_op("scatter_nd_add")
def scatter_nd_add(ref, indices, updates):
    return ref.at[tuple(jnp.moveaxis(indices, -1, 0))].add(updates)


@register_op("scatter_nd_sub")
def scatter_nd_sub(ref, indices, updates):
    return ref.at[tuple(jnp.moveaxis(indices, -1, 0))].add(-updates)


@register_op("scatter_nd_update")
def scatter_nd_update(ref, indices, updates):
    return ref.at[tuple(jnp.moveaxis(indices, -1, 0))].set(updates)


# ------------------------------------------------------------ indexing
@register_op("roll")
def roll(x, shift, axis=None):
    return jnp.roll(x, shift, axis=axis)


@register_op("flip")
def flip(x, axis=None):
    return jnp.flip(x, axis=axis)


@register_op("rot90")
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@register_op("bincount")
def bincount(x, weights=None, minlength=0, maxlength=None,
             binary_output=False):
    """Static output size (jit-safe). TF semantics: ``maxlength`` CAPS
    the bin count (values >= maxlength are dropped); ``minlength``
    guarantees a floor; ``binary_output`` reports presence (0/1)
    instead of counts (TF rejects weights in that mode, so do we)."""
    if binary_output and weights is not None:
        raise ValueError("bincount: binary_output with weights is "
                         "undefined (TF rejects it too)")
    # maxlength CAPS the count of values (>= maxlength dropped) but the
    # static output size must still cover [minlength, maxlength) — a
    # min() here would silently drop counts in that range.
    nbins = maxlength if maxlength is not None else minlength
    if nbins <= 0:
        raise ValueError("bincount needs a static minlength/maxlength "
                         "under jit")
    w = jnp.ones_like(x, jnp.float32) if weights is None else weights
    idx = x.reshape(-1).astype(jnp.int32)
    keep = idx < nbins
    idx = jnp.where(keep, idx, nbins)  # overflow bucket, sliced off
    out = jax.ops.segment_sum(
        jnp.where(keep.reshape(w.reshape(-1).shape), w.reshape(-1), 0.0),
        idx, num_segments=nbins + 1)[:nbins]
    # TF bincount's default output dtype is int32 (and int64 would just
    # truncate + warn under x64-disabled JAX)
    if binary_output:
        return (out > 0).astype(jnp.int32)
    return out if weights is not None else out.astype(jnp.int32)


@register_op("searchsorted")
def searchsorted(sorted_seq, values, side="left"):
    return jnp.searchsorted(sorted_seq, values, side=side)


@register_op("nth_element")
def nth_element(x, n, reverse=False):
    """n-th smallest (or largest) along the last axis (reference:
    nth_element.cpp)."""
    s = jnp.sort(x, axis=-1)
    if reverse:
        s = jnp.flip(s, axis=-1)
    return s[..., n]


@register_op("histogram_fixed_width")
def histogram_fixed_width(x, range_min, range_max, nbins=100):
    edges_scale = (range_max - range_min) / nbins
    idx = jnp.clip(((x - range_min) / edges_scale).astype(jnp.int32),
                   0, nbins - 1)
    return jax.ops.segment_sum(jnp.ones_like(x, jnp.int32).reshape(-1),
                               idx.reshape(-1), num_segments=nbins)


@register_op("sequence_mask")
def sequence_mask(lengths, maxlen):
    """[*, maxlen] bool mask (reference: sequence_mask.cpp)."""
    r = jnp.arange(maxlen)
    return r < jnp.expand_dims(lengths, -1)


@register_op("batch_gather")
def batch_gather(params, indices):
    """Gather along axis 1 with a leading shared batch dim."""
    return jnp.take_along_axis(
        params, indices.reshape(indices.shape + (1,) * (
            params.ndim - indices.ndim)).astype(jnp.int32), axis=1)


@register_op("dynamic_partition_masks")
def dynamic_partition_masks(data, partitions, num_partitions):
    """Static-shape stand-in for dynamic_partition (whose ragged outputs
    are untileable on TPU): returns [num_partitions, ...data] with
    non-members zeroed plus a [num_partitions, n] bool mask — callers
    reduce per partition instead of slicing ragged arrays."""
    masks = jnp.stack([partitions == p for p in range(num_partitions)])
    expanded = masks.reshape(masks.shape + (1,) * (data.ndim - 1))
    return data[None] * expanded.astype(data.dtype), masks


@register_op("dynamic_stitch")
def dynamic_stitch(indices_list, data_list, size):
    """Merge partitions back (reference: dynamic_stitch.cpp) with a
    static output size."""
    out = jnp.zeros((size,) + data_list[0].shape[1:], data_list[0].dtype)
    for idx, d in zip(indices_list, data_list):
        out = out.at[idx].set(d)
    return out
