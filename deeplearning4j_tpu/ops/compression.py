"""Threshold gradient compression (reference: libnd4j encodeThreshold /
decodeThreshold ops + EncodedGradientsAccumulator, SURVEY.md §2.29).

The reference threshold-encodes gradients into sparse int indices for
Aeron UDP broadcast, keeping the sub-threshold remainder as a residual.
On TPU, intra-slice all-reduce rides ICI and needs no compression (the
whole subsystem collapses into ``psum``); this module exists for the
**DCN multi-slice path** where bandwidth can bind, and for capability
parity. Two XLA-friendly encodings (both static-shape, jit-safe):

- ternary: sign(g)*t where |g|>=t, stored as int8 — 4x smaller than f32,
  exact equivalent of the reference's "threshold element" semantics.
- topk: fixed-capacity sparse (indices, values) via lax.top_k — the
  static-shape analog of the reference's variable-length index list.

Both return the residual (g - encoded) exactly as the reference's
residual post-processors do.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import register_op


@register_op("encode_threshold")
def encode_threshold(grad, threshold):
    """Ternary threshold encoding.

    Returns (encoded_int8, residual). decode = encoded * threshold.
    """
    flat = grad
    mask = jnp.abs(flat) >= threshold
    enc = jnp.where(mask, jnp.sign(flat), 0.0)
    residual = flat - enc * threshold
    return enc.astype(jnp.int8), residual


@register_op("decode_threshold")
def decode_threshold(encoded, threshold, dtype=jnp.float32):
    return encoded.astype(dtype) * threshold


@register_op("encode_topk")
def encode_topk(grad, k):
    """Fixed-capacity sparse encoding: largest-|g| k elements.

    grad: flat [D]. Returns (indices int32 [k], values [k], residual [D]).
    """
    absg = jnp.abs(grad)
    _, idx = lax.top_k(absg, k)
    vals = grad[idx]
    residual = grad.at[idx].set(0.0)
    return idx.astype(jnp.int32), vals, residual


@register_op("decode_topk")
def decode_topk(indices, values, size):
    out = jnp.zeros((size,), values.dtype)
    return out.at[indices].add(values)


@register_op("adaptive_threshold")
def adaptive_threshold(grad, target_sparsity=1e-3, current_threshold=1e-3,
                       decay=0.95, min_threshold=1e-5):
    """Adaptive threshold update (reference: AdaptiveThresholdAlgorithm —
    adjusts threshold so encoded density tracks a target)."""
    density = jnp.mean((jnp.abs(grad) >= current_threshold).astype(jnp.float32))
    too_dense = density > target_sparsity
    new_t = jnp.where(too_dense, current_threshold / decay, current_threshold * decay)
    return jnp.maximum(new_t, min_threshold)
