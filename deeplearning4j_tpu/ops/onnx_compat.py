"""ONNX-semantics helper ops (reference: samediff-import-onnx's
per-op attribute adapters, SURVEY.md §2.14 — op SEMANTICS live with the
op set so a bare ``import deeplearning4j_tpu.ops`` registers the full
registry; the importer module only maps nodes onto these names)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import register_op


@register_op("onnx_reshape")
def onnx_reshape(x, shape):
    """ONNX Reshape: 0 copies the input dim, -1 infers."""
    resolved = [x.shape[i] if s == 0 else int(s)
                for i, s in enumerate(shape)] if 0 in list(shape) \
        else [int(s) for s in shape]
    return jnp.reshape(x, tuple(resolved))


@register_op("onnx_flatten")
def onnx_flatten(x, axis=1):
    lead = 1
    for d in x.shape[:axis]:
        lead *= d
    return jnp.reshape(x, (lead, -1))


@register_op("onnx_slice")
def onnx_slice(x, starts, ends, axes, steps):
    idx = [slice(None)] * x.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        n = x.shape[ax]
        en = min(en, n) if en >= 0 else en
        idx[ax] = slice(st, en, sp)
    return x[tuple(idx)]
# (broadcast_to: canonical registration lives in ops/shape.py)


@register_op("hardmax")
def hardmax(x, axis=-1):
    """ONNX Hardmax: 1.0 at the (first) argmax along axis, else 0."""
    idx = jnp.argmax(x, axis=axis)
    return jax.nn.one_hot(idx, x.shape[axis], axis=axis, dtype=x.dtype)


@register_op("shrink")
def shrink(x, lambd=0.5, bias=0.0):
    """ONNX Shrink: x+bias if x < -lambd; x-bias if x > lambd; else 0."""
    return jnp.where(x < -lambd, x + bias,
                     jnp.where(x > lambd, x - bias,
                               jnp.zeros((), x.dtype)))


@register_op("mean_variance_norm")
def mean_variance_norm(x, axes=(0, 2, 3), eps=1e-9):
    """ONNX MeanVarianceNormalization: (x - mean) / std over axes."""
    m = jnp.mean(x, axis=tuple(axes), keepdims=True)
    v = jnp.var(x, axis=tuple(axes), keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps)


def _per_axis_qparams(x, scale, zero_point, axis):
    """Broadcast ONNX per-tensor or per-axis quantization params against
    x. A scalar zero_point (incl. the omitted-input default 0) stays
    scalar even when scale is per-axis — it broadcasts fine."""
    scale = jnp.asarray(scale)
    zp = jnp.asarray(0 if zero_point is None else zero_point)
    if scale.ndim == 1 and scale.shape[0] > 1:
        shape = [1] * x.ndim
        shape[axis] = scale.shape[0]
        scale = scale.reshape(shape)
        if zp.size > 1:
            zp = zp.reshape(shape)
    return scale, zp


@register_op("quantize_linear")
def quantize_linear(x, scale, zero_point=None, axis=1, qmin=0, qmax=255):
    """ONNX QuantizeLinear: saturate(round(x/scale) + zero_point)."""
    scale, zp = _per_axis_qparams(x, scale, zero_point, axis)
    q = jnp.round(x / scale) + zp.astype(jnp.float32)
    return jnp.clip(q, qmin, qmax).astype(
        jnp.uint8 if qmin == 0 else jnp.int8)


@register_op("dequantize_linear")
def dequantize_linear(x, scale, zero_point=None, axis=1):
    """ONNX DequantizeLinear: (x - zero_point) * scale — the QDQ-format
    entry point (quantized exports import as float through this)."""
    scale, zp = _per_axis_qparams(x, scale, zero_point, axis)
    return (x.astype(jnp.float32) - zp.astype(jnp.float32)) \
        * scale.astype(jnp.float32)


@register_op("grid_sample")
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=False):
    """ONNX/torch GridSample, NHWC x + [N,Hout,Wout,2] grid of (x,y) in
    [-1,1]. Bilinear or nearest; zeros or border padding. Indices are
    traced VALUES (static shapes), so XLA lowers this to gathers."""
    n, h, w, c = x.shape
    gx, gy = grid[..., 0], grid[..., 1]
    if align_corners:
        fx = (gx + 1) * 0.5 * (w - 1)
        fy = (gy + 1) * 0.5 * (h - 1)
    else:
        fx = ((gx + 1) * w - 1) * 0.5
        fy = ((gy + 1) * h - 1) * 0.5

    def sample(iy, ix):
        iyc = jnp.clip(iy, 0, h - 1)
        ixc = jnp.clip(ix, 0, w - 1)
        batch = jnp.arange(n).reshape(n, 1, 1)
        vals = x[batch, iyc, ixc]          # [N,Hout,Wout,C]
        if padding_mode == "zeros":
            ok = ((iy >= 0) & (iy <= h - 1) & (ix >= 0)
                  & (ix <= w - 1))[..., None]
            vals = jnp.where(ok, vals, jnp.zeros((), x.dtype))
        return vals

    if mode == "nearest":
        return sample(jnp.round(fy).astype(jnp.int32),
                      jnp.round(fx).astype(jnp.int32))
    y0 = jnp.floor(fy)
    x0 = jnp.floor(fx)
    wy = (fy - y0)[..., None].astype(x.dtype)
    wx = (fx - x0)[..., None].astype(x.dtype)
    y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
    v00 = sample(y0i, x0i)
    v01 = sample(y0i, x0i + 1)
    v10 = sample(y0i + 1, x0i)
    v11 = sample(y0i + 1, x0i + 1)
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return top * (1 - wy) + bot * wy
