"""ONNX-semantics helper ops (reference: samediff-import-onnx's
per-op attribute adapters, SURVEY.md §2.14 — op SEMANTICS live with the
op set so a bare ``import deeplearning4j_tpu.ops`` registers the full
registry; the importer module only maps nodes onto these names)."""

from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import register_op


@register_op("onnx_reshape")
def onnx_reshape(x, shape):
    """ONNX Reshape: 0 copies the input dim, -1 infers."""
    resolved = [x.shape[i] if s == 0 else int(s)
                for i, s in enumerate(shape)] if 0 in list(shape) \
        else [int(s) for s in shape]
    return jnp.reshape(x, tuple(resolved))


@register_op("onnx_flatten")
def onnx_flatten(x, axis=1):
    lead = 1
    for d in x.shape[:axis]:
        lead *= d
    return jnp.reshape(x, (lead, -1))


@register_op("onnx_slice")
def onnx_slice(x, starts, ends, axes, steps):
    idx = [slice(None)] * x.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        n = x.shape[ax]
        en = min(en, n) if en >= 0 else en
        idx[ax] = slice(st, en, sp)
    return x[tuple(idx)]
# (broadcast_to: canonical registration lives in ops/shape.py)
