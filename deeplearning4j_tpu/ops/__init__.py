"""Op set (reference: nd4j op hierarchy + libnd4j declarable ops).

The reference registers ~500 C++ ``DeclarableOp``s in an
``OpRegistrator`` keyed by name/hash and dispatches each eagerly through
JNI (SURVEY.md §2.2, §2.6, §3.3). Here ops are pure jax functions over
``jax.Array`` registered by name; they compose freely under ``jit`` so
XLA fuses them — the per-op dispatch stack the reference pays for every
call exists here only at the eager API edge (``Nd4j.exec``).

Modules:
- registry: name -> fn registration and dispatch
- transforms: elementwise/activation math (reference: Transforms.java)
- nn: conv/pool/norm/rnn/attention ops (reference: ops/declarable/generic/nn)
- random: distribution ops
- compression: threshold gradient encode/decode (reference: encodeThreshold)
"""

from deeplearning4j_tpu.ops.registry import get_op, list_ops, register_op
from deeplearning4j_tpu.ops import transforms, nn, random, compression  # noqa: F401 (register)

__all__ = ["get_op", "list_ops", "register_op"]
