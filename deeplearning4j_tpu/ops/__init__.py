"""Op set (reference: nd4j op hierarchy + libnd4j declarable ops).

The reference registers ~500 C++ ``DeclarableOp``s in an
``OpRegistrator`` keyed by name/hash and dispatches each eagerly through
JNI (SURVEY.md §2.2, §2.6, §3.3). Here ops are pure jax functions over
``jax.Array`` registered by name; they compose freely under ``jit`` so
XLA fuses them — the per-op dispatch stack the reference pays for every
call exists here only at the eager API edge (``Nd4j.exec``).

Modules:
- registry: name -> fn registration and dispatch
- transforms: elementwise/activation math (reference: Transforms.java)
- nn: conv/pool/norm/rnn/attention ops (reference: ops/declarable/generic/nn)
- random: distribution ops
- compression: threshold gradient encode/decode (reference: encodeThreshold)
- reduce: reductions / index & pairwise-distance reductions / segment ops
- shape: shape manipulation, gather/scatter, pad, layout movement
- linalg: matmul family + decompositions (reference: generic/blas+linalg)
- image: resize/crop/NMS/colorspace (reference: generic/images)
- bitwise: bit ops, comparisons, safe division
"""

from deeplearning4j_tpu.ops.registry import get_op, list_ops, register_op
from deeplearning4j_tpu.ops import (  # noqa: F401 (register)
    transforms, nn, random, compression, reduce, shape, linalg, image,
    bitwise, extra_math, extra_indexing, tensor_array, tf_compat,
    declarable_tail, flash_attention, onnx_compat, conv_pallas,
    residual_tail_pallas, fused_update_pallas, paged_attention_pallas,
)
# The SameDiff math module owns the canonical registrations for the
# graph-execution op names (reduce_sum with `dimensions=`, etc. — the
# TF-import attr contract); importing it here makes the full op set
# available from a bare `deeplearning4j_tpu.ops` import. Cycle-safe:
# nothing in that chain imports the ops PACKAGE, only ops.registry.
from deeplearning4j_tpu.autodiff import ops_math as _ops_math  # noqa: F401,E402
from deeplearning4j_tpu.autodiff import control_flow as _control_flow  # noqa: F401,E402

__all__ = ["get_op", "list_ops", "register_op"]
