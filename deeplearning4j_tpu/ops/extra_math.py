"""Long-tail math / special-function ops (reference: libnd4j
ops/declarable/generic/transforms + legacy scalar/pairwise loops —
the declarable-op families VERDICT r1 flagged as missing breadth,
SURVEY.md §2.6).

All are thin jax/lax compositions — XLA fuses them; there is no
per-op dispatch cost (SURVEY §3.3's JNI stack collapses under jit).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import register_op


# ----------------------------------------------------------- unary math
@register_op("asinh")
def asinh(x):
    return jnp.arcsinh(x)


@register_op("acosh")
def acosh(x):
    return jnp.arccosh(x)


@register_op("atanh")
def atanh(x):
    return jnp.arctanh(x)


@register_op("expm1")
def expm1(x):
    return jnp.expm1(x)


@register_op("rint")
def rint(x):
    return jnp.rint(x)


@register_op("trunc")
def trunc(x):
    return jnp.trunc(x)


@register_op("cbrt")
def cbrt(x):
    return jnp.cbrt(x)


@register_op("erfinv")
def erfinv(x):
    return jax.scipy.special.erfinv(x)


@register_op("lgamma")
def lgamma(x):
    return jax.scipy.special.gammaln(x)


@register_op("digamma")
def digamma(x):
    return jax.scipy.special.digamma(x)


@register_op("polygamma")
def polygamma(n, x):
    # TF/reference take float n with integral values; jax requires an
    # integer dtype for n (non-integral n is NaN territory upstream too)
    n = jnp.asarray(n)
    if not jnp.issubdtype(n.dtype, jnp.integer):
        n = n.astype(jnp.int32)
    return jax.scipy.special.polygamma(n, x)


@register_op("igamma")
def igamma(a, x):
    """Regularized lower incomplete gamma (reference: igamma.cpp)."""
    return jax.scipy.special.gammainc(a, x)


@register_op("igammac")
def igammac(a, x):
    """Regularized upper incomplete gamma (reference: igammac.cpp)."""
    return jax.scipy.special.gammaincc(a, x)


@register_op("betainc")
def betainc(a, b, x):
    return jax.scipy.special.betainc(a, b, x)


@register_op("sinc")
def sinc(x):
    return jnp.sinc(x)


@register_op("deg2rad")
def deg2rad(x):
    return jnp.deg2rad(x)


@register_op("rad2deg")
def rad2deg(x):
    return jnp.rad2deg(x)


@register_op("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@register_op("log_cosh")
def log_cosh(x):
    # numerically stable: |x| + log1p(exp(-2|x|)) - log 2
    ax = jnp.abs(x)
    return ax + jnp.log1p(jnp.exp(-2.0 * ax)) - jnp.log(2.0)


@register_op("softmin")
def softmin(x, axis=-1):
    return jax.nn.softmax(-x, axis=axis)


# --------------------------------------------------------- binary math
@register_op("logaddexp")
def logaddexp(a, b):
    return jnp.logaddexp(a, b)


@register_op("logaddexp2")
def logaddexp2(a, b):
    return jnp.logaddexp2(a, b)


@register_op("hypot")
def hypot(a, b):
    return jnp.hypot(a, b)


@register_op("heaviside")
def heaviside(x, h0):
    return jnp.heaviside(x, h0)


@register_op("copysign")
def copysign(a, b):
    return jnp.copysign(a, b)


@register_op("fmod")
def fmod(a, b):
    return jnp.fmod(a, b)


@register_op("xdivy")
def xdivy(x, y):
    """0 if x==0 else x/y (reference: xdivy TF parity op)."""
    return jnp.where(x == 0, jnp.zeros_like(x), x / jnp.where(
        x == 0, jnp.ones_like(y), y))


@register_op("xlogy")
def xlogy(x, y):
    return jax.scipy.special.xlogy(x, y)


@register_op("xlog1py")
def xlog1py(x, y):
    return jax.scipy.special.xlog1py(x, y)


@register_op("lerp")
def lerp(a, b, weight):
    """a + weight*(b-a) (reference: lerp scalar/pairwise op)."""
    return a + weight * (b - a)


@register_op("addcmul")
def addcmul(x, t1, t2, value=1.0):
    return x + value * t1 * t2


@register_op("addcdiv")
def addcdiv(x, t1, t2, value=1.0):
    return x + value * t1 / t2


@register_op("polyval")
def polyval(coeffs, x):
    """Horner evaluation; coeffs[0] is the highest power."""
    out = jnp.zeros_like(x) + coeffs[0]
    for c in coeffs[1:]:
        out = out * x + c
    return out


@register_op("absolute_difference")
def absolute_difference(a, b):
    return jnp.abs(a - b)


# -------------------------------------------------- nan-skipping reduces
@register_op("nanmean")
def nanmean(x, dimensions=None, keep_dims=False):
    axis = tuple(dimensions) if dimensions is not None else None
    return jnp.nanmean(x, axis=axis, keepdims=keep_dims)


@register_op("nansum")
def nansum(x, dimensions=None, keep_dims=False):
    axis = tuple(dimensions) if dimensions is not None else None
    return jnp.nansum(x, axis=axis, keepdims=keep_dims)


@register_op("nanmax")
def nanmax(x, dimensions=None, keep_dims=False):
    axis = tuple(dimensions) if dimensions is not None else None
    return jnp.nanmax(x, axis=axis, keepdims=keep_dims)


@register_op("nanmin")
def nanmin(x, dimensions=None, keep_dims=False):
    axis = tuple(dimensions) if dimensions is not None else None
    return jnp.nanmin(x, axis=axis, keepdims=keep_dims)


# ----------------------------------------------------------- percentile
@register_op("percentile")
def percentile(x, q, dimensions=None, keep_dims=False,
               interpolation="linear"):
    """reference: percentile.cpp (linear/lower/higher/nearest modes)."""
    axis = tuple(dimensions) if dimensions is not None else None
    return jnp.percentile(x, q, axis=axis, keepdims=keep_dims,
                          method=interpolation)


@register_op("median")
def median(x, dimensions=None, keep_dims=False):
    axis = tuple(dimensions) if dimensions is not None else None
    return jnp.median(x, axis=axis, keepdims=keep_dims)


@register_op("quantile")
def quantile(x, q, dimensions=None, keep_dims=False):
    axis = tuple(dimensions) if dimensions is not None else None
    return jnp.quantile(x, q, axis=axis, keepdims=keep_dims)


# --------------------------------------------------------- cumulative
@register_op("cummax")
def cummax(x, axis=0):
    return lax.cummax(x, axis=axis)


@register_op("cummin")
def cummin(x, axis=0):
    return lax.cummin(x, axis=axis)


@register_op("diff")
def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


@register_op("trapz")
def trapz(y, dx=1.0, axis=-1):
    return jax.scipy.integrate.trapezoid(y, dx=dx, axis=axis)


@register_op("einsum")
def einsum(*operands, equation):
    """General tensor contraction (reference: the libnd4j einsum-style
    composite ops; ONNX Einsum). XLA lowers this straight onto the MXU
    for contraction terms."""
    return jnp.einsum(equation, *operands)
