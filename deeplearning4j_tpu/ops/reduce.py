"""Reduction / index-reduction / accumulation ops.

Reference: nd4j ``org/nd4j/linalg/api/ops/impl/reduce/**`` (ReduceOp
hierarchy: same/float/long variants), ``indexaccum/**`` (IndexMax et
al.), ``reduce3/**`` (pairwise distance reductions) and the libnd4j
legacy reduce loops (SURVEY.md §2.2, §2.7). All pure jax: under jit
these lower to single XLA reduce fusions — the reference pays one JNI
dispatch + TAD pass per call.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import register_op


# -- plain reductions --------------------------------------------------
@register_op("count_zero")
def count_zero(x, axis=None, keepdims=False):
    return jnp.sum((x == 0).astype(jnp.int32), axis=axis,
                   keepdims=keepdims)


# -- norms (reference: reduce/floating/Norm1,Norm2,NormMax,SquaredNorm)
@register_op("norm1")
def norm1(x, axis=None, keepdims=False):
    return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims)


@register_op("norm2")
def norm2(x, axis=None, keepdims=False):
    return jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=keepdims))


@register_op("normmax")
def normmax(x, axis=None, keepdims=False):
    return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdims)


@register_op("squared_norm")
def squared_norm(x, axis=None, keepdims=False):
    return jnp.sum(x * x, axis=axis, keepdims=keepdims)


@register_op("std")
def std(x, axis=None, keepdims=False, ddof=0):
    return jnp.std(x, axis=axis, keepdims=keepdims, ddof=ddof)


@register_op("variance")
def variance(x, axis=None, keepdims=False, ddof=0):
    return jnp.var(x, axis=axis, keepdims=keepdims, ddof=ddof)


@register_op("moments")
def moments(x, axis=None, keepdims=False):
    """(mean, variance) in one pass (reference: moments op)."""
    m = jnp.mean(x, axis=axis, keepdims=keepdims)
    v = jnp.var(x, axis=axis, keepdims=keepdims)
    return m, v


@register_op("entropy")
def entropy(x, axis=None):
    """-sum(p * log(p)) (reference: reduce/floating/Entropy)."""
    return -jnp.sum(x * jnp.log(x), axis=axis)


@register_op("log_entropy")
def log_entropy(x, axis=None):
    return jnp.log(entropy(x, axis=axis))


@register_op("shannon_entropy")
def shannon_entropy(x, axis=None):
    return -jnp.sum(x * jnp.log2(x), axis=axis)


# -- index reductions (reference: indexaccum/{IMax,IMin,FirstIndex,...})
@register_op("argamax")
def argamax(x, axis=None):
    """Index of max ABSOLUTE value (reference: IAMax)."""
    return jnp.argmax(jnp.abs(x), axis=axis)


@register_op("argamin")
def argamin(x, axis=None):
    return jnp.argmin(jnp.abs(x), axis=axis)


# -- accumulations along an axis ---------------------------------------
# -- reduce3: pairwise distance reductions (reference: reduce3/**) -----
@register_op("dot")
def dot(x, y, axis=None):
    return jnp.sum(x * y, axis=axis)


@register_op("cosine_similarity")
def cosine_similarity(x, y, axis=None, eps=1e-12):
    num = jnp.sum(x * y, axis=axis)
    den = jnp.sqrt(jnp.sum(x * x, axis=axis)
                   * jnp.sum(y * y, axis=axis))
    return num / jnp.maximum(den, eps)


@register_op("cosine_distance")
def cosine_distance(x, y, axis=None):
    return 1.0 - cosine_similarity(x, y, axis=axis)


@register_op("euclidean_distance")
def euclidean_distance(x, y, axis=None):
    d = x - y
    return jnp.sqrt(jnp.sum(d * d, axis=axis))


@register_op("manhattan_distance")
def manhattan_distance(x, y, axis=None):
    return jnp.sum(jnp.abs(x - y), axis=axis)


@register_op("hamming_distance")
def hamming_distance(x, y, axis=None):
    return jnp.sum((x != y).astype(jnp.float32), axis=axis)


@register_op("jaccard_distance")
def jaccard_distance(x, y, axis=None, eps=1e-12):
    mn = jnp.sum(jnp.minimum(x, y), axis=axis)
    mx = jnp.sum(jnp.maximum(x, y), axis=axis)
    return 1.0 - mn / jnp.maximum(mx, eps)


# -- segment reductions (reference: ops/declarable/generic/transforms/
#    segment_*.cpp + unsorted variants; sum/mean/max/min registered by
#    autodiff/ops_math — only the variants missing there live here) ----
@register_op("segment_prod")
def segment_prod(data, segment_ids, num_segments):
    return jax.ops.segment_prod(data, segment_ids,
                                num_segments=num_segments,
                                indices_are_sorted=True)


def segment_mean(data, segment_ids, num_segments, *, sorted=True):
    s = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments,
                            indices_are_sorted=sorted)
    n = jax.ops.segment_sum(jnp.ones_like(data), segment_ids,
                            num_segments=num_segments,
                            indices_are_sorted=sorted)
    return s / jnp.maximum(n, 1)


@register_op("unsorted_segment_sum")
def unsorted_segment_sum(data, segment_ids, num_segments):
    return jax.ops.segment_sum(data, segment_ids,
                               num_segments=num_segments,
                               indices_are_sorted=False)


@register_op("unsorted_segment_mean")
def unsorted_segment_mean(data, segment_ids, num_segments):
    return segment_mean(data, segment_ids, num_segments, sorted=False)


# -- top-k family ------------------------------------------------------
@register_op("in_top_k")
def in_top_k(predictions, targets, k):
    """[N, C] predictions, [N] targets -> [N] bool (reference:
    in_top_k.cpp)."""
    _, idx = lax.top_k(predictions, k)
    return jnp.any(idx == targets[:, None], axis=-1)


@register_op("confusion_matrix")
def confusion_matrix(labels, predictions, num_classes, weights=None):
    flat = labels.astype(jnp.int32) * num_classes \
        + predictions.astype(jnp.int32)
    w = jnp.ones_like(flat, jnp.float32) if weights is None else weights
    cm = jax.ops.segment_sum(w, flat, num_segments=num_classes ** 2)
    return cm.reshape(num_classes, num_classes)


@register_op("zero_fraction")
def zero_fraction(x):
    return jnp.mean((x == 0).astype(jnp.float32))


@register_op("matrix_trace")
def matrix_trace(x):
    return jnp.trace(x, axis1=-2, axis2=-1)
