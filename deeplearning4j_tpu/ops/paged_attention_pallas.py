"""Paged-attention Pallas kernel: the serving engine's per-layer page
gather + masked softmax + weighted sum in ONE pass over the page pool.

Reference role: the cuDNN fuse-the-memory-bound-chain playbook
(Chetlur et al., arXiv:1410.0759) applied to the DECODE loop, exactly
as ``ops/fused_update_pallas.py`` applied it to the optimizer. The
roofline registry (profiler/programs.py) classifies ``serving_decode``
as memory-bound: every decode step streams the whole paged KV cache
through the einsum pair

    logits = einsum("nhqd,nphod->nhqpo", q, gather(kpool, tables))
    ctx    = einsum("nhqpo,nphod->nhqd", softmax(logits), gather(vpool))

materializing (a) the gathered page copies and (b) the full
``[n,h,q,p,o]`` logits tensor in HBM between the two contractions.
This kernel instead walks the slot's page table via scalar prefetch —
the table lookup happens in the BlockSpec index_map, so each K/V page
block is DMA'd from the pool into VMEM directly, no gathered copy —
and runs an online-softmax (flash-style) accumulation per slot: a
running max ``m``, running sum ``l`` and context accumulator carried
in VMEM scratch across the page-walk grid dimension. The logits
tensor never exists; pages are read once.

Layout contract (kv_pages.py): pools are ``[L, n_pages, H, ps, hd]``
with page 0 the never-read null page; ``tables`` rows are page ids in
position order, so flat position ``p*ps + o`` of sequence ``n`` lives
at ``pool[layer, tables[n, p], :, o]`` and the causal mask is a plain
``flat <= qpos``. Queries are ``[N, H, Q, hd]`` where query ``i`` of
sequence ``n`` sits at absolute position ``qbase[n] + i`` — Q=1 with
per-slot positions for the decode step, N=1 with consecutive suffix
positions for the prefix-prefill program.

fp8 KV (``kv_dtype="fp8_e4m3"``): the pools store float8_e4m3fn with
per-page-per-head fp32 scale planes ``[L, n_pages, H]`` beside them;
the kernel dequantizes each page block in VMEM (one scalar multiply
per block) so HBM traffic stays fp8 — the other half of the
bytes/step reduction.

Dispatch (``paged_attention_mode()``), mirroring
``DL4J_TPU_FUSED_UPDATE``:
- ``pallas``    — real TPU backend: the kernel above.
- ``interpret`` — forced via ``DL4J_TPU_PAGED_ATTN=interpret``: the
  same kernel through the Pallas interpreter (CPU-testable path; what
  the CI token-identity gate runs).
- ``xla``       — everything else (CPU/GPU, or
  ``DL4J_TPU_PAGED_ATTN=xla``): the exact einsum pair above, verbatim
  — the serving engine built in this mode is program-for-program
  identical to the pre-kernel engine.

Numerics: the xla path IS the reference (bit-identical to the decode
core it replaced). The kernel is float-equivalent but not
bit-identical (online softmax reduces in a different order, f32
accumulation); the greedy TOKEN-identity gate at f32 — the same
contract the engine already holds against ``generate()`` — is what
tests and run_tests.sh pin.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.ops.registry import register_op

#: f32 mask value. Masked scores sit at this floor; the online-softmax
#: update zeroes their exp() contribution explicitly (``where(valid)``)
#: so an all-masked page leaves (m, l, acc) untouched.
_MASK_MIN = float(np.finfo(np.float32).min)


def paged_attention_mode() -> str:
    """'pallas' | 'interpret' | 'xla' — see module docstring."""
    env = os.environ.get("DL4J_TPU_PAGED_ATTN", "auto").strip().lower()
    if env in ("pallas", "interpret", "xla"):
        return env
    return "pallas" if jax.default_backend() == "tpu" else "xla"


# ------------------------------------------------------- xla reference
def _xla_paged_attention(q, kv, layer, tables, qbase):
    """The exact einsum pair from the pre-kernel decode core /
    prefix-prefill program (serving/engine.py PR 8-9 lineage). This is
    the dispatch target when the kernel is off, so it must stay
    op-for-op what those programs inlined — the engine's greedy
    bit-identity to ``CausalLM.generate()`` rests on it."""
    N, H, Q, hd = q.shape
    ck = kv["k"][layer][tables]           # [N, P, H, ps, hd]
    cv = kv["v"][layer][tables]
    if "k_scale" in kv:
        cd = q.dtype
        ck = ck.astype(cd) * kv["k_scale"][layer][tables][
            ..., None, None].astype(cd)
        cv = cv.astype(cd) * kv["v_scale"][layer][tables][
            ..., None, None].astype(cd)
    P, ps = ck.shape[1], ck.shape[3]
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, q.dtype))
    qpos = qbase[:, None] + jnp.arange(Q, dtype=jnp.int32)[None, :]
    # page-major contraction: (p, o) together are the flat key axis
    logits = jnp.einsum("nhqd,nphod->nhqpo", q, ck) \
        .reshape(N, H, Q, P * ps) * scale
    neg = jnp.asarray(jnp.finfo(logits.dtype).min, logits.dtype)
    valid = (jnp.arange(P * ps)[None, None, None, :]
             <= qpos[:, None, :, None])
    logits = jnp.where(valid, logits, neg)
    w = jax.nn.softmax(logits, axis=-1).reshape(N, H, Q, P, ps)
    return jnp.einsum("nhqpo,nphod->nhqd", w, cv)


# -------------------------------------------------------------- kernel
def _kernel(tables_ref, qbase_ref, q_ref, k_ref, v_ref, *rest,
            layer, page_size, sm_scale, fp8):
    """One (sequence n, head h, page p) grid step of the online-softmax
    walk. Scratch (m, l, acc) persists across the sequential innermost
    page dimension; initialized at p == 0, finalized into the output
    block at the last page."""
    from jax.experimental import pallas as pl

    if fp8:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    n = pl.program_id(0)
    p = pl.program_id(2)
    last = pl.num_programs(2) - 1

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, _MASK_MIN, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

    q = q_ref[0, 0].astype(jnp.float32)          # [Q, hd]
    k = k_ref[0, 0, 0].astype(jnp.float32)       # [ps, hd]
    v = v_ref[0, 0, 0].astype(jnp.float32)
    if fp8:
        k = k * ks_ref[0, 0, 0]
        v = v * vs_ref[0, 0, 0]
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * sm_scale
    # causal over flat positions: key at flat p*ps + o is admitted by
    # query i iff it is <= qbase[n] + i (2-D iotas per the TPU rule)
    qi = lax.broadcasted_iota(jnp.int32, s.shape, 0)
    oi = lax.broadcasted_iota(jnp.int32, s.shape, 1)
    valid = (p * page_size + oi) <= (qbase_ref[n] + qi)
    s = jnp.where(valid, s, _MASK_MIN)
    m_prev = m_ref[...]                          # [Q, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    # explicit zero for masked lanes: an all-masked page would
    # otherwise contribute exp(MASK_MIN - MASK_MIN) == 1 per lane
    pexp = jnp.where(valid, jnp.exp(s - m_new), 0.0)
    l_new = alpha * l_ref[...] + jnp.sum(pexp, axis=-1, keepdims=True)
    acc_new = acc_ref[...] * alpha + lax.dot_general(
        pexp, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[...] = m_new
    l_ref[...] = l_new
    acc_ref[...] = acc_new

    @pl.when(p == last)
    def _finish():
        # every query admits flat position 0 (qpos >= 0 always), so
        # l >= exp(0) == 1 at the end of the walk — safe division
        o_ref[0, 0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def _pallas_paged_attention(q, kv, layer, tables, qbase, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, H, Q, hd = q.shape
    P = tables.shape[1]
    ps = kv["k"].shape[3]
    fp8 = "k_scale" in kv
    grid = (N, H, P)

    # scalar-prefetch index maps: the page-table lookup IS the
    # BlockSpec index, so each page block DMAs straight from the pool
    # (index_map args: grid indices, then the prefetched scalar refs)
    q_spec = pl.BlockSpec((1, 1, Q, hd),
                          lambda n, h, p, t, b: (n, h, 0, 0))
    kv_spec = pl.BlockSpec((1, 1, 1, ps, hd),
                           lambda n, h, p, t, b: (layer, t[n, p], h,
                                                  0, 0))
    out_spec = pl.BlockSpec((1, 1, Q, hd),
                            lambda n, h, p, t, b: (n, h, 0, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q, kv["k"], kv["v"]]
    if fp8:
        sc_spec = pl.BlockSpec((1, 1, 1),
                               lambda n, h, p, t, b: (layer, t[n, p],
                                                      h))
        in_specs += [sc_spec, sc_spec]
        args += [kv["k_scale"], kv["v_scale"]]
    kernel = functools.partial(
        _kernel, layer=layer, page_size=ps,
        sm_scale=float(1.0 / np.sqrt(np.float32(hd))), fp8=fp8)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_spec,
            scratch_shapes=[pltpu.VMEM((Q, 1), jnp.float32),
                            pltpu.VMEM((Q, 1), jnp.float32),
                            pltpu.VMEM((Q, hd), jnp.float32)]),
        out_shape=jax.ShapeDtypeStruct((N, H, Q, hd), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), qbase.astype(jnp.int32), *args)


# ------------------------------------------------------------ dispatch
def paged_attention(q, kv, layer, tables, qbase, *, mode=None):
    """Per-layer paged attention over the serving engine's KV tree.

    Parameters
    ----------
    q : ``[N, H, Q, hd]`` queries in the compute dtype.
    kv : the page-pool tree (``kv_pages.PagePool.tree()``): ``"k"`` /
        ``"v"`` pools ``[L, n_pages, H, ps, hd]``, plus ``"k_scale"`` /
        ``"v_scale"`` planes ``[L, n_pages, H]`` when the pool is fp8.
    layer : static layer index (the engine's layer loop is unrolled).
    tables : ``[N, P]`` int32 page tables, rows in position order.
    qbase : ``[N]`` int32; query ``i`` of row ``n`` sits at absolute
        position ``qbase[n] + i``.
    mode : overrides :func:`paged_attention_mode` (tests/benches).

    Returns ``[N, H, Q, hd]`` context in ``q.dtype``.
    """
    mode = mode or paged_attention_mode()
    if mode not in ("xla", "pallas", "interpret"):
        raise ValueError(
            f"unknown paged-attention mode {mode!r} (expected 'pallas',"
            " 'interpret' or 'xla')")
    if mode == "xla":
        return _xla_paged_attention(q, kv, layer, tables, qbase)
    return _pallas_paged_attention(q, kv, layer, tables, qbase,
                                   interpret=(mode == "interpret"))


@register_op("paged_attention")
def _op(q, kv, layer, tables, qbase, mode=None):
    return paged_attention(q, kv, int(layer), tables, qbase, mode=mode)
