"""Fused master-update Pallas kernel: loss-scale unscale + global-norm
clip + Adam bias-corrected update in ONE pass over a contiguous
flattened fp32 master shard.

Reference role: the cuDNN fused-primitives playbook (Chetlur et al.,
arXiv:1410.0759) applied to the OPTIMIZER, as called for by
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (Xu et al., arXiv:2004.13336): once the weight update is
sharded across replicas, each replica touches a contiguous 1/N slice
of the fp32 masters + Adam moments, and the whole update —

    g_eff   = grad * inv_scale * clip_coef        (unscale + clip)
    m'      = b1*m + (1-b1)*g_eff                 (first moment)
    v'      = b2*v + (1-b2)*g_eff^2               (second moment)
    master' = master - alpha * m' / (sqrt(v')+eps)

with ``alpha = lr * sqrt(1-b2^t) / (1-b1^t)`` (the bias-corrected step
size, ``updaters._step_float`` semantics) — is a single elementwise
pass. XLA schedules it as several fusions that round-trip the four
vectors through HBM; this kernel reads grad/m/v/master ONCE and writes
m'/v'/master' ONCE.

Numerics contract: bit-for-float identical to composing
``precision.unscale_grads`` -> global-norm clip ->
``updaters.Adam.apply`` -> ``p - u`` on the same flat f32 vector (the
golden test in tests/test_update_sharding.py checks step 300, where a
half-precision bias-correction power would long since have decayed —
see ``updaters._step_float``).

Dispatch (``fused_update_mode()``):
- ``pallas``    — real TPU backend: the kernel above.
- ``interpret`` — forced via ``DL4J_TPU_FUSED_UPDATE=interpret``: the
  same kernel through the Pallas interpreter (CPU-testable path for
  the kernel + shard_map plumbing).
- ``xla``       — everything else (CPU/GPU, or
  ``DL4J_TPU_FUSED_UPDATE=xla``): the identical jnp formula; XLA fuses
  it well enough off-TPU and the numerics are the same by
  construction.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.learning.updaters import Adam, _step_float
from deeplearning4j_tpu.ops.registry import register_op

_LANE = 128


def fused_update_mode() -> str:
    """'pallas' | 'interpret' | 'xla' — see module docstring."""
    env = os.environ.get("DL4J_TPU_FUSED_UPDATE", "auto").strip().lower()
    if env in ("pallas", "interpret", "xla"):
        return env
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def adam_update_scalars(updater: Adam, step, inv_scale=None,
                        clip_norm=None, grad_norm=None):
    """The two per-step scalars the fused kernel consumes, as one (2,)
    f32 array ``[gscale, alpha]``:

    - ``gscale`` — the combined gradient multiplier: loss-scale
      unscale (``1/scale``) times the global-norm clip coefficient
      ``min(1, clip_norm / ||unscaled_grad||)``. Either factor defaults
      to 1 when its feature is off.
    - ``alpha`` — Adam's bias-corrected step size at ``step``
      (``updaters.Adam.apply`` formula, f32 powers per ``_step_float``).

    ``grad_norm`` is the norm of the (still-scaled) gradient vector;
    required when ``clip_norm`` is set.
    """
    gscale = jnp.asarray(1.0, jnp.float32)
    inv = None
    if inv_scale is not None:
        inv = jnp.asarray(inv_scale, jnp.float32)
        gscale = gscale * inv
    if clip_norm is not None:
        if grad_norm is None:
            raise ValueError("clip_norm requires grad_norm")
        unscaled = jnp.asarray(grad_norm, jnp.float32)
        if inv is not None:
            unscaled = unscaled * inv
        gscale = gscale * jnp.minimum(
            1.0, jnp.asarray(clip_norm, jnp.float32)
            / jnp.maximum(unscaled, 1e-12))
    lr = updater._lr(step)
    tf = _step_float(step + 1)
    bc1 = 1 - jnp.power(jnp.float32(updater.beta1), tf)
    bc2 = 1 - jnp.power(jnp.float32(updater.beta2), tf)
    alpha = jnp.asarray(lr, jnp.float32) * jnp.sqrt(bc2) / bc1
    return jnp.stack([gscale, alpha])


# ------------------------------------------------------------- formula
def _formula(master, m, v, grad, gscale, alpha, beta1, beta2, eps):
    """The reference jnp math (XLA fallback; also the kernel's spec)."""
    g = grad.astype(jnp.float32) * gscale
    m2 = beta1 * m + (1 - beta1) * g
    v2 = beta2 * v + (1 - beta2) * g * g
    upd = alpha * m2 / (jnp.sqrt(v2) + eps)
    return (master - upd.astype(master.dtype)), m2, v2


# -------------------------------------------------------------- kernel
def _k_fused(sc_ref, g_ref, m_ref, v_ref, p_ref, om_ref, ov_ref,
             op_ref, *, beta1, beta2, eps):
    gs = sc_ref[0]
    al = sc_ref[1]
    g = g_ref[...].astype(jnp.float32) * gs
    m = beta1 * m_ref[...] + (1 - beta1) * g
    v = beta2 * v_ref[...] + (1 - beta2) * g * g
    om_ref[...] = m
    ov_ref[...] = v
    op_ref[...] = (p_ref[...]
                   - (al * m / (jnp.sqrt(v) + eps)).astype(op_ref.dtype))


def _pick_block(total, cap):
    b = min(cap, total)
    while total % b:
        b -= 1
    return b


def _pallas_update(master, m, v, grad, scalars, beta1, beta2, eps,
                   interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = master.shape[0]
    rows = n // _LANE
    as2d = lambda a: a.reshape(rows, _LANE)
    # VMEM budget: 7 row-block buffers (4 in + 3 out) double-buffered
    # in f32 — cap each at ~512 KB so the working set stays well under
    # the ~16 MB VMEM even with pipelining
    bm = _pick_block(rows, max(8, (512 * 1024) // (4 * _LANE)))
    grid = (rows // bm,)
    row_spec = pl.BlockSpec((bm, _LANE), lambda i: (i, 0))
    sc_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    # out_shape order matches the kernel's out refs: (m', v', master')
    out_m, out_v, out_p = pl.pallas_call(
        functools.partial(_k_fused, beta1=beta1, beta2=beta2, eps=eps),
        grid=grid,
        in_specs=[sc_spec, row_spec, row_spec, row_spec, row_spec],
        out_specs=[row_spec, row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANE), master.dtype)],
        interpret=interpret,
    )(scalars.astype(jnp.float32), as2d(grad), as2d(m), as2d(v),
      as2d(master))
    return (out_p.reshape(n), out_m.reshape(n), out_v.reshape(n))


# ------------------------------------------------------------ dispatch
def adam_segment_update(master, m, v, grad, scalars, *, beta1, beta2,
                        eps, mode=None):
    """One fused update pass over a contiguous flat segment (the
    per-replica master shard). ``scalars`` is ``[gscale, alpha]`` from
    :func:`adam_update_scalars`. Returns ``(master', m', v')``.

    ``mode`` overrides :func:`fused_update_mode` (tests)."""
    mode = mode or fused_update_mode()
    if mode == "xla":
        return _formula(master, m, v, grad, scalars[0], scalars[1],
                        beta1, beta2, eps)
    n = master.shape[0]
    pad = (-n) % _LANE
    if pad:
        zpad = lambda a: jnp.concatenate(
            [a, jnp.zeros((pad,), a.dtype)])
        master, m, v, grad = map(zpad, (master, m, v, grad))
    out = _pallas_update(master, m, v, grad, scalars, beta1, beta2,
                         eps, interpret=(mode == "interpret"))
    if pad:
        out = tuple(a[:n] for a in out)
    return out


def fused_master_update(master, m, v, grad, step, updater: Adam,
                        inv_scale=None, clip_norm=None, grad_norm=None,
                        mode=None):
    """Convenience entry: scalars + segment update in one call (the
    golden-test surface; the sharded trainer composes the two pieces
    itself so the scalar math runs once per step, not per shard)."""
    if type(updater) is not Adam:
        raise TypeError(
            f"fused_master_update implements the Adam formula; got "
            f"{type(updater).__name__} (use the generic flat-updater "
            "path)")
    if clip_norm is not None and grad_norm is None:
        grad_norm = jnp.sqrt(
            jnp.sum(grad.astype(jnp.float32) ** 2))
    sc = adam_update_scalars(updater, step, inv_scale=inv_scale,
                             clip_norm=clip_norm, grad_norm=grad_norm)
    return adam_segment_update(master, m, v, grad, sc,
                               beta1=updater.beta1, beta2=updater.beta2,
                               eps=updater.epsilon, mode=mode)


@register_op("fused_adam_master_update")
def _op(master, m, v, grad, step, updater, inv_scale=None,
        clip_norm=None, grad_norm=None, mode=None):
    return fused_master_update(master, m, v, grad, step, updater,
                               inv_scale=inv_scale, clip_norm=clip_norm,
                               grad_norm=grad_norm, mode=mode)
