"""Image ops (NHWC throughout — the TPU-native layout).

Reference: libnd4j ``ops/declarable/generic/images/**`` (resize family,
crop_and_resize, non_max_suppression, rgb/hsv/yuv conversions,
extract_image_patches, image flips) — SURVEY.md §2.6. Resizes ride
``jax.image``; NMS is a fixed-trip-count lax.fori_loop (XLA-safe
formulation of the reference's data-dependent loop).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import register_op


def _resize(x, size, method):
    shape = (x.shape[0], int(size[0]), int(size[1]), x.shape[3])
    # antialias=False: the reference semantics for these op names (TF1
    # ResizeBilinear/ResizeBicubic and ONNX Resize antialias=0) do not
    # low-pass filter on downscale; jax.image defaults to True, which
    # diverges badly on any downscaling resize (upscales are identical).
    return jax.image.resize(x, shape, method=method, antialias=False)


@register_op("resize_bilinear")
def resize_bilinear(x, size):
    return _resize(x, size, "bilinear")


@register_op("resize_nearest_neighbor")
def resize_nearest_neighbor(x, size):
    return _resize(x, size, "nearest")


@register_op("resize_bicubic")
def resize_bicubic(x, size):
    return _resize(x, size, "cubic")


@register_op("resize_area")
def resize_area(x, size):
    # area resize == linear resize with antialiasing over boxes;
    # jax.image 'linear' + antialias approximates TF's area kernel
    shape = (x.shape[0], int(size[0]), int(size[1]), x.shape[3])
    return jax.image.resize(x, shape, method="linear", antialias=True)


@register_op("crop_and_resize")
def crop_and_resize(image, boxes, box_indices, crop_size,
                    method="bilinear"):
    """image [N,H,W,C]; boxes [B,4] normalized (y1,x1,y2,x2);
    box_indices [B] -> [B, ch, cw, C] (reference: crop_and_resize.cpp)."""
    h, w = image.shape[1], image.shape[2]
    ch, cw = crop_size

    def one(box, bi):
        y1, x1, y2, x2 = box
        img = image[bi]
        ys = y1 * (h - 1) + jnp.arange(ch) / max(ch - 1, 1) \
            * (y2 - y1) * (h - 1)
        xs = x1 * (w - 1) + jnp.arange(cw) / max(cw - 1, 1) \
            * (x2 - x1) * (w - 1)
        if method == "nearest":
            yi = jnp.clip(jnp.round(ys), 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(jnp.round(xs), 0, w - 1).astype(jnp.int32)
            return img[yi][:, xi]
        y0 = jnp.clip(jnp.floor(ys), 0, h - 1).astype(jnp.int32)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        tl = img[y0][:, x0]
        tr = img[y0][:, x1i]
        bl = img[y1i][:, x0]
        br = img[y1i][:, x1i]
        top = tl * (1 - wx) + tr * wx
        bot = bl * (1 - wx) + br * wx
        return top * (1 - wy) + bot * wy

    return jax.vmap(one)(boxes, box_indices)


@register_op("extract_image_patches")
def extract_image_patches(x, ksizes, strides, rates=(1, 1),
                          padding="VALID"):
    """[N,H,W,C] -> [N,OH,OW,kh*kw*C] (reference:
    extract_image_patches.cpp). Implemented as a depthwise identity
    conv-style gather via conv_general_dilated_patches."""
    kh, kw = ksizes
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), strides, padding,
        rhs_dilation=rates, dimension_numbers=("NHWC", "HWIO", "NHWC"))
    # conv patches emits channel-major [C*kh*kw]; reference order is
    # [kh*kw*C] — transpose the feature blocks
    n, oh, ow, _ = patches.shape
    c = x.shape[3]
    patches = patches.reshape(n, oh, ow, c, kh * kw)
    return patches.transpose(0, 1, 2, 4, 3).reshape(n, oh, ow,
                                                    kh * kw * c)


@register_op("image_flip_left_right")
def flip_left_right(x):
    return jnp.flip(x, axis=2)


@register_op("image_flip_up_down")
def flip_up_down(x):
    return jnp.flip(x, axis=1)


@register_op("adjust_brightness")
def adjust_brightness(x, delta):
    return x + delta


@register_op("adjust_contrast")
def adjust_contrast(x, factor):
    mean = jnp.mean(x, axis=(1, 2), keepdims=True)
    return (x - mean) * factor + mean


@register_op("adjust_saturation")
def adjust_saturation(x, factor):
    h, s, v = _rgb_to_hsv_tuple(x)
    return _hsv_to_rgb_tuple(h, jnp.clip(s * factor, 0.0, 1.0), v)


@register_op("adjust_hue")
def adjust_hue(x, delta):
    h, s, v = _rgb_to_hsv_tuple(x)
    return _hsv_to_rgb_tuple((h + delta) % 1.0, s, v)


def _rgb_to_hsv_tuple(x):
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = jnp.maximum(jnp.maximum(r, g), b)
    mn = jnp.minimum(jnp.minimum(r, g), b)
    d = mx - mn
    safe = jnp.where(d == 0, 1.0, d)
    h = jnp.where(
        mx == r, ((g - b) / safe) % 6.0,
        jnp.where(mx == g, (b - r) / safe + 2.0, (r - g) / safe + 4.0))
    h = jnp.where(d == 0, 0.0, h) / 6.0
    s = jnp.where(mx == 0, 0.0, d / jnp.where(mx == 0, 1.0, mx))
    return h, s, mx


def _hsv_to_rgb_tuple(h, s, v):
    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(jnp.int32) % 6
    r = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [v, q, p, p, t, v])
    g = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [t, v, v, q, p, p])
    b = jnp.select([i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
                   [p, p, t, v, v, q])
    return jnp.stack([r, g, b], axis=-1)


@register_op("rgb_to_hsv")
def rgb_to_hsv(x):
    h, s, v = _rgb_to_hsv_tuple(x)
    return jnp.stack([h, s, v], axis=-1)


@register_op("hsv_to_rgb")
def hsv_to_rgb(x):
    return _hsv_to_rgb_tuple(x[..., 0], x[..., 1], x[..., 2])


@register_op("rgb_to_grayscale")
def rgb_to_grayscale(x):
    w = jnp.asarray([0.2989, 0.5870, 0.1140], x.dtype)
    return jnp.sum(x * w, axis=-1, keepdims=True)


@register_op("rgb_to_yuv")
def rgb_to_yuv(x):
    m = jnp.asarray([[0.299, -0.14714119, 0.61497538],
                     [0.587, -0.28886916, -0.51496512],
                     [0.114, 0.43601035, -0.10001026]], x.dtype)
    return x @ m


@register_op("yuv_to_rgb")
def yuv_to_rgb(x):
    m = jnp.asarray([[1.0, 1.0, 1.0],
                     [0.0, -0.394642334, 2.03206185],
                     [1.13988303, -0.58062185, 0.0]], x.dtype)
    return x @ m


@register_op("non_max_suppression")
def non_max_suppression(boxes, scores, max_output_size,
                        iou_threshold=0.5, score_threshold=float("-inf")):
    """Greedy NMS, fixed output size (XLA-safe). boxes [B,4]
    (y1,x1,y2,x2); returns (selected_indices [K], valid_count).
    Unused slots hold -1 (reference: non_max_suppression.cpp)."""
    b = boxes.shape[0]
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 0) \
        * jnp.maximum(boxes[:, 3] - boxes[:, 1], 0)

    def iou(i, j):
        y1 = jnp.maximum(boxes[i, 0], boxes[j, 0])
        x1 = jnp.maximum(boxes[i, 1], boxes[j, 1])
        y2 = jnp.minimum(boxes[i, 2], boxes[j, 2])
        x2 = jnp.minimum(boxes[i, 3], boxes[j, 3])
        inter = jnp.maximum(y2 - y1, 0) * jnp.maximum(x2 - x1, 0)
        return inter / jnp.maximum(area[i] + area[j] - inter, 1e-9)

    def body(k, state):
        sel, count, live, sc = state
        best = jnp.argmax(jnp.where(live, sc, -jnp.inf))
        ok = jnp.logical_and(live[best], sc[best] > score_threshold)
        sel = sel.at[k].set(jnp.where(ok, best, -1))
        count = count + ok.astype(jnp.int32)
        ious = jax.vmap(lambda j: iou(best, j))(jnp.arange(b))
        live = live & (ious <= iou_threshold)
        live = live.at[best].set(False)
        live = jnp.where(ok, live, jnp.zeros_like(live))
        return sel, count, live, sc

    sel0 = jnp.full((max_output_size,), -1, jnp.int32)
    live0 = jnp.ones((b,), bool)
    sel, count, _, _ = lax.fori_loop(
        0, max_output_size, body, (sel0, jnp.asarray(0, jnp.int32),
                                   live0, scores.astype(jnp.float32)))
    return sel, count


@register_op("central_crop")
def central_crop(img, fraction):
    """Keep the central fraction of H/W (TF semantics: trim
    int((d - d*fraction)/2) from each side, so odd extents keep the
    extra row/col)."""
    h, w = img.shape[-3], img.shape[-2]
    top = int((h - h * fraction) / 2)
    left = int((w - w * fraction) / 2)
    return img[..., top:h - top, left:w - left, :]


@register_op("per_image_standardization")
def per_image_standardization(img):
    axes = tuple(range(img.ndim - 3, img.ndim))
    m = jnp.mean(img, axis=axes, keepdims=True)
    n = 1
    for a in axes:
        n *= img.shape[a]
    s = jnp.std(img, axis=axes, keepdims=True)
    return (img - m) / jnp.maximum(s, 1.0 / jnp.sqrt(float(n)))


@register_op("image_gradients")
def image_gradients(img):
    """(dy, dx) with zero last row/col, NHWC (TF parity)."""
    dy = jnp.pad(img[:, 1:] - img[:, :-1], ((0, 0), (0, 1), (0, 0), (0, 0)))
    dx = jnp.pad(img[:, :, 1:] - img[:, :, :-1],
                 ((0, 0), (0, 0), (0, 1), (0, 0)))
    return dy, dx


@register_op("sobel_edges")
def sobel_edges(img):
    """[N,H,W,C,2] sobel (dy, dx) per channel."""
    ky = jnp.array([[-1, -2, -1], [0, 0, 0], [1, 2, 1]], img.dtype)
    kx = ky.T
    c = img.shape[-1]
    k = jnp.stack([ky, kx], -1)                          # [3,3,2]
    # grouped conv: rhs [3,3,1,C*2], outputs blocked per input channel
    k = jnp.tile(k[:, :, None, :], (1, 1, c, 1)).reshape(3, 3, 1, c * 2)
    # TF reflect-pads the image, then convolves VALID — zero padding
    # would corrupt every border pixel
    padded = jnp.pad(img, ((0, 0), (1, 1), (1, 1), (0, 0)),
                     mode="reflect")
    out = lax.conv_general_dilated(
        padded, k, (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c)
    return out.reshape(img.shape[:-1] + (c, 2))


@register_op("pad_to_bounding_box")
def pad_to_bounding_box(img, offset_h, offset_w, target_h, target_w):
    h, w = img.shape[-3], img.shape[-2]
    return jnp.pad(img, ((0, 0),) * (img.ndim - 3) + (
        (offset_h, target_h - h - offset_h),
        (offset_w, target_w - w - offset_w), (0, 0)))


@register_op("crop_to_bounding_box")
def crop_to_bounding_box(img, offset_h, offset_w, target_h, target_w):
    return img[..., offset_h:offset_h + target_h,
               offset_w:offset_w + target_w, :]


@register_op("adjust_gamma")
def adjust_gamma(img, gamma=1.0, gain=1.0):
    return gain * img ** gamma


@register_op("image_translate")
def image_translate(img, dy, dx):
    """Integer translate with zero fill (host-static offsets)."""
    out = jnp.roll(jnp.roll(img, dy, axis=-3), dx, axis=-2)
    h, w = img.shape[-3], img.shape[-2]
    rows = jnp.arange(h)
    cols = jnp.arange(w)
    rmask = (rows >= dy) & (rows < h + dy) if dy >= 0 else \
        (rows < h + dy)
    cmask = (cols >= dx) & (cols < w + dx) if dx >= 0 else \
        (cols < w + dx)
    m = rmask[:, None] & cmask[None, :]
    return out * m[..., None].astype(img.dtype)
