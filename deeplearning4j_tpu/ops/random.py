"""Random/distribution ops (reference: libnd4j random loops + Philox
RandomGenerator, SURVEY.md §2.39). Pure jax, explicit-key style: under
jit the key is an argument, eager calls go through Nd4j.getRandom().
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import register_op


@register_op("random_uniform")
def random_uniform(rng, shape, minval=0.0, maxval=1.0, dtype=jnp.float32):
    return jax.random.uniform(rng, shape, dtype=dtype, minval=minval, maxval=maxval)


@register_op("random_normal")
def random_normal(rng, shape, mean=0.0, std=1.0, dtype=jnp.float32):
    return mean + std * jax.random.normal(rng, shape, dtype=dtype)


@register_op("random_bernoulli")
def random_bernoulli(rng, shape, p=0.5, dtype=jnp.float32):
    return jax.random.bernoulli(rng, p, shape).astype(dtype)


@register_op("random_exponential")
def random_exponential(rng, shape, lam=1.0, dtype=jnp.float32):
    return jax.random.exponential(rng, shape, dtype=dtype) / lam


@register_op("truncated_normal")
def truncated_normal(rng, shape, mean=0.0, std=1.0, dtype=jnp.float32):
    return mean + std * jax.random.truncated_normal(rng, -2.0, 2.0, shape, dtype=dtype)


@register_op("random_gamma")
def random_gamma(rng, shape, alpha=1.0, dtype=jnp.float32):
    return jax.random.gamma(rng, alpha, shape, dtype=dtype)


@register_op("random_poisson")
def random_poisson(rng, shape, lam=1.0, dtype=jnp.int32):
    return jax.random.poisson(rng, lam, shape, dtype=dtype)


@register_op("dropout_mask")
def dropout_mask(rng, shape, keep_prob, dtype=jnp.float32):
    return jax.random.bernoulli(rng, keep_prob, shape).astype(dtype) / keep_prob


@register_op("random_laplace")
def random_laplace(rng, shape, loc=0.0, scale=1.0, dtype=jnp.float32):
    return loc + scale * jax.random.laplace(rng, shape, dtype=dtype)


@register_op("random_cauchy")
def random_cauchy(rng, shape, loc=0.0, scale=1.0, dtype=jnp.float32):
    return loc + scale * jax.random.cauchy(rng, shape, dtype=dtype)


@register_op("random_gumbel")
def random_gumbel(rng, shape, dtype=jnp.float32):
    return jax.random.gumbel(rng, shape, dtype=dtype)


@register_op("random_beta")
def random_beta(rng, shape, a=1.0, b=1.0, dtype=jnp.float32):
    return jax.random.beta(rng, a, b, shape, dtype=dtype)


@register_op("random_categorical")
def random_categorical(rng, logits, num_samples):
    """[batch, num_samples] class draws (reference: random_multinomial)."""
    return jax.random.categorical(
        rng, logits[:, None, :], axis=-1,
        shape=logits.shape[:1] + (num_samples,))


@register_op("random_shuffle")
def random_shuffle(rng, x, axis=0):
    return jax.random.permutation(rng, x, axis=axis, independent=False)


@register_op("random_rademacher")
def random_rademacher(rng, shape, dtype=jnp.float32):
    return jax.random.rademacher(rng, shape, dtype=dtype)
