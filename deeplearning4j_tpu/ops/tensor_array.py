"""TensorArray as a dense device array (jit-safe, TPU-native).

Reference: nd4j executes TensorArrayV3 read/write/scatter/stack ops as
an interpreter-side list of INDArrays inside AbstractSession frames
(org/nd4j/autodiff/samediff/internal/AbstractSession plus the
tensorarray declarable ops — SURVEY.md §3.4/§2.14). A host-side list
cannot live inside a compiled XLA loop, so here a TensorArray IS a
dense ``(size, *elem_shape)`` array carried as loop state: write =
``dynamic_update_slice``, read = dynamic gather, stack = identity. The
TF "flow" scalar that sequences TA side effects becomes the array value
itself, which makes the data dependence explicit and XLA-schedulable.

TF2 TensorList ops (the v2 TensorArray) share the same representation.
"""

from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import register_op


@register_op("tensorarray_reserve")
def tensorarray_reserve(size=None, elem_shape=(), dtype="float32"):
    """Dense TA of ``size`` elements (TensorArrayV3 / TensorListReserve).

    With an unknown element_shape the array is a 1-D dummy; a full
    ``tensorarray_scatter`` (the unstack path) replaces it wholesale and
    defines the real shape.
    """
    return jnp.zeros((int(size),) + tuple(int(d) for d in elem_shape),
                     jnp.dtype(dtype))


@register_op("tensorarray_write")
def tensorarray_write(flow, index, value):
    """TensorArrayWriteV3 / TensorListSetItem: out[index] = value."""
    index = jnp.asarray(index).astype(jnp.int32).reshape(())
    return flow.at[index].set(value.astype(flow.dtype))


@register_op("tensorarray_scatter")
def tensorarray_scatter(flow, indices, value):
    """TensorArrayScatterV3 (unstack): scatter value rows at indices.

    When ``flow`` already has the element shape, prior writes at
    disjoint indices are preserved (TF semantics). A dummy-reserved TA
    (unknown element_shape, 1-D flow) is rebuilt from ``value``'s
    shape instead; unwritten entries are zero.
    """
    indices = jnp.asarray(indices).astype(jnp.int32)
    if flow.shape[1:] == value.shape[1:]:
        return flow.at[indices].set(value.astype(flow.dtype))
    base = jnp.zeros((flow.shape[0],) + tuple(value.shape[1:]),
                     value.dtype)
    return base.at[indices].set(value)


@register_op("tensorarray_size")
def tensorarray_size(flow):
    """TensorArraySizeV3 / TensorListLength — static under jit."""
    return jnp.asarray(flow.shape[0], jnp.int32)
