"""Elementwise transform ops (reference: org/nd4j/linalg/ops/transforms/
Transforms.java and libnd4j legacy transform loops, SURVEY.md §2.7).

All functions are pure jax (VPU-mapped elementwise under XLA) and are
registered in the op registry by their reference names. The `Transforms`
class mirrors the reference's static API over NDArray for eager use.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ndarray.ndarray import NDArray, _unwrap
from deeplearning4j_tpu.ops.registry import register_op


# -- raw jax ops (jit-friendly) ----------------------------------------
@register_op("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register_op("tanh")
def tanh(x):
    return jnp.tanh(x)


@register_op("relu")
def relu(x):
    return jax.nn.relu(x)


@register_op("relu6")
def relu6(x):
    return jax.nn.relu6(x)


@register_op("leakyrelu")
def leaky_relu(x, alpha=0.01):
    return jax.nn.leaky_relu(x, alpha)


@register_op("elu")
def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


@register_op("selu")
def selu(x):
    return jax.nn.selu(x)


@register_op("gelu")
def gelu(x):
    return jax.nn.gelu(x)


@register_op("softplus")
def softplus(x):
    return jax.nn.softplus(x)


@register_op("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@register_op("swish")
def swish(x):
    return jax.nn.swish(x)


@register_op("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@register_op("hardsigmoid")
def hard_sigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


@register_op("hardtanh")
def hard_tanh(x):
    return jnp.clip(x, -1.0, 1.0)


@register_op("softmax")
def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


@register_op("log_softmax")
def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


@register_op("exp")
def exp(x):
    return jnp.exp(x)


@register_op("log")
def log(x):
    return jnp.log(x)


@register_op("log1p")
def log1p(x):
    return jnp.log1p(x)


@register_op("sqrt")
def sqrt(x):
    return jnp.sqrt(x)


@register_op("rsqrt")
def rsqrt(x):
    return jax.lax.rsqrt(x)


@register_op("abs")
def abs_(x):
    return jnp.abs(x)


@register_op("sign")
def sign(x):
    return jnp.sign(x)


@register_op("floor")
def floor(x):
    return jnp.floor(x)


@register_op("ceil")
def ceil(x):
    return jnp.ceil(x)


@register_op("round")
def round_(x):
    return jnp.round(x)


@register_op("pow")
def pow_(x, p):
    return jnp.power(x, p)


@register_op("square")
def square(x):
    return jnp.square(x)


@register_op("cube")
def cube(x):
    return x * x * x


@register_op("reciprocal")
def reciprocal(x):
    return 1.0 / x


@register_op("sin")
def sin(x):
    return jnp.sin(x)


@register_op("cos")
def cos(x):
    return jnp.cos(x)


@register_op("tan")
def tan(x):
    return jnp.tan(x)


@register_op("asin")
def asin(x):
    return jnp.arcsin(x)


@register_op("acos")
def acos(x):
    return jnp.arccos(x)


@register_op("atan")
def atan(x):
    return jnp.arctan(x)


@register_op("atan2")
def atan2(y, x):
    return jnp.arctan2(y, x)


@register_op("sinh")
def sinh(x):
    return jnp.sinh(x)


@register_op("cosh")
def cosh(x):
    return jnp.cosh(x)


@register_op("erf")
def erf(x):
    return jax.scipy.special.erf(x)


@register_op("clip_by_value")
def clip_by_value(x, lo, hi):
    return jnp.clip(x, lo, hi)


@register_op("clip_by_norm")
def clip_by_norm(x, clip_norm, axis=None):
    n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=axis is not None))
    scale = jnp.where(n > clip_norm, clip_norm / jnp.maximum(n, 1e-12), 1.0)
    return x * scale


@register_op("max_pairwise")
def max_pairwise(x, y):
    return jnp.maximum(x, y)


@register_op("min_pairwise")
def min_pairwise(x, y):
    return jnp.minimum(x, y)


@register_op("isnan")
def isnan(x):
    return jnp.isnan(x)


@register_op("isinf")
def isinf(x):
    return jnp.isinf(x)


@register_op("step")
def step(x):
    return (x > 0).astype(x.dtype)


@register_op("rationaltanh")
def rational_tanh(x):
    # reference: RationalTanh op — tanh approximation f(x)=1.7159*tanh(2x/3)
    return 1.7159 * jnp.tanh(2.0 * x / 3.0)


@register_op("recttanh")
def rectified_tanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


@register_op("thresholdedrelu")
def thresholded_relu(x, theta=1.0):
    return jnp.where(x > theta, x, 0.0)


@register_op("prelu")
def prelu(x, alpha):
    return jnp.where(x >= 0, x, alpha * x)


@register_op("standardize")
def standardize(x, axis=-1, eps=1e-5):
    m = jnp.mean(x, axis=axis, keepdims=True)
    v = jnp.var(x, axis=axis, keepdims=True)
    return (x - m) / jnp.sqrt(v + eps)


class Transforms:
    """Eager NDArray front-end mirroring Transforms.java's static API."""

    @staticmethod
    def _apply(fn, x, *args, **kwargs):
        out = fn(_unwrap(x), *args, **kwargs)
        return NDArray(out) if isinstance(x, NDArray) else out

    sigmoid = staticmethod(lambda x: Transforms._apply(sigmoid, x))
    tanh = staticmethod(lambda x: Transforms._apply(tanh, x))
    relu = staticmethod(lambda x: Transforms._apply(relu, x))
    leakyRelu = staticmethod(lambda x, a=0.01: Transforms._apply(leaky_relu, x, a))
    elu = staticmethod(lambda x: Transforms._apply(elu, x))
    softmax = staticmethod(lambda x: Transforms._apply(softmax, x))
    exp = staticmethod(lambda x: Transforms._apply(exp, x))
    log = staticmethod(lambda x: Transforms._apply(log, x))
    sqrt = staticmethod(lambda x: Transforms._apply(sqrt, x))
    abs = staticmethod(lambda x: Transforms._apply(abs_, x))
    sign = staticmethod(lambda x: Transforms._apply(sign, x))
    floor = staticmethod(lambda x: Transforms._apply(floor, x))
    ceil = staticmethod(lambda x: Transforms._apply(ceil, x))
    round = staticmethod(lambda x: Transforms._apply(round_, x))
    pow = staticmethod(lambda x, p: Transforms._apply(pow_, x, p))
    sin = staticmethod(lambda x: Transforms._apply(sin, x))
    cos = staticmethod(lambda x: Transforms._apply(cos, x))
    unitVec = staticmethod(lambda x: x.div(x.norm2()))
    max = staticmethod(lambda x, y: Transforms._apply(max_pairwise, x, _unwrap(y)))
    min = staticmethod(lambda x, y: Transforms._apply(min_pairwise, x, _unwrap(y)))

    @staticmethod
    def euclideanDistance(a, b) -> float:
        d = _unwrap(a) - _unwrap(b)
        return float(jnp.sqrt(jnp.sum(d * d)))

    @staticmethod
    def manhattanDistance(a, b) -> float:
        return float(jnp.sum(jnp.abs(_unwrap(a) - _unwrap(b))))

    @staticmethod
    def cosineSim(a, b) -> float:
        av, bv = _unwrap(a).ravel(), _unwrap(b).ravel()
        denom = jnp.linalg.norm(av) * jnp.linalg.norm(bv)
        return float(jnp.vdot(av, bv) / jnp.maximum(denom, 1e-12))
