"""Bitwise / boolean / comparison ops.

Reference: libnd4j ``ops/declarable/generic/bitwise/**`` (and, or, xor,
shifts, cyclic shifts, toggle_bits) and the pairwise/boolean legacy
loops (SURVEY.md §2.6, §2.7).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import register_op


@register_op("bitwise_and")
def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


@register_op("bitwise_or")
def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


@register_op("bitwise_xor")
def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


@register_op("bitwise_not")
def bitwise_not(x):
    return jnp.bitwise_not(x)


@register_op("shift_left")
def shift_left(x, n):
    return jnp.left_shift(x, n)


@register_op("shift_right")
def shift_right(x, n):
    return jnp.right_shift(x, n)


_UNSIGNED = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}


def _rotate(x, n, left):
    # rotate on the UNSIGNED view: arithmetic right-shift on signed ints
    # sign-extends with 1s and corrupts any rotation of a negative value;
    # also guard n % bits == 0 (shift by full width is undefined)
    x = jnp.asarray(x)
    bits = x.dtype.itemsize * 8
    n = n % bits
    if n == 0:
        return x
    u = x.view(_UNSIGNED[x.dtype.itemsize])
    if left:
        r = jnp.bitwise_or(jnp.left_shift(u, n),
                           jnp.right_shift(u, bits - n))
    else:
        r = jnp.bitwise_or(jnp.right_shift(u, n),
                           jnp.left_shift(u, bits - n))
    return r.view(x.dtype)


@register_op("cyclic_shift_left")
def cyclic_shift_left(x, n):
    return _rotate(x, n, left=True)


@register_op("cyclic_shift_right")
def cyclic_shift_right(x, n):
    return _rotate(x, n, left=False)


@register_op("toggle_bits")
def toggle_bits(x):
    return jnp.bitwise_not(x)


@register_op("bits_hamming_distance")
def bits_hamming_distance(x, y):
    return jnp.sum(lax.population_count(jnp.bitwise_xor(x, y)))


@register_op("bitcast")
def bitcast(x, dtype):
    return lax.bitcast_convert_type(x, dtype)


# -- comparisons -------------------------------------------------------
@register_op("equals")
def equals(x, y):
    return x == y


@register_op("not_equals")
def not_equals(x, y):
    return x != y


@register_op("greater")
def greater(x, y):
    return x > y


@register_op("greater_equal")
def greater_equal(x, y):
    return x >= y


@register_op("less")
def less(x, y):
    return x < y


@register_op("less_equal")
def less_equal(x, y):
    return x <= y


@register_op("is_close")
def is_close(x, y, rtol=1e-5, atol=1e-8):
    return jnp.isclose(x, y, rtol=rtol, atol=atol)


@register_op("floormod")
def floormod(x, y):
    return jnp.mod(x, y)


@register_op("truncatediv")
def truncatediv(x, y):
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if jnp.issubdtype(jnp.result_type(x, y), jnp.integer):
        # stay in integers: the float round-trip loses precision past
        # the f32 mantissa (e.g. 16777217 // 1 != itself via float)
        q = jnp.abs(x) // jnp.abs(y)
        return (jnp.sign(x) * jnp.sign(y)).astype(q.dtype) * q
    return jnp.trunc(x / y).astype(jnp.result_type(x, y))


@register_op("divide_no_nan")
def divide_no_nan(x, y):
    # divide by a SAFE denominator before masking: where(y==0, 0, x/y)
    # alone still differentiates the x/0 branch and NaNs the gradient
    safe = jnp.where(y == 0, jnp.ones((), jnp.result_type(y)), y)
    return jnp.where(y == 0, jnp.zeros((), jnp.result_type(x, y)),
                     x / safe)
