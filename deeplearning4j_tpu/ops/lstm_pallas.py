"""Pallas persistent-weights LSTM recurrence (the CudnnLSTMHelper
experiment, SURVEY.md §2.9 — VERDICT r2 weak #4 asked for one honest
attempt at the small-cell fast path; VERDICT r3 item #6 asked for a
backward so the H>=512 win applies to TRAINING, which is the config
class CudnnLSTMHelper actually serves).

Design: the input projection is hoisted (ops/nn.py lstm_layer already
does one [N*T, in] x [in, 4H] MXU matmul); this kernel runs the
RECURRENT part with w_hh and the (h, c) carry resident in VMEM across
the whole sequence — grid over T/k chunks with sequential semantics,
k timesteps advanced per grid step to amortize the grid/DMA boundary.

Differentiation: ``pallas_lstm_recurrence`` carries a ``jax.custom_vjp``.
The un-differentiated call runs the lean kernel (no cell-state stream);
under ``jax.grad`` the forward runs a variant that additionally writes
the per-step cell states ``cs`` to HBM, and the backward is a
reverse-time ``lax.scan`` that RECOMPUTES the gate pre-activations from
(h_{t-1}, x_proj_t) — one extra [N,H]x[H,4H] matmul per step instead of
storing 4 gate planes, the standard memory/FLOP trade for RNN VJPs
(same choice the reference's cudnnRNNBackwardWeights path makes with
its reserve-space, except we trade the reserve space away entirely).

Measured A/B on the v5e chip (2026-07-31, interleaved min-of-6 windows
— see BASELINE.md "Pallas LSTM recurrence A/B"): ~par at the zoo
default (N=256, H=256: 1.07x min, par median), ~1.3x at H=512 forward.
XLA already compiles lax.scan into a tight on-chip loop, so the cuDNN-
style win (eliminating per-step kernel dispatch) has nothing to
eliminate on TPU. The scan path therefore REMAINS THE DEFAULT; the
kernel is the documented experiment and an opt-in
(``lstm_layer(..., impl="pallas")``) for larger hidden sizes — now for
training as well as inference (training A/B in BASELINE.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _kernel(xp_ref, whh_ref, h0_ref, c0_ref, *refs, k_steps,
            collect_cell):
    """Advance k_steps timesteps per grid step with (h, c) resident in
    VMEM scratch. With collect_cell the per-step cell states are
    streamed out as an extra output for the VJP's recompute pass; the
    lean variant omits that HBM traffic entirely."""
    from jax.experimental import pallas as pl

    if collect_cell:
        ys_ref, cs_ref, ht_ref, ct_ref, h_scr, c_scr = refs
    else:
        ys_ref, ht_ref, ct_ref, h_scr, c_scr = refs
        cs_ref = None

    t = pl.program_id(0)
    nt = pl.num_programs(0)
    hidden = whh_ref.shape[0]

    @pl.when(t == 0)
    def _():
        h_scr[...] = h0_ref[...].astype(jnp.float32)
        c_scr[...] = c0_ref[...].astype(jnp.float32)

    def body(j, _):
        h = h_scr[...]
        gates = jnp.dot(h.astype(whh_ref.dtype), whh_ref[...],
                        preferred_element_type=jnp.float32)
        gates = gates + xp_ref[j].astype(jnp.float32)
        i = jax.nn.sigmoid(gates[:, :hidden])
        f = jax.nn.sigmoid(gates[:, hidden:2 * hidden])
        g = jnp.tanh(gates[:, 2 * hidden:3 * hidden])
        o = jax.nn.sigmoid(gates[:, 3 * hidden:])
        c = f * c_scr[...] + i * g
        h2 = o * jnp.tanh(c)
        h_scr[...] = h2
        c_scr[...] = c
        ys_ref[j] = h2.astype(ys_ref.dtype)
        if cs_ref is not None:
            cs_ref[j] = c.astype(cs_ref.dtype)
        return 0

    lax.fori_loop(0, k_steps, body, 0)

    @pl.when(t == nt - 1)
    def _():
        ht_ref[...] = h_scr[...].astype(ht_ref.dtype)
        ct_ref[...] = c_scr[...].astype(ct_ref.dtype)


def _pick_k(t: int, n: int, fourh: int, itemsize: int) -> int:
    """Largest divisor of T whose double-buffered x_proj block fits a
    conservative VMEM budget (~6MB for the streamed input)."""
    budget = 6 * 1024 * 1024
    best = 1
    for k in range(1, min(t, 16) + 1):
        if t % k == 0 and 2 * k * n * fourh * itemsize <= budget:
            best = k
    return best


def _run(x_proj, w_hh, h0, c0, k_steps, interpret, collect_cell):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        # Auto: Mosaic only targets TPU; interpret everywhere else so
        # the same call sites run on the CPU test mesh.
        interpret = jax.default_backend() != "tpu"
    t, n, fourh = x_proj.shape
    hidden = fourh // 4
    if k_steps is None:
        k_steps = _pick_k(t, n, fourh, x_proj.dtype.itemsize)
    if t % k_steps:
        raise ValueError(f"T={t} not divisible by k_steps={k_steps}")

    seq_specs = [pl.BlockSpec((k_steps, n, hidden), lambda i: (i, 0, 0))]
    seq_shapes = [jax.ShapeDtypeStruct((t, n, hidden), x_proj.dtype)]
    if collect_cell:
        seq_specs = seq_specs * 2
        seq_shapes = seq_shapes * 2
    return pl.pallas_call(
        functools.partial(_kernel, k_steps=k_steps,
                          collect_cell=collect_cell),
        grid=(t // k_steps,),
        in_specs=[
            pl.BlockSpec((k_steps, n, fourh), lambda i: (i, 0, 0)),
            pl.BlockSpec((hidden, fourh), lambda i: (0, 0)),
            pl.BlockSpec((n, hidden), lambda i: (0, 0)),
            pl.BlockSpec((n, hidden), lambda i: (0, 0)),
        ],
        out_specs=seq_specs + [
            pl.BlockSpec((n, hidden), lambda i: (0, 0)),
            pl.BlockSpec((n, hidden), lambda i: (0, 0)),
        ],
        out_shape=seq_shapes + [
            jax.ShapeDtypeStruct((n, hidden), x_proj.dtype),
            jax.ShapeDtypeStruct((n, hidden), x_proj.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((n, hidden), jnp.float32),
            pltpu.VMEM((n, hidden), jnp.float32),
        ],
        # name drift across pallas versions: TPUCompilerParams (older)
        # was renamed CompilerParams (newer)
        compiler_params=getattr(pltpu, "CompilerParams",
                                getattr(pltpu, "TPUCompilerParams", None))(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x_proj, w_hh, h0, c0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _recurrence(k_steps, interpret, x_proj, w_hh, h0, c0):
    ys, hT, cT = _run(x_proj, w_hh, h0, c0, k_steps, interpret,
                      collect_cell=False)
    return ys, hT, cT


def _recurrence_fwd(k_steps, interpret, x_proj, w_hh, h0, c0):
    ys, cs, hT, cT = _run(x_proj, w_hh, h0, c0, k_steps, interpret,
                          collect_cell=True)
    return (ys, hT, cT), (x_proj, w_hh, h0, c0, ys, cs)


def _recurrence_bwd(k_steps, interpret, res, cots):
    """Reverse-time scan, recomputing gates from (h_{t-1}, xp_t).

    Gate order i, f, g, o (identical to the forward and ops/nn.py).
    All accumulation in float32 regardless of the stored dtype; grads
    are cast back to the primal dtypes at the end.
    """
    x_proj, w_hh, h0, c0, ys, cs = res
    dys, dhT, dcT = cots
    hidden = w_hh.shape[0]
    whh32 = w_hh.astype(jnp.float32)

    # States ENTERING each step t: h_{t-1}, c_{t-1}.
    h_prev = jnp.concatenate([h0[None], ys[:-1]], axis=0)
    c_prev = jnp.concatenate([c0[None], cs[:-1]], axis=0)

    def step(carry, inp):
        dh, dc, dw = carry
        xp_t, hp_t, cp_t, c_t, dy_t = inp
        dh = dh + dy_t.astype(jnp.float32)
        hp32 = hp_t.astype(jnp.float32)
        gates = jnp.dot(hp_t.astype(w_hh.dtype), w_hh,
                        preferred_element_type=jnp.float32)
        gates = gates + xp_t.astype(jnp.float32)
        i = jax.nn.sigmoid(gates[:, :hidden])
        f = jax.nn.sigmoid(gates[:, hidden:2 * hidden])
        g = jnp.tanh(gates[:, 2 * hidden:3 * hidden])
        o = jax.nn.sigmoid(gates[:, 3 * hidden:])
        tanh_c = jnp.tanh(c_t.astype(jnp.float32))
        do = dh * tanh_c
        dc = dc + dh * o * (1.0 - tanh_c * tanh_c)
        di = dc * g
        df = dc * cp_t.astype(jnp.float32)
        dg = dc * i
        da = jnp.concatenate([
            di * i * (1.0 - i),
            df * f * (1.0 - f),
            dg * (1.0 - g * g),
            do * o * (1.0 - o),
        ], axis=-1)
        dw = dw + hp32.T @ da
        dh_next = da @ whh32.T
        dc_next = dc * f
        return (dh_next, dc_next, dw), da

    init = (dhT.astype(jnp.float32), dcT.astype(jnp.float32),
            jnp.zeros(w_hh.shape, jnp.float32))
    (dh0, dc0, dw_hh), das = lax.scan(
        step, init, (x_proj, h_prev, c_prev, cs, dys), reverse=True)
    return (das.astype(x_proj.dtype), dw_hh.astype(w_hh.dtype),
            dh0.astype(h0.dtype), dc0.astype(c0.dtype))


_recurrence.defvjp(_recurrence_fwd, _recurrence_bwd)


def pallas_lstm_recurrence(x_proj, w_hh, h0, c0, k_steps=None,
                           interpret: bool | None = None):
    """x_proj: [T, N, 4H] (input projection + bias, precomputed);
    w_hh: [H, 4H]; h0/c0: [N, H]. Returns (ys [T, N, H], hT, cT).
    Gate order i, f, g, o — identical to ops/nn.py lstm_layer.

    Differentiable: under ``jax.grad`` the forward streams per-step cell
    states and the backward recomputes gates in a reverse scan (module
    docstring has the design rationale and the measured training A/B).
    """
    t, n, fourh = x_proj.shape
    if k_steps is None:
        k_steps = _pick_k(t, n, fourh, x_proj.dtype.itemsize)
    return _recurrence(k_steps, interpret, x_proj, w_hh, h0, c0)
