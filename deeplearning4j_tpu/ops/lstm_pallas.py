"""Pallas persistent-weights LSTM recurrence (the CudnnLSTMHelper
experiment, SURVEY.md §2.9 — VERDICT r2 weak #4 asked for one honest
attempt at the small-cell fast path).

Design: the input projection is hoisted (ops/nn.py lstm_layer already
does one [N*T, in] x [in, 4H] MXU matmul); this kernel runs the
RECURRENT part with w_hh and the (h, c) carry resident in VMEM across
the whole sequence — grid over T/k chunks with sequential semantics,
k timesteps advanced per grid step to amortize the grid/DMA boundary.

Measured A/B on the v5e chip (2026-07-31, interleaved min-of-6 windows
— see BASELINE.md "Pallas LSTM recurrence A/B"): ~par at the zoo
default (N=256, H=256: 1.07x min, par median), ~1.3x at H=512. XLA
already compiles lax.scan into a tight on-chip loop, so the cuDNN-
style win (eliminating per-step kernel dispatch) has nothing to
eliminate on TPU. The scan path therefore REMAINS THE DEFAULT; this
kernel is the documented experiment and an opt-in
(``lstm_layer(..., impl="pallas")``) for inference at larger hidden
sizes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _kernel(xp_ref, whh_ref, h0_ref, c0_ref, ys_ref, ht_ref, ct_ref,
            h_scr, c_scr, *, k_steps):
    from jax.experimental import pallas as pl

    t = pl.program_id(0)
    nt = pl.num_programs(0)
    hidden = whh_ref.shape[0]

    @pl.when(t == 0)
    def _():
        h_scr[...] = h0_ref[...].astype(jnp.float32)
        c_scr[...] = c0_ref[...].astype(jnp.float32)

    def body(j, _):
        h = h_scr[...]
        gates = jnp.dot(h.astype(whh_ref.dtype), whh_ref[...],
                        preferred_element_type=jnp.float32)
        gates = gates + xp_ref[j].astype(jnp.float32)
        i = jax.nn.sigmoid(gates[:, :hidden])
        f = jax.nn.sigmoid(gates[:, hidden:2 * hidden])
        g = jnp.tanh(gates[:, 2 * hidden:3 * hidden])
        o = jax.nn.sigmoid(gates[:, 3 * hidden:])
        c = f * c_scr[...] + i * g
        h2 = o * jnp.tanh(c)
        h_scr[...] = h2
        c_scr[...] = c
        ys_ref[j] = h2.astype(ys_ref.dtype)
        return 0

    lax.fori_loop(0, k_steps, body, 0)

    @pl.when(t == nt - 1)
    def _():
        ht_ref[...] = h_scr[...].astype(ht_ref.dtype)
        ct_ref[...] = c_scr[...].astype(ct_ref.dtype)


def _pick_k(t: int, n: int, fourh: int, itemsize: int) -> int:
    """Largest divisor of T whose double-buffered x_proj block fits a
    conservative VMEM budget (~6MB for the streamed input)."""
    budget = 6 * 1024 * 1024
    best = 1
    for k in range(1, min(t, 16) + 1):
        if t % k == 0 and 2 * k * n * fourh * itemsize <= budget:
            best = k
    return best


def pallas_lstm_recurrence(x_proj, w_hh, h0, c0, k_steps=None,
                           interpret: bool = False):
    """x_proj: [T, N, 4H] (input projection + bias, precomputed);
    w_hh: [H, 4H]; h0/c0: [N, H]. Returns (ys [T, N, H], hT, cT).
    Gate order i, f, g, o — identical to ops/nn.py lstm_layer."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t, n, fourh = x_proj.shape
    hidden = fourh // 4
    if k_steps is None:
        k_steps = _pick_k(t, n, fourh, x_proj.dtype.itemsize)
    if t % k_steps:
        raise ValueError(f"T={t} not divisible by k_steps={k_steps}")
    return pl.pallas_call(
        functools.partial(_kernel, k_steps=k_steps),
        grid=(t // k_steps,),
        in_specs=[
            pl.BlockSpec((k_steps, n, fourh), lambda i: (i, 0, 0)),
            pl.BlockSpec((hidden, fourh), lambda i: (0, 0)),
            pl.BlockSpec((n, hidden), lambda i: (0, 0)),
            pl.BlockSpec((n, hidden), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k_steps, n, hidden), lambda i: (i, 0, 0)),
            pl.BlockSpec((n, hidden), lambda i: (0, 0)),
            pl.BlockSpec((n, hidden), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, n, hidden), x_proj.dtype),
            jax.ShapeDtypeStruct((n, hidden), x_proj.dtype),
            jax.ShapeDtypeStruct((n, hidden), x_proj.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((n, hidden), jnp.float32),
            pltpu.VMEM((n, hidden), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x_proj, w_hh, h0, c0)
