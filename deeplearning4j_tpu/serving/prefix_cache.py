"""Cross-request KV reuse: a page-granularity prefix cache.

Real serving traffic is dominated by SHARED PREFIXES — one system
prompt in front of millions of user turns, few-shot preambles,
multi-turn conversations replaying their own history — yet a plain
engine re-prefills every one of those tokens from position 0. The
prefill work is exactly the memory-bound K/V materialization the paged
cache exists to avoid repeating, so this module indexes the pool's
COMMITTED pages by the tokens that produced them:

- **Key = chained page hash.** A full page (``page_size`` token ids)
  hashes together with its PARENT page's digest
  (``blake2b(parent_digest || token_bytes)``), so a digest names an
  entire prefix ``[0, (depth+1)*page_size)`` — equal digests mean
  equal full prefixes, and the digest chain IS a radix tree over
  prompts with page-sized edges. Nodes store their token ids, so a
  hash collision is detected (token compare) rather than served.
- **Sharing is refcounted, never copied.** A lookup hit maps the
  cached pages straight into the new slot's page table and bumps their
  refcount (``PagePool.share``) — N slots attend through the SAME
  device pages. Cached-only pages sit at refcount 1 (the cache's own
  reference).
- **Copy-on-write on mid-page divergence.** When a prompt agrees with
  a cached child page for ``m`` of its ``page_size`` tokens and then
  diverges, the hit still covers those ``m`` positions: the engine
  copies THAT ONE page (``kv_pages.copy_page``) into a private page,
  maps the copy, and prefills only from the divergence point. Causal
  attention makes the first ``m`` positions' K/V depend only on the
  agreed tokens, so the copied prefix is exact.
- **LRU leaf eviction, readers protected.** Under page pressure the
  admission path evicts least-recently-used LEAF nodes whose page has
  no reader besides the cache (refcount 1). A page mapped into a live
  slot (refcount > 1) is never reclaimed; evicting leaves first keeps
  every remaining node's chain intact.

The cache is HOST-side bookkeeping only: it never touches device
memory itself (the engine dispatches the copy/prefill programs) and is
guarded by one lock — the scheduler thread mutates it, while
``submit()`` reads a hit hint for the page-budget check.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from deeplearning4j_tpu.profiler import flight_recorder as _flight
from deeplearning4j_tpu.profiler import telemetry as _telemetry

#: parent digest of depth-0 pages (the radix root)
_ROOT = b"root"


def page_digest(parent: bytes, tokens: np.ndarray) -> bytes:
    """Chained rolling hash of one full page: digest of the parent
    prefix folded with this page's token ids. Equal digests name equal
    full prefixes (verified by token compare on hit)."""
    h = hashlib.blake2b(parent, digest_size=16)
    h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
    return h.digest()


class _Node:
    __slots__ = ("digest", "parent", "depth", "page", "tokens", "tick")

    def __init__(self, digest: bytes, parent: bytes, depth: int,
                 page: int, tokens: np.ndarray, tick: int):
        self.digest = digest
        self.parent = parent
        self.depth = depth          # page index within the prefix
        self.page = page
        self.tokens = tokens        # the page_size token ids (host copy)
        self.tick = tick            # LRU clock


class PrefixHit:
    """One lookup result. ``pages`` are the matched FULL pages in
    position order; ``cow_src``/``cow_tokens`` describe a mid-page
    partial match (copy that page, keep its first ``cow_tokens``
    positions). ``tokens`` is the total reusable position count. All
    referenced pages have been ref'd for the caller (``release`` undoes
    that if admission fails)."""

    def __init__(self, pages: List[int], cow_src: Optional[int],
                 cow_tokens: int, page_size: int):
        self.pages = pages
        self.cow_src = cow_src
        self.cow_tokens = int(cow_tokens)
        self.tokens = len(pages) * page_size + self.cow_tokens

    def release(self, pool) -> None:
        held = list(self.pages)
        if self.cow_src is not None:
            held.append(self.cow_src)
        if held:
            pool.free(held)


class PrefixCache:
    """Radix/hash index over committed prompt pages (module doc)."""

    def __init__(self, page_size: int, engine_id: str = "solo"):
        self.page_size = int(page_size)
        self.engine_id = str(engine_id)
        self._nodes: Dict[bytes, _Node] = {}
        #: parent digest -> child digests (radix fan-out)
        self._children: Dict[bytes, Set[bytes]] = {}
        self._lock = threading.Lock()
        self._tick = itertools.count()
        # local counters (telemetry mirrors them when enabled)
        self.hits = 0
        self.misses = 0
        self.hit_tokens_total = 0
        self.inserted_pages = 0
        self.evicted_pages = 0

    # ------------------------------------------------------------ stats
    @property
    def cached_pages(self) -> int:
        return len(self._nodes)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "cached_pages": len(self._nodes),
                "hits": self.hits,
                "misses": self.misses,
                "hit_tokens_total": self.hit_tokens_total,
                "inserted_pages": self.inserted_pages,
                "evicted_pages": self.evicted_pages,
            }

    def _gauge(self) -> None:
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().gauge(
                _telemetry.SERVING_PREFIX_CACHED_PAGES,
                "KV pages currently indexed by the prefix cache").set(
                len(self._nodes), engine=self.engine_id)

    # ----------------------------------------------------------- lookup
    def _walk(self, prompt: np.ndarray) -> Tuple[List[_Node], bytes]:
        """Longest chain of cached FULL pages matching ``prompt``.
        Returns (matched nodes in order, digest of the matched prefix).
        Caller holds the lock."""
        ps = self.page_size
        matched: List[_Node] = []
        parent = _ROOT
        for d in range(int(prompt.size) // ps):
            seg = prompt[d * ps:(d + 1) * ps]
            dig = page_digest(parent, seg)
            node = self._nodes.get(dig)
            if node is None or not np.array_equal(node.tokens, seg):
                break               # miss, or a detected hash collision
            matched.append(node)
            parent = dig
        return matched, parent

    def hit_tokens_hint(self, prompt: np.ndarray) -> int:
        """Read-only count of currently-reusable FULL-page tokens —
        the submit()-time page-budget hint. Takes no references and
        moves no LRU clocks; admission re-resolves."""
        prompt = np.asarray(prompt, np.int32)
        with self._lock:
            matched, _ = self._walk(prompt)
        return len(matched) * self.page_size

    def lookup_acquire(self, prompt: np.ndarray, pool) -> PrefixHit:
        """Resolve the longest cached prefix of ``prompt`` and take one
        reference per matched page (full pages AND the mid-page
        copy-on-write source, if any) so eviction cannot reclaim them
        before the engine maps/copies them. The hit is capped at
        ``len(prompt) - 1`` tokens — the engine must still compute the
        last prompt position's logits to sample the first token."""
        prompt = np.asarray(prompt, np.int32)
        ps = self.page_size
        t0 = int(prompt.size)
        with self._lock:
            matched, parent = self._walk(prompt)
            # cap: leave >= 1 prompt token for the suffix prefill
            max_full = (t0 - 1) // ps
            matched = matched[:max_full]
            parent = matched[-1].digest if matched else _ROOT
            full_tokens = len(matched) * ps
            # mid-page divergence: does any child of the matched prefix
            # agree with the NEXT prompt tokens for m >= 1 positions?
            cow_node, cow_m = None, 0
            rest = prompt[full_tokens:min(t0 - 1, full_tokens + ps)]
            if rest.size:
                for child_dig in self._children.get(parent, ()):
                    child = self._nodes.get(child_dig)
                    if child is None:
                        continue
                    m = int(np.argmin(np.concatenate(
                        [np.equal(child.tokens[:rest.size], rest),
                         [False]])))
                    if m > cow_m:
                        cow_node, cow_m = child, m
            tick = next(self._tick)
            for n in matched:
                n.tick = tick
            if cow_node is not None:
                cow_node.tick = tick
            pages = [n.page for n in matched]
            held = pages + ([cow_node.page] if cow_node else [])
            if held:
                pool.share(held)
            hit = PrefixHit(pages,
                            cow_node.page if cow_node else None,
                            cow_m, ps)
        return hit

    def record_session(self, hit_tokens: int) -> None:
        """Count a sticky-session resume in the cache's own hit
        totals, so ``prefix_stats()`` and the telemetry counters tell
        one story (both describe ALL cross-request reuse)."""
        with self._lock:
            self.hits += 1
            self.hit_tokens_total += int(hit_tokens)

    def record(self, hit: PrefixHit,
               kind: Optional[str] = None) -> None:
        """Count one ADMITTED lookup (the engine calls this once per
        admission, not per head-of-line retry, so the hit/miss
        counters measure served traffic). ``kind`` overrides the
        full/partial label (the session-resume path passes
        ``"session"``)."""
        with self._lock:
            if hit.tokens:
                self.hits += 1
                self.hit_tokens_total += hit.tokens
            else:
                self.misses += 1
        if _telemetry.enabled():
            reg = _telemetry.MetricsRegistry.get_default()
            if hit.tokens:
                reg.counter(
                    _telemetry.SERVING_PREFIX_HITS,
                    "prefix-cache lookups that reused >= 1 committed "
                    "page").inc(
                    kind=kind or ("partial" if hit.cow_src is not None
                                  else "full"),
                    engine=self.engine_id)
                reg.counter(
                    _telemetry.SERVING_PREFIX_HIT_TOKENS,
                    "prompt tokens served from cached KV pages instead "
                    "of prefill compute").inc(hit.tokens,
                                              engine=self.engine_id)
            else:
                reg.counter(
                    _telemetry.SERVING_PREFIX_MISSES,
                    "prefix-cache lookups with no reusable page").inc(
                    engine=self.engine_id)

    # ----------------------------------------------------------- insert
    def insert(self, prompt: np.ndarray, table_pages: List[int],
               pool) -> int:
        """Index every FULL page of ``prompt`` (``table_pages[d]``
        holds positions ``[d*ps, (d+1)*ps)``). Already-cached depths
        are skipped; new nodes take one cache reference on their page.
        Returns the number of pages newly inserted."""
        prompt = np.asarray(prompt, np.int32)
        ps = self.page_size
        added = 0
        with self._lock:
            parent = _ROOT
            tick = next(self._tick)
            for d in range(int(prompt.size) // ps):
                seg = prompt[d * ps:(d + 1) * ps]
                dig = page_digest(parent, seg)
                node = self._nodes.get(dig)
                if node is None or not np.array_equal(node.tokens, seg):
                    if node is not None:
                        # hash collision: keep the resident entry
                        break
                    if d >= len(table_pages):
                        break
                    node = _Node(dig, parent, d, int(table_pages[d]),
                                 seg.copy(), tick)
                    pool.share([node.page])
                    self._nodes[dig] = node
                    self._children.setdefault(parent, set()).add(dig)
                    added += 1
                else:
                    node.tick = tick
                parent = dig
            self.inserted_pages += added
            self._gauge()
        return added

    # --------------------------------------------------------- eviction
    def evict(self, pool, n_pages: int) -> int:
        """Free up to ``n_pages`` cached pages: least-recently-used
        LEAF nodes first, and ONLY pages whose sole reference is the
        cache's own (refcount 1) — a page mapped by a live slot or
        pinned by a session is never reclaimed. Returns pages freed."""
        freed = 0
        with self._lock:
            # one LRU sort per PASS, evicting every eligible leaf it
            # meets; a further pass only runs when an eviction turned
            # a parent into a fresh leaf (sorting the whole index per
            # freed page would stall the scheduler between bursts)
            while freed < n_pages:
                progress = False
                for node in sorted(self._nodes.values(),
                                   key=lambda n: n.tick):
                    if freed >= n_pages:
                        break
                    if self._children.get(node.digest):
                        continue            # not a leaf
                    if pool.refcount(node.page) != 1:
                        continue            # live readers
                    self._drop(node, pool)
                    freed += 1
                    self.evicted_pages += 1
                    progress = True
                if not progress:
                    break
        if freed:
            if _telemetry.enabled():
                _telemetry.MetricsRegistry.get_default().counter(
                    _telemetry.SERVING_PREFIX_EVICTED_PAGES,
                    "prefix-cache pages reclaimed by LRU eviction "
                    "under page pressure").inc(freed,
                                               engine=self.engine_id)
            _flight.record("prefix_evict", pages=freed,
                           cached_pages=len(self._nodes))
        return freed

    def _drop(self, node: _Node, pool) -> None:
        """Remove one node and release the cache's page reference.
        Caller holds the lock."""
        del self._nodes[node.digest]
        sibs = self._children.get(node.parent)
        if sibs is not None:
            sibs.discard(node.digest)
            if not sibs:
                del self._children[node.parent]
        self._children.pop(node.digest, None)
        pool.free([node.page])
        self._gauge()

    def clear(self, pool) -> int:
        """Drop every node and release every cache reference (engine
        shutdown — the drain contract: with no slots and no sessions
        left, the pool returns to fully free)."""
        with self._lock:
            nodes = list(self._nodes.values())
            self._nodes.clear()
            self._children.clear()
            if nodes:
                pool.free([n.page for n in nodes])
            self._gauge()
        return len(nodes)


__all__ = ["PrefixCache", "PrefixHit", "page_digest"]
