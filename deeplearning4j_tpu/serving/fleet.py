"""Serving fleet: replicated decode engines behind one KV-aware
router, with disaggregated prefill.

PRs 7-9 built a production single-device serving core — continuous
batching, paged KV, prefix cache, sticky sessions. "Millions of users"
needs the fleet around it, and the same fuse-and-overlap playbook the
repo applied to kernels applies one level up:

- **One admission queue, N replicas.** ``ServingFleet`` owns N
  ``DecodeEngine`` replicas (one per device, or N same-device CPU
  replicas for tests) behind ONE queue and a router thread
  ("ServingFleetRouter"). Clients get a ``FleetRequest`` handle with
  the same ``result()``/``stream()`` API as a ``ServingRequest``.
- **KV-aware routing.** A request is scored onto the replica where it
  will run best: free KV pages and free slots (capacity), plus each
  replica's OWN prefix-cache hit hint for this prompt (locality — the
  replica already holding the prompt's prefix pages wins, so shared
  system prompts concentrate instead of re-prefilling everywhere).
- **Session affinity.** A ``session_id`` whose pages are pinned on
  replica k routes back to k (warm resume, zero history re-prefill),
  falling back to a cold prefill elsewhere only when k is saturated or
  dead.
- **Disaggregated prefill.** Prompts >= ``prefill_threshold`` tokens
  run on a dedicated prefill lane — its own AOT-compiled executables
  and its own submission thread ("ServingPrefillLane") — and the
  computed K/V is handed to the decode replica through
  ``DecodeEngine.submit_prepared``: the replica commits it with one
  page scatter (``kv_pages.handoff_commit``) between decode bursts, so
  a 2048-token bucket-padded prefill never stalls anyone's decode
  bursts. The lane's output is bit-identical to the replica's own
  prefill (same forward, same bucket padding), so greedy outputs stay
  token-identical to a solo engine.
- **One AOT compile.** Same-device replicas adopt replica 0's
  warm-pool executables (``_WarmPool.adopt``): fleet startup lowers
  and compiles each program once, not once per replica. Distinct
  devices compile per device (executables are device-bound).
- **Replica death and elastic resize.** A replica whose scheduler dies
  fails its in-flight work; the fleet re-routes every such request to
  a survivor and REPLAYS it, suppressing tokens the client already
  received (greedy and seeded sampling replay exactly; unseeded
  sampling may change distribution at the failover point — documented,
  not hidden). Sessions pinned on the dead replica are gone; their
  next turn re-admits cold elsewhere. ``add_replica`` /
  ``remove_replica`` grow and shrink the fleet at runtime (the
  scheduler's alert-driven elasticity path), ``drain_replica`` /
  ``restart_replica`` give in-place resize, and ``kill_replica`` is
  the chaos hook the CI drill uses. Replica identity is a stable id
  (``_Replica.rid``), never a list position, so affinity and
  drain/kill targets survive the list shrinking underneath them.

Everything is observable: ``SERVING_*`` metrics are labelled
``engine=<id>`` per replica, the fleet adds routed/reroute counters and
a live-replica gauge, traces carry per-replica ``engine`` tags plus
``route``/``lane_prefill`` spans, and the flight recorder sees
``fleet_replica_dead`` / ``fleet_reroute`` events.
"""

from __future__ import annotations

import itertools
import queue as _queue
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.profiler import flight_recorder as _flight
from deeplearning4j_tpu.profiler import telemetry as _telemetry
from deeplearning4j_tpu.serving.engine import (
    CapacityRejected, DecodeEngine, ServingRequest, device_sds,
    prefill_forward,
)

#: process-wide fleet ordinals — the ``fleet=<id>`` label on the
#: queue-pressure gauge (and the key the control plane's SLO-driven
#: scale-up matches alerts back to a ServeJob with)
_FLEET_IDS = itertools.count()


# ---------------------------------------------------------------- client
class FleetRequest:
    """Client handle for one fleet request: the ``ServingRequest`` API
    (``result``/``stream``/``done``/timings) over whatever replica —
    or sequence of replicas, under failover — actually serves it.

    Token replay on failover: the proxy counts tokens per attempt and
    suppresses the first ``len(tokens)`` of a replayed attempt, so the
    client-visible stream never duplicates or loses a token."""

    def __init__(self, prompt: np.ndarray, max_new_tokens: int,
                 temperature: float, eos_id, sample_seed, session_id,
                 spec_decode: Optional[bool] = None):
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.sample_seed = sample_seed
        self.session_id = session_id
        #: per-request speculative-decoding opt-in/out (None follows
        #: the replica engines' spec_decode config) — replayed verbatim
        #: on failover so a rerouted request keeps its draft behavior
        self.spec_decode = spec_decode
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.ttft_s: Optional[float] = None
        self.latency_s: Optional[float] = None
        self.cache_hit_tokens = 0
        #: draft-token tally of the FINAL attempt (set at finish):
        #: front-ends echo these as the response's ``spec`` stats
        self.spec_proposed = 0
        self.spec_accepted = 0
        #: routing facts front-ends echo: replica, reason, lane, attempts
        self.routing: Dict[str, Any] = {}
        self.engine_id: Optional[str] = None
        self.attempts = 0
        self._fleet: Optional["ServingFleet"] = None
        self._inner: Optional[ServingRequest] = None
        self._engine: Optional[DecodeEngine] = None
        self._replica_index: Optional[int] = None
        self._lane_result = None     # cached (handoff, lane_span)
        self._no_lane = False        # lane failed once: go direct
        self._cancelled = False      # cancel(): never reroute/re-run
        self._skip = 0               # replayed tokens to suppress
        self._seen = 0               # tokens seen from current attempt
        self._t_submit = time.perf_counter()
        self._stream: "_queue.Queue" = _queue.Queue()
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        self._lock = threading.RLock()

    # -- engine-side hooks (see ServingRequest._sink) -------------------
    def _attach(self, inner: ServingRequest, engine: DecodeEngine) \
            -> None:
        """Called synchronously inside engine.submit BEFORE the request
        becomes visible to the scheduler — no token can race this."""
        with self._lock:
            self._inner = inner
            self._engine = engine
            self._seen = 0
            self._skip = len(self.tokens)
            self.engine_id = engine.engine_id
            self.routing.update(replica=engine.engine_id,
                                attempts=self.attempts)

    def _on_token(self, inner: ServingRequest, token: int) -> None:
        with self._lock:
            if inner is not self._inner or self._done.is_set():
                return
            self._seen += 1
            if self._seen <= self._skip:
                return               # replayed token the client has
            if self.ttft_s is None:
                self.ttft_s = time.perf_counter() - self._t_submit
            self.tokens.append(token)
        self._stream.put(token)

    def _on_finish(self, inner: ServingRequest, reason: str,
                   error: Optional[BaseException]) -> None:
        with self._lock:
            if inner is not self._inner or self._done.is_set():
                return
            cancelled = self._cancelled
        fleet = self._fleet
        if cancelled:
            # the client asked for this teardown: however the inner
            # request actually ended (clean evict, replica death, or
            # engine shutdown racing the abort pass), the contract is
            # reason=cancelled + partial tokens, never a raised error
            self._finalize("cancelled", None, inner)
            return
        if error is not None and fleet is not None \
                and fleet._maybe_reroute(self, inner, error):
            return                   # re-queued onto a survivor
        self._finalize(reason, error, inner)

    def _finalize(self, reason: str, error: Optional[BaseException],
                  inner: Optional[ServingRequest] = None) -> None:
        with self._lock:
            if self._done.is_set():
                return
            self.finish_reason = reason
            self._error = error
            self.latency_s = time.perf_counter() - self._t_submit
            if inner is not None:
                self.cache_hit_tokens = inner.cache_hit_tokens
                self.spec_proposed = inner.spec_proposed
                self.spec_accepted = inner.spec_accepted
            self._stream.put(None)
            self._done.set()
        fleet = self._fleet
        if fleet is not None:
            fleet._on_request_done(self)

    def _fail(self, error: BaseException) -> None:
        self._finalize("error", error)

    # -- client side ----------------------------------------------------
    @property
    def request_id(self):
        inner = self._inner
        return inner.request_id if inner is not None else None

    @property
    def trace_id(self):
        inner = self._inner
        return inner.trace_id if inner is not None else None

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"fleet request not done within {timeout}s")
        if self._error is not None:
            raise self._error
        return np.asarray(self.tokens, np.int32)

    def cancel(self) -> bool:
        """Cancel this request wherever it is right now: queued at the
        fleet router, waiting at the prefill lane, or decoding in a
        replica slot (the engine frees the slot and drains its pages
        to rc0). The stream ends, ``result()`` returns the tokens
        received so far, ``finish_reason`` becomes ``"cancelled"`` and
        the trace closes with the same reason. A ``result(timeout=)``
        that timed out should call this — otherwise the request keeps
        decoding (and holding KV pages) to completion. False when the
        request already finished."""
        with self._lock:
            if self._done.is_set():
                return False
            self._cancelled = True
            inner, eng = self._inner, self._engine
        if inner is not None and eng is not None and not inner.done \
                and eng.abort(inner):
            return True      # engine-side teardown flows back via sink
        # not routed (or raced completion/death): finalize here — the
        # router and lane skip finished requests
        self._finalize("cancelled", None, inner)
        return True

    def stream(self):
        """Yield tokens as they decode — across failovers; raises the
        request's error (if any) after the stream ends."""
        while True:
            tok = self._stream.get()
            if tok is None:
                break
            yield tok
        if self._error is not None:
            raise self._error


# ---------------------------------------------------------------- lane
class _PrefillLane:
    """Disaggregated prefill: a dedicated submission thread and its own
    AOT-compiled executables run long prompts' prefill forward, then
    hand the K/V stacks to the target replica via submit_prepared. The
    arrays are immutable jax values, so the handoff needs no
    cross-thread synchronization beyond the queue."""

    def __init__(self, fleet: "ServingFleet", model, params,
                 buckets: List[int], threshold: int, device=None):
        self.fleet = fleet
        self.model = model
        self.params = params          # replica-0's device-put tree
        #: replica 0's device: the lane compiles and runs THERE (its
        #: params already live there); handoff to another replica is a
        #: device_put inside the target's _admit
        self._device = device
        self.buckets = sorted(buckets)
        self.threshold = int(threshold)
        self._exec: Dict[int, Any] = {}
        self._queue: "_queue.Queue" = _queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.n_prefills = 0
        self.n_fallbacks = 0

    def _build_fn(self):
        m = self.model

        def lane_prefill(params, prompt, t0):
            # the ONE shared prefill math (engine.prefill_forward):
            # lane-served prompts are bit-identical to engine-served
            # ones by construction, not by parallel maintenance
            ks, vs, last = prefill_forward(m, params, prompt, t0)
            return ks, vs, last.astype(jnp.float32)

        return lane_prefill

    def _sds(self, shape, dtype):
        return device_sds(shape, dtype, self._device)

    def start(self) -> None:
        fn = self._build_fn()
        i32 = jnp.int32
        with _telemetry.span("serving_lane_warmup",
                             buckets=len(self.buckets)):
            abs_params = jax.tree_util.tree_map(
                lambda a: self._sds(a.shape, a.dtype), self.params)
            for b in self.buckets:
                # one AOT executable per long bucket — the lane never
                # compiles after startup (its dispatch is the compiled
                # executable directly, like the engines' warm pool)
                self._exec[b] = jax.jit(fn).lower(
                    abs_params, self._sds((1, b), i32),
                    self._sds((), i32)).compile()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ServingPrefillLane")
        self._thread.start()

    def enqueue(self, freq: FleetRequest, replica: "_Replica") -> None:
        self._queue.put((freq, replica))

    def shutdown(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._queue.put(None)
        t = self._thread
        if t is not None:
            t.join(timeout)
        # strand no one: requests still queued at the lane (the loop
        # fails everything it dequeues after _stop, but a racing
        # enqueue can land behind the sentinel) fail here
        while True:
            try:
                item = self._queue.get_nowait()
            except _queue.Empty:
                break
            if item is not None:
                item[0]._fail(RuntimeError("fleet has been shut down"))

    def stats(self) -> Dict[str, Any]:
        return {"threshold": self.threshold,
                "buckets": list(self.buckets),
                "prefills": self.n_prefills,
                "fallbacks": self.n_fallbacks,
                "queued": self._queue.qsize()}

    def _loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                break
            freq, replica = item
            if self._stop.is_set():
                # keep draining up to the sentinel: every queued
                # request gets a clean error, never a silent hang
                freq._fail(RuntimeError("fleet has been shut down"))
                continue
            try:
                self._serve(freq, replica)
            except BaseException as e:     # lane must not die silently
                freq._no_lane = True
                self.n_fallbacks += 1
                _flight.record("lane_fallback", error=repr(e)[:200])
                self.fleet._requeue(freq, "lane_error")

    def _serve(self, freq: FleetRequest, replica: "_Replica") -> None:
        if freq.done:
            return                   # cancelled while lane-queued
        t0 = int(freq.prompt.size)
        bucket = next((b for b in self.buckets if b >= t0), None)
        if bucket is None:
            # longer than every compiled lane bucket: cold prefill on
            # the replica (its own out-of-bucket fallback handles it)
            freq._no_lane = True
            self.n_fallbacks += 1
            self.fleet._submit_to(replica, freq)
            return
        if freq._lane_result is None:
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :t0] = freq.prompt
            t_a = time.perf_counter()
            ks, vs, last = self._exec[bucket](
                self.params, jnp.asarray(padded),
                jnp.asarray(t0, jnp.int32))
            logits = np.asarray(last)
            t_b = time.perf_counter()
            self.n_prefills += 1
            _telemetry.record_span(
                "serving_lane_prefill", t_a, t_b,
                metric=_telemetry.SERVING_LANE_SECONDS, bucket=bucket)
            if _telemetry.enabled():
                _telemetry.MetricsRegistry.get_default().counter(
                    _telemetry.SERVING_LANE_PREFILLS,
                    "long-prompt prefills run on the disaggregated "
                    "lane instead of a decode replica").inc(
                    bucket=bucket)
            freq._lane_result = ((ks, vs, bucket, logits),
                                 (t_a, t_b, bucket))
        self.fleet._submit_to(replica, freq,
                              handoff=freq._lane_result)


# ------------------------------------------------------------- replicas
class _Replica:
    """One fleet member. Identity is the stable ``rid`` — allocated
    once, never reused — NOT the replica's position in the fleet's
    list: after a ``remove_replica`` shrinks the list, router
    affinity, ``restart_replica``, kill/drain targets and the
    capacity-listener callback all still name the engine they meant.
    ``index`` is a read-only alias for ``rid`` kept for callers (the
    scheduler's poll/rebalance paths, tests) that predate elastic
    resize."""

    __slots__ = ("rid", "engine", "alive", "draining",
                 "needs_cleanup")

    def __init__(self, rid: int, engine: DecodeEngine):
        self.rid = rid
        self.engine = engine
        self.alive = True
        self.draining = False
        self.needs_cleanup = False

    @property
    def index(self) -> int:
        return self.rid


# ---------------------------------------------------------------- fleet
class ServingFleet:
    """N decode-engine replicas, one admission queue, a KV-aware
    router, and an optional disaggregated prefill lane (module doc).

    Duck-types the ``DecodeEngine`` front-end surface (``submit`` /
    ``generate`` / ``stats`` / ``prefix_stats`` / ``release_session`` /
    ``shutdown``), so ``JsonModelServer(engine=fleet)`` and
    ``GenerativeInference`` work unchanged.

    Parameters
    ----------
    replicas : engine count (ignored when ``devices`` is given).
    devices : one jax device per replica; None places every replica on
        the default device (the N-CPU-replicas test topology), which
        also lets them share one AOT compile.
    prefill_threshold : prompts with at least this many tokens prefill
        on the dedicated lane; None disables disaggregation (and then
        a 1-replica fleet is greedy token-identical to a solo engine).
    max_queue : fleet admission queue bound — beyond it ``submit``
        raises the structured ``CapacityRejected``.
    engine_kwargs : forwarded to every ``DecodeEngine`` (slots,
        page_size, prefix_cache, session_capacity, kv_dtype,
        attn_mode, ...) — kept verbatim, so restarted replicas keep
        e.g. fp8 KV pools and the paged-attention kernel choice. The
        disaggregated prefill lane is unaffected by ``kv_dtype``: it
        hands off COMPUTE-dtype K/V stacks and each replica's adopt
        scatter quantizes into its own pool.
    """

    #: failed-over requests get this many total attempts before the
    #: error surfaces to the client
    MAX_ATTEMPTS_EXTRA = 1

    def __init__(self, model, params, *, replicas: int = 2,
                 devices: Optional[List[Any]] = None,
                 prefill_threshold: Optional[int] = None,
                 max_queue: int = 1024,
                 **engine_kwargs):
        if devices is not None:
            replicas = len(devices)
        if replicas < 1:
            raise ValueError("need at least one replica")
        engine_kwargs.setdefault("max_queue", max(256, max_queue))
        self.model = model
        self.fleet_id = f"fleet-{next(_FLEET_IDS)}"
        self.prefill_threshold = prefill_threshold
        #: the exact per-engine config, kept verbatim so
        #: restart_replica builds an identical engine (reverse-
        #: engineering kwargs from a live engine silently drops any
        #: newly-added knob)
        self._engine_kwargs = dict(engine_kwargs)
        #: replica-id allocator — ids are stable for the fleet's
        #: lifetime and never reused, so a removed replica's id can
        #: never silently re-target a later engine
        self._rids = itertools.count()
        self._replicas: List[_Replica] = []
        self._by_rid: Dict[int, _Replica] = {}
        first: Optional[DecodeEngine] = None
        for i in range(replicas):
            dev = devices[i] if devices is not None else None
            eng = DecodeEngine(
                model, params, device=dev,
                handoff_threshold=prefill_threshold,
                warm_source=first, **engine_kwargs)
            if first is None:
                first = eng
            r = _Replica(next(self._rids), eng)
            self._replicas.append(r)
            self._by_rid[r.rid] = r
        self._lane: Optional[_PrefillLane] = None
        if prefill_threshold is not None:
            self._lane = _PrefillLane(
                self, model, first.params,
                first.handoff_buckets, prefill_threshold,
                device=first._device)
        self._queue: "_queue.Queue" = _queue.Queue(maxsize=max_queue)
        #: live request handles (weak — finished requests fall out with
        #: their client references): what cancel_pending() sweeps
        self._live: "weakref.WeakSet" = weakref.WeakSet()
        self._affinity: Dict[str, int] = {}
        self._aff_lock = threading.Lock()
        #: serializes dead-replica cleanup (router _health_check) vs
        #: restart_replica's engine swap — without it the router can
        #: shut down a freshly-restarted engine it mistook for the
        #: dead one
        self._cleanup_lock = threading.Lock()
        self._router: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._start_lock = threading.Lock()
        self._rr = itertools.count()       # score tie-break rotation
        #: callable(replica_index, device, reason) invoked when a
        #: replica's capacity leaves the fleet (drained or dead) — how
        #: a JobScheduler gets its chip back for rebalancing. reason is
        #: "drained" | "dead".
        self.capacity_listener = None
        # fleet stats
        self.n_requests = 0
        self.n_completed = 0
        self.n_reroutes = 0
        #: elastic-resize operations currently in flight (+1 per
        #: add_replica, -1 per remove_replica) — published as the
        #: pending-scale gauge so dashboards can tell "small fleet"
        #: from "fleet mid-resize"
        self._pending_scale = 0
        self._routed: Dict[str, int] = {}
        self._stats_lock = threading.Lock()
        self._last_pressure_t = 0.0     # gauge-publish throttle

    # ------------------------------------------------------- lifecycle
    def start(self) -> "ServingFleet":
        with self._start_lock:
            if self._router is not None:
                return self
            if self._stop.is_set():
                raise RuntimeError("fleet has been shut down")
            with _telemetry.span("serving_fleet_start",
                                 replicas=len(self._replicas)):
                # replica 0 first: it compiles the shared warm pool the
                # others adopt
                for r in self._replicas:
                    r.engine.start()
                if self._lane is not None:
                    self._lane.start()
            self._gauge_replicas()
            self._router = threading.Thread(
                target=self._route_loop, daemon=True,
                name="ServingFleetRouter")
            self._router.start()
        return self

    def shutdown(self, timeout: float = 30.0) -> None:
        self._stop.set()
        self._queue.put(None)              # router sentinel
        if self._lane is not None:
            self._lane.shutdown(timeout)
        t = self._router
        if t is not None:
            t.join(timeout)
        # anything still queued at the fleet fails now, explicitly
        while True:
            try:
                item = self._queue.get_nowait()
            except _queue.Empty:
                break
            if isinstance(item, FleetRequest):
                item._fail(RuntimeError("fleet has been shut down"))
        for r in list(self._replicas):
            r.engine.shutdown(timeout)
        self._gauge_replicas()
        # the pressure gauge is only meaningful for a LIVE fleet —
        # same stale-series discipline as the per-engine gauges
        _telemetry.MetricsRegistry.get_default().remove_matching(
            "fleet", self.fleet_id, kinds=("gauge",))

    def __enter__(self) -> "ServingFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ---------------------------------------------------------- client
    def submit(self, prompt_ids, max_new_tokens: int,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               sample_seed: Optional[int] = None,
               session_id: Optional[str] = None,
               spec_decode: Optional[bool] = None) -> FleetRequest:
        if self._stop.is_set():
            raise RuntimeError("fleet has been shut down")
        # validate synchronously (every replica has the same config)
        prompt = self._replicas[0].engine._validate(prompt_ids,
                                                    max_new_tokens)
        if self._router is None:
            self.start()
        freq = FleetRequest(prompt, max_new_tokens, temperature,
                            eos_id, sample_seed, session_id,
                            spec_decode=spec_decode)
        freq._fleet = self
        try:
            self._queue.put_nowait(freq)
        except _queue.Full:
            hints = [r.engine.retry_after_hint()
                     for r in list(self._replicas) if r.alive]
            hint = min(hints) if hints else 1.0
            if _telemetry.enabled():
                _telemetry.MetricsRegistry.get_default().counter(
                    _telemetry.SERVING_REJECTS,
                    "submissions rejected because the admission "
                    "queue was full (429 at the HTTP front-end)").inc(
                    engine="fleet")
            raise CapacityRejected(
                f"fleet admission queue full ({self._queue.maxsize}); "
                f"retry after ~{hint}s", retry_after_s=hint)
        # close the submit/shutdown race (same contract as the engine's
        # _enqueue): if shutdown's final drain ran before our put, we
        # must fail the stranded request ourselves — seeing _stop clear
        # here proves shutdown will drain after us
        if self._stop.is_set():
            while True:
                try:
                    item = self._queue.get_nowait()
                except _queue.Empty:
                    break
                if isinstance(item, FleetRequest):
                    item._fail(RuntimeError("fleet has been shut "
                                            "down"))
        self._live.add(freq)
        self.n_requests += 1
        return freq

    def cancel_pending(self) -> int:
        """Cancel every live request (queued, laned, or decoding) —
        the scheduler's job-cancel path: the fleet stops cleanly
        without failing anyone with an opaque shutdown error. Returns
        the number cancelled."""
        n = 0
        for freq in list(self._live):
            if not freq.done and freq.cancel():
                n += 1
        return n

    def generate(self, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None) -> np.ndarray:
        return self.submit(prompt_ids, max_new_tokens, temperature,
                           eos_id).result(timeout)

    def release_session(self, session_id: str) -> bool:
        with self._aff_lock:
            self._affinity.pop(session_id, None)
        # every alive replica, not just the current affinity target: an
        # affinity_fallback may have left an older pin on a replica the
        # session was served from earlier — an explicit release must
        # free those pages too, not wait out their TTL
        hit = False
        for r in list(self._replicas):
            if r.alive:
                hit = r.engine.release_session(session_id) or hit
        return hit

    # ----------------------------------------------------------- stats
    @property
    def n_dispatches(self) -> int:
        return sum(r.engine.n_dispatches for r in list(self._replicas))

    def alive_replicas(self) -> int:
        return sum(1 for r in list(self._replicas) if r.alive)

    def queue_pressure(self) -> float:
        """Admission pressure normalized by live capacity: (fleet queue
        + per-replica queue depth) / live decode slots. ~0 = idle,
        >= 1 = a full slot-generation of work waiting. The train-vs-
        serve rebalancing signal: a scheduler hands chips from a fleet
        sitting near 0 to a starved train job, and back when pressure
        climbs. inf when nothing is alive."""
        alive = [r for r in list(self._replicas)
                 if r.alive and not r.draining]
        if not alive:
            return float("inf")
        depth = self._queue.qsize() + sum(
            r.engine.queue_depth() for r in alive)
        return depth / max(1, sum(r.engine.slots for r in alive))

    def stats(self) -> Dict[str, Any]:
        reps = list(self._replicas)
        e0 = reps[0].engine
        with self._stats_lock:
            routed = dict(self._routed)
        return {
            "fleet": True,
            "fleet_id": self.fleet_id,
            "replicas": [dict(r.engine.stats(), id=r.rid,
                              alive=r.alive, draining=r.draining)
                         for r in reps],
            "alive_replicas": self.alive_replicas(),
            "slots": sum(r.engine.slots for r in reps if r.alive),
            "pending_scale": self._pending_scale,
            "page_size": e0.page_size,
            "max_context": e0.max_context,
            "quantization": e0.quantization,
            "prefill_buckets": list(e0.prefill_buckets),
            "requests": self.n_requests,
            "completed": self.n_completed,
            "reroutes": self.n_reroutes,
            "router": {"queue_depth": self._queue.qsize(),
                       "routed": routed,
                       "affinity_entries": len(self._affinity)},
            **({"prefill_lane": self._lane.stats()}
               if self._lane is not None else {}),
        }

    def prefix_stats(self) -> Dict[str, Any]:
        return {
            "fleet": True,
            "replicas": {r.engine.engine_id: r.engine.prefix_stats()
                         for r in list(self._replicas)},
        }

    # --------------------------------------------------- elastic resize
    def _resolve(self, index: int) -> _Replica:
        """Replica lookup by stable id. Raises IndexError (what list
        indexing raised before ids existed) when no replica carries
        that id — including ids retired by ``remove_replica``."""
        r = self._by_rid.get(index)
        if r is None:
            raise IndexError(
                f"no replica with id {index} "
                f"(current ids: {sorted(self._by_rid)})")
        return r

    def add_replica(self, device: Any = None) -> int:
        """Grow the fleet by one replica at runtime and return its
        stable id.

        The new engine is built on ``device`` (None = wherever a live
        replica already runs, the shared-compile test topology),
        adopting a live same-device replica's AOT warm pool so it
        serves its first request with zero compiles. Compilation for a
        DISTINCT device happens here, on the caller's thread — the
        router never blocks on it. Registration is atomic: the replica
        list only ever shows fully-started engines, so in-flight
        routing cannot pick a half-built replica. On a started fleet
        the engine starts before registration; on an unstarted fleet
        it is registered cold and ``start()`` brings it up with the
        rest."""
        if self._stop.is_set():
            raise RuntimeError("fleet has been shut down")
        donor = next((x for x in list(self._replicas)
                      if x.alive and not x.draining), None)
        if device is None and donor is not None:
            device = donor.engine._device
        warm = donor.engine if donor is not None and \
            (donor.engine._device is device
             or donor.engine._device == device) else None
        params = donor.engine.params if donor is not None \
            else self._replicas[0].engine.params
        self._pending_scale += 1
        self._gauge_replicas()
        t0 = time.perf_counter()
        try:
            eng = DecodeEngine(
                self.model, params, device=device,
                handoff_threshold=self.prefill_threshold,
                warm_source=warm, **self._engine_kwargs)
            with self._start_lock:
                # under the start lock: either start() already ran (we
                # must start the engine ourselves, off the router's
                # critical path — this thread) or it hasn't (we
                # register cold and start() starts every replica)
                if self._router is not None:
                    try:
                        eng.start()
                    except BaseException:
                        # never leak a half-built engine's threads or
                        # gauge series into a fleet that rejected it
                        try:
                            eng.shutdown(timeout=5.0)
                        except Exception:
                            pass
                        raise
                with self._cleanup_lock:
                    rid = next(self._rids)
                    r = _Replica(rid, eng)
                    self._by_rid[rid] = r
                    self._replicas.append(r)
        finally:
            self._pending_scale -= 1
        self._gauge_replicas()
        _flight.record("fleet_replica_added",
                       engine=eng.engine_id, rid=rid,
                       adopted=eng._warm.adopted,
                       startup_s=round(time.perf_counter() - t0, 3))
        return rid

    def remove_replica(self, index: int,
                       timeout: Optional[float] = 60.0) -> bool:
        """Shrink the fleet: drain replica ``index`` (stop routing,
        finish in-flight work, hand its pinned sessions off — they
        re-admit cold on survivors and re-pin there), shut the engine
        down (retiring its engine-labelled gauge series), then retire
        the id. The capacity listener hears ``"drained"`` so a
        scheduler reclaims the chip. True when the drain was clean."""
        r = self._resolve(index)
        live = [x for x in list(self._replicas)
                if x.alive and not x.draining]
        if r.alive and not r.draining and len(live) <= 1:
            raise ValueError(
                "cannot remove the last live replica "
                f"(id {r.rid}) — shut the fleet down instead")
        self._pending_scale -= 1
        self._gauge_replicas()
        try:
            ok = True
            if r.alive:
                ok = self.drain_replica(r.rid, timeout)
            else:
                # dead replica: its scheduler thread already exited;
                # finish the cleanup the router would have done
                with self._cleanup_lock:
                    pending = r.needs_cleanup
                    r.needs_cleanup = False
                if pending:
                    try:
                        r.engine.shutdown(timeout=5.0)
                    except Exception:
                        pass
            with self._cleanup_lock:
                self._by_rid.pop(r.rid, None)
                try:
                    self._replicas.remove(r)
                except ValueError:
                    pass
        finally:
            self._pending_scale += 1
        self._gauge_replicas()
        _flight.record("fleet_replica_removed",
                       engine=r.engine.engine_id, rid=r.rid,
                       clean=ok)
        return ok

    def drain_replica(self, index: int,
                      timeout: Optional[float] = 60.0) -> bool:
        """Stop routing to replica ``index``, wait for its queued and
        in-flight requests to finish, then shut it down. Sessions
        pinned there are released (their next turn re-admits cold
        elsewhere). True when fully drained."""
        r = self._resolve(index)
        r.draining = True
        self._drop_affinity(r.rid)
        ok = r.engine.drain(timeout)
        r.engine.shutdown()
        r.alive = False
        self._gauge_replicas()
        _flight.record("fleet_replica_drained",
                       engine=r.engine.engine_id, clean=ok)
        self._notify_capacity(r, "drained")
        return ok

    def restart_replica(self, index: int) -> None:
        """Bring a drained/dead replica back: a fresh engine (adopting
        a live same-device replica's warm pool when possible) starts
        and rejoins routing."""
        r = self._resolve(index)
        if r.alive:
            raise ValueError(f"replica {index} is still alive")
        old = r.engine
        # finish the dead engine's cleanup HERE (under the same lock
        # the router's pass takes) before the new engine becomes
        # visible — otherwise a concurrent _health_check could shut
        # down the fresh engine it mistakes for the dead one
        with self._cleanup_lock:
            pending = r.needs_cleanup
            r.needs_cleanup = False
        if pending:
            try:
                old.shutdown(timeout=5.0)
            except Exception:
                pass
        donor = next((x.engine for x in list(self._replicas)
                      if x.alive and x.engine._device == old._device),
                     None)
        eng = DecodeEngine(
            self.model, old.params, device=old._device,
            handoff_threshold=self.prefill_threshold,
            warm_source=donor, **self._engine_kwargs)
        eng.start()
        with self._cleanup_lock:
            r.engine = eng
            r.alive = True
            r.draining = False
        self._gauge_replicas()
        _flight.record("fleet_replica_restarted",
                       engine=eng.engine_id, index=r.rid)

    def kill_replica(self, index: int,
                     error: Optional[BaseException] = None) -> None:
        """Chaos hook: poison replica ``index``'s scheduler so it dies
        the way a real fault would — evictions, incident dump,
        re-routing. The CI kill-a-replica drill calls this."""
        self._resolve(index).engine._die(
            error or RuntimeError(f"replica {index} killed by chaos "
                                  "hook"))

    # ----------------------------------------------------------- router
    def _gauge_pressure(self) -> None:
        """Publish queue_pressure() as a gauge (throttled): the
        continuous signal the SLO engine's ``serving_queue_pressure``
        rule windows — sustained pressure (not one busy poll) is what
        fires the scheduler's scale-up hook."""
        if not _telemetry.enabled():
            return
        now = time.monotonic()
        if now - self._last_pressure_t < 0.25:
            return
        self._last_pressure_t = now
        _telemetry.MetricsRegistry.get_default().gauge(
            _telemetry.SERVING_FLEET_PRESSURE,
            "fleet admission pressure: queued work per live decode "
            "slot (~0 idle, >=1 a full slot-generation waiting)").set(
            self.queue_pressure(), fleet=self.fleet_id)

    def _route_loop(self) -> None:
        while True:
            self._gauge_pressure()
            try:
                item = self._queue.get(timeout=0.05)
            except _queue.Empty:
                self._health_check()
                continue
            if item is None or self._stop.is_set():
                if isinstance(item, FleetRequest):
                    item._fail(RuntimeError("fleet has been shut "
                                            "down"))
                break
            self._health_check()
            try:
                self._route(item)
            except BaseException as e:
                item._fail(e)

    def _health_check(self) -> None:
        for r in list(self._replicas):
            if r.alive and r.engine._dead is not None:
                self._mark_dead(r, r.engine._dead)
            if r.needs_cleanup:
                # scheduler thread already exited; shutdown() joins it
                # and releases sessions/prefix references so the dead
                # pool's accounting drains (router thread only — the
                # dying thread must never join itself). The lock keeps
                # this from racing restart_replica's engine swap.
                with self._cleanup_lock:
                    if not r.needs_cleanup:
                        continue
                    r.needs_cleanup = False
                    dead_engine = r.engine
                try:
                    dead_engine.shutdown(timeout=5.0)
                except Exception:
                    pass

    def _mark_dead(self, r: _Replica, err: BaseException) -> None:
        if not r.alive:
            return
        r.alive = False
        r.needs_cleanup = True
        self._drop_affinity(r.rid)
        self._gauge_replicas()
        _flight.record("fleet_replica_dead",
                       engine=r.engine.engine_id,
                       error=repr(err)[:200])
        self._notify_capacity(r, "dead")

    def _notify_capacity(self, r: _Replica, reason: str) -> None:
        cb = self.capacity_listener
        if cb is not None:
            try:
                cb(r.rid, r.engine._device, reason)
            except Exception:
                pass   # a broken listener must not break routing

    def _drop_affinity(self, index: int) -> None:
        with self._aff_lock:
            for sid in [s for s, i in self._affinity.items()
                        if i == index]:
                del self._affinity[sid]

    def _gauge_replicas(self) -> None:
        if not _telemetry.enabled():
            return
        reg = _telemetry.MetricsRegistry.get_default()
        reg.gauge(
            _telemetry.SERVING_FLEET_REPLICAS,
            "decode replicas currently alive and routable").set(
            self.alive_replicas())
        reg.gauge(
            _telemetry.SERVING_FLEET_SIZE,
            "replicas registered with the fleet router (alive or "
            "not) — the elastic-resize size signal").set(
            len(self._replicas), fleet=self.fleet_id)
        reg.gauge(
            _telemetry.SERVING_FLEET_PENDING_SCALE,
            "elastic-resize operations in flight (+1 per add_replica,"
            " -1 per remove_replica; 0 = fleet at rest)").set(
            self._pending_scale, fleet=self.fleet_id)

    def _saturated(self, r: _Replica) -> bool:
        eng = r.engine
        depth = eng._queue.qsize() + len(eng._waiting)
        # hard-full admission counts as saturated even with a free
        # slot (a page-blocked head-of-line request can idle a slot
        # while the queue is at max_queue — routing there would only
        # bounce off CapacityRejected)
        if depth >= eng.max_queue:
            return True
        return bool(eng._active.all()) and depth >= eng.slots

    def _route(self, freq: FleetRequest) -> None:
        if freq.done:
            return                   # cancelled while queued
        t_r0 = time.perf_counter()
        cands = [r for r in list(self._replicas)
                 if r.alive and not r.draining]
        if not cands:
            freq._fail(RuntimeError("no live replicas"))
            return
        target: Optional[_Replica] = None
        reason = "score"
        if freq.session_id is not None:
            with self._aff_lock:
                idx = self._affinity.get(freq.session_id)
            if idx is not None:
                # affinity pins the stable replica id — a removed
                # replica's id resolves to None (cold fallback), never
                # to whatever engine now occupies its old list slot
                aff = self._by_rid.get(idx)
                if aff is not None and aff.alive \
                        and not aff.draining \
                        and not self._saturated(aff):
                    target, reason = aff, "affinity"
                else:
                    # pinned replica saturated or gone: cold elsewhere
                    reason = "affinity_fallback"
        if target is None:
            target = self._pick(freq, cands)
        if freq.session_id is not None:
            with self._aff_lock:
                self._affinity[freq.session_id] = target.rid
        freq.routing.update(reason=reason)
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.SERVING_FLEET_ROUTED,
                "requests routed to a replica (labels: reason, "
                "target engine)").inc(
                reason=reason, engine=target.engine.engine_id)
        with self._stats_lock:
            self._routed[reason] = self._routed.get(reason, 0) + 1
        lane_ok = (self._lane is not None and not freq._no_lane
                   and reason != "affinity"
                   and freq.prompt.size >= self._lane.threshold)
        freq.routing["lane"] = bool(lane_ok)
        freq.routing["route_ms"] = round(
            (time.perf_counter() - t_r0) * 1e3, 3)
        if lane_ok:
            self._lane.enqueue(freq, target)
        else:
            self._submit_to(target, freq, handoff=freq._lane_result)

    def _pick(self, freq: FleetRequest,
              cands: List[_Replica]) -> _Replica:
        """KV-aware score: free pages + free slots (capacity), the
        replica's own prefix-cache hit hint for this prompt
        (locality), minus queue depth. Ties rotate round-robin."""
        off = next(self._rr)
        best, best_score = None, None
        n = len(cands)
        for j in range(n):
            r = cands[(j + off) % n]
            eng = r.engine
            free_pages = eng.pool.free_pages / max(eng.pool.capacity, 1)
            free_slots = (eng.slots - int(eng._active.sum())) \
                / eng.slots
            depth = eng._queue.qsize() + len(eng._waiting)
            hit = 0.0
            if eng._prefix is not None:
                hit = eng._prefix.hit_tokens_hint(freq.prompt) \
                    / max(int(freq.prompt.size), 1)
            score = 2.0 * hit + free_pages + free_slots \
                - 0.5 * depth / eng.slots
            if best_score is None or score > best_score:
                best, best_score = r, score
        return best

    def _submit_to(self, target: _Replica, freq: FleetRequest,
                   handoff=None) -> None:
        """Hand a routed request to a replica engine (router or lane
        thread). Replica trouble re-queues instead of failing."""
        if freq.done:
            return                   # cancelled while in flight
        eng = target.engine
        freq.attempts += 1
        freq._replica_index = target.rid
        t_s0 = time.perf_counter()
        try:
            if handoff is not None:
                ho, lane_span = handoff
                inner = eng.submit_prepared(
                    freq.prompt, freq.max_new_tokens,
                    freq.temperature, freq.eos_id, freq.sample_seed,
                    session_id=freq.session_id,
                    spec_decode=freq.spec_decode, handoff=ho,
                    lane_span=lane_span, _sink=freq)
                freq._lane_result = None
            else:
                inner = eng.submit(
                    freq.prompt, freq.max_new_tokens,
                    freq.temperature, freq.eos_id, freq.sample_seed,
                    session_id=freq.session_id,
                    spec_decode=freq.spec_decode, _sink=freq)
        except CapacityRejected:
            # replica queue full (rare: fleet sizes replica queues
            # generously) — try again through the router
            self._requeue(freq, "replica_full")
            return
        except RuntimeError as e:
            # engine died/shut down between health checks
            self._mark_dead(target, e)
            self._requeue(freq, "dead_on_submit")
            return
        if inner._trace is not None:
            inner._trace.event("route", t_s0,
                               replica=eng.engine_id,
                               reason=freq.routing.get("reason"),
                               lane=freq.routing.get("lane", False),
                               attempts=freq.attempts)

    def _requeue(self, freq: FleetRequest, why: str) -> None:
        if self._stop.is_set():
            freq._fail(RuntimeError("fleet has been shut down"))
            return
        if freq.attempts > len(self._replicas) \
                + self.MAX_ATTEMPTS_EXTRA:
            if why == "replica_full":
                # every replica's admission queue rejected us: this IS
                # the capacity case — keep the structured 429 contract
                # (retry_after_s) instead of an opaque error
                hints = [r.engine.retry_after_hint()
                         for r in list(self._replicas) if r.alive]
                freq._fail(CapacityRejected(
                    f"every replica at capacity after {freq.attempts} "
                    "attempts",
                    retry_after_s=min(hints) if hints else 1.0))
                return
            freq._fail(RuntimeError(
                f"request failed after {freq.attempts} attempts "
                f"({why})"))
            return
        _flight.record("fleet_requeue", why=why,
                       attempts=freq.attempts,
                       tokens_done=len(freq.tokens))
        try:
            self._queue.put_nowait(freq)
        except _queue.Full:
            freq._fail(CapacityRejected(
                "fleet queue full during re-route", 1.0))

    # ------------------------------------------------------- failover
    def _maybe_reroute(self, freq: FleetRequest,
                       inner: ServingRequest,
                       error: BaseException) -> bool:
        """Engine-death failover (called from the dying engine's
        scheduler thread via the sink hook). True when the request was
        re-queued onto a survivor; False surfaces the error."""
        if self._stop.is_set():
            return False
        idx = getattr(freq, "_replica_index", None)
        if idx is None:
            return False
        r = self._by_rid.get(idx)
        if r is None:
            return False    # replica already removed from the fleet
        eng = r.engine
        if eng._dead is None and not eng._stop.is_set():
            return False        # genuine per-request error: surface it
        self._mark_dead(r, error)
        if freq.attempts > len(self._replicas) \
                + self.MAX_ATTEMPTS_EXTRA:
            return False
        self.n_reroutes += 1
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.SERVING_FLEET_REROUTES,
                "requests replayed on a survivor after their replica "
                "died").inc(engine=eng.engine_id)
        _flight.record("fleet_reroute",
                       request_id=inner.request_id,
                       from_engine=eng.engine_id,
                       tokens_done=len(freq.tokens),
                       attempts=freq.attempts)
        try:
            self._queue.put_nowait(freq)
        except _queue.Full:
            return False
        return True

    def _on_request_done(self, freq: FleetRequest) -> None:
        self.n_completed += 1


__all__ = ["ServingFleet", "FleetRequest"]
