"""Paged KV cache: fixed-size page pool + per-slot page tables.

Why pages: ``CausalLM.generate()`` allocates one dense
``[L, N, H, max_len, hd]`` cache per compiled ``(batch, prompt, new)``
shape — every distinct request geometry is a fresh multi-hundred-MB
allocation and a fresh executable. The serving engine instead owns ONE
pool of fixed-size pages shared by every slot:

- ``kv tree``: ``{"k", "v"}`` pools of shape
  ``[L, n_pages, H, page_size, hd]``, allocated once at engine
  startup, plus — when the pool is fp8-quantized — ``"k_scale"`` /
  ``"v_scale"`` per-page-per-head fp32 scale planes ``[L, n_pages,
  H]`` stored beside them. Page 0 is the NULL page — a scratch target
  that absorbs writes from inactive slots and from the padded tail of
  prefill commits; it is never read through a valid attention
  position.
- per-slot page table: row ``j`` of a slot's table names the page
  holding absolute positions ``[j*page_size, (j+1)*page_size)`` of
  that slot's sequence. Unallocated tail entries point at the null
  page and are masked by the position check (attention only admits
  flat position ``<= pos``).
- ``PagePool`` is the HOST-side allocator (free list, REFERENCE
  COUNTS, utilization gauge); the device arrays thread functionally
  through the jitted prefill/decode steps as ONE pytree
  (``pool.tree()``) and are rebound by the engine
  (``pool.rebind(kv)``).

Reference counts are what make cross-request KV reuse safe
(serving/prefix_cache.py, serving/sessions.py): a page can be mapped
read-only into several slots' tables at once — ``alloc`` hands a page
out at refcount 1, ``share`` adds readers, ``free`` DECREMENTS and
only returns the page to the free list when the last reader is gone.
Writers must hold the only reference; a slot about to write into a
shared page takes a private copy first (``copy_page``, the
copy-on-write step) and swaps its table entry.

fp8 KV (``kv_dtype="fp8_e4m3"``, nn/precision.py helpers): pages
store float8_e4m3fn — HALF the bytes of bf16, so the same HBM budget
holds ~2x the KV positions and every decode step streams half the
cache bytes. Scales are per-page-per-head and follow the page's
lifecycle exactly: written at commit (prefill/handoff compute exact
per-page absmax over the REAL positions), frozen once a page has
entries (the decode append only mints a fresh scale at offset 0, so
earlier tokens in the page are never re-scaled under their feet),
carried by ``copy_page`` (a CoW clone keeps the source's scales), and
reclaimed implicitly with the page (scale rows of free pages are
garbage, exactly like their page contents). Scale planes initialize
to ONES so the zero-filled pools round-trip exactly and no division
ever sees zero.

The jax functions here are pure and shape-static, so the engine's one
decode executable serves every mix of request lengths.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import precision as _precision
from deeplearning4j_tpu.profiler import telemetry as _telemetry


class PagePool:
    """Host-side refcounting page allocator over the device-resident
    K/V pools.

    ``n_pages`` INCLUDES the reserved null page 0, so the usable
    capacity is ``n_pages - 1`` pages. ``alloc`` returns None when the
    request cannot be satisfied — the scheduler keeps the request
    queued (head-of-line) until eviction frees pages.

    ``dtype`` is the engine's COMPUTE dtype; ``kv_dtype`` optionally
    quantizes the STORED pages (``"fp8_e4m3"``) with fp32 scale planes
    beside them — ``tree()`` then carries four leaves instead of two.

    Thread safety: the free list and refcounts are guarded by a lock —
    the scheduler thread allocates/frees, while session release and
    submit-time budget hints may touch refcounts from client threads.
    """

    def __init__(self, n_layers: int, n_heads: int, page_size: int,
                 head_dim: int, n_pages: int, dtype=jnp.bfloat16,
                 engine_id: str = "solo", device=None, kv_dtype=None):
        if page_size < 1 or n_pages < 2:
            raise ValueError(
                f"need page_size >= 1 and n_pages >= 2 (one null page "
                f"+ one usable), got {page_size}/{n_pages}")
        self.page_size = int(page_size)
        self.n_pages = int(n_pages)
        #: ``engine=`` label on the utilization gauges, so N pools in
        #: one process (a serving fleet) stay distinguishable series
        self.engine_id = str(engine_id)
        self.kv_dtype = _precision.resolve_kv_dtype(kv_dtype)
        store = (_precision.fp8_kv_dtype() if self.kv_dtype
                 else jnp.dtype(dtype))
        #: ``kv_dtype=`` label value on every SERVING_KV_* series
        self.dtype_label = self.kv_dtype or jnp.dtype(dtype).name
        shape = (n_layers, n_pages, n_heads, page_size, head_dim)
        self.k = jnp.zeros(shape, store)
        self.v = jnp.zeros(shape, store)
        self.k_scale = self.v_scale = None
        if self.kv_dtype:
            sshape = (n_layers, n_pages, n_heads)
            self.k_scale = jnp.ones(sshape, jnp.float32)
            self.v_scale = jnp.ones(sshape, jnp.float32)
        if device is not None:
            self.k = jax.device_put(self.k, device)
            self.v = jax.device_put(self.v, device)
            if self.kv_dtype:
                self.k_scale = jax.device_put(self.k_scale, device)
                self.v_scale = jax.device_put(self.v_scale, device)
        # LIFO free list: recently-freed pages are re-used first, which
        # keeps the hot working set of pages small and cache-friendly
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        #: page -> live reference count; absent means the page is free
        self._refs: Dict[int, int] = {}
        self._high_water = 0
        self._lock = threading.Lock()
        self._gauge()

    # ----------------------------------------------------- device tree
    def tree(self) -> Dict[str, jnp.ndarray]:
        """The device-side KV pytree threaded through jitted programs:
        ``{"k", "v"}`` (+ ``"k_scale"``/``"v_scale"`` when fp8). Dict
        insertion order is the flatten order, so a non-fp8 tree
        flattens to exactly the (kpool, vpool) pair the pre-tree
        programs took — both features off stays program-identical."""
        kv = {"k": self.k, "v": self.v}
        if self.k_scale is not None:
            kv["k_scale"] = self.k_scale
            kv["v_scale"] = self.v_scale
        return kv

    def rebind(self, kv: Dict[str, jnp.ndarray]) -> None:
        """Adopt the arrays a jitted program returned (the functional
        counterpart of ``tree()``; donation invalidated the old ones).
        """
        self.k, self.v = kv["k"], kv["v"]
        if "k_scale" in kv:
            self.k_scale, self.v_scale = kv["k_scale"], kv["v_scale"]

    # ------------------------------------------------------- accounting
    @property
    def capacity(self) -> int:
        return self.n_pages - 1

    @property
    def allocated(self) -> int:
        return self.capacity - len(self._free)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def high_water(self) -> int:
        return self._high_water

    def utilization(self) -> float:
        return self.allocated / max(self.capacity, 1)

    def refcount(self, page: int) -> int:
        """Live references on ``page`` (0 when free)."""
        with self._lock:
            return self._refs.get(int(page), 0)

    def shared_pages(self) -> int:
        """Pages with MORE than one reader (prefix-cache hits mapped
        into live slots, cache+session double holds, ...)."""
        with self._lock:
            return sum(1 for r in self._refs.values() if r > 1)

    def bytes_per_page(self) -> int:
        # k + v (+ scale planes), all layers, one page
        per = self.k.size // self.n_pages
        total = 2 * per * jnp.dtype(self.k.dtype).itemsize
        if self.k_scale is not None:
            total += 2 * (self.k_scale.size // self.n_pages) \
                * jnp.dtype(self.k_scale.dtype).itemsize
        return total

    # ------------------------------------------------------- allocation
    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` pages at refcount 1 each, or None if the pool can't
        satisfy it (caller keeps the request queued)."""
        with self._lock:
            if n > len(self._free):
                return None
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                self._refs[p] = 1
            self._high_water = max(self._high_water, self.allocated)
        self._gauge()
        return pages

    def share(self, pages: Sequence[int]) -> None:
        """Add one reference per listed page (a page listed twice gains
        two). Every page must currently be live — sharing a free page
        is a use-after-free and raises."""
        with self._lock:
            for p in pages:
                self._check_range(p)
                if int(p) not in self._refs:
                    raise ValueError(
                        f"cannot share free page {int(p)} (not "
                        "currently allocated)")
            for p in pages:
                self._refs[int(p)] += 1
        self._gauge()

    def free(self, pages: Sequence[int]) -> None:
        """Drop one reference per listed page; a page whose last
        reference drops returns to the free list.

        The whole call is validated BEFORE any mutation: out-of-range
        or null-page indices, frees of already-free pages, and
        DUPLICATES WITHIN ONE CALL that exceed the page's live count
        all raise with the free list untouched (a silent bad free is a
        corrupted allocator and, pages being shared now, somebody
        else's KV cache)."""
        with self._lock:
            demand = collections.Counter()
            for p in pages:
                self._check_range(p)
                demand[int(p)] += 1
            for p, n in demand.items():
                have = self._refs.get(p, 0)
                if have == 0:
                    raise ValueError(f"double free of page {p} "
                                     "(already on the free list)")
                if n > have:
                    raise ValueError(
                        f"over-free of page {p}: {n} frees in one call "
                        f"but only {have} live reference(s)")
            for p, n in demand.items():
                left = self._refs[p] - n
                if left == 0:
                    del self._refs[p]
                    self._free.append(p)
                else:
                    self._refs[p] = left
        self._gauge()

    def _check_range(self, p) -> None:
        if not isinstance(p, (int,)) and not hasattr(p, "__index__"):
            raise ValueError(f"page index {p!r} is not an integer")
        p = int(p)
        if not 0 < p < self.n_pages:
            raise ValueError(f"page {p} outside pool (null page 0 "
                             "is never allocated or freed)")

    def _gauge(self) -> None:
        if _telemetry.enabled():
            reg = _telemetry.MetricsRegistry.get_default()
            labels = dict(engine=self.engine_id,
                          kv_dtype=self.dtype_label)
            reg.gauge(
                _telemetry.SERVING_KV_PAGE_UTILIZATION,
                "fraction of KV-cache pages currently allocated to "
                "live requests").set(self.utilization(), **labels)
            reg.gauge(
                _telemetry.SERVING_SHARED_PAGES,
                "KV pages mapped by more than one reader (prefix-"
                "cache sharing)").set(self.shared_pages(), **labels)
            reg.gauge(
                _telemetry.SERVING_KV_PAGE_BYTES,
                "bytes per KV page (k + v + scale planes, all "
                "layers)").set(self.bytes_per_page(), **labels)


# ------------------------------------------------------- pure jax ops
def _is_fp8(kv) -> bool:
    return "k_scale" in kv


def commit_prefill(kv, ks, vs, page_row, page_size: int, n_valid=None):
    """Scatter one prompt's prefill K/V into its pages.

    ``ks``/``vs``: ``[L, 1, H, B, hd]`` from the parallel-prefill
    forward over the padded prompt (bucket width ``B``, a multiple of
    ``page_size``). ``page_row``: ``[B // page_size]`` page ids — real
    pages for chunks the slot owns, null page 0 for the padded tail
    (garbage written there is never read: positions beyond the true
    prompt length stay masked until the decode loop overwrites them).

    fp8 pools additionally compute the exact per-page-per-head absmax
    — over REAL positions only when ``n_valid`` (the true prompt
    length, a traced scalar) is given, so padded-tail garbage can't
    inflate a page's scale — and scatter the minted scales beside the
    quantized pages.
    """
    L, one, H, B, hd = ks.shape
    pb = B // page_size
    ck = ks[:, 0].reshape(L, H, pb, page_size, hd).transpose(0, 2, 1, 3, 4)
    cv = vs[:, 0].reshape(L, H, pb, page_size, hd).transpose(0, 2, 1, 3, 4)
    out = dict(kv)
    if not _is_fp8(kv):
        out["k"] = kv["k"].at[:, page_row].set(ck.astype(kv["k"].dtype))
        out["v"] = kv["v"].at[:, page_row].set(cv.astype(kv["v"].dtype))
        return out

    def amax(c):  # [L, pb, H, ps, hd] -> [L, pb, H]
        a = jnp.abs(c.astype(jnp.float32))
        if n_valid is not None:
            flat = (jnp.arange(pb)[:, None] * page_size
                    + jnp.arange(page_size)[None, :])
            mask = (flat < n_valid)[None, :, None, :, None]
            a = jnp.where(mask, a, 0.0)
        return jnp.max(a, axis=(3, 4))

    ksc = _precision.fp8_scale(amax(ck))
    vsc = _precision.fp8_scale(amax(cv))
    out["k"] = kv["k"].at[:, page_row].set(
        _precision.quantize_fp8(ck, ksc[..., None, None]))
    out["v"] = kv["v"].at[:, page_row].set(
        _precision.quantize_fp8(cv, vsc[..., None, None]))
    out["k_scale"] = kv["k_scale"].at[:, page_row].set(ksc)
    out["v_scale"] = kv["v_scale"].at[:, page_row].set(vsc)
    return out


def append_token(kv, layer: int, page_idx, offset, k, v):
    """Write one DECODE position's K/V per lane: lane ``s`` lands at
    ``(layer, page_idx[s], :, offset[s])``. Inactive slots' page_idx
    must already point at the null page.

    fp8 scale rule (frozen-at-page-start): a lane writing ``offset ==
    0`` is the first entry of a fresh page and mints the page's scale
    from its own absmax; every later offset REUSES the stored scale —
    rescaling a partially-filled page would corrupt the entries
    already quantized under the old scale. The absmax of one token
    only estimates the page's range, so later outlier tokens clip at
    ±448 (bounded error) instead of silently breaking earlier ones.
    """
    out = dict(kv)
    if not _is_fp8(kv):
        out["k"] = kv["k"].at[layer, page_idx, :, offset].set(
            k.astype(kv["k"].dtype))
        out["v"] = kv["v"].at[layer, page_idx, :, offset].set(
            v.astype(kv["v"].dtype))
        return out
    fresh = (offset == 0)[:, None]

    def one(pool, scales, x):
        xf = x.astype(jnp.float32)                       # [S, H, hd]
        cand = _precision.fp8_scale(jnp.max(jnp.abs(xf), axis=-1))
        sc = jnp.where(fresh, cand, scales[layer, page_idx])  # [S, H]
        q = _precision.quantize_fp8(xf, sc[..., None])
        return (pool.at[layer, page_idx, :, offset].set(q),
                scales.at[layer, page_idx].set(sc))

    out["k"], out["k_scale"] = one(kv["k"], kv["k_scale"], k)
    out["v"], out["v_scale"] = one(kv["v"], kv["v_scale"], v)
    return out


def append_suffix(kv, layer: int, page_idx, offset, k, v, *,
                  chunk=None, real=None, table=None):
    """Write a PREFIX-PREFILL suffix's K/V: one lane per suffix
    position, consecutive positions, padded lanes pointing at the null
    page. Identical to :func:`append_token` for float pools.

    fp8 pools need page-granular scales over lanes that SHARE pages:
    ``chunk`` ([B], this lane's table row, or P for padded lanes),
    ``real`` ([B], lane < true prompt length) and ``table`` ([P], the
    slot's page table) drive a segment-max absmax per touched page. A
    page whose offset-0 lane is in this batch mints a fresh scale
    (exact over every lane it receives here; decode continues it
    frozen); a page entered mid-way (the resume boundary page, already
    committed by the prefix-cache hit) keeps its stored scale.
    """
    if not _is_fp8(kv):
        return append_token(kv, layer, page_idx, offset, k, v)
    P = table.shape[0]
    seg = chunk  # [B]; padded lanes carry the trash segment P
    started = jax.ops.segment_max(
        jnp.where(real & (offset == 0), 1, 0), seg,
        num_segments=P + 1)[:P] > 0                       # [P]
    out = dict(kv)

    def one(pool, scales, x):
        xf = x.astype(jnp.float32)                       # [B, H, hd]
        am = jnp.where(real[:, None],
                       jnp.max(jnp.abs(xf), axis=-1), 0.0)
        am_pg = jax.ops.segment_max(am, seg,
                                    num_segments=P + 1)[:P]  # [P, H]
        cur = scales[layer, table]
        sc_pg = jnp.where(started[:, None],
                          _precision.fp8_scale(am_pg), cur)
        sc = jnp.where(real[:, None],
                       sc_pg[jnp.minimum(chunk, P - 1)], 1.0)
        q = _precision.quantize_fp8(xf, sc[..., None])
        return (pool.at[layer, page_idx, :, offset].set(q),
                scales.at[layer, table].set(sc_pg))

    out["k"], out["k_scale"] = one(kv["k"], kv["k_scale"], k)
    out["v"], out["v_scale"] = one(kv["v"], kv["v_scale"], v)
    return out


def append_spec(kv, layer: int, page_idx, offset, k, v, *,
                chunk=None, real=None, tables=None):
    """Write one VERIFY dispatch's K/V: ``W = k_drafts + 1`` lanes per
    slot at consecutive positions (``[S, W]`` index arrays, ``[S, W,
    H, hd]`` values), padded/inactive lanes pointing at the null page.
    The batched, multi-slot sibling of :func:`append_suffix`.

    Rewind contract (speculative decoding): lanes past the accepted
    prefix wrote K/V that the engine's position rollback
    (:func:`spec_rewind`) makes invisible — attention only admits flat
    position ``<= query pos`` — and the NEXT dispatch overwrites those
    exact (page, offset) cells because positions are consecutive. No
    device-side cleanup ever runs.

    fp8 scale composition with that rollback: ``chunk`` ([S, W], the
    lane's table row, or P for padded lanes), ``real`` ([S, W]) and
    ``tables`` ([S, P]) drive a per-(slot, page) segment-max absmax,
    exactly :func:`append_suffix` per slot. A page whose offset-0 lane
    is real in this batch mints a fresh scale; others keep their
    stored scale. A scale minted partly from later-REJECTED lanes
    merely over-covers the values that replace them (bounded
    quantization error, the same ±448 clip bound as
    :func:`append_token`'s one-token mint) — and when the rollback
    lands back ON the page's offset 0, the overwriting dispatch
    re-mints the scale fresh, so rejected garbage never outlives the
    page's first committed entry. The ``tables`` scatter may carry the
    same page in several slots' rows (prefix-cache sharing); those
    duplicates are value-identical writes — shared pages are read-only
    for every slot (writers own their pages at refcount 1), so their
    scale rows always re-write the stored value."""
    if not _is_fp8(kv):
        out = dict(kv)
        out["k"] = kv["k"].at[layer, page_idx, :, offset].set(
            k.astype(kv["k"].dtype))
        out["v"] = kv["v"].at[layer, page_idx, :, offset].set(
            v.astype(kv["v"].dtype))
        return out
    S, W = real.shape
    P = tables.shape[1]
    # per-(slot, page) segments: slot s's table row c -> s*(P+1) + c,
    # padded lanes -> the slot's trash segment s*(P+1) + P
    segf = (jnp.arange(S, dtype=jnp.int32)[:, None] * (P + 1)
            + chunk).reshape(-1)
    started = jax.ops.segment_max(
        jnp.where(real & (offset == 0), 1, 0).reshape(-1), segf,
        num_segments=S * (P + 1)).reshape(S, P + 1)[:, :P] > 0  # [S, P]
    out = dict(kv)

    def one(pool, scales, x):
        xf = x.astype(jnp.float32)                     # [S, W, H, hd]
        H = xf.shape[2]
        am = jnp.where(real[..., None],
                       jnp.max(jnp.abs(xf), axis=-1), 0.0)  # [S, W, H]
        am_pg = jax.ops.segment_max(
            am.reshape(S * W, H), segf,
            num_segments=S * (P + 1)).reshape(S, P + 1, H)[:, :P]
        cur = scales[layer, tables]                        # [S, P, H]
        sc_pg = jnp.where(started[..., None],
                          _precision.fp8_scale(am_pg), cur)
        sc = jnp.take_along_axis(
            sc_pg, jnp.minimum(chunk, P - 1)[..., None], axis=1)
        sc = jnp.where(real[..., None], sc, 1.0)           # [S, W, H]
        q = _precision.quantize_fp8(xf, sc[..., None])
        return (pool.at[layer, page_idx, :, offset].set(q),
                scales.at[layer, tables].set(sc_pg))

    out["k"], out["k_scale"] = one(kv["k"], kv["k_scale"], k)
    out["v"], out["v_scale"] = one(kv["v"], kv["v_scale"], v)
    return out


def spec_rewind(pos, n_acc):
    """Post-verify position rollback: the new committed extent after a
    verify dispatch accepted ``n_acc[s]`` tokens (accepted drafts + the
    correction) per slot. Positions ``>= pos + n_acc`` hold the
    REJECTED drafts' K/V — invisible to attention (flat position ``<=
    query pos``) and overwritten in place by the next dispatch, so the
    rollback is this one addition: no page is freed, no cell is
    cleared, CoW/prefix/session sharing is untouched (verify only ever
    writes pages the slot exclusively owns)."""
    return pos + n_acc


def gather_pages(pool, layer: int, tables) -> jnp.ndarray:
    """Each slot's pages in page-major layout ``[S, P, H, ps, hd]``:
    flat position ``p*page_size + o`` of slot ``s`` lives at
    ``[s, p, :, o]`` (table row order IS position order — what makes
    the position mask a plain ``<= pos``). Kept page-major so the
    attention einsums contract ``(p, o)`` directly instead of paying a
    transpose+reshape copy of the whole cache per layer per step."""
    return pool[layer][tables]


def copy_page(kv, src, dst):
    """Copy-on-write step: duplicate page ``src`` into ``dst`` across
    every layer of the whole KV tree — scale rows travel with their
    page, so a CoW clone of an fp8 page dequantizes identically to its
    source. ``src``/``dst`` are traced scalars so ONE compiled program
    serves every copy. The caller then swaps its page-table entry to
    ``dst`` and drops its reference on ``src`` — readers of ``src``
    never observe the writer's divergence."""
    out = {"k": kv["k"].at[:, dst].set(kv["k"][:, src]),
           "v": kv["v"].at[:, dst].set(kv["v"][:, src])}
    if _is_fp8(kv):
        out["k_scale"] = kv["k_scale"].at[:, dst].set(
            kv["k_scale"][:, src])
        out["v_scale"] = kv["v_scale"].at[:, dst].set(
            kv["v_scale"][:, src])
    return out


def handoff_commit(kv, ks, vs, page_row, page_size: int, n_valid=None):
    """Cross-pool page handoff: scatter K/V computed by ANOTHER
    executable stream (the fleet's disaggregated prefill lane) into
    this pool's pages. The lane runs the prompt forward on its own
    thread and hands over the raw per-layer stacks — immutable jax
    arrays, so the snapshot stays valid however far the lane has moved
    on — and the destination engine commits them between decode bursts
    with this one cheap scatter instead of re-running the bucket-padded
    prefill. Same layout contract as ``commit_prefill`` (real pages for
    owned chunks, null page 0 for the padded tail); the dtype cast —
    or fp8 quantization with freshly minted scales — to the
    destination pool's format happens inside."""
    return commit_prefill(kv, ks, vs, page_row, page_size,
                          n_valid=n_valid)


def pages_needed(total_positions: int, page_size: int) -> int:
    return -(-int(total_positions) // int(page_size))


__all__ = ["PagePool", "commit_prefill", "append_token",
           "append_suffix", "append_spec", "spec_rewind",
           "gather_pages", "copy_page", "handoff_commit",
           "pages_needed"]
