"""Paged KV cache: fixed-size page pool + per-slot page tables.

Why pages: ``CausalLM.generate()`` allocates one dense
``[L, N, H, max_len, hd]`` cache per compiled ``(batch, prompt, new)``
shape — every distinct request geometry is a fresh multi-hundred-MB
allocation and a fresh executable. The serving engine instead owns ONE
pool of fixed-size pages shared by every slot:

- ``kpool``/``vpool``: ``[L, n_pages, H, page_size, hd]`` device
  arrays, allocated once at engine startup. Page 0 is the NULL page —
  a scratch target that absorbs writes from inactive slots and from
  the padded tail of prefill commits; it is never read through a valid
  attention position.
- per-slot page table: row ``j`` of a slot's table names the page
  holding absolute positions ``[j*page_size, (j+1)*page_size)`` of
  that slot's sequence. Unallocated tail entries point at the null
  page and are masked by the position check (attention only admits
  flat position ``<= pos``).
- ``PagePool`` is the HOST-side allocator (free list, REFERENCE
  COUNTS, utilization gauge); the device arrays thread functionally
  through the jitted prefill/decode steps and are rebound by the
  engine.

Reference counts are what make cross-request KV reuse safe
(serving/prefix_cache.py, serving/sessions.py): a page can be mapped
read-only into several slots' tables at once — ``alloc`` hands a page
out at refcount 1, ``share`` adds readers, ``free`` DECREMENTS and
only returns the page to the free list when the last reader is gone.
Writers must hold the only reference; a slot about to write into a
shared page takes a private copy first (``copy_page``, the
copy-on-write step) and swaps its table entry.

The jax functions here are pure and shape-static, so the engine's one
decode executable serves every mix of request lengths.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.profiler import telemetry as _telemetry


class PagePool:
    """Host-side refcounting page allocator over the device-resident
    K/V pools.

    ``n_pages`` INCLUDES the reserved null page 0, so the usable
    capacity is ``n_pages - 1`` pages. ``alloc`` returns None when the
    request cannot be satisfied — the scheduler keeps the request
    queued (head-of-line) until eviction frees pages.

    Thread safety: the free list and refcounts are guarded by a lock —
    the scheduler thread allocates/frees, while session release and
    submit-time budget hints may touch refcounts from client threads.
    """

    def __init__(self, n_layers: int, n_heads: int, page_size: int,
                 head_dim: int, n_pages: int, dtype=jnp.bfloat16,
                 engine_id: str = "solo", device=None):
        if page_size < 1 or n_pages < 2:
            raise ValueError(
                f"need page_size >= 1 and n_pages >= 2 (one null page "
                f"+ one usable), got {page_size}/{n_pages}")
        self.page_size = int(page_size)
        self.n_pages = int(n_pages)
        #: ``engine=`` label on the utilization gauges, so N pools in
        #: one process (a serving fleet) stay distinguishable series
        self.engine_id = str(engine_id)
        shape = (n_layers, n_pages, n_heads, page_size, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        if device is not None:
            self.k = jax.device_put(self.k, device)
            self.v = jax.device_put(self.v, device)
        # LIFO free list: recently-freed pages are re-used first, which
        # keeps the hot working set of pages small and cache-friendly
        self._free: List[int] = list(range(n_pages - 1, 0, -1))
        #: page -> live reference count; absent means the page is free
        self._refs: Dict[int, int] = {}
        self._high_water = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------- accounting
    @property
    def capacity(self) -> int:
        return self.n_pages - 1

    @property
    def allocated(self) -> int:
        return self.capacity - len(self._free)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def high_water(self) -> int:
        return self._high_water

    def utilization(self) -> float:
        return self.allocated / max(self.capacity, 1)

    def refcount(self, page: int) -> int:
        """Live references on ``page`` (0 when free)."""
        with self._lock:
            return self._refs.get(int(page), 0)

    def shared_pages(self) -> int:
        """Pages with MORE than one reader (prefix-cache hits mapped
        into live slots, cache+session double holds, ...)."""
        with self._lock:
            return sum(1 for r in self._refs.values() if r > 1)

    def bytes_per_page(self) -> int:
        # k + v, all layers, one page
        per = self.k.size // self.n_pages
        return 2 * per * jnp.dtype(self.k.dtype).itemsize

    # ------------------------------------------------------- allocation
    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` pages at refcount 1 each, or None if the pool can't
        satisfy it (caller keeps the request queued)."""
        with self._lock:
            if n > len(self._free):
                return None
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                self._refs[p] = 1
            self._high_water = max(self._high_water, self.allocated)
        self._gauge()
        return pages

    def share(self, pages: Sequence[int]) -> None:
        """Add one reference per listed page (a page listed twice gains
        two). Every page must currently be live — sharing a free page
        is a use-after-free and raises."""
        with self._lock:
            for p in pages:
                self._check_range(p)
                if int(p) not in self._refs:
                    raise ValueError(
                        f"cannot share free page {int(p)} (not "
                        "currently allocated)")
            for p in pages:
                self._refs[int(p)] += 1
        self._gauge()

    def free(self, pages: Sequence[int]) -> None:
        """Drop one reference per listed page; a page whose last
        reference drops returns to the free list.

        The whole call is validated BEFORE any mutation: out-of-range
        or null-page indices, frees of already-free pages, and
        DUPLICATES WITHIN ONE CALL that exceed the page's live count
        all raise with the free list untouched (a silent bad free is a
        corrupted allocator and, pages being shared now, somebody
        else's KV cache)."""
        with self._lock:
            demand = collections.Counter()
            for p in pages:
                self._check_range(p)
                demand[int(p)] += 1
            for p, n in demand.items():
                have = self._refs.get(p, 0)
                if have == 0:
                    raise ValueError(f"double free of page {p} "
                                     "(already on the free list)")
                if n > have:
                    raise ValueError(
                        f"over-free of page {p}: {n} frees in one call "
                        f"but only {have} live reference(s)")
            for p, n in demand.items():
                left = self._refs[p] - n
                if left == 0:
                    del self._refs[p]
                    self._free.append(p)
                else:
                    self._refs[p] = left
        self._gauge()

    def _check_range(self, p) -> None:
        if not isinstance(p, (int,)) and not hasattr(p, "__index__"):
            raise ValueError(f"page index {p!r} is not an integer")
        p = int(p)
        if not 0 < p < self.n_pages:
            raise ValueError(f"page {p} outside pool (null page 0 "
                             "is never allocated or freed)")

    def _gauge(self) -> None:
        if _telemetry.enabled():
            reg = _telemetry.MetricsRegistry.get_default()
            reg.gauge(
                _telemetry.SERVING_KV_PAGE_UTILIZATION,
                "fraction of KV-cache pages currently allocated to "
                "live requests").set(self.utilization(),
                                     engine=self.engine_id)
            reg.gauge(
                _telemetry.SERVING_SHARED_PAGES,
                "KV pages mapped by more than one reader (prefix-"
                "cache sharing)").set(self.shared_pages(),
                                      engine=self.engine_id)


# ------------------------------------------------------- pure jax ops
def commit_prefill(kpool, vpool, ks, vs, page_row, page_size: int):
    """Scatter one prompt's prefill K/V into its pages.

    ``ks``/``vs``: ``[L, 1, H, B, hd]`` from the parallel-prefill
    forward over the padded prompt (bucket width ``B``, a multiple of
    ``page_size``). ``page_row``: ``[B // page_size]`` page ids — real
    pages for chunks the slot owns, null page 0 for the padded tail
    (garbage written there is never read: positions beyond the true
    prompt length stay masked until the decode loop overwrites them).
    """
    L, one, H, B, hd = ks.shape
    pb = B // page_size
    ck = ks[:, 0].reshape(L, H, pb, page_size, hd).transpose(0, 2, 1, 3, 4)
    cv = vs[:, 0].reshape(L, H, pb, page_size, hd).transpose(0, 2, 1, 3, 4)
    return (kpool.at[:, page_row].set(ck.astype(kpool.dtype)),
            vpool.at[:, page_row].set(cv.astype(vpool.dtype)))


def append_token(kpool, vpool, layer: int, page_idx, offset, k, v):
    """Write one position's K/V per lane: lane ``s`` lands at
    ``(layer, page_idx[s], :, offset[s])``. Lanes are decode slots in
    the decode step (inactive slots' page_idx must already point at the
    null page) and suffix positions in the prefix-prefill step (padded
    positions point at the null page)."""
    return (kpool.at[layer, page_idx, :, offset].set(
                k.astype(kpool.dtype)),
            vpool.at[layer, page_idx, :, offset].set(
                v.astype(vpool.dtype)))


def gather_pages(pool, layer: int, tables) -> jnp.ndarray:
    """Each slot's pages in page-major layout ``[S, P, H, ps, hd]``:
    flat position ``p*page_size + o`` of slot ``s`` lives at
    ``[s, p, :, o]`` (table row order IS position order — what makes
    the position mask a plain ``<= pos``). Kept page-major so the
    attention einsums contract ``(p, o)`` directly instead of paying a
    transpose+reshape copy of the whole cache per layer per step."""
    return pool[layer][tables]


def copy_page(kpool, vpool, src, dst):
    """Copy-on-write step: duplicate page ``src`` into ``dst`` across
    every layer of both pools. ``src``/``dst`` are traced scalars so
    ONE compiled program serves every copy. The caller then swaps its
    page-table entry to ``dst`` and drops its reference on ``src`` —
    readers of ``src`` never observe the writer's divergence."""
    return (kpool.at[:, dst].set(kpool[:, src]),
            vpool.at[:, dst].set(vpool[:, src]))


def handoff_commit(kpool, vpool, ks, vs, page_row, page_size: int):
    """Cross-pool page handoff: scatter K/V computed by ANOTHER
    executable stream (the fleet's disaggregated prefill lane) into
    this pool's pages. The lane runs the prompt forward on its own
    thread and hands over the raw per-layer stacks — immutable jax
    arrays, so the snapshot stays valid however far the lane has moved
    on — and the destination engine commits them between decode bursts
    with this one cheap scatter instead of re-running the bucket-padded
    prefill. Same layout contract as ``commit_prefill`` (real pages for
    owned chunks, null page 0 for the padded tail); the dtype cast to
    the destination pool's dtype happens inside."""
    return commit_prefill(kpool, vpool, ks, vs, page_row, page_size)


def pages_needed(total_positions: int, page_size: int) -> int:
    return -(-int(total_positions) // int(page_size))


__all__ = ["PagePool", "commit_prefill", "append_token",
           "gather_pages", "copy_page", "handoff_commit",
           "pages_needed"]
