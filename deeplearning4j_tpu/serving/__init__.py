"""Production inference serving: continuous-batching decode engine.

``DecodeEngine`` (engine.py) is the server-grade generation path over
``models/gpt.py``'s CausalLM: a fixed-shape slot-based decode step
jitted ONCE and fed by a scheduler that joins new requests into free
slots and evicts finished ones between steps, over a paged KV cache
(kv_pages.py — refcounted pages, so N slots can share one committed
prefix read-only). Cross-request KV reuse: ``PrefixCache``
(prefix_cache.py) indexes committed prompt pages by chained page hash
with copy-on-write divergence, and ``SessionStore`` (sessions.py)
pins a finished conversation's pages for its next turn
(``DecodeEngine(prefix_cache=True, session_capacity=N)``).
Front-ends: ``parallel.wrapper.GenerativeInference``
(ParallelInference-parity submit/stream API) and
``remote.server.JsonModelServer(engine=...)`` (HTTP).
"""

from deeplearning4j_tpu.serving.engine import (
    DecodeEngine, ServingRequest,
)
from deeplearning4j_tpu.serving.kv_pages import PagePool
from deeplearning4j_tpu.serving.prefix_cache import PrefixCache
from deeplearning4j_tpu.serving.sessions import SessionStore

__all__ = ["DecodeEngine", "ServingRequest", "PagePool",
           "PrefixCache", "SessionStore"]
