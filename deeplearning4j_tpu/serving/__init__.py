"""Production inference serving: continuous-batching decode engine.

``DecodeEngine`` (engine.py) is the server-grade generation path over
``models/gpt.py``'s CausalLM: a fixed-shape slot-based decode step
jitted ONCE and fed by a scheduler that joins new requests into free
slots and evicts finished ones between steps, over a paged KV cache
(kv_pages.py). Front-ends: ``parallel.wrapper.GenerativeInference``
(ParallelInference-parity submit/stream API) and
``remote.server.JsonModelServer(engine=...)`` (HTTP).
"""

from deeplearning4j_tpu.serving.engine import (
    DecodeEngine, ServingRequest,
)
from deeplearning4j_tpu.serving.kv_pages import PagePool

__all__ = ["DecodeEngine", "ServingRequest", "PagePool"]
