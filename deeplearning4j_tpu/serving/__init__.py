"""Production inference serving: continuous-batching decode engine.

``DecodeEngine`` (engine.py) is the server-grade generation path over
``models/gpt.py``'s CausalLM: a fixed-shape slot-based decode step
jitted ONCE and fed by a scheduler that joins new requests into free
slots and evicts finished ones between steps, over a paged KV cache
(kv_pages.py — refcounted pages, so N slots can share one committed
prefix read-only). Cross-request KV reuse: ``PrefixCache``
(prefix_cache.py) indexes committed prompt pages by chained page hash
with copy-on-write divergence, and ``SessionStore`` (sessions.py)
pins a finished conversation's pages for its next turn
(``DecodeEngine(prefix_cache=True, session_capacity=N)``).
Scale-out: ``ServingFleet`` (fleet.py) puts N engine replicas behind
one KV-aware router with session affinity, disaggregated prefill
(long prompts on a dedicated lane, K/V handed off via
``kv_pages.handoff_commit``), shared AOT warm pools, and
kill/drain/restart replica lifecycle. Hard capacity rejects raise
``CapacityRejected`` (structured 429 at the HTTP front-end).
Front-ends: ``parallel.wrapper.GenerativeInference``
(ParallelInference-parity submit/stream API; ``replicas=N`` builds a
fleet) and ``remote.server.JsonModelServer(engine=...)`` (HTTP).
Speculative decoding: ``SpecConfig`` (spec_decode.py) drives a
host-side draft + one fixed-shape verify dispatch per burst
(``DecodeEngine(spec_decode=4)``), emitting up to k+1 tokens per
weight read with greedy outputs token-identical to the plain path.
"""

from deeplearning4j_tpu.serving.engine import (
    CapacityRejected, DecodeEngine, ServingRequest,
)
from deeplearning4j_tpu.serving.fleet import FleetRequest, ServingFleet
from deeplearning4j_tpu.serving.kv_pages import PagePool
from deeplearning4j_tpu.serving.prefix_cache import PrefixCache
from deeplearning4j_tpu.serving.sessions import SessionStore
from deeplearning4j_tpu.serving.spec_decode import NGramDraft, SpecConfig

__all__ = ["DecodeEngine", "ServingRequest", "CapacityRejected",
           "ServingFleet", "FleetRequest", "PagePool",
           "PrefixCache", "SessionStore", "SpecConfig", "NGramDraft"]
