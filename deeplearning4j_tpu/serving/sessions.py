"""Sticky sessions: pin a finished request's KV pages for its next turn.

A multi-turn conversation replays its whole history to a stateless
engine every turn; the prefix cache (prefix_cache.py) recovers the
full-page part of that, but the conversation's OWN tail — its last
partial page, its generated tokens — is better than cacheable: it is
known to belong to exactly one client. A sticky session keeps it:

- on finish, a request submitted with a ``session_id`` PINS its pages
  (trimmed to the committed positions) together with the committed
  token history instead of freeing them;
- the next ``submit()`` with the same id whose prompt EXTENDS that
  history resumes decode after prefilling only the new tokens —
  mid-page, via the engine's suffix-prefill program — with zero
  re-prefill of the history;
- a prompt that contradicts the history releases the session and falls
  back to the prefix cache (full history pages were inserted there at
  the first turn, so even the fallback stays warm).

Eviction: TTL (``ttl`` seconds since last use), capacity (oldest
session evicted when ``capacity`` is exceeded), explicit
``release(session_id)``, and — because pinned pages must never starve
live traffic — the engine's admission path evicts the oldest session
when the pool cannot otherwise satisfy a request. Pages are released
through ``PagePool.free`` (refcount decrement), so history pages also
indexed by the prefix cache survive the session's death.

All mutation happens on the engine's scheduler thread except
``release``/``stats`` (client-callable); one lock guards the table.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.profiler import flight_recorder as _flight
from deeplearning4j_tpu.profiler import telemetry as _telemetry


class Session:
    """One pinned conversation: committed token history (prompt +
    generated tokens whose K/V landed in the pool) and the pages
    holding positions ``[0, pos)``."""

    __slots__ = ("session_id", "pages", "tokens", "pos", "created",
                 "last_used", "turns")

    def __init__(self, session_id: str, pages: List[int],
                 tokens: np.ndarray, now: float, turns: int = 1):
        self.session_id = session_id
        self.pages = pages
        self.tokens = tokens
        self.pos = int(tokens.size)
        self.created = now
        self.last_used = now
        self.turns = int(turns)   # finished turns pinned under this id


class SessionStore:
    """TTL + capacity bounded table of pinned sessions."""

    def __init__(self, capacity: int, ttl: float,
                 engine_id: str = "solo"):
        if capacity < 1:
            raise ValueError(f"session capacity must be >= 1, got "
                             f"{capacity}")
        self.capacity = int(capacity)
        self.ttl = float(ttl)
        self.engine_id = str(engine_id)
        self._sessions: "Dict[str, Session]" = {}
        self._lock = threading.Lock()
        self.pinned = 0
        self.resumed = 0
        self.expired = 0
        self.released = 0

    # ------------------------------------------------------------ stats
    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)

    def pinned_pages(self) -> int:
        with self._lock:
            return sum(len(s.pages) for s in self._sessions.values())

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "sessions": len(self._sessions),
                "pinned_pages": sum(len(s.pages)
                                    for s in self._sessions.values()),
                "pinned_total": self.pinned,
                "resumed_total": self.resumed,
                "expired_total": self.expired,
                "released_total": self.released,
            }

    def _gauge(self) -> None:
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().gauge(
                _telemetry.SERVING_PINNED_PAGES,
                "KV pages pinned under sticky sessions awaiting the "
                "next turn").set(
                sum(len(s.pages) for s in self._sessions.values()),
                engine=self.engine_id)

    # ------------------------------------------------------------- pin
    def pin(self, session_id: str, pages: List[int],
            tokens: np.ndarray, pool, turns: int = 1) -> None:
        """Pin a finished request's committed state. Re-pinning an id
        replaces (and frees) the previous pin; past ``capacity`` the
        LEAST-RECENTLY-USED session is evicted."""
        now = time.monotonic()
        with self._lock:
            old = self._sessions.pop(session_id, None)
            if old is not None:
                pool.free(old.pages)
            while len(self._sessions) >= self.capacity:
                lru = min(self._sessions.values(),
                          key=lambda s: s.last_used)
                self._evict_locked(lru, pool, "capacity")
            self._sessions[session_id] = Session(
                session_id, list(pages), np.asarray(tokens, np.int32),
                now, turns=turns)
            self.pinned += 1
            self._gauge()
        _flight.record("session_pin", session_id=str(session_id),
                       pages=len(pages), pos=int(tokens.size),
                       turns=int(turns))

    def peek(self, session_id: str):
        """(pages copy, pos, turns) without removing — the admission
        planner sizes its allocation BEFORE committing to take()."""
        with self._lock:
            s = self._sessions.get(session_id)
            if s is None:
                return None
            return list(s.pages), s.pos, s.turns

    # ----------------------------------------------------------- resume
    def match_pos(self, session_id: str, prompt: np.ndarray) \
            -> Optional[int]:
        """Committed position count if ``prompt`` extends the pinned
        history (resume possible), else None. Read-only — the budget
        hint and the admission planner both use it."""
        with self._lock:
            s = self._sessions.get(session_id)
            if s is None:
                return None
            prompt = np.asarray(prompt, np.int32)
            if prompt.size < s.pos \
                    or not np.array_equal(prompt[:s.pos], s.tokens):
                return None
            return s.pos

    def pages_hint(self, session_id: str) -> int:
        with self._lock:
            s = self._sessions.get(session_id)
            return len(s.pages) if s is not None else 0

    def take(self, session_id: str) -> Optional[Session]:
        """Remove and return the session — its pages (and their
        references) now belong to the caller's slot. ``last_used`` is
        refreshed so a re-pin keeps its LRU position."""
        with self._lock:
            s = self._sessions.pop(session_id, None)
            if s is not None:
                s.last_used = time.monotonic()
                self.resumed += 1
                self._gauge()
        return s

    # --------------------------------------------------------- eviction
    def release(self, session_id: str, pool) -> bool:
        """Explicit client release: free the pinned pages now."""
        with self._lock:
            s = self._sessions.pop(session_id, None)
            if s is None:
                return False
            self._free_locked(s, pool)
            self.released += 1
            self._gauge()
        _flight.record("session_release",
                       session_id=str(session_id))
        return True

    def expire(self, pool, now: Optional[float] = None) -> int:
        """TTL sweep: evict every session idle past ``ttl``."""
        now = time.monotonic() if now is None else now
        n = 0
        with self._lock:
            for s in [s for s in self._sessions.values()
                      if now - s.last_used > self.ttl]:
                del self._sessions[s.session_id]
                self._evict_locked(s, pool, "ttl", pop=False)
                n += 1
        return n

    def evict_oldest(self, pool) -> int:
        """Admission-pressure valve: drop the least-recently-used
        session so a live request can allocate. Returns pages freed."""
        with self._lock:
            if not self._sessions:
                return 0
            lru = min(self._sessions.values(),
                      key=lambda s: s.last_used)
            del self._sessions[lru.session_id]
            freed = len(lru.pages)
            self._evict_locked(lru, pool, "pressure", pop=False)
        return freed

    def clear(self, pool) -> int:
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
            for s in sessions:
                self._free_locked(s, pool)
            self._gauge()
        return len(sessions)

    def _evict_locked(self, s: Session, pool, reason: str,
                      pop: bool = True) -> None:
        if pop:
            self._sessions.pop(s.session_id, None)
        self._free_locked(s, pool)
        self.expired += 1
        self._gauge()
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.SERVING_SESSION_EVICTIONS,
                "sticky sessions evicted (label: ttl | capacity | "
                "pressure)").inc(reason=reason,
                                 engine=self.engine_id)
        _flight.record("session_expire", session_id=str(s.session_id),
                       reason=reason, pages=len(s.pages))

    @staticmethod
    def _free_locked(s: Session, pool) -> None:
        if s.pages:
            pool.free(s.pages)
        s.pages = []


__all__ = ["Session", "SessionStore"]
