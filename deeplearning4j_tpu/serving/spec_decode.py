"""Speculative decoding for the serving engine: draft -> verify.

The decode loop is memory-bound — every emitted token pays one full
read of the (already int8-quantized) weights plus the KV pages. The
paged-attention kernel and the fp8 KV cache cut the KV half of that
traffic; the weight half only amortizes by emitting MORE TOKENS PER
WEIGHT READ. That is what draft–verify buys:

- a cheap **draft** proposes ``k`` candidate tokens per slot
  (host-side n-gram self-draft by default — zero extra device
  programs — or any object with a ``propose`` method, e.g. a
  distilled checkpoint);
- ONE fixed-shape **verify** dispatch scores all ``k+1`` positions
  through the target model (``engine._build_verify_fn``), reusing the
  prefix-prefill/suffix machinery: the paged-attention op already
  handles multi-position suffix queries against a slot's page table;
- :func:`accept_tokens` keeps the longest draft prefix the target
  agrees with and emits one correction/bonus token on top, so every
  verify yields between 1 and ``k+1`` tokens for a single weight read.

Acceptance semantics (the standard rejection-sampling scheme,
specialized to a DETERMINISTIC draft ``q = delta(d_i)``):

- greedy (temperature 0): draft ``d_i`` is accepted iff it equals the
  target argmax at its position — the emitted sequence is EXACTLY the
  plain greedy rollout, token for token, which is why the engine's f32
  greedy token-identity gates carry over verbatim;
- temperature > 0: ``d_i`` is accepted with probability
  ``min(1, p(d_i)/q(d_i)) = p(d_i)``; on the first rejection the
  correction is sampled from the residual ``p`` with ``d_i`` removed
  and renormalized, and when every draft survives a bonus token is
  sampled from the target's next-position distribution. The emitted
  marginal is the target distribution exactly (Leviathan et al.,
  arXiv:2211.17192) — speculation changes latency, never the law.

Rejected drafts need no device-side cleanup: the verify program wrote
their K/V at positions ``>= pos + n_accepted``, the engine's position
rollback (``new_pos = pos + n_accepted``) makes those entries
invisible (attention admits flat position ``<= query pos`` only), and
the next verify overwrites them in place — see
``kv_pages.append_spec`` for the fp8-scale composition.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


class NGramDraft:
    """Prompt-lookup / n-gram self-draft (host-side, zero device
    programs): propose the ``k`` tokens that FOLLOWED the most recent
    earlier occurrence of the history's trailing n-gram, longest match
    first. Greedy rollouts of small models fall into repeating
    attractors quickly, and real text re-uses spans from its own
    prompt (quoting, code, JSON keys), so this trivial draft reaches
    high acceptance exactly where speculation pays — and it is fully
    deterministic, which the per-seed determinism tests pin."""

    def __init__(self, match_len: int = 3):
        if match_len < 1:
            raise ValueError("match_len must be >= 1")
        self.match_len = int(match_len)

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        """``k`` candidate continuations of ``history`` (1-D int
        array of prompt + all emitted tokens). Always returns exactly
        ``k`` tokens; the fallback repeats the last token."""
        h = np.asarray(history, np.int32).ravel()
        L = h.size
        out: Optional[np.ndarray] = None
        for n in range(min(self.match_len, L - 1), 0, -1):
            pat = h[L - n:]
            # candidate starts strictly before the trailing occurrence
            win = np.lib.stride_tricks.sliding_window_view(h, n)[:L - n]
            hits = np.flatnonzero((win == pat).all(axis=1))
            if hits.size:
                j = int(hits[-1]) + n
                out = h[j:j + k]
                break
        if out is None or out.size == 0:
            last = h[-1] if L else np.int32(0)
            return np.full((k,), last, np.int32)
        if out.size < k:
            out = np.concatenate(
                [out, np.full((k - out.size,), out[-1], np.int32)])
        return out.astype(np.int32)


@dataclass
class SpecConfig:
    """Engine-level speculative-decoding configuration.

    k : drafts proposed (and verified) per slot per dispatch — the
        verify program's fixed width is ``k + 1`` and is AOT-warmed
        once per engine under the ``("verify", k)`` key.
    draft : ``"ngram"`` (the built-in self-draft) or any object with
        ``propose(history, k) -> np.ndarray`` — e.g. a wrapper over a
        truncated/distilled variant of the served checkpoint.
    match_len : longest trailing n-gram the n-gram draft matches on
        (ignored for custom draft objects).
    """

    k: int = 4
    draft: Any = "ngram"
    match_len: int = 3

    def __post_init__(self):
        self.k = int(self.k)
        if self.k < 1:
            raise ValueError(f"spec_decode k must be >= 1, got {self.k}")

    @classmethod
    def resolve(cls, spec) -> Optional["SpecConfig"]:
        """Canonicalize the engine's ``spec_decode=`` argument:
        None/False -> off, int -> k drafts of n-gram self-draft,
        "ngram" -> defaults, dict -> kwargs, SpecConfig -> itself."""
        if spec is None or spec is False:
            return None
        if isinstance(spec, cls):
            return spec
        if spec is True:
            return cls()
        if isinstance(spec, int):
            return cls(k=spec)
        if isinstance(spec, str):
            if spec != "ngram":
                raise ValueError(
                    f"unknown spec_decode draft {spec!r} (expected "
                    "'ngram', an int k, a dict, or a SpecConfig)")
            return cls()
        if isinstance(spec, dict):
            return cls(**spec)
        raise ValueError(f"cannot resolve spec_decode={spec!r}")

    def make_draft(self):
        if self.draft == "ngram":
            return NGramDraft(self.match_len)
        if not hasattr(self.draft, "propose"):
            raise ValueError(
                "spec_decode draft must be 'ngram' or expose "
                "propose(history, k)")
        return self.draft


def accept_tokens(logits, drafts, n_draft, keydata, temps):
    """Fixed-shape rejection-sampling acceptance over one verify
    dispatch's logits.

    logits : ``[S, W, V]`` f32 — row ``i`` of slot ``s`` is the
        target's distribution for the token FOLLOWING position
        ``pos[s] + i`` (row 0 scores the pending token's successor,
        row ``i >= 1`` scores the successor of draft ``i``).
    drafts : ``[S, K]`` int32 (``W = K + 1``) — draft column ``i`` is
        scored against logits row ``i``.
    n_draft : ``[S]`` int32, 0..K — real drafts per slot; rows past
        ``n_draft`` are padding and never accepted.
    keydata / temps : per-slot sampling state, same conventions as the
        decode core (temperature <= 0 means greedy).

    Returns ``(out_tokens [S, W], n_acc [S], new_keydata)``:
    ``out_tokens[s, :n_acc[s]]`` are the emitted tokens — the accepted
    draft prefix followed by one correction (greedy: the argmax at the
    first mismatch; sampled: the residual draw) or bonus token. Always
    ``1 <= n_acc <= n_draft + 1``. Key bookkeeping is fixed-shape:
    every slot advances its key exactly once per call regardless of
    acceptance, so replays are deterministic per seed."""
    S, W, V = logits.shape
    K = W - 1
    logits = logits.astype(jnp.float32)
    drafts = drafts.astype(jnp.int32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)      # [S, W]
    keys = jax.random.wrap_key_data(keydata)
    # 2K + 2 subkeys per slot: [carry, u_0..u_{K-1}, r_0..r_{K-1},
    # bonus] — a FIXED split schedule, so acceptance patterns never
    # perturb later randomness
    nk = jax.vmap(lambda kk: jax.random.split(kk, 2 * K + 2))(keys)
    carry = nk[:, 0]
    safe_t = jnp.where(temps > 0, temps, 1.0)[:, None, None]
    scaled = logits / safe_t                                    # [S, W, V]
    # acceptance probability of draft i under a deterministic draft
    # distribution: min(1, p/q) with q = 1 at the draft token -> p(d_i)
    p = jax.nn.softmax(scaled[:, :K], axis=-1)                  # [S, K, V]
    p_draft = jnp.take_along_axis(p, drafts[..., None],
                                  axis=-1)[..., 0]              # [S, K]
    u = jax.vmap(jax.vmap(jax.random.uniform))(nk[:, 1:K + 1])  # [S, K]
    # residual distribution on rejection at i: p with d_i removed,
    # renormalized (categorical over masked logits does exactly that)
    hole = jax.nn.one_hot(drafts, V, dtype=bool)                # [S, K, V]
    residual = jnp.where(hole, -jnp.inf, scaled[:, :K])
    rej = jax.vmap(jax.vmap(jax.random.categorical))(
        nk[:, K + 1:2 * K + 1], residual).astype(jnp.int32)     # [S, K]
    # bonus draw from the row AFTER the last draft (row n_draft)
    bonus_row = jnp.take_along_axis(
        scaled, n_draft[:, None, None], axis=1)[:, 0]           # [S, V]
    bonus = jax.vmap(jax.random.categorical)(
        nk[:, 2 * K + 1], bonus_row).astype(jnp.int32)          # [S]
    idx = jnp.arange(K, dtype=jnp.int32)[None, :]
    ok_greedy = drafts == greedy[:, :K]
    ok_sample = u < p_draft
    ok = jnp.where((temps > 0)[:, None], ok_sample, ok_greedy) \
        & (idx < n_draft[:, None])
    # m = length of the leading accepted prefix (0..n_draft)
    m = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1),
                axis=1).astype(jnp.int32)                       # [S]
    rej_m = jnp.take_along_axis(
        rej, jnp.clip(m, 0, max(K - 1, 0))[:, None], axis=1)[:, 0]
    greedy_m = jnp.take_along_axis(greedy, m[:, None], axis=1)[:, 0]
    corr_sampled = jnp.where(m < n_draft, rej_m, bonus)
    corr = jnp.where(temps > 0, corr_sampled, greedy_m)
    # out row: accepted drafts in columns < m, the correction at m
    # (columns past m are don't-care; fill with the correction)
    cols = jnp.arange(W, dtype=jnp.int32)[None, :]
    drafts_w = jnp.concatenate([drafts, drafts[:, :1]], axis=1) \
        if K else jnp.zeros((S, W), jnp.int32)
    out = jnp.where(cols < m[:, None], drafts_w, corr[:, None])
    return (out.astype(jnp.int32), (m + 1).astype(jnp.int32),
            jax.random.key_data(carry))


__all__ = ["SpecConfig", "NGramDraft", "accept_tokens"]
