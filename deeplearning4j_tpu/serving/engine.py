"""Continuous-batching decode engine over ``models/gpt.py`` CausalLM.

The problem with ``generate()`` as a serving path: it compiles one
program per ``(batch, prompt, new_tokens)`` shape, runs the whole batch
in lockstep until the SLOWEST request finishes, and pays a trace +
XLA compile on the first request of every new shape. This module
replaces it with a server-grade engine:

- **Slot-based fixed-shape decode step, jitted ONCE.** A static batch
  of ``slots`` decode lanes; each slot carries its own KV pages,
  position and sampling state. Throughput is set by slot OCCUPANCY,
  not by the longest request: a finished request's slot is refilled
  from the queue between steps while its neighbors keep decoding.
- **Paged KV cache** (kv_pages.py): one page pool allocated at
  startup; per-slot page tables. No per-shape cache allocations, no
  per-shape executables.
- **AOT warm pool**: startup ``jit(...).lower(...).compile()``s the
  decode step and every prefill bucket (the same lower/compile
  workflow the V5E16_AOT.json projection used), and the engine calls
  the compiled executables directly — the first request never pays a
  trace (``jax.jit``'s call cache is NOT populated by AOT compilation,
  so the warm pool bypasses the jit call path entirely; the
  recompile-detector counters at the ``serving_*`` sites stay 0).
- **int8 weight-only decode** (``quantization="int8"``): decode is
  HBM-bandwidth-bound; the decode step reads int8 weights with
  per-channel scales (nn/precision.py) and dequantizes inside the
  matmul. Prefill (compute-bound) keeps the full-precision weights.

Greedy parity contract (tested): with temperature 0 and no
quantization, every request decoded through the engine — joining and
leaving mid-flight next to arbitrary other requests — produces
token-identical output to a solo ``CausalLM.generate()`` call. The
per-slot math is row-independent and the paged attention masks exactly
the positions the dense cache masks.

Telemetry: request p50/p99 latency + time-to-first-token histograms,
queue-depth / slot-occupancy / KV-page-utilization gauges, warm-pool
hit/miss counters (profiler/telemetry.py ``SERVING_*`` names), all on
``/metrics`` and ``/telemetry``.
"""

from __future__ import annotations

import collections
import itertools
import logging
import queue as _queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.nn.precision import (int8_matmul, quantize_int8,
                                             resolve_kv_dtype)
from deeplearning4j_tpu.ops.paged_attention_pallas import (
    paged_attention, paged_attention_mode)
from deeplearning4j_tpu.profiler import flight_recorder as _flight
from deeplearning4j_tpu.profiler import telemetry as _telemetry
from deeplearning4j_tpu.profiler import tracing as _tracing
from deeplearning4j_tpu.serving import kv_pages
from deeplearning4j_tpu.serving.prefix_cache import PrefixCache
from deeplearning4j_tpu.serving.sessions import SessionStore
from deeplearning4j_tpu.serving.spec_decode import (SpecConfig,
                                                    accept_tokens)

log = logging.getLogger("deeplearning4j_tpu")


# ------------------------------------------------------------ requests
#: PROCESS-wide request ids: the tracing registries and the
#: /v1/serving/requests/<id> lookups key on request_id, so two engines
#: in one process (or an engine restart) must not both mint id 0
_REQUEST_IDS = itertools.count()

#: PROCESS-wide engine ordinals: every SERVING_* metric is labelled
#: ``engine=<id>`` so N engines in one process (a serving fleet, or a
#: test constructing engines back to back) stay distinguishable series
#: instead of merging into one
_ENGINE_IDS = itertools.count()


class CapacityRejected(RuntimeError):
    """Hard capacity reject: the admission queue is full. Carries a
    ``retry_after_s`` hint (derived from recent request latency and
    queue depth) so front-ends can answer with a structured
    429-with-Retry-After instead of an opaque error, and clients can
    back off for a meaningful interval."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class ServingRequest:
    """Handle for one submitted generation request.

    ``result()`` blocks until completion and returns the generated
    tokens (np.int32, length <= max_new_tokens — shorter on EOS).
    ``stream()`` yields tokens as the engine emits them. ``ttft_s`` /
    ``latency_s`` are populated as the request progresses."""

    def __init__(self, request_id: int, prompt: np.ndarray,
                 max_new_tokens: int, temperature: float,
                 eos_id: Optional[int], keydata: np.ndarray,
                 session_id: Optional[str] = None):
        self.request_id = request_id
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.session_id = session_id
        #: prompt tokens whose K/V came from the prefix cache or a
        #: sticky session instead of prefill compute (0 = cold)
        self.cache_hit_tokens = 0
        #: speculative decoding: per-request opt-in/out (None follows
        #: the engine's spec_decode config) and the request's own
        #: draft-token acceptance tally — front-ends echo these as the
        #: response's ``spec`` stats
        self.spec_enabled: Optional[bool] = None
        self.spec_proposed = 0
        self.spec_accepted = 0
        #: conversation turn this request will pin as (resume bumps it)
        self._session_turns = 1
        self._keydata = keydata
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None   # length | eos | error
        self.ttft_s: Optional[float] = None
        self.latency_s: Optional[float] = None
        #: id of the engine serving this request (set at submit) — the
        #: per-replica tag front-ends echo in responses and traces
        self.engine_id: Optional[str] = None
        #: fleet-side mirror (serving/fleet.py FleetRequest): receives
        #: _on_token/_on_finish callbacks. None outside a fleet — the
        #: solo-engine hot path pays one attribute read per token.
        self._sink = None
        #: disaggregated-prefill handoff (ks, vs, bucket, logits) from
        #: the fleet's prefill lane; None for every normal request
        self._handoff = None
        #: per-request trace (profiler/tracing.py) — None with tracing
        #: off; the timeline is served at /v1/serving/requests/<id>
        self._trace = None
        #: the engine serving this request (set at submit) — what makes
        #: ``cancel()`` routable without the caller holding the engine
        self._engine = None
        self._t_submit = time.perf_counter()
        self._stream: "_queue.Queue" = _queue.Queue()
        self._done = threading.Event()
        self._error: Optional[BaseException] = None

    # -- engine side ----------------------------------------------------
    def _push(self, token: int) -> None:
        if self.ttft_s is None:
            self.ttft_s = time.perf_counter() - self._t_submit
        self.tokens.append(token)
        self._stream.put(token)
        sink = self._sink
        if sink is not None:
            sink._on_token(self, token)

    def _finish(self, reason: str,
                error: Optional[BaseException] = None) -> None:
        self.finish_reason = reason
        self._error = error
        self.latency_s = time.perf_counter() - self._t_submit
        if self._trace is not None:
            tn = time.perf_counter()
            self._trace.event("finish", tn, tn, reason=reason,
                              tokens=len(self.tokens))
            _tracing.finish_trace(self._trace, reason=reason)
        self._stream.put(None)            # stream sentinel
        self._done.set()
        sink = self._sink
        if sink is not None:
            sink._on_finish(self, reason, error)

    # -- client side ----------------------------------------------------
    @property
    def trace_id(self) -> Optional[str]:
        return self._trace.trace_id if self._trace is not None else None

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not done within {timeout}s")
        if self._error is not None:
            raise self._error
        return np.asarray(self.tokens, np.int32)

    def cancel(self) -> bool:
        """Abort this request (client-callable, any thread): queued, it
        never runs; decoding, its slot is freed and its KV pages drain
        back to the pool at the scheduler's next pass. The request
        finishes with ``finish_reason="cancelled"`` (trace closed with
        the same reason) and ``result()`` returns the tokens generated
        so far. False when the request already finished — a ``result``
        / ``stream`` timeout no longer leaves the request holding its
        slot and pages forever."""
        eng = self._engine
        if eng is None:
            return False
        return eng.abort(self)

    def stream(self):
        """Yield tokens as they are generated; raises the request's
        error (if any) after the stream ends."""
        while True:
            tok = self._stream.get()
            if tok is None:
                break
            yield tok
        if self._error is not None:
            raise self._error


# ----------------------------------------------------------- warm pool
class _WarmPool:
    """AOT-compiled executables keyed by program name. ``run`` calls
    the warm executable when present (zero trace); otherwise falls back
    to the instrumented jit path, which counts the compile."""

    def __init__(self, engine_id: str = "solo"):
        self._exec: Dict[Any, Any] = {}
        self.engine_id = engine_id
        self.hits = 0
        self.misses = 0
        #: executables adopted from another engine's warm pool (the
        #: fleet's shared-AOT startup) rather than compiled here
        self.adopted = 0
        #: program-registry signature per key (profiler/programs.py) —
        #: populated at compile/adopt time only when the registry was
        #: enabled, so run() attributes dispatches with one dict.get
        self._prog_sig: Dict[Any, str] = {}

    @staticmethod
    def _prog_site(key) -> str:
        return f"serving_{key[0]}"

    def _prog_register(self, key, ex, compile_seconds: float,
                       source: str) -> None:
        """Roofline registry registration (no-op when the registry is
        off; never raises — serving startup must not depend on it)."""
        from deeplearning4j_tpu.profiler import programs as _programs

        if not _programs.enabled():
            return
        sig = f"{key[0]}[{key[1]}]"
        self._prog_sig[key] = sig
        _programs.get_default().register(
            self._prog_site(key), sig, ex, source=source,
            engine=self.engine_id, compile_seconds=compile_seconds)

    def compile(self, key, jitted, *abstract_args) -> None:
        t0 = time.perf_counter()
        ex = self._exec[key] = jitted.lower(*abstract_args).compile()
        self._prog_register(key, ex, time.perf_counter() - t0,
                            "warm_pool")

    def adopt(self, source: "_WarmPool") -> int:
        """Share another engine's AOT executables (same shapes, same
        device): fleet replicas lower+compile ONCE and every further
        same-device replica adopts, so fleet startup does not pay N x
        the warm-pool cost. Returns the number adopted."""
        fresh = {k: v for k, v in source._exec.items()
                 if k not in self._exec}
        self._exec.update(fresh)
        self.adopted += len(fresh)
        for k, ex in fresh.items():
            self._prog_register(k, ex, 0.0, "adopted")
        return len(fresh)

    def __contains__(self, key) -> bool:
        return key in self._exec

    def run(self, key, fallback, *args):
        ex = self._exec.get(key)
        reg = (_telemetry.MetricsRegistry.get_default()
               if _telemetry.enabled() else None)
        if ex is not None:
            self.hits += 1
            if reg:
                reg.counter(_telemetry.SERVING_WARM_HITS,
                            "decode/prefill dispatches served by AOT-"
                            "compiled warm-pool executables").inc(
                    program=str(key[0]), engine=self.engine_id)
            sig = self._prog_sig.get(key)
            if sig is not None:
                # registry was on at compile time: per-dispatch
                # roofline accounting (host wall — decode queueing
                # slack included, see programs.py caveat)
                from deeplearning4j_tpu.profiler import \
                    programs as _programs

                t0 = time.perf_counter()
                out = ex(*args)
                _programs.record_dispatch(
                    self._prog_site(key), sig,
                    time.perf_counter() - t0)
                return out
            return ex(*args)
        self.misses += 1
        if reg:
            reg.counter(_telemetry.SERVING_WARM_MISSES,
                        "dispatches that missed the warm pool and "
                        "took the (compiling) jit path").inc(
                program=str(key[0]), engine=self.engine_id)
        return fallback(*args)


# --------------------------------------------------------- the engine
def prefill_forward(model, params, prompt, t0):
    """The prefill math every serving prefill shares: one batched
    forward over the padded ``[1, B]`` prompt (positions >= t0 are
    causally invisible) returning the per-layer K/V stacks and the
    last REAL position's logits slice. The engine's prefill program
    and the fleet's disaggregated lane BOTH call this, so lane-served
    and engine-served prompts are bit-identical by construction — the
    token-identity gate rests on there being exactly one copy of this
    function."""
    logits, ks, vs = model.forward(params, prompt, return_kv=True)
    last = lax.dynamic_index_in_dim(logits[0], t0 - 1, axis=0,
                                    keepdims=False)
    return ks, vs, last


def device_sds(shape, dtype, device=None) -> jax.ShapeDtypeStruct:
    """Abstract value for AOT lowering, pinned to ``device`` when one
    is given — lowering from unpinned abstracts compiles for the
    process default device and fails placement on any replica living
    elsewhere. Shared by the engine warm pool and the fleet's lane."""
    if device is not None:
        return jax.ShapeDtypeStruct(
            shape, dtype,
            sharding=jax.sharding.SingleDeviceSharding(device))
    return jax.ShapeDtypeStruct(shape, dtype)


class DecodeEngine:
    """Continuous-batching generation server over a CausalLM.

    Parameters
    ----------
    model, params : the CausalLM and its parameter tree.
    slots : static decode-batch width (requests in flight per step).
    page_size : KV-cache page length in positions.
    max_context : per-request position budget (prompt + generated);
        defaults to (and is capped at) ``model.cfg.max_len``.
    n_pages : total KV pool pages (incl. the null page). Default sizes
        the pool so every slot can hold ``max_context`` positions.
    prefill_buckets : prompt padding widths to AOT-compile; default
        powers of two (times page_size granularity) up to max_context.
    quantization : None | "int8" — int8 weight-only decode weights
        (per-channel scales, dequant-in-matmul); prefill stays full
        precision.
    kv_dtype : None | "fp8_e4m3" — quantize the KV-cache PAGES to
        float8_e4m3fn with per-page-per-head fp32 scale planes
        (kv_pages.py): half the page bytes of bf16, so ~2x effective
        KV capacity and half the cache traffic per decode step.
        Quantize-on-commit / dequantize-in-attention; greedy outputs
        agree with the float engine to quantization error (CI gates
        >= 0.99 token agreement), not bit-identically.
    attn_mode : None | "pallas" | "interpret" | "xla" — attention
        implementation for the decode core and the prefix-prefill
        program (ops/paged_attention_pallas.py). None follows
        ``DL4J_TPU_PAGED_ATTN`` / backend auto-detection: the fused
        online-softmax kernel on TPU, the reference einsum pair
        elsewhere. "xla" is op-for-op the pre-kernel engine.
    spec_decode : None | int k | "ngram" | dict | SpecConfig —
        speculative decoding (serving/spec_decode.py): a host-side
        draft proposes up to ``k`` tokens per slot per burst and one
        AOT-warmed fixed-shape VERIFY program scores all ``k+1``
        positions through the target model in a single weight read;
        the accepted prefix plus one correction/bonus token is
        emitted (greedy: exactly the plain rollout, token for token;
        temperature > 0: the target distribution is preserved by
        rejection sampling). None (the default) builds no verify
        program — the engine stays program-for-program identical to
        the spec-less path. Requests opt out per-submit with
        ``spec_decode=False``.
    prefix_cache : index committed prompt pages by chained page hash
        (serving/prefix_cache.py) and serve later prompts' shared
        prefixes from the SAME refcounted pages — copy-on-write on
        mid-page divergence, LRU leaf eviction under page pressure,
        prefill restricted to the uncached suffix. Off (the default)
        keeps the engine bit-identical to the cache-less path.
    session_capacity / session_ttl : > 0 enables sticky sessions
        (serving/sessions.py): a finished request submitted with a
        ``session_id`` pins its pages + token history, and the next
        turn whose prompt extends that history resumes decode after
        prefilling only the new tokens. Bounded by capacity (LRU),
        ttl seconds idle, explicit ``release_session``, and
        admission pressure.
    max_chunk : upper bound (a power of two) on decode steps fused
        into ONE dispatch via lax.scan. The scheduler picks the
        largest power-of-two chunk that cannot overshoot the nearest
        request completion, so join/evict granularity is preserved
        exactly while host dispatch overhead and slot-state transfers
        amortize over up to ``max_chunk`` tokens. 1 disables chunking.
    warm_start : AOT-compile the decode + prefill executables in
        ``start()`` so no request ever pays a trace.
    """

    def __init__(self, model, params, *, slots: int = 8,
                 page_size: int = 16, max_context: Optional[int] = None,
                 n_pages: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 quantization: Optional[str] = None,
                 max_chunk: int = 8,
                 max_queue: int = 512, seed: int = 0,
                 warm_start: bool = True,
                 prefix_cache: bool = False,
                 session_capacity: int = 0,
                 session_ttl: float = 600.0,
                 engine_id: Optional[str] = None,
                 device=None,
                 handoff_threshold: Optional[int] = None,
                 warm_source: Optional["DecodeEngine"] = None,
                 kv_dtype: Optional[str] = None,
                 attn_mode: Optional[str] = None,
                 spec_decode=None):
        cfg = model.cfg
        self.model = model
        #: metric/trace label for this engine (``engine=<id>`` on every
        #: SERVING_* series); auto-minted process-wide when not given
        self.engine_id = (str(engine_id) if engine_id is not None
                          else f"e{next(_ENGINE_IDS)}")
        #: placement for params + KV pools (None = default device, the
        #: pre-fleet path byte-for-byte); a fleet passes one device per
        #: replica
        self._device = device
        #: fleet replica mode: prompts >= this many tokens may arrive
        #: PRE-FILLED from the disaggregated prefill lane
        #: (submit_prepared) — the adopt scatter programs for the
        #: corresponding buckets are built and AOT-warmed. None (the
        #: default) builds none of it.
        self.handoff_threshold = handoff_threshold
        self._warm_source = warm_source
        self.slots = int(slots)
        self.page_size = int(page_size)
        self.max_context = int(min(max_context or cfg.max_len,
                                   cfg.max_len))
        if self.slots < 1:
            raise ValueError("need at least one slot")
        if self.max_context < self.page_size:
            raise ValueError(
                f"max_context {self.max_context} < page_size "
                f"{self.page_size}")
        self.pages_per_slot = kv_pages.pages_needed(self.max_context,
                                                    self.page_size)
        if n_pages is None:
            n_pages = 1 + self.slots * self.pages_per_slot
        self.params = (jax.device_put(params, device)
                       if device is not None else jax.device_put(params))
        self.quantization = quantization
        if quantization not in (None, "int8"):
            raise ValueError(f"unknown quantization {quantization!r} "
                             "(expected None or 'int8')")
        self._decode_params = (self._quantize_decode_params(self.params)
                               if quantization == "int8" else self.params)
        #: canonical kv_dtype (None = pool in the compute dtype) and
        #: the attention implementation, both resolved ONCE here and
        #: baked statically into the step builders — every executable
        #: of this engine uses one consistent path
        self.kv_dtype = resolve_kv_dtype(kv_dtype)
        self._attn_mode = (str(attn_mode) if attn_mode is not None
                           else paged_attention_mode())
        if self._attn_mode not in ("pallas", "interpret", "xla"):
            raise ValueError(
                f"unknown attn_mode {attn_mode!r} (expected None, "
                "'pallas', 'interpret' or 'xla')")
        self.pool = kv_pages.PagePool(
            cfg.n_layers, cfg.n_heads, self.page_size, cfg.head_dim,
            n_pages, dtype=model._cdtype, engine_id=self.engine_id,
            device=device, kv_dtype=self.kv_dtype)
        self.prefill_buckets = self._resolve_buckets(prefill_buckets)
        # sampling-key width follows the process PRNG impl (threefry=2,
        # rbg=4) so keydata shapes match whatever jax.config says
        self._kd_width = int(
            jax.random.key_data(jax.random.key(0)).shape[-1])
        self._base_key = jax.random.key(seed)
        self._req_counter = _REQUEST_IDS   # process-wide, see above
        # sampling keys fold a PER-ENGINE ordinal (not the global
        # request id): two engines built with the same seed must
        # sample identically regardless of process-wide submission
        # history
        self._sample_counter = itertools.count()
        # host-side slot state (the jitted step's small inputs)
        S, P = self.slots, self.pages_per_slot
        self._tables = np.zeros((S, P), np.int32)
        self._pos = np.zeros((S,), np.int32)
        self._tok = np.zeros((S,), np.int32)
        self._temps = np.zeros((S,), np.float32)
        self._keydata = np.zeros((S, self._kd_width), np.uint32)
        self._active = np.zeros((S,), bool)
        self._slot_req: List[Optional[ServingRequest]] = [None] * S
        self._slot_pages: List[List[int]] = [[] for _ in range(S)]
        self._slot_emitted = np.zeros((S,), np.int64)
        # device-side mirrors of the slot state that only changes on
        # join/evict (tables/active/temps): re-uploaded only when dirty
        self._dev_static = None
        # programs: one chunk executable per power-of-two step count
        if max_chunk < 1 or (max_chunk & (max_chunk - 1)):
            raise ValueError(
                f"max_chunk must be a power of two >= 1, got "
                f"{max_chunk}")
        self.max_chunk = int(max_chunk)
        self._chunks = []
        k = 1
        while k <= self.max_chunk:
            self._chunks.append(k)
            k *= 2
        core = self._build_step_core()
        # donate the KV tree (pools + any scale planes): the engine
        # rebinds it from every call's outputs, and without donation
        # XLA must copy the whole cache at every dispatch boundary
        # (the scan inside a chunk already aliases; donation extends
        # that across dispatches)
        self._decode_jits = {
            k: jax.jit(self._make_chunk(core, k), donate_argnums=(1,))
            for k in self._chunks}
        self._decode_fallbacks = {
            k: _telemetry.instrument_jit("serving_decode", fn)
            for k, fn in self._decode_jits.items()}
        self._prefill_jit = jax.jit(self._build_prefill_fn(),
                                    donate_argnums=(1,))
        self._prefill_fallback = _telemetry.instrument_jit(
            "serving_prefill", self._prefill_jit)
        # fleet replica mode: the adopt scatter that commits a prefill
        # lane's handed-off K/V into this engine's pages. Buckets are
        # the prefill buckets a lane-eligible prompt can land in
        # (smallest bucket >= a threshold-sized prompt is itself >=
        # threshold). None of this exists outside a fleet.
        self.handoff_buckets: List[int] = []
        if handoff_threshold is not None:
            if handoff_threshold < 1:
                raise ValueError("handoff_threshold must be >= 1")
            self.handoff_buckets = [
                b for b in self.prefill_buckets
                if b >= int(handoff_threshold)]
            self._adopt_jit = jax.jit(self._build_adopt_fn(),
                                      donate_argnums=(0,))
            self._adopt_fallback = _telemetry.instrument_jit(
                "serving_adopt", self._adopt_jit)
        # cross-request KV reuse (prefix_cache.py / sessions.py). Both
        # ride on the same two extra programs: a SUFFIX prefill that
        # attends through the slot's page table (so cached prefix
        # pages are read, only new positions are computed/written) and
        # the copy-on-write page copy. Neither exists when reuse is
        # off — the cache-less engine stays program-for-program
        # identical to the pre-reuse path.
        self._prefix = (PrefixCache(self.page_size,
                                    engine_id=self.engine_id)
                        if prefix_cache else None)
        self._sessions = (SessionStore(session_capacity, session_ttl,
                                       engine_id=self.engine_id)
                          if session_capacity > 0 else None)
        self._reuse = (self._prefix is not None
                       or self._sessions is not None)
        if self._reuse:
            self._prefix_prefill_jit = jax.jit(
                self._build_prefix_prefill_fn(), donate_argnums=(1,))
            self._prefix_prefill_fallback = _telemetry.instrument_jit(
                "serving_prefix_prefill", self._prefix_prefill_jit)
            self._copy_jit = jax.jit(kv_pages.copy_page,
                                     donate_argnums=(0,))
            self._copy_fallback = _telemetry.instrument_jit(
                "serving_cow_copy", self._copy_jit)
        # speculative decoding (serving/spec_decode.py): a host-side
        # draft proposes k tokens per slot and ONE fixed-shape verify
        # program scores all k+1 positions per weight read. Off (the
        # default) builds NOTHING — the engine's program set stays
        # byte-identical to the spec-less path, same gating discipline
        # as self._reuse above.
        self._spec = SpecConfig.resolve(spec_decode)
        if self._spec is not None:
            self._spec_draft = self._spec.make_draft()
            self._verify_jit = jax.jit(self._build_verify_fn(),
                                       donate_argnums=(1,))
            self._verify_fallback = _telemetry.instrument_jit(
                "serving_verify", self._verify_jit)
        self.n_spec_proposed = 0
        self.n_spec_accepted = 0
        #: verify dispatches summed over the ACTIVE lanes they served —
        #: the denominator of tokens-per-weight-read (a plain decode
        #: lane-step scores exactly 1 token per weight read)
        self.n_verify_lane_steps = 0
        self.n_verify_dispatches = 0
        self._warm = _WarmPool(engine_id=self.engine_id)
        self._warm_start = bool(warm_start)
        # scheduler. max_queue bounds queued + head-of-line-waiting
        # requests together (the scheduler drains the Queue into
        # _waiting between bursts, so the Queue's own maxsize alone
        # would not be a real admission bound).
        self.max_queue = int(max_queue)
        self._queue: "_queue.Queue" = _queue.Queue(maxsize=max_queue)
        self._waiting: "collections.deque" = collections.deque()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._start_lock = threading.Lock()
        self._dead: Optional[BaseException] = None
        #: chaos/drill hook (fleet.kill_replica): the scheduler raises
        #: this at its next loop iteration, exercising the real
        #: engine-death path (evictions, flight incident, re-routing)
        self._poison: Optional[BaseException] = None
        #: chaos hook (profiler/chaos.hang_replica): the scheduler
        #: sleeps this long at its next pass — hung, not dead
        self._hang_s: float = 0.0
        #: requests to cancel at the scheduler's next pass (abort());
        #: client threads add, the scheduler thread drains
        self._aborts: set = set()
        self._abort_lock = threading.Lock()
        #: monotonic clock of the last scheduler progress (admit or
        #: decode burst) — the control plane's stalled-replica signal
        self.last_progress = time.monotonic()
        # stats
        self.n_requests = 0
        self.n_completed = 0
        self.n_steps = 0         # decode steps (tokens per slot-lane)
        self.n_dispatches = 0    # chunked device calls
        self.n_tokens = 0
        self._occupancy_sum = 0.0
        # newest finished requests (id + finish reason + timings), so
        # client logs can join against server traces via stats()
        self._recent: "collections.deque" = collections.deque(maxlen=32)

    # ------------------------------------------------------ construction
    def _resolve_buckets(self, buckets) -> List[int]:
        ps, mc = self.page_size, self.max_context
        if buckets is None:
            buckets, b = [], ps
            while b < mc:
                buckets.append(b)
                b *= 2
            buckets.append(kv_pages.pages_needed(mc, ps) * ps)
        out = sorted({int(b) for b in buckets})
        for b in out:
            if b % ps or b < ps:
                raise ValueError(
                    f"prefill bucket {b} is not a multiple of "
                    f"page_size {ps}")
        return out

    def _quantize_decode_params(self, params):
        """int8 weight-only tree for the decode step: every 2-D matmul
        weight gets per-output-channel scales; tok_emb is per-ROW
        scaled so the same tensor serves the embedding gather (rows)
        and the tied LM head (rows become output channels of x@W.T).
        Biases, norms, positions stay float."""
        def q(w, axis):
            wq = quantize_int8(w, axis=axis)
            return {"q": wq["q"], "s": wq["s"]}   # drop static axis key

        out = {"tok_emb": q(params["tok_emb"], 0),
               "pos_emb": params["pos_emb"],
               "ln_f": params["ln_f"],
               "layers": []}
        for lp in params["layers"]:
            out["layers"].append({
                "ln1": lp["ln1"], "ln2": lp["ln2"],
                "wqkv": q(lp["wqkv"], 1), "bqkv": lp["bqkv"],
                "wo": q(lp["wo"], 1), "bo": lp["bo"],
                "w1": q(lp["w1"], 1), "b1": lp["b1"],
                "w2": q(lp["w2"], 1), "b2": lp["b2"],
            })
        return jax.device_put(out)

    # --------------------------------------------------- jitted programs
    @staticmethod
    def _rows(w, idx, cd):
        """Embedding-row gather, quantization-aware (per-row scales)."""
        if isinstance(w, dict):
            return w["q"][idx].astype(cd) * w["s"][idx][:, None].astype(cd)
        return w.astype(cd)[idx]

    @staticmethod
    def _head(x, w, cd):
        """Tied LM head ``x @ tok_emb.T`` (per-row scales become
        per-output-column scales of the transpose)."""
        if isinstance(w, dict):
            return (x @ w["q"].astype(cd).T) * w["s"].astype(cd)[None, :]
        return x @ w.astype(cd).T

    def _build_step_core(self):
        """One fixed-shape decode step for all S slots. Mirrors
        ``CausalLM._decode_one`` op-for-op (same attention math, same
        residual association, same masking value) so greedy outputs
        are token-identical to the solo path — the only difference is
        that K/V live in paged pools instead of a dense cache. The
        attention itself (page gather + masked softmax + weighted sum)
        dispatches through ops/paged_attention_pallas.py: in "xla"
        mode that is verbatim the einsum pair this core used to
        inline; on TPU the fused kernel walks the page table without
        materializing the gathered pages or the logits tensor."""
        cfg = self.model.cfg
        cd = self.model._cdtype
        S, ps = self.slots, self.page_size
        ln = self.model._ln
        attn = self._attn_mode

        def step(params, kv, tables, pos, tok, keydata, temps):
            x = self._rows(params["tok_emb"], tok, cd) \
                + params["pos_emb"].astype(cd)[pos]
            # inactive/evicted slots carry all-null tables, so their
            # writes land on the null page by construction
            page = tables[jnp.arange(S), pos // ps]
            off = pos % ps
            for li, lp in enumerate(params["layers"]):
                h = ln(x, lp["ln1"])
                qkv = int8_matmul(h, lp["wqkv"], cd) \
                    + lp["bqkv"].astype(cd)
                q, k, v = jnp.split(qkv, 3, axis=-1)
                hs = lambda y: y.reshape(S, cfg.n_heads, 1, cfg.head_dim)
                q, k, v = hs(q), hs(k), hs(v)
                kv = kv_pages.append_token(
                    kv, li, page, off, k[:, :, 0], v[:, :, 0])
                ctx = paged_attention(q, kv, li, tables, pos, mode=attn)
                ctx = ctx.reshape(S, cfg.d_model)
                x = x + int8_matmul(ctx, lp["wo"], cd) \
                    + lp["bo"].astype(cd)
                h = ln(x, lp["ln2"])
                x = x + int8_matmul(
                    jax.nn.gelu(int8_matmul(h, lp["w1"], cd)
                                + lp["b1"].astype(cd)),
                    lp["w2"], cd) + lp["b2"].astype(cd)
            x = ln(x, params["ln_f"])
            logits = self._head(x, params["tok_emb"], cd) \
                .astype(jnp.float32)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            keys = jax.random.wrap_key_data(keydata)
            nk = jax.vmap(jax.random.split)(keys)      # [S, 2] keys
            safe_t = jnp.where(temps > 0, temps, 1.0)
            sampled = jax.vmap(jax.random.categorical)(
                nk[:, 1], logits / safe_t[:, None]).astype(jnp.int32)
            nxt = jnp.where(temps > 0, sampled, greedy)
            return kv, nxt, jax.random.key_data(nk[:, 0])

        return step

    @staticmethod
    def _make_chunk(core, n_steps: int):
        """``n_steps`` decode steps fused into one lax.scan program.
        The scheduler guarantees no active request completes mid-chunk
        (chunk <= min remaining), so the slot roster (tables / active /
        temps) is loop-invariant and only the per-token state (pos /
        tok / keys / pools) carries. A chunk of 1 is the plain step."""

        def chunk(params, kv, tables, pos, active, tok,
                  keydata, temps):
            def body(carry, _):
                kv, pos, tok, kd = carry
                kv, nxt, nkd = core(
                    params, kv, tables, pos, tok, kd, temps)
                pos = pos + active.astype(pos.dtype)
                tok = jnp.where(active, nxt, tok)
                return (kv, pos, tok, nkd), nxt

            (kv, pos, tok, kd), toks = lax.scan(
                body, (kv, pos, tok, keydata), None,
                length=n_steps)
            return kv, toks.T, pos, tok, kd

        return chunk

    def _build_prefill_fn(self):
        """Parallel prefill of one request: batched forward over the
        padded prompt writes every position's K/V into the slot's
        pages; returns the last REAL position's logits (the first
        generated token's distribution). Positions >= t0 see padding
        but are causally invisible to positions < t0, so the committed
        K/V and returned logits are exact."""
        m, ps = self.model, self.page_size

        def prefill(params, kv, prompt, page_row, t0):
            ks, vs, last = prefill_forward(m, params, prompt, t0)
            # t0 bounds the REAL positions: an fp8 pool's page scales
            # are minted from them only, never from padding garbage
            kv = kv_pages.commit_prefill(
                kv, ks, vs, page_row, ps, n_valid=t0)
            return kv, last.astype(jnp.float32)

        return prefill

    def _build_prefix_prefill_fn(self):
        """SUFFIX prefill for a warm-prefix admission: forward over the
        padded new tokens (bucket width ``B``) at absolute positions
        ``t_start..``, attending through the slot's WHOLE page table —
        the cached prefix pages are read in place, and only the suffix
        positions' K/V are computed and scattered (positions past the
        real prompt write to the null page). ``t_start`` may sit
        mid-page (copy-on-write divergence, session resume), which the
        per-position (page, offset) scatter handles for free. The
        attention dispatches through the SAME paged_attention op as
        the decode core (queries at consecutive positions ``t_start +
        i``), so warm greedy outputs stay token-identical to a cold
        prefill."""
        cfg = self.model.cfg
        cd = self.model._cdtype
        P, ps = self.pages_per_slot, self.page_size
        ln = self.model._ln
        attn = self._attn_mode

        def prefill(params, kv, tokens, table, t_start, t0):
            B = tokens.shape[0]
            pos = t_start + jnp.arange(B, dtype=jnp.int32)
            x = params["tok_emb"].astype(cd)[tokens] \
                + params["pos_emb"].astype(cd)[
                    jnp.minimum(pos, cfg.max_len - 1)]
            real = pos < t0
            chunk = jnp.minimum(pos // ps, P - 1)
            page = jnp.where(real, table[chunk], 0)
            off = pos % ps
            # fp8 scale segments: padded lanes land in trash segment P
            seg = jnp.where(real, chunk, P)
            qbase = jnp.reshape(t_start, (1,)).astype(jnp.int32)
            for li, lp in enumerate(params["layers"]):
                h = ln(x, lp["ln1"])
                qkv = h @ lp["wqkv"].astype(cd) + lp["bqkv"].astype(cd)
                q, k, v = jnp.split(qkv, 3, axis=-1)
                hs = lambda y: y.reshape(B, cfg.n_heads, cfg.head_dim)
                q, k, v = hs(q), hs(k), hs(v)
                kv = kv_pages.append_suffix(
                    kv, li, page, off, k, v, chunk=seg, real=real,
                    table=table)
                qq = q.transpose(1, 0, 2)[None]        # [1, H, B, hd]
                ctx = paged_attention(qq, kv, li, table[None], qbase,
                                      mode=attn)
                ctx = ctx[0].transpose(1, 0, 2).reshape(B, cfg.d_model)
                x = x + ctx @ lp["wo"].astype(cd) + lp["bo"].astype(cd)
                h = ln(x, lp["ln2"])
                x = x + jax.nn.gelu(
                    h @ lp["w1"].astype(cd) + lp["b1"].astype(cd)) \
                    @ lp["w2"].astype(cd) + lp["b2"].astype(cd)
            x = ln(x, params["ln_f"])
            logits = (x @ params["tok_emb"].astype(cd).T) \
                .astype(jnp.float32)
            last = lax.dynamic_index_in_dim(
                logits, t0 - 1 - t_start, axis=0, keepdims=False)
            return kv, last

        return prefill

    def _build_adopt_fn(self):
        """Fleet handoff commit: scatter the prefill lane's K/V stacks
        (computed on the lane's own executable stream) into this
        engine's pages. One scatter program per handoff bucket — the
        decode replica pays a page write, never the bucket-padded
        prefill forward itself.

        The float program's signature is exactly the pre-fp8 one; the
        fp8 variant takes the true prompt length ``t0`` as one extra
        traced scalar so the minted page scales ignore the padded
        tail."""
        ps = self.page_size
        if not self.kv_dtype:
            def adopt(kv, ks, vs, page_row):
                return kv_pages.handoff_commit(kv, ks, vs, page_row, ps)
        else:
            def adopt(kv, ks, vs, page_row, t0):
                return kv_pages.handoff_commit(kv, ks, vs, page_row,
                                               ps, n_valid=t0)

        return adopt

    def _build_verify_fn(self):
        """Speculative VERIFY: one fixed-shape dispatch scores the
        pending token plus ``K`` draft tokens per slot — ``W = K + 1``
        consecutive positions ``pos[s]..pos[s]+K`` — through the
        target model, then accepts the longest draft prefix the target
        agrees with (spec_decode.accept_tokens). The multi-position
        machinery is the prefix-prefill suffix path batched over
        slots: per-lane (page, offset) scatter (kv_pages.append_spec),
        attention through the slot's whole page table with the SAME
        paged_attention op the decode core uses (query ``i`` of row
        ``s`` at absolute position ``pos[s] + i``, causal by the
        kernel's flat-position mask), DECODE params — the int8 weight
        read this whole feature exists to amortize happens ONCE for
        all W positions.

        Rollback is positional only: lanes past the accepted prefix
        wrote K/V at positions ``>= new_pos``, which the flat-position
        mask hides and the next dispatch overwrites in place
        (kv_pages.spec_rewind). Greedy rows are token-identical to the
        decode core's by row independence — the same batched-vs-single
        argument the prefix-prefill identity gate already rests on."""
        cfg = self.model.cfg
        cd = self.model._cdtype
        S, ps, P = self.slots, self.page_size, self.pages_per_slot
        ln = self.model._ln
        attn = self._attn_mode
        K = self._spec.k
        W = K + 1

        def emb_rows(w, idx):
            # 2-D token-index variant of self._rows (per-row scales)
            if isinstance(w, dict):
                return w["q"][idx].astype(cd) \
                    * w["s"][idx][..., None].astype(cd)
            return w.astype(cd)[idx]

        def verify(params, kv, tables, pos, active, tok, drafts,
                   n_draft, keydata, temps):
            toks = jnp.concatenate([tok[:, None], drafts], axis=1)
            posw = pos[:, None] \
                + jnp.arange(W, dtype=jnp.int32)[None, :]   # [S, W]
            x = emb_rows(params["tok_emb"], toks) \
                + params["pos_emb"].astype(cd)[
                    jnp.minimum(posw, cfg.max_len - 1)]
            # lane 0 is the pending token (always real while the slot
            # is live); lane i >= 1 is draft i, real up to n_draft.
            # Padded/inactive lanes write to the null page.
            real = (jnp.arange(W, dtype=jnp.int32)[None, :]
                    <= n_draft[:, None]) & active[:, None]
            chunk = jnp.minimum(posw // ps, P - 1)
            page = jnp.where(
                real, jnp.take_along_axis(tables, chunk, axis=1), 0)
            off = posw % ps
            seg = jnp.where(real, chunk, P)
            for li, lp in enumerate(params["layers"]):
                h = ln(x, lp["ln1"])
                qkv = int8_matmul(h, lp["wqkv"], cd) \
                    + lp["bqkv"].astype(cd)
                q, k, v = jnp.split(qkv, 3, axis=-1)
                hs = lambda y: y.reshape(S, W, cfg.n_heads,
                                         cfg.head_dim)
                q, k, v = hs(q), hs(k), hs(v)
                # write BEFORE attending: draft i's scoring must see
                # the K/V of drafts 1..i-1 written this dispatch
                kv = kv_pages.append_spec(
                    kv, li, page, off, k, v, chunk=seg, real=real,
                    tables=tables)
                ctx = paged_attention(q.transpose(0, 2, 1, 3), kv, li,
                                      tables, pos, mode=attn)
                ctx = ctx.transpose(0, 2, 1, 3) \
                    .reshape(S, W, cfg.d_model)
                x = x + int8_matmul(ctx, lp["wo"], cd) \
                    + lp["bo"].astype(cd)
                h = ln(x, lp["ln2"])
                x = x + int8_matmul(
                    jax.nn.gelu(int8_matmul(h, lp["w1"], cd)
                                + lp["b1"].astype(cd)),
                    lp["w2"], cd) + lp["b2"].astype(cd)
            x = ln(x, params["ln_f"])
            logits = self._head(x, params["tok_emb"], cd) \
                .astype(jnp.float32)
            out, n_acc, nkd = accept_tokens(logits, drafts, n_draft,
                                            keydata, temps)
            adv = jnp.where(active, n_acc, 0)
            new_pos = kv_pages.spec_rewind(pos, adv)
            corr = jnp.take_along_axis(
                out, (n_acc - 1)[:, None], axis=1)[:, 0]
            new_tok = jnp.where(active, corr, tok)
            return kv, out, adv, new_pos, new_tok, nkd

        return verify

    # ---------------------------------------------------------- startup
    def start(self) -> "DecodeEngine":
        with self._start_lock:
            if self._thread is not None:
                return self
            if self._dead is not None:
                raise RuntimeError("engine has been shut down")
            # black-box coverage: a crash that kills the process leaves
            # an incident dump with the scheduler's last decisions
            _flight.install_excepthook()
            if self._warm_start:
                self._aot_warmup()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="ServingEngine")
            self._thread.start()
        return self

    def _sds(self, shape, dtype) -> jax.ShapeDtypeStruct:
        return device_sds(shape, dtype, self._device)

    def _abstract(self, tree):
        return jax.tree_util.tree_map(
            lambda a: self._sds(a.shape, a.dtype), tree)

    def _aot_warmup(self) -> None:
        """lower+compile every executable the steady state needs, so
        the first request is served entirely from the warm pool.

        Fleet replicas share one AOT compile: when a same-device
        ``warm_source`` engine was given, its executables are ADOPTED
        (shapes and device identical by construction) and only the
        programs it lacks are compiled here — fleet startup pays the
        warm-pool cost once, not once per replica."""
        src = self._warm_source
        if src is not None and (src._device is self._device
                                or src._device == self._device) \
                and (src.slots, src.page_size, src.max_context,
                     src.quantization, tuple(src.prefill_buckets),
                     src.max_chunk, src._reuse, src.kv_dtype,
                     src._attn_mode,
                     src._spec.k if src._spec else None) \
                == (self.slots, self.page_size, self.max_context,
                    self.quantization, tuple(self.prefill_buckets),
                    self.max_chunk, self._reuse, self.kv_dtype,
                    self._attn_mode,
                    self._spec.k if self._spec else None):
            self._warm.adopt(src._warm)
        S, P, kw = self.slots, self.pages_per_slot, self._kd_width
        i32, u32, f32 = jnp.int32, jnp.uint32, jnp.float32
        sds, _abs = self._sds, self._abstract
        cd = self.model._cdtype
        cfg = self.model.cfg
        with _telemetry.span("serving_aot_warmup",
                             buckets=len(self.prefill_buckets),
                             chunks=len(self._chunks),
                             engine=self.engine_id,
                             adopted=self._warm.adopted):
            kv_abs = _abs(self.pool.tree())
            for k in self._chunks:
                if ("decode", k) in self._warm:
                    continue
                self._warm.compile(
                    ("decode", k), self._decode_jits[k],
                    _abs(self._decode_params), kv_abs,
                    sds((S, P), i32), sds((S,), i32), sds((S,), bool),
                    sds((S,), i32), sds((S, kw), u32), sds((S,), f32))
            for b in self.prefill_buckets:
                if ("prefill", b) in self._warm:
                    continue
                self._warm.compile(
                    ("prefill", b), self._prefill_jit,
                    _abs(self.params), kv_abs, sds((1, b), i32),
                    sds((b // self.page_size,), i32), sds((), i32))
            for b in self.handoff_buckets:
                if ("adopt", b) in self._warm:
                    continue
                kv_sds = sds((cfg.n_layers, 1, cfg.n_heads, b,
                              cfg.head_dim), cd)
                extra = ((sds((), i32),) if self.kv_dtype else ())
                self._warm.compile(
                    ("adopt", b), self._adopt_jit,
                    kv_abs, kv_sds, kv_sds,
                    sds((b // self.page_size,), i32), *extra)
            if self._spec is not None:
                K = self._spec.k
                if ("verify", K) not in self._warm:
                    self._warm.compile(
                        ("verify", K), self._verify_jit,
                        _abs(self._decode_params), kv_abs,
                        sds((S, P), i32), sds((S,), i32),
                        sds((S,), bool), sds((S,), i32),
                        sds((S, K), i32), sds((S,), i32),
                        sds((S, kw), u32), sds((S,), f32))
            if self._reuse:
                if ("cow_copy", 0) not in self._warm:
                    self._warm.compile(
                        ("cow_copy", 0), self._copy_jit,
                        kv_abs, sds((), i32), sds((), i32))
                for b in self.prefill_buckets:
                    if ("prefix_prefill", b) in self._warm:
                        continue
                    self._warm.compile(
                        ("prefix_prefill", b), self._prefix_prefill_jit,
                        _abs(self.params), kv_abs, sds((b,), i32),
                        sds((P,), i32), sds((), i32), sds((), i32))

    # ----------------------------------------------------------- client
    def _validate(self, prompt_ids, max_new_tokens: int) -> np.ndarray:
        """Shape/budget validation shared by submit(),
        submit_prepared() and the fleet front-end (which must reject
        bad requests synchronously, before routing)."""
        prompt = np.asarray(prompt_ids, np.int32)
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]          # [1, t0] convenience
        if prompt.ndim != 1:
            raise ValueError(
                f"submit() takes ONE sequence per call (got shape "
                f"{prompt.shape}); submit each row — the engine "
                "batches across requests")
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = prompt.size + int(max_new_tokens)
        if total > self.max_context:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_context "
                f"({self.max_context})")
        # hard physical bound: every page-table row is a DISTINCT
        # resident page, shared or not — a request whose total
        # footprint exceeds the pool can never be admitted. Pages the
        # request will merely SHARE are accounted at admission instead
        # (_plan_admission allocates only the uncached suffix), so a
        # long-shared-prefix request queues only for the pages it
        # actually consumes.
        if kv_pages.pages_needed(total, self.page_size) \
                > self.pool.capacity:
            raise ValueError(
                f"request needs more KV pages than the pool holds "
                f"({self.pool.capacity}); raise n_pages")
        return prompt

    def retry_after_hint(self) -> float:
        """Seconds a rejected client should wait before retrying:
        median recent request latency scaled by how many queue 'turns'
        are ahead of it — a measured hint, not a constant."""
        lats = [r["latency_ms"] for r in self._recent.copy()
                if r.get("latency_ms")]
        p50_s = (sorted(lats)[len(lats) // 2] / 1e3) if lats else 1.0
        depth = self._queue.qsize() + len(self._waiting)
        return round(min(30.0, max(
            0.05, p50_s * max(1.0, depth / max(self.slots, 1)))), 3)

    def submit(self, prompt_ids, max_new_tokens: int,
               temperature: float = 0.0, eos_id: Optional[int] = None,
               sample_seed: Optional[int] = None,
               session_id: Optional[str] = None,
               spec_decode: Optional[bool] = None,
               _sink=None) -> ServingRequest:
        prompt = self._validate(prompt_ids, max_new_tokens)
        req = self._make_request(prompt, max_new_tokens, temperature,
                                 eos_id, sample_seed, session_id,
                                 _sink, spec_decode=spec_decode)
        self._enqueue(req)
        return req

    def submit_prepared(self, prompt_ids, max_new_tokens: int,
                        temperature: float = 0.0,
                        eos_id: Optional[int] = None,
                        sample_seed: Optional[int] = None,
                        session_id: Optional[str] = None,
                        spec_decode: Optional[bool] = None,
                        handoff=None, lane_span=None,
                        _sink=None) -> ServingRequest:
        """Fleet replica mode: submit a request whose prompt K/V was
        already computed by the disaggregated prefill lane. ``handoff``
        is ``(ks, vs, bucket, last_logits)`` — immutable device arrays
        from the lane's executable plus the host logits of the last
        real position; admission commits them with the AOT adopt
        scatter instead of running prefill. ``lane_span`` carries the
        lane's (t0, t1, bucket) timing for the request's trace."""
        if not self.handoff_buckets:
            raise ValueError(
                "engine built without handoff support (pass "
                "handoff_threshold=)")
        prompt = self._validate(prompt_ids, max_new_tokens)
        req = self._make_request(prompt, max_new_tokens, temperature,
                                 eos_id, sample_seed, session_id,
                                 _sink, spec_decode=spec_decode)
        req._handoff = handoff
        if req._trace is not None and lane_span is not None:
            t0, t1, bucket = lane_span
            req._trace.event("lane_prefill", t0, t1, bucket=bucket)
        self._enqueue(req)
        return req

    def _make_request(self, prompt: np.ndarray, max_new_tokens: int,
                      temperature: float, eos_id, sample_seed,
                      session_id, sink,
                      spec_decode: Optional[bool] = None) \
            -> ServingRequest:
        if self._dead is not None or self._stop.is_set():
            raise RuntimeError("engine has been shut down")
        rid = next(self._req_counter)
        key = (jax.random.key(sample_seed) if sample_seed is not None
               else jax.random.fold_in(self._base_key,
                                       next(self._sample_counter)))
        req = ServingRequest(rid, prompt, max_new_tokens, temperature,
                             eos_id, np.asarray(jax.random.key_data(key)),
                             session_id=session_id)
        req.engine_id = self.engine_id
        req._engine = self
        req.spec_enabled = spec_decode
        if sink is not None:
            # attach BEFORE the queue put: the scheduler may admit and
            # emit tokens the instant the request is visible, and the
            # sink must already know its inner request by then
            req._sink = sink
            sink._attach(req, self)
        req._trace = _tracing.new_trace(
            "serving_request", request_id=rid,
            prompt_tokens=int(prompt.size),
            max_new_tokens=int(max_new_tokens),
            engine=self.engine_id)
        return req

    def _enqueue(self, req: ServingRequest) -> None:
        _flight.record("serving_submit", request_id=req.request_id,
                       engine=self.engine_id,
                       prompt_tokens=int(req.prompt.size),
                       max_new_tokens=int(req.max_new_tokens))
        if self._thread is None:
            self.start()
        # hard capacity bound over queued + head-of-line-waiting (the
        # Queue alone drains into _waiting, so its maxsize is not the
        # real admission depth)
        if self._queue.qsize() + len(self._waiting) >= self.max_queue:
            self._reject(req)
        try:
            self._queue.put_nowait(req)
        except _queue.Full:
            self._reject(req)
        self.n_requests += 1
        if _telemetry.enabled():
            reg = _telemetry.MetricsRegistry.get_default()
            reg.counter(_telemetry.SERVING_REQUESTS,
                        "generation requests submitted").inc(
                engine=self.engine_id)
        # close the submit/shutdown race: if shutdown's final queue
        # drain happened before our put, _stop was set before it — so
        # seeing _stop clear here proves shutdown will drain AFTER us
        if self._stop.is_set():
            err = self._dead or RuntimeError(
                "engine has been shut down")
            while True:
                try:
                    r = self._queue.get_nowait()
                except _queue.Empty:
                    break
                r._finish("error", err)
        self._gauge_queue_depth()

    def _reject(self, req: ServingRequest) -> None:
        """Structured hard capacity reject — with a measured
        retry-after, so front-ends answer 429 instead of stalling the
        client thread on a blocking put."""
        hint = self.retry_after_hint()
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.SERVING_REJECTS,
                "submissions rejected because the admission "
                "queue was full (429 at the HTTP front-end)").inc(
                engine=self.engine_id)
        _flight.record("serving_reject",
                       request_id=req.request_id,
                       engine=self.engine_id,
                       retry_after_s=hint)
        if req._trace is not None:
            _tracing.finish_trace(req._trace, reason="rejected")
        raise CapacityRejected(
            f"admission queue full ({self.max_queue} requests "
            f"waiting); retry after ~{hint}s", retry_after_s=hint)

    def generate(self, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None) -> np.ndarray:
        """Blocking single-request convenience over submit()."""
        return self.submit(prompt_ids, max_new_tokens, temperature,
                           eos_id).result(timeout)

    def prefix_stats(self) -> Dict[str, Any]:
        """Cross-request KV-reuse stats: prefix-cache index counters,
        sharing gauges, sticky-session table (the /v1/serving/
        prefix_cache endpoint body)."""
        out: Dict[str, Any] = {
            "enabled": self._prefix is not None,
            "sessions_enabled": self._sessions is not None,
            "page_size": self.page_size,
        }
        if self._prefix is not None:
            out.update(self._prefix.stats())
            out["shared_pages"] = self.pool.shared_pages()
        if self._sessions is not None:
            out["sessions"] = self._sessions.stats()
        return out

    def release_session(self, session_id: str) -> bool:
        """Explicitly free a sticky session's pinned pages (client-
        callable; thread-safe against the scheduler). True if the id
        was pinned."""
        if self._sessions is None:
            return False
        return self._sessions.release(session_id, self.pool)

    def stats(self) -> Dict[str, Any]:
        return {
            "engine_id": self.engine_id,
            "slots": self.slots,
            "page_size": self.page_size,
            "max_context": self.max_context,
            "quantization": self.quantization,
            "kv_dtype": self.pool.dtype_label,
            "attn_mode": self._attn_mode,
            "prefill_buckets": list(self.prefill_buckets),
            "handoff_buckets": list(self.handoff_buckets),
            "max_chunk": self.max_chunk,
            "requests": self.n_requests,
            "completed": self.n_completed,
            "decode_steps": self.n_steps,
            "dispatches": self.n_dispatches,
            "tokens": self.n_tokens,
            "active_slots": int(self._active.sum()),
            "queued": self._queue.qsize() + len(self._waiting),
            "avg_occupancy": (self._occupancy_sum / self.n_steps
                              if self.n_steps else 0.0),
            "kv_pages": {"capacity": self.pool.capacity,
                         "allocated": self.pool.allocated,
                         "high_water": self.pool.high_water,
                         "shared": self.pool.shared_pages(),
                         "page_bytes": self.pool.bytes_per_page()},
            "warm_pool": {"hits": self._warm.hits,
                          "misses": self._warm.misses,
                          "adopted": self._warm.adopted},
            **({"spec": {
                "k": self._spec.k,
                "verify_dispatches": self.n_verify_dispatches,
                "proposed": self.n_spec_proposed,
                "accepted": self.n_spec_accepted,
                "acceptance": (self.n_spec_accepted
                               / self.n_spec_proposed
                               if self.n_spec_proposed else 0.0),
                # tokens emitted per weight read per decode lane;
                # the plain chunked burst is 1.0 by construction
                "tokens_per_dispatch": (
                    (self.n_spec_accepted + self.n_verify_lane_steps)
                    / self.n_verify_lane_steps
                    if self.n_verify_lane_steps else 0.0),
            }} if self._spec is not None else {}),
            **({"prefix_cache": self.prefix_stats()}
               if self._reuse else {}),
            # newest-first: client logs join on request_id, per-request
            # timelines at /v1/serving/requests/<id> (tracing on).
            # .copy() is one C call (atomic under the GIL) — iterating
            # the live deque would race the scheduler thread's appends
            "recent_requests": list(reversed(self._recent.copy())),
        }

    # ------------------------------------------------------------ abort
    def queue_depth(self) -> int:
        """Requests admitted but not yet in a slot (queued + head-of-
        line waiting) — the engine-level rebalancing signal."""
        return self._queue.qsize() + len(self._waiting)

    def abort(self, request: ServingRequest) -> bool:
        """Cancel ``request`` from any thread (see
        ``ServingRequest.cancel``). The actual teardown — slot freed,
        pages drained to rc0, trace closed ``finish{reason=cancelled}``
        — happens on the scheduler thread at its next pass, so no lock
        is ever taken against a decode dispatch. Returns False when the
        request already finished (or belongs to a dead engine, whose
        teardown already failed it)."""
        if request.done:
            return False
        with self._abort_lock:
            self._aborts.add(request)
        if self._dead is not None:
            # scheduler gone: _fail_pending already (or will have)
            # finished everything — nothing will drain the abort set
            with self._abort_lock:
                self._aborts.discard(request)
            return False
        return True

    def _process_aborts(self) -> None:
        """Scheduler-thread half of ``abort``: evict cancelled slot
        residents, drop cancelled queued/waiting requests."""
        with self._abort_lock:
            if not self._aborts:
                return
            aborts, self._aborts = self._aborts, set()
        # queued requests must be visible in _waiting to be dropped
        while True:
            try:
                self._waiting.append(self._queue.get_nowait())
            except _queue.Empty:
                break
        for req in aborts:
            if req.done:
                continue
            for s in range(self.slots):
                if self._slot_req[s] is req:
                    self._evict(s, "cancelled")
                    break
            else:
                if req in self._waiting:
                    self._waiting.remove(req)
                    _flight.record("serving_cancel",
                                   request_id=req.request_id,
                                   engine=self.engine_id, queued=True)
                    if _telemetry.enabled():
                        _telemetry.MetricsRegistry.get_default() \
                            .histogram(
                                _telemetry.SERVING_REQUEST_LATENCY,
                                "submit -> completion per request"
                            ).observe(
                                time.perf_counter() - req._t_submit,
                                reason="cancelled",
                                engine=self.engine_id)
                    req._finish("cancelled")
        self._gauge_queue_depth()

    # ---------------------------------------------- drain / chaos hooks
    @property
    def idle(self) -> bool:
        """No request in a slot, none queued — drained. Polled by the
        fleet's drain_replica before it shuts a replica down for an
        elastic resize."""
        return (not self._active.any() and self._queue.empty()
                and not self._waiting)

    def drain(self, timeout: Optional[float] = None,
              poll_s: float = 0.01) -> bool:
        """Wait for every queued + in-flight request to finish (the
        caller must have stopped submitting). True when drained; False
        on timeout or engine death."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while not self.idle:
            if self._dead is not None or self._stop.is_set():
                return False
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(poll_s)
        return True

    def _die(self, error: BaseException) -> None:
        """Chaos hook (fleet kill-a-replica drill): make the scheduler
        raise ``error`` at its next iteration, driving the REAL death
        path — slot evictions, flight-recorder incident, fleet
        re-routing."""
        self._poison = error

    def shutdown(self, timeout: float = 30.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        if self._dead is None:
            self._dead = RuntimeError("engine has been shut down")
        # the scheduler thread is gone (joined above), so running its
        # abort pass here is single-threaded-safe: a cancel that raced
        # shutdown must finish as reason=cancelled with its partial
        # tokens, not as the opaque shutdown error below
        try:
            self._process_aborts()
        except Exception:
            log.exception("abort pass during shutdown failed")
        # safe to fail whatever remains
        self._fail_pending(self._dead)
        # drain contract: with every slot failed, releasing the
        # session pins and the cache's own references brings every
        # refcount to 0 — the pool leaves fully free
        if self._sessions is not None:
            self._sessions.clear(self.pool)
        if self._prefix is not None:
            self._prefix.clear(self.pool)
        # stale-series expiry: this engine's gauges (queue depth, slot
        # occupancy, KV utilization, shared/pinned pages) would stay
        # frozen at their last value forever — drop them so
        # serving_snapshot(), /metrics, and SLO rules never evaluate a
        # ghost engine. Counters/histograms stay: cumulative history
        # keeps fleet aggregates correct. LAST in shutdown — the abort
        # pass above still updates the queue-depth gauge.
        _telemetry.retire_engine_series(self.engine_id)

    def __enter__(self) -> "DecodeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # -------------------------------------------------------- scheduler
    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                if self._poison is not None:   # chaos/drill hook
                    raise self._poison
                hang = self._hang_s
                if hang:                       # chaos.hang_replica
                    self._hang_s = 0.0
                    _flight.record("chaos_hang", engine=self.engine_id,
                                   seconds=hang)
                    time.sleep(hang)
                self._process_aborts()
                self._admit_waiting()
                if not self._active.any():
                    try:
                        self._waiting.append(
                            self._queue.get(timeout=0.02))
                    except _queue.Empty:
                        pass
                    continue
                self._decode_step()
        except BaseException as e:       # engine died: strand no one
            self._dead = e
            _flight.incident("serving_engine_died",
                             engine=self.engine_id,
                             error=repr(e)[:400])
            self._fail_pending(e)
        finally:
            if self._dead is None:
                self._dead = RuntimeError("engine has been shut down")

    def _fail_pending(self, err: BaseException) -> None:
        for s in range(self.slots):
            req = self._slot_req[s]
            if req is not None:
                self._evict(s, "error", err)
        pend = list(self._waiting)
        self._waiting.clear()
        while True:
            try:
                pend.append(self._queue.get_nowait())
            except _queue.Empty:
                break
        for req in pend:
            req._finish("error", RuntimeError(
                f"engine stopped before request {req.request_id} "
                f"ran: {err}"))

    def _admit_waiting(self) -> None:
        while True:
            try:
                self._waiting.append(self._queue.get_nowait())
            except _queue.Empty:
                break
        if self._sessions is not None:
            self._sessions.expire(self.pool)     # TTL sweep
        while self._waiting and not self._active.all():
            req = self._waiting[0]
            plan = self._plan_admission(req)
            if plan is None:
                break        # head-of-line waits for evictions
            self._waiting.popleft()
            try:
                self._admit(req, plan)
            except BaseException as e:
                self._release_plan(plan)
                req._finish("error", e)
        self._gauge_queue_depth()

    # ----------------------------------------------- admission planning
    def _shared_pages_hint(self, prompt: np.ndarray,
                           session_id: Optional[str]) -> int:
        """Pages this request would REUSE (pinned session pages,
        cached full-prefix pages) rather than newly allocate — the
        read-only budget hint admission re-resolves authoritatively.
        Exposed for capacity planning ("would this prompt fit right
        now?"): a request consumes only ``pages_needed(total) - hint``
        free pages."""
        if not self._reuse:
            return 0
        if self._sessions is not None and session_id is not None \
                and self._sessions.match_pos(session_id,
                                             prompt) is not None:
            return self._sessions.pages_hint(session_id)
        if self._prefix is not None:
            full = self._prefix.hit_tokens_hint(prompt) // self.page_size
            return min(full, (int(prompt.size) - 1) // self.page_size)
        return 0

    def _alloc_with_evict(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` fresh pages, evicting cold prefix-cache
        entries (LRU leaves with no live readers) and, as a last
        resort, the oldest pinned session — cached/pinned pages must
        never starve a live request. None when the pool is genuinely
        full of live readers (caller keeps the request queued)."""
        if n <= 0:
            return []
        while True:
            pages = self.pool.alloc(n)
            if pages is not None:
                return pages
            freed = 0
            if self._prefix is not None:
                freed += self._prefix.evict(
                    self.pool, n - self.pool.free_pages)
            if self.pool.free_pages < n and self._sessions is not None:
                freed += self._sessions.evict_oldest(self.pool)
                # a session's history pages may double as cache
                # entries; with the session's reference gone another
                # cache pass can reclaim them
                if self._prefix is not None \
                        and self.pool.free_pages < n:
                    freed += self._prefix.evict(
                        self.pool, n - self.pool.free_pages)
            if freed == 0:
                return None

    def _plan_admission(self, req: ServingRequest) \
            -> Optional[Dict[str, Any]]:
        """Resolve where this request's pages come from:

        - ``session``: its ``session_id`` pins a history the prompt
          extends — map the pinned pages, prefill only the new tokens;
        - ``prefix``: the prefix cache holds full (and possibly one
          divergent, copy-on-write) pages of the prompt — share them,
          prefill the suffix;
        - ``cold``: allocate everything, full prefill (the pre-reuse
          path, byte-for-byte).

        Returns None when pages cannot be found even after eviction
        (request stays head-of-line). Every page referenced by the
        returned plan holds a reference for this request;
        ``_release_plan`` undoes that on admission failure."""
        t0 = int(req.prompt.size)
        ps = self.page_size
        total_pages = kv_pages.pages_needed(
            t0 + req.max_new_tokens, ps)
        if req._handoff is not None:
            # disaggregated prefill: the lane already computed the
            # prompt's K/V — allocate the full footprint and commit via
            # the adopt scatter in _admit. Bypasses session/prefix
            # resolution by construction (the router never lanes a
            # session-affine resume).
            pages = self._alloc_with_evict(total_pages)
            if pages is None:
                return None
            return {"kind": "handoff", "rows": pages, "copies": [],
                    "drop_after_copy": [], "t_start": 0,
                    "session": None}
        t_l0 = time.perf_counter()
        plan: Optional[Dict[str, Any]] = None
        if self._sessions is not None and req.session_id is not None:
            plan = self._plan_session(req, t0, total_pages)
            if plan == "retry":
                return None
        if plan is None and self._prefix is not None:
            hit = self._prefix.lookup_acquire(req.prompt, self.pool)
            new = self._alloc_with_evict(total_pages - len(hit.pages))
            if new is None:
                hit.release(self.pool)
                return None
            self._prefix.record(hit)
            copies, drop = [], []
            if hit.cow_src is not None:
                # mid-page divergence: private copy of exactly that
                # page; our acquire-reference on the source drops once
                # the copy is dispatched
                copies = [(hit.cow_src, new[0])]
                drop = [hit.cow_src]
            plan = {"kind": "prefix" if hit.tokens else "cold",
                    "rows": hit.pages + new,
                    "copies": copies, "drop_after_copy": drop,
                    "t_start": hit.tokens, "session": None}
        if plan is None:
            pages = self._alloc_with_evict(total_pages)
            if pages is None:
                return None
            plan = {"kind": "cold", "rows": pages, "copies": [],
                    "drop_after_copy": [], "t_start": 0,
                    "session": None}
        if req._trace is not None and self._reuse:
            req._trace.event("prefix_lookup", t_l0,
                             hit_tokens=plan["t_start"],
                             kind=plan["kind"])
        return plan

    def _plan_session(self, req: ServingRequest, t0: int,
                      total_pages: int):
        """Sticky-session leg of the planner: None = no resumable
        session (fall through to the prefix cache, releasing a
        contradicted pin), "retry" = resumable but pages are short
        (stay head-of-line), else the session plan."""
        sid = req.session_id
        pos = self._sessions.match_pos(sid, req.prompt)
        if pos is None:
            if self._sessions.pages_hint(sid):
                # pinned history contradicts the prompt: the
                # conversation restarted — release the stale pin (its
                # full pages stay reachable through the prefix cache)
                self._sessions.release(sid, self.pool)
            return None
        peeked = self._sessions.peek(sid)
        if peeked is None:         # raced a TTL/capacity eviction
            return None
        peek_pages, peek_pos, _turns = peeked
        ps = self.page_size
        t_start = min(peek_pos, t0 - 1)
        extra_n = total_pages - len(peek_pages)
        idx = t_start // ps
        cow_src = (peek_pages[idx] if idx < len(peek_pages)
                   and self.pool.refcount(peek_pages[idx]) > 1
                   else None)
        # size the allocation BEFORE take(): a head-of-line request
        # stalled on pages must not churn take/pin (and their
        # counters/flight events) every scheduler pass
        new = self._alloc_with_evict(extra_n
                                     + (1 if cow_src is not None else 0))
        if new is None:
            return "retry"         # session stays pinned untouched
        sess = self._sessions.take(sid)
        if sess is None:           # raced an eviction/release
            if new:
                self.pool.free(new)
            return None
        copies, drop = [], []
        rows = list(sess.pages)
        if cow_src is not None:
            # the resume point sits mid-page in a page other readers
            # still map (e.g. it is also a cached full prompt page):
            # write into a private copy instead
            rows[idx] = new[0]
            copies = [(cow_src, new[0])]
            drop = [cow_src]
            new = new[1:]
        rows += new
        # a resume is a reuse hit like any other: the hit-rate and
        # hit-token metrics describe ALL cross-request KV reuse
        if self._prefix is not None:
            self._prefix.record_session(t_start)
        if _telemetry.enabled():
            reg = _telemetry.MetricsRegistry.get_default()
            reg.counter(
                _telemetry.SERVING_PREFIX_HITS,
                "prefix-cache lookups that reused >= 1 committed "
                "page").inc(kind="session", engine=self.engine_id)
            reg.counter(
                _telemetry.SERVING_PREFIX_HIT_TOKENS,
                "prompt tokens served from cached KV pages instead "
                "of prefill compute").inc(t_start,
                                          engine=self.engine_id)
        req._session_turns = sess.turns + 1
        _flight.record("session_resume", session_id=str(sid),
                       engine=self.engine_id,
                       request_id=req.request_id, pos=int(sess.pos),
                       new_tokens=t0 - t_start, turns=sess.turns)
        return {"kind": "session", "rows": rows, "copies": copies,
                "drop_after_copy": drop, "t_start": t_start,
                "session": sess}

    def _release_plan(self, plan: Dict[str, Any]) -> None:
        self.pool.free(plan["rows"] + plan["drop_after_copy"])

    # ---------------------------------------------------------- admit
    def _admit(self, req: ServingRequest, plan: Dict[str, Any]) -> None:
        t0 = int(req.prompt.size)
        ps = self.page_size
        rows: List[int] = plan["rows"]
        t_start: int = plan["t_start"]
        for src, dst in plan["copies"]:
            # copy-on-write BEFORE any write can land in the shared
            # page: concurrent readers of src never see our tokens
            self.pool.rebind(self._warm.run(
                ("cow_copy", 0), self._copy_fallback, self.pool.tree(),
                jnp.asarray(src, jnp.int32),
                jnp.asarray(dst, jnp.int32)))
        if plan["drop_after_copy"]:
            self.pool.free(plan["drop_after_copy"])
            plan["drop_after_copy"] = []
        t_pre = time.perf_counter()
        if plan["kind"] == "handoff":
            # fleet handoff: the prefill lane computed ks/vs/logits on
            # its own executable stream; commit is one page scatter
            ks, vs, bucket, last = req._handoff
            req._handoff = None
            if self._device is not None:
                # cross-device fleet: the lane computed on the default
                # device; land the stacks on this replica's device
                ks = jax.device_put(ks, self._device)
                vs = jax.device_put(vs, self._device)
            page_row = np.zeros((bucket // ps,), np.int32)
            n_real = min(len(rows), bucket // ps)
            page_row[:n_real] = rows[:n_real]
            extra = ((jnp.asarray(t0, jnp.int32),)
                     if self.kv_dtype else ())
            kvt = self._warm.run(
                ("adopt", bucket), self._adopt_fallback,
                self.pool.tree(), ks, vs, jnp.asarray(page_row),
                *extra)
        elif t_start == 0:
            bucket = next((b for b in self.prefill_buckets if b >= t0),
                          kv_pages.pages_needed(t0, ps) * ps)
            prompt = np.zeros((1, bucket), np.int32)
            prompt[0, :t0] = req.prompt
            page_row = np.zeros((bucket // ps,), np.int32)
            n_real = min(len(rows), bucket // ps)
            page_row[:n_real] = rows[:n_real]
            kvt, last = self._warm.run(
                ("prefill", bucket), self._prefill_fallback, self.params,
                self.pool.tree(), jnp.asarray(prompt),
                jnp.asarray(page_row), jnp.asarray(t0, jnp.int32))
        else:
            # warm path: prefill ONLY the uncached suffix, mid-page
            # starts included — attention reads the shared prefix
            # pages through the full table
            sl = t0 - t_start
            bucket = next((b for b in self.prefill_buckets if b >= sl),
                          kv_pages.pages_needed(sl, ps) * ps)
            suffix = np.zeros((bucket,), np.int32)
            suffix[:sl] = req.prompt[t_start:]
            table = np.zeros((self.pages_per_slot,), np.int32)
            table[:len(rows)] = rows
            kvt, last = self._warm.run(
                ("prefix_prefill", bucket),
                self._prefix_prefill_fallback, self.params,
                self.pool.tree(), jnp.asarray(suffix),
                jnp.asarray(table), jnp.asarray(t_start, jnp.int32),
                jnp.asarray(t0, jnp.int32))
        logits = np.asarray(last)
        t_post = time.perf_counter()
        self.pool.rebind(kvt)
        if plan["kind"] == "handoff":
            _telemetry.record_span(
                "serving_handoff", t_pre, t_post,
                metric=_telemetry.SERVING_HANDOFF_SECONDS,
                bucket=bucket, engine=self.engine_id)
        else:
            _telemetry.record_span(
                "serving_prefill", t_pre,
                metric=_telemetry.SERVING_PREFILL_SECONDS,
                bucket=bucket, engine=self.engine_id)
        first = self._sample_first(req, logits)
        s = int(np.flatnonzero(~self._active)[0])
        req.cache_hit_tokens = t_start
        if req._trace is not None:
            req._trace.event("queue_wait", req._t_submit, t_pre)
            req._trace.event("prefill", t_pre, t_post, bucket=bucket,
                             slot=s, hit_tokens=t_start,
                             handoff=plan["kind"] == "handoff")
        _flight.record("serving_admit", request_id=req.request_id,
                       engine=self.engine_id,
                       slot=s, bucket=bucket, pages=len(rows),
                       reuse=plan["kind"], hit_tokens=t_start,
                       queue_ms=round((t_pre - req._t_submit) * 1e3, 3))
        self._slot_req[s] = req
        self._slot_pages[s] = rows
        self._slot_emitted[s] = 0
        self._tables[s] = 0
        self._tables[s, :len(rows)] = rows
        self._pos[s] = t0
        self._tok[s] = first
        self._temps[s] = req.temperature
        self._keydata[s] = req._keydata
        self._active[s] = True
        self._dev_static = None      # roster changed: re-upload
        if self._prefix is not None:
            # index this prompt's full pages (freshly prefilled ones
            # AND, for a session resume, committed history pages) for
            # the next shared-prefix request
            self._prefix.insert(req.prompt, rows, self.pool)
        self._emit(s, first)
        self.last_progress = time.monotonic()
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.SERVING_TOKENS,
                "tokens generated across all requests").inc(
                engine=self.engine_id)

    def _sample_first(self, req: ServingRequest,
                      logits: np.ndarray) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(logits))
        key = jax.random.wrap_key_data(jnp.asarray(req._keydata))
        key, sub = jax.random.split(key)
        req._keydata = np.asarray(jax.random.key_data(key))
        return int(jax.random.categorical(
            sub, jnp.asarray(logits) / req.temperature))

    def _dev_slot_state(self):
        """tables/active/temps change only on join/evict: upload once
        per roster change, not once per dispatch."""
        if self._dev_static is None:
            self._dev_static = (jnp.asarray(self._tables),
                                jnp.asarray(self._active),
                                jnp.asarray(self._temps))
        return self._dev_static

    #: dispatches chained device-to-device per burst before tokens are
    #: fetched and emitted (bounds streaming latency; joins/evictions
    #: can only happen at roster boundaries anyway)
    MAX_BURST_DISPATCHES = 4

    def _spec_burst(self) -> bool:
        """One speculative draft -> verify burst: the host drafts up to
        ``k`` tokens per eligible slot (from the slot's own emitted
        history — the draft must see the newest accepted tokens, which
        is why a verify burst is exactly one dispatch), ONE warm
        verify call scores every slot's ``k+1`` positions through the
        decode weights, and the accepted prefix + correction is
        emitted. Slots whose request opted out (``spec_decode=False``)
        or that have a single token of budget left ride along with
        ``n_draft = 0`` — their lane is op-for-op a plain decode step.

        Returns False when NO active slot is spec-eligible this pass,
        and the caller falls through to the plain chunked burst — a
        fully opted-out roster never pays the wider program."""
        t0 = time.perf_counter()
        active_idx = np.flatnonzero(self._active)
        K = self._spec.k
        S = self.slots
        drafts = np.zeros((S, K), np.int32)
        n_draft = np.zeros((S,), np.int32)
        for s in active_idx:
            req = self._slot_req[int(s)]
            if req.spec_enabled is False:
                continue
            # never draft past the request budget: the correction
            # token always emits, so at most rem - 1 drafts can land
            rem = req.max_new_tokens - int(self._slot_emitted[s])
            nd = min(K, rem - 1)
            if nd <= 0:
                continue
            history = np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int32)])
            prop = np.asarray(self._spec_draft.propose(history, nd),
                              np.int32).ravel()[:nd]
            drafts[s, :prop.size] = prop
            n_draft[s] = prop.size
        if not n_draft.any():
            return False
        tables, active, temps = self._dev_slot_state()
        occupancy = float(len(active_idx)) / S
        (kvt, out, adv, pos, tok, kd) = self._warm.run(
            ("verify", K), self._verify_fallback, self._decode_params,
            self.pool.tree(), tables, jnp.asarray(self._pos), active,
            jnp.asarray(self._tok), jnp.asarray(drafts),
            jnp.asarray(n_draft), jnp.asarray(self._keydata), temps)
        self.pool.rebind(kvt)
        self.n_dispatches += 1
        self.n_verify_dispatches += 1
        # ONE host sync for the whole burst (np.array copies: _admit
        # writes joined slots' state into these buffers in place)
        out = np.asarray(out)
        nacc = np.array(adv)
        self._pos = np.array(pos)
        self._tok = np.array(tok)
        self._keydata = np.array(kd)
        self.n_steps += 1
        self._occupancy_sum += occupancy
        lanes = int(len(active_idx))
        self.n_verify_lane_steps += lanes
        proposed = int(n_draft[active_idx].sum())
        accepted = int((nacc[active_idx] - 1).sum())
        self.n_spec_proposed += proposed
        self.n_spec_accepted += accepted
        _telemetry.record_span(
            "serving_verify", t0,
            metric=_telemetry.SERVING_VERIFY_SECONDS,
            engine=self.engine_id)
        _flight.record("serving_verify", engine=self.engine_id,
                       k=K, lanes=lanes, proposed=proposed,
                       accepted=accepted,
                       occupancy=round(occupancy, 4))
        if _tracing.enabled():
            t_end = time.perf_counter()
            for s in active_idx:
                r = self._slot_req[int(s)]
                if r is not None and r._trace is not None:
                    r._trace.event("verify", t0, t_end, slot=int(s),
                                   proposed=int(n_draft[s]),
                                   accepted=int(nacc[s] - 1))
        if _telemetry.enabled():
            reg = _telemetry.MetricsRegistry.get_default()
            reg.gauge(_telemetry.SERVING_SLOT_OCCUPANCY,
                      "fraction of decode slots occupied by live "
                      "requests this step").set(occupancy,
                                                engine=self.engine_id)
            reg.counter(_telemetry.SERVING_DECODE_STEPS,
                        "fixed-shape decode steps executed").inc(
                engine=self.engine_id)
            if proposed:
                reg.counter(
                    _telemetry.SERVING_SPEC_PROPOSED,
                    "draft tokens proposed to the verify "
                    "program").inc(proposed, engine=self.engine_id)
            if accepted:
                reg.counter(
                    _telemetry.SERVING_SPEC_ACCEPTED,
                    "draft tokens the target model accepted").inc(
                    accepted, engine=self.engine_id)
            if self.n_spec_proposed:
                reg.gauge(
                    _telemetry.SERVING_SPEC_ACCEPTANCE,
                    "cumulative accepted / proposed draft "
                    "tokens").set(
                    self.n_spec_accepted / self.n_spec_proposed,
                    engine=self.engine_id)
            if self.n_verify_lane_steps:
                reg.gauge(
                    _telemetry.SERVING_TOKENS_PER_DISPATCH,
                    "tokens emitted per weight read per decode lane "
                    "(plain decode = 1.0)").set(
                    (self.n_spec_accepted + self.n_verify_lane_steps)
                    / self.n_verify_lane_steps,
                    engine=self.engine_id)
        emitted0 = self.n_tokens
        for s in active_idx:
            req = self._slot_req[int(s)]
            if req is not None:
                req.spec_proposed += int(n_draft[s])
                req.spec_accepted += int(nacc[s] - 1)
            for i in range(int(nacc[s])):
                if not self._active[s]:
                    break          # finished on eos mid-acceptance
                self._emit(int(s), int(out[s, i]))
        self.last_progress = time.monotonic()
        if _telemetry.enabled() and self.n_tokens > emitted0:
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.SERVING_TOKENS,
                "tokens generated across all requests").inc(
                self.n_tokens - emitted0, engine=self.engine_id)
        return True

    def _decode_step(self) -> None:
        """One decode BURST: chain chunk dispatches device-to-device —
        pos/tok/keys flow from one executable's output straight into
        the next call, tokens accumulate as device arrays — and sync
        to the host only when the roster can change: the nearest
        request completion, an active eos_id (completion unpredictable
        -> single chunk), or a queued request that could join a free
        slot."""
        if self._spec is not None and self._spec_burst():
            return
        t0 = time.perf_counter()
        active_idx = np.flatnonzero(self._active)
        min_rem = min(
            self._slot_req[s].max_new_tokens - int(self._slot_emitted[s])
            for s in active_idx)
        has_eos = any(self._slot_req[s].eos_id is not None
                      for s in active_idx)
        free_slots = not self._active.all()
        tables, active, temps = self._dev_slot_state()
        pos = jnp.asarray(self._pos)
        tok = jnp.asarray(self._tok)
        kd = jnp.asarray(self._keydata)
        occupancy = float(len(active_idx)) / self.slots
        chunks: List[Any] = []
        steps = 0
        while True:
            k = 1
            while k * 2 <= min(min_rem - steps, self.max_chunk):
                k *= 2
            (kvt, toks, pos, tok, kd) = self._warm.run(
                ("decode", k), self._decode_fallbacks[k],
                self._decode_params, self.pool.tree(), tables,
                pos, active, tok, kd, temps)
            self.pool.rebind(kvt)
            chunks.append(toks)
            steps += k
            self.n_dispatches += 1
            if has_eos or steps >= min_rem \
                    or len(chunks) >= self.MAX_BURST_DISPATCHES:
                break
            if free_slots and not self._queue.empty():
                break          # a waiting request can join a free slot
        # ONE host sync for the whole burst
        toks = np.concatenate([np.asarray(c) for c in chunks], axis=1)
        # np.array (copy): device views are read-only, and _admit
        # writes newly-joined slots' state into these buffers in place
        self._pos = np.array(pos)
        self._tok = np.array(tok)
        self._keydata = np.array(kd)
        self.n_steps += steps
        self._occupancy_sum += occupancy * steps
        _telemetry.record_span(
            "serving_decode_step", t0,
            metric=_telemetry.SERVING_DECODE_STEP_SECONDS,
            engine=self.engine_id)
        _flight.record("serving_burst", engine=self.engine_id,
                       steps=steps, dispatches=len(chunks),
                       occupancy=round(occupancy, 4))
        if _tracing.enabled():
            t_burst_end = time.perf_counter()
            for s in active_idx:
                r = self._slot_req[int(s)]
                if r is not None and r._trace is not None:
                    r._trace.event("decode_burst", t0, t_burst_end,
                                   tokens=steps, slot=int(s))
        if _telemetry.enabled():
            reg = _telemetry.MetricsRegistry.get_default()
            reg.gauge(_telemetry.SERVING_SLOT_OCCUPANCY,
                      "fraction of decode slots occupied by live "
                      "requests this step").set(occupancy,
                                                engine=self.engine_id)
            reg.counter(_telemetry.SERVING_DECODE_STEPS,
                        "fixed-shape decode steps executed").inc(
                steps, engine=self.engine_id)
        emitted0 = self.n_tokens
        for s in active_idx:
            for k in range(steps):
                if not self._active[s]:
                    break              # finished on eos mid-chunk
                self._emit(int(s), int(toks[s, k]))
        self.last_progress = time.monotonic()
        if _telemetry.enabled() and self.n_tokens > emitted0:
            _telemetry.MetricsRegistry.get_default().counter(
                _telemetry.SERVING_TOKENS,
                "tokens generated across all requests").inc(
                self.n_tokens - emitted0, engine=self.engine_id)

    def _emit(self, s: int, token: int) -> None:
        """Hot loop (up to burst_steps x slots calls between
        dispatches): no registry lookups here except the rare
        first-token TTFT sample — the token counter is bulk-inc'd once
        per burst/admit by the callers."""
        req = self._slot_req[s]
        req._push(token)
        self._slot_emitted[s] += 1
        self.n_tokens += 1
        if self._slot_emitted[s] == 1 and _telemetry.enabled():
            reg = _telemetry.MetricsRegistry.get_default()
            reg.histogram(
                _telemetry.SERVING_TTFT,
                "submit -> first generated token").observe(
                req.ttft_s, engine=self.engine_id)
            if req.cache_hit_tokens:
                reg.histogram(
                    _telemetry.SERVING_WARM_TTFT,
                    "submit -> first token for requests whose prompt "
                    "reused cached KV (prefix-cache or session "
                    "hit)").observe(req.ttft_s, engine=self.engine_id)
        if self._slot_emitted[s] >= req.max_new_tokens:
            self._evict(s, "length")
        elif req.eos_id is not None and token == req.eos_id:
            self._evict(s, "eos")

    def _evict(self, s: int, reason: str,
               error: Optional[BaseException] = None) -> None:
        req = self._slot_req[s]
        if not self._maybe_pin_session(s, req, reason, error):
            self.pool.free(self._slot_pages[s])
        self._slot_req[s] = None
        self._slot_pages[s] = []
        self._slot_emitted[s] = 0
        self._tables[s] = 0      # all-null row: decode writes -> page 0
        self._pos[s] = 0
        self._tok[s] = 0
        self._temps[s] = 0.0
        self._active[s] = False
        self._dev_static = None      # roster changed: re-upload
        self.n_completed += 1
        req._finish(reason, error)
        _flight.record("serving_evict", request_id=req.request_id,
                       engine=self.engine_id,
                       reason=reason, tokens=len(req.tokens))
        self._recent.append({
            "request_id": req.request_id,
            "finish_reason": reason,
            "tokens": len(req.tokens),
            "latency_ms": round(req.latency_s * 1e3, 3)
            if req.latency_s is not None else None,
            "ttft_ms": round(req.ttft_s * 1e3, 3)
            if req.ttft_s is not None else None,
        })
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().histogram(
                _telemetry.SERVING_REQUEST_LATENCY,
                "submit -> completion per request").observe(
                req.latency_s, reason=reason, engine=self.engine_id)

    def _maybe_pin_session(self, s: int, req: ServingRequest,
                           reason: str,
                           error: Optional[BaseException]) -> bool:
        """On a clean finish of a ``session_id`` request, pin the
        COMMITTED state under that id instead of freeing it: the token
        history whose K/V actually landed in the pool (prompt + all
        generated tokens but the last — the final token was emitted,
        never fed back through the decode step) and the pages holding
        it. Pages past the committed extent are freed now."""
        if (error is not None or reason not in ("length", "eos")
                or req.session_id is None or self._sessions is None):
            return False
        pages = self._slot_pages[s]
        history = np.concatenate(
            [req.prompt, np.asarray(req.tokens[:-1], np.int32)])
        keep = kv_pages.pages_needed(history.size, self.page_size)
        if not 0 < keep <= len(pages):
            return False
        if len(pages) > keep:
            self.pool.free(pages[keep:])
        self._sessions.pin(req.session_id, pages[:keep], history,
                           self.pool, turns=req._session_turns)
        return True

    def _gauge_queue_depth(self) -> None:
        if _telemetry.enabled():
            _telemetry.MetricsRegistry.get_default().gauge(
                _telemetry.SERVING_QUEUE_DEPTH,
                "requests waiting for a free decode slot").set(
                len(self._waiting) + self._queue.qsize(),
                engine=self.engine_id)


__all__ = ["DecodeEngine", "ServingRequest", "CapacityRejected"]
