"""History processing for pixel observations (reference: rl4j
IHistoryProcessor / HistoryProcessor + its Configuration —
org/deeplearning4j/rl4j/util/HistoryProcessor.java: frame skip,
crop/rescale, and stacking the last `historyLength` frames into the
network input, the DQN-for-Atari preprocessing).

No OpenCV in this environment (the reference uses JavaCV): rescaling
is area-averaging via reshape-mean (exact for integer factors, the
common ALE 210x160 -> 84x84 path uses crop-to-multiple first), and
grayscale is the standard luma weighting. All numpy, host-side —
this runs in the env-stepping loop, not on the accelerator.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.rl.mdp import MDP


@dataclasses.dataclass
class HistoryProcessorConfiguration:
    history_length: int = 4            # stacked frames fed to the net
    rescaled_width: int = 84
    rescaled_height: int = 84
    crop_top: int = 0                  # croppingHeight offset
    crop_left: int = 0
    skip_frame: int = 4                # act every Nth frame
    normalize: bool = True             # /255 (reference scales uint8
    #                                    frames by 1/255 at train time)


class HistoryProcessor:
    """record(frame) accumulates; get_history() -> [history_length, H, W]
    float32 stack (oldest first), zero-padded until warm."""

    def __init__(self, conf: Optional[HistoryProcessorConfiguration] = None):
        self.conf = conf or HistoryProcessorConfiguration()
        self._frames: deque = deque(maxlen=self.conf.history_length)

    def _to_gray(self, frame: np.ndarray) -> np.ndarray:
        if frame.ndim == 3:
            c = frame.shape[-1]
            if c == 1:          # gym/ALE grayscale convention (H,W,1)
                return frame[..., 0].astype(np.float32)
            if c in (3, 4):     # RGB / RGBA (alpha ignored)
                return frame[..., :3] @ np.asarray(
                    [0.299, 0.587, 0.114], np.float32)
            raise ValueError(
                f"frame has {c} channels; expected (H,W), (H,W,1), "
                "(H,W,3) or (H,W,4)")
        if frame.ndim != 2:
            raise ValueError(f"frame rank {frame.ndim} not supported")
        return frame.astype(np.float32)

    def _rescale(self, g: np.ndarray) -> np.ndarray:
        c = self.conf
        g = g[c.crop_top:, c.crop_left:]
        h, w = g.shape
        th, tw = c.rescaled_height, c.rescaled_width
        if (h, w) == (th, tw):
            return g
        if h < th or w < tw:
            raise ValueError(
                f"frame {h}x{w} smaller than target {th}x{tw}")
        # crop to an integer multiple, then area-average
        fh, fw = h // th, w // tw
        g = g[:fh * th, :fw * tw]
        return g.reshape(th, fh, tw, fw).mean((1, 3))

    def record(self, frame: np.ndarray) -> None:
        g = self._rescale(self._to_gray(np.asarray(frame)))
        if self.conf.normalize:
            g = g / 255.0
        self._frames.append(g)

    def get_history(self) -> np.ndarray:
        c = self.conf
        out = np.zeros((c.history_length, c.rescaled_height,
                        c.rescaled_width), np.float32)
        for i, f in enumerate(self._frames):
            out[c.history_length - len(self._frames) + i] = f
        return out

    def reset(self) -> None:
        self._frames.clear()


class HistoryMDP(MDP):
    """Wraps a pixel MDP with a HistoryProcessor: observations become
    the flattened frame stack, actions repeat for skip_frame steps with
    rewards summed (reference: the learning loop's skip handling)."""

    def __init__(self, inner: MDP,
                 conf: Optional[HistoryProcessorConfiguration] = None):
        self._inner = inner
        self.processor = HistoryProcessor(conf)
        c = self.processor.conf
        self._obs_shape: Tuple[int, ...] = (
            c.history_length, c.rescaled_height, c.rescaled_width)

    @property
    def obs_size(self) -> int:
        return int(np.prod(self._obs_shape))

    @property
    def n_actions(self) -> int:
        return self._inner.n_actions

    def reset(self) -> np.ndarray:
        self.processor.reset()
        self.processor.record(self._inner.reset())
        return self.processor.get_history().reshape(-1)

    def step(self, action: int):
        c = self.processor.conf
        total, done, info = 0.0, False, {}
        for _ in range(max(c.skip_frame, 1)):
            frame, r, done, info = self._inner.step(action)
            total += r
            if done:
                break
        self.processor.record(frame)
        return (self.processor.get_history().reshape(-1), total, done,
                info)

    def close(self) -> None:
        self._inner.close()


__all__ = ["HistoryProcessor", "HistoryProcessorConfiguration",
           "HistoryMDP"]
