"""Experience replay (reference: rl4j org/deeplearning4j/rl4j/learning/
sync/ExpReplay — circular store + uniform minibatch sampling)."""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np


@dataclasses.dataclass
class Transition:
    obs: np.ndarray
    action: int
    reward: float
    next_obs: np.ndarray
    done: bool


class ExpReplay:
    """Preallocated circular buffer; sample() returns stacked arrays
    ready for the jitted update (one host->device transfer per batch)."""

    def __init__(self, max_size: int, obs_size: int,
                 seed: int = 0):
        self.max_size = max_size
        self._obs = np.zeros((max_size, obs_size), np.float32)
        self._act = np.zeros(max_size, np.int32)
        self._rew = np.zeros(max_size, np.float32)
        self._nobs = np.zeros((max_size, obs_size), np.float32)
        self._done = np.zeros(max_size, np.float32)
        self._n = 0
        self._i = 0
        self._rng = np.random.RandomState(seed)

    def __len__(self) -> int:
        return self._n

    def store(self, t: Transition) -> None:
        i = self._i
        self._obs[i] = t.obs
        self._act[i] = t.action
        self._rew[i] = t.reward
        self._nobs[i] = t.next_obs
        self._done[i] = float(t.done)
        self._i = (i + 1) % self.max_size
        self._n = min(self._n + 1, self.max_size)

    def sample(self, batch: int) -> Tuple[np.ndarray, ...]:
        idx = self._rng.randint(0, self._n, size=batch)
        return (self._obs[idx], self._act[idx], self._rew[idx],
                self._nobs[idx], self._done[idx])


__all__ = ["ExpReplay", "Transition"]
