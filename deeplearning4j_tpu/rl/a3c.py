"""Asynchronous advantage actor-critic — the rl4j headline RL feature
(reference: A3CDiscreteDense + AsyncLearning + AsyncGlobal,
org/deeplearning4j/rl4j/learning/async/**).

rl4j's async design: N worker threads each own an env, roll out n
steps against a periodically-synced copy of the global net, compute
gradients locally, and enqueue them at a central AsyncGlobal that
applies them to the shared parameters (Hogwild over a lock). The
TPU-idiomatic translation keeps that actor/learner split exactly —
host threads own the (host-side, latency-bound) env stepping, which
is where asynchrony actually pays — but each worker's math is one
jitted grad call, and the global applies updates under a lock with a
single jitted Adam step:

- workers READ the current global params without any lock (a published
  pytree reference is immutable; torn reads are impossible by
  construction — the JAX arrays in a snapshot never mutate, unlike the
  reference's synchronized copyFromGlobal),
- gradient COMPUTATION runs outside the lock (the async part: stale
  gradients are accepted, same semantics as rl4j's queue),
- gradient APPLICATION is serialized (the AsyncGlobal role).

A2CDiscreteDense (a2c.py) remains the synchronous vector-env variant;
this module is the async one for envs whose step latency dominates —
the regime rl4j built A3C for (gym-java-client round trips).
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import time
import types
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.learning.updaters import Adam, apply_updater
from deeplearning4j_tpu.rl.a2c import actor_critic_loss
from deeplearning4j_tpu.rl.mdp import MDP
from deeplearning4j_tpu.rl.policy import ACPolicy
from deeplearning4j_tpu.rl.qlearning import _init_mlp, _mlp


@functools.lru_cache(maxsize=32)
def _compiled(lr: float, entropy_coef: float, value_coef: float):
    """Jitted probs/value/grads/apply shared across trainer instances
    with the same hyperparameters. Per-instance `jax.jit` closures
    would recompile (~2s on the CPU mesh) for every trainer — more
    than a whole small training run — and XLA caches by function
    identity, so the cache must outlive the instance."""
    updater = Adam(learning_rate=lr)

    def grads_fn(nets, obs, act, ret):
        return jax.value_and_grad(
            lambda n: actor_critic_loss(n, obs, act, ret, value_coef,
                                        entropy_coef))(nets)

    def apply_fn(nets, opt_state, grads, it):
        updates, new_opt = apply_updater(updater, opt_state, grads,
                                         nets, it)
        new_nets = jax.tree_util.tree_map(lambda p, u: p - u, nets,
                                          updates)
        return new_nets, new_opt

    return types.SimpleNamespace(
        updater=updater,
        probs=jax.jit(lambda p, x: jax.nn.softmax(_mlp(p, x), -1)),
        value=jax.jit(lambda p, x: _mlp(p, x)[:, 0]),
        grads=jax.jit(grads_fn),
        apply=jax.jit(apply_fn),
    )


@dataclasses.dataclass
class A3CConfiguration:
    seed: int = 0
    gamma: float = 0.99
    n_step: int = 8                   # rollout length (reference: nstep)
    n_workers: int = 4                # async actor threads (numThread)
    learning_rate: float = 7e-4
    entropy_coef: float = 0.01
    value_coef: float = 0.5
    hidden: tuple = (64,)


class A3CDiscreteDense:
    """Async actor-learner training over a factory of MDP instances.

    Matches A2CDiscreteDense's public surface (getPolicy / train /
    episode_rewards); `train(updates)` is the TOTAL update budget
    consumed jointly by all workers (a shared atomic counter, the
    reference's maxStep role).
    """

    def __init__(self, mdp_factory: Callable[[], MDP],
                 conf: Optional[A3CConfiguration] = None):
        self.conf = c = conf or A3CConfiguration()
        self._mdp_factory = mdp_factory
        probe = mdp_factory()
        key = jax.random.key(c.seed)
        k1, k2 = jax.random.split(key)
        trunk = (probe.obs_size,) + tuple(c.hidden)
        self._nets = {"actor": _init_mlp(k1, trunk + (probe.n_actions,)),
                      "critic": _init_mlp(k2, trunk + (1,))}
        probe.close()
        fns = _compiled(c.learning_rate, c.entropy_coef, c.value_coef)
        self._updater = fns.updater
        self._probs, self._value = fns.probs, fns.value
        self._grads, self._apply = fns.grads, fns.apply
        self._opt_state = self._updater.init_state(self._nets)
        self._it = 0
        self._lock = threading.Lock()
        self.episode_rewards: List[float] = []

    def getPolicy(self, greedy: bool = True) -> ACPolicy:
        actor = self._nets["actor"]
        return ACPolicy(
            lambda x: np.asarray(self._probs(actor, jnp.asarray(x))),
            greedy=greedy, seed=self.conf.seed)

    # -- the worker loop (reference: A3CThreadDiscrete.trainSubEpoch) --
    def _worker(self, wid: int, budget: "_Counter"):
        c = self.conf
        rng = np.random.RandomState(c.seed * 9973 + wid)
        env = self._mdp_factory()
        obs = env.reset()
        ep_r = 0.0
        while budget.take():
            # Lock-free snapshot: the published pytree is immutable.
            nets = self._nets
            t_obs, t_act, t_rew, t_done = [], [], [], []
            for _ in range(c.n_step):
                probs = np.asarray(self._probs(
                    nets["actor"], jnp.asarray(obs[None])))[0]
                a = int(rng.choice(len(probs), p=probs / probs.sum()))
                nobs, r, d, _info = env.step(a)
                t_obs.append(obs)
                t_act.append(a)
                t_rew.append(r)
                t_done.append(float(d))
                ep_r += r
                if d:
                    with self._lock:
                        self.episode_rewards.append(ep_r)
                    ep_r = 0.0
                    obs = env.reset()
                else:
                    obs = nobs
            last_v = float(np.asarray(self._value(
                nets["critic"], jnp.asarray(obs[None]))[0]))
            rets = np.zeros(len(t_rew), np.float32)
            running = last_v
            for t in reversed(range(len(t_rew))):
                running = t_rew[t] + c.gamma * running * (1 - t_done[t])
                rets[t] = running
            # Gradient outside the lock (stale-by-construction, same
            # semantics as the reference's gradient queue)...
            _loss, grads = self._grads(
                nets, jnp.asarray(np.stack(t_obs)),
                jnp.asarray(np.asarray(t_act, np.int32)),
                jnp.asarray(rets))
            # ...application serialized (the AsyncGlobal role).
            with self._lock:
                self._nets, self._opt_state = self._apply(
                    self._nets, self._opt_state, grads,
                    jnp.asarray(self._it))
                self._it += 1
        env.close()

    def train(self, updates: int = 400) -> List[float]:
        budget = _Counter(updates)
        threads = [threading.Thread(target=self._worker,
                                    args=(w, budget), daemon=True)
                   for w in range(self.conf.n_workers)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.train_seconds = time.perf_counter() - t0
        return self.episode_rewards


class _Counter:
    """Shared atomic update budget (the reference's maxStep/T_max)."""

    def __init__(self, n: int):
        self._n = n
        self._lock = threading.Lock()

    def take(self) -> bool:
        with self._lock:
            if self._n <= 0:
                return False
            self._n -= 1
            return True


__all__ = ["A3CDiscreteDense", "A3CConfiguration"]
