"""Reinforcement learning (reference: rl4j/** — QLearning/DQN, A3C,
policies, MDP abstraction, experience replay. SURVEY.md §2.41).

TPU-first redesign notes:
- The value/policy networks are jax pytrees with ONE jitted update step
  (replay batch in, new params out) instead of rl4j's per-op eager path.
- Two actor-critic trainers, matching the two regimes:
  * A2CDiscreteDense — synchronous vectorized rollouts (K env copies in
    lockstep, one compiled update per rollout). Right when env stepping
    is cheap and the accelerator is the bottleneck.
  * A3CDiscreteDense — the reference's headline ASYNC design (worker
    threads + shared global params behind a lock, stale gradients
    accepted). Right when env step LATENCY dominates (the
    gym-java-client regime rl4j built async learning for); workers
    overlap env waiting, compute stays jitted.
"""

from deeplearning4j_tpu.rl.mdp import MDP, GridWorldMDP, CorridorMDP, SlowMDP
from deeplearning4j_tpu.rl.replay import ExpReplay, Transition
from deeplearning4j_tpu.rl.policy import (
    DQNPolicy, EpsGreedy, Policy, ACPolicy,
)
from deeplearning4j_tpu.rl.qlearning import QLearningDiscreteDense, QLConfiguration
from deeplearning4j_tpu.rl.a2c import A2CDiscreteDense, A2CConfiguration
from deeplearning4j_tpu.rl.a3c import A3CDiscreteDense, A3CConfiguration
from deeplearning4j_tpu.rl.async_nstep_q import (
    AsyncNStepQLearningDiscrete, AsyncNStepQLConfiguration,
)
from deeplearning4j_tpu.rl.history import (
    HistoryMDP, HistoryProcessor, HistoryProcessorConfiguration,
)

__all__ = ["MDP", "GridWorldMDP", "CorridorMDP", "SlowMDP",
           "ExpReplay", "Transition",
           "Policy", "EpsGreedy", "DQNPolicy", "ACPolicy",
           "QLearningDiscreteDense", "QLConfiguration",
           "A2CDiscreteDense", "A2CConfiguration",
           "A3CDiscreteDense", "A3CConfiguration",
           "AsyncNStepQLearningDiscrete", "AsyncNStepQLConfiguration",
           "HistoryProcessor", "HistoryProcessorConfiguration",
           "HistoryMDP"]
