"""Reinforcement learning (reference: rl4j/** — QLearning/DQN, A3C,
policies, MDP abstraction, experience replay. SURVEY.md §2.41).

TPU-first redesign notes:
- The value/policy networks are jax pytrees with ONE jitted update step
  (replay batch in, new params out) instead of rl4j's per-op eager path.
- rl4j's A3C (async Hogwild workers) does not map to XLA's compilation
  model; the equivalent here is synchronous vectorized A2C — the same
  advantage-actor-critic math, batched over parallel env instances, one
  compiled update per step (the standard accelerator-era replacement).
"""

from deeplearning4j_tpu.rl.mdp import MDP, GridWorldMDP, CorridorMDP
from deeplearning4j_tpu.rl.replay import ExpReplay, Transition
from deeplearning4j_tpu.rl.policy import (
    DQNPolicy, EpsGreedy, Policy, ACPolicy,
)
from deeplearning4j_tpu.rl.qlearning import QLearningDiscreteDense, QLConfiguration
from deeplearning4j_tpu.rl.a2c import A2CDiscreteDense, A2CConfiguration

__all__ = ["MDP", "GridWorldMDP", "CorridorMDP", "ExpReplay", "Transition",
           "Policy", "EpsGreedy", "DQNPolicy", "ACPolicy",
           "QLearningDiscreteDense", "QLConfiguration",
           "A2CDiscreteDense", "A2CConfiguration"]
