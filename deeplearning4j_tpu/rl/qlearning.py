"""Deep Q-learning (reference: rl4j org/deeplearning4j/rl4j/learning/
sync/qlearning/discrete/QLearningDiscreteDense + QLearning.QLConfiguration
+ network factory DQNFactoryStdDense).

TPU design: the Q-network is a plain pytree MLP; TD update (gather the
taken action's Q, bootstrap from the target network, Huber-free MSE as
in the reference, Adam) is ONE jitted function. Target-network sync is a
pytree copy every `target_dqn_update_freq` steps. Double-DQN selects
argmax with the online net and evaluates with the target net.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.learning.updaters import Adam, apply_updater
from deeplearning4j_tpu.rl.mdp import MDP
from deeplearning4j_tpu.rl.policy import DQNPolicy, EpsGreedy
from deeplearning4j_tpu.rl.replay import ExpReplay, Transition


@dataclasses.dataclass
class QLConfiguration:
    """Mirror of QLearning.QLConfiguration (reference fields kept)."""

    seed: int = 0
    max_step: int = 20_000
    exp_replay_size: int = 10_000
    batch_size: int = 32
    target_dqn_update_freq: int = 100
    update_start: int = 100           # warm-up transitions before learning
    gamma: float = 0.99
    eps_start: float = 1.0
    min_epsilon: float = 0.05
    epsilon_nb_step: int = 3000
    double_dqn: bool = True
    learning_rate: float = 1e-3
    hidden: tuple = (64, 64)


def _init_mlp(key, sizes, dtype=jnp.float32) -> List[dict]:
    params = []
    for i in range(len(sizes) - 1):
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(2.0 / sizes[i])
        params.append({
            "W": jax.random.normal(sub, (sizes[i], sizes[i + 1]),
                                   dtype) * scale,
            "b": jnp.zeros((sizes[i + 1],), dtype),
        })
    return params


def _mlp(params, x):
    for i, p in enumerate(params):
        x = x @ p["W"] + p["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


class QLearningDiscreteDense:
    def __init__(self, mdp: MDP, conf: Optional[QLConfiguration] = None):
        self.mdp = mdp
        self.conf = conf or QLConfiguration()
        c = self.conf
        key = jax.random.key(c.seed)
        sizes = (mdp.obs_size,) + tuple(c.hidden) + (mdp.n_actions,)
        self.params = _init_mlp(key, sizes)
        self.target_params = jax.tree_util.tree_map(lambda a: a, self.params)
        self._updater = Adam(learning_rate=c.learning_rate)
        self._opt_state = self._updater.init_state(self.params)
        self.replay = ExpReplay(c.exp_replay_size, mdp.obs_size, seed=c.seed)
        self._q = jax.jit(_mlp)
        self._steps = 0
        self.episode_rewards: List[float] = []

        gamma, double = c.gamma, c.double_dqn

        def td_step(params, target_params, opt_state, it, obs, act, rew,
                    nobs, done):
            if double:
                sel = jnp.argmax(_mlp(params, nobs), -1)
                qn = jnp.take_along_axis(_mlp(target_params, nobs),
                                         sel[:, None], -1)[:, 0]
            else:
                qn = jnp.max(_mlp(target_params, nobs), -1)
            target = rew + gamma * (1.0 - done) * qn
            target = jax.lax.stop_gradient(target)

            def loss_fn(p):
                q = jnp.take_along_axis(_mlp(p, obs),
                                        act[:, None].astype(jnp.int32),
                                        -1)[:, 0]
                return jnp.mean((q - target) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, new_opt = apply_updater(self._updater, opt_state,
                                             grads, params, it)
            new_params = jax.tree_util.tree_map(lambda p, u: p - u, params,
                                                updates)
            return new_params, new_opt, loss

        # NB: params cannot be donated here — target_params aliases the
        # same buffers right after every sync (f(donate(a), a) is invalid)
        self._td_step = jax.jit(td_step, donate_argnums=(2,))

    # -- q-value access -------------------------------------------------
    def q_values(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self._q(self.params, jnp.asarray(obs)))

    def getPolicy(self) -> DQNPolicy:
        return DQNPolicy(self.q_values, learner=self)

    # -- persistence (reference: DQNPolicy#save / DQNPolicy.load) -------
    def save(self, path: str) -> None:
        """Q-network params + config to one .npz (the DQNPolicy.save
        role; optimizer/replay state is NOT saved — matching the
        reference, which persists the policy network only)."""
        import dataclasses as _dc
        import json as _json

        arrays = {}
        for i, layer in enumerate(self.params):
            for k, v in layer.items():
                arrays[f"l{i}_{k}"] = np.asarray(v)
        arrays["_meta"] = np.frombuffer(_json.dumps({
            "conf": _dc.asdict(self.conf),
            "n_layers": len(self.params),
            "obs_size": self.mdp.obs_size,
            "n_actions": self.mdp.n_actions,
        }).encode(), dtype=np.uint8)
        np.savez(path, **arrays)

    @classmethod
    def load(cls, path: str, mdp: MDP) -> "QLearningDiscreteDense":
        import json as _json

        with np.load(path) as z:
            meta = _json.loads(bytes(z["_meta"]).decode())
            if (meta["obs_size"], meta["n_actions"]) != (mdp.obs_size,
                                                         mdp.n_actions):
                raise ValueError(
                    f"saved policy is for obs_size={meta['obs_size']}/"
                    f"n_actions={meta['n_actions']}, mdp has "
                    f"{mdp.obs_size}/{mdp.n_actions}")
            learner = cls(mdp, QLConfiguration(**meta["conf"]))
            learner.params = [
                {k.split("_", 1)[1]: jnp.asarray(z[k])
                 for k in z.files if k.startswith(f"l{i}_")}
                for i in range(meta["n_layers"])]
        learner.target_params = jax.tree_util.tree_map(
            lambda a: a, learner.params)
        return learner

    # -- training loop (reference: QLearningDiscrete#trainStep) ---------
    def train(self, max_steps: Optional[int] = None) -> List[float]:
        c = self.conf
        limit = max_steps or c.max_step
        policy = EpsGreedy(self.getPolicy(), self.mdp.n_actions,
                           eps_start=c.eps_start, eps_min=c.min_epsilon,
                           anneal_steps=c.epsilon_nb_step, seed=c.seed)
        obs = self.mdp.reset()
        ep_reward = 0.0
        while self._steps < limit:
            a = policy.next_action(obs)
            nobs, r, done, _ = self.mdp.step(a)
            self.replay.store(Transition(obs, a, r, nobs, done))
            ep_reward += r
            obs = nobs
            self._steps += 1
            if done:
                self.episode_rewards.append(ep_reward)
                ep_reward = 0.0
                obs = self.mdp.reset()
            if len(self.replay) >= max(c.update_start, c.batch_size):
                b = self.replay.sample(c.batch_size)
                self.params, self._opt_state, _ = self._td_step(
                    self.params, self.target_params, self._opt_state,
                    jnp.asarray(self._steps), *map(jnp.asarray, b))
                if self._steps % c.target_dqn_update_freq == 0:
                    self.target_params = jax.tree_util.tree_map(
                        lambda a: a, self.params)
        return self.episode_rewards


__all__ = ["QLearningDiscreteDense", "QLConfiguration"]
