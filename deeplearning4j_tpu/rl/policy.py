"""Policies (reference: rl4j org/deeplearning4j/rl4j/policy/{Policy,
DQNPolicy,EpsGreedy,ACPolicy})."""

from __future__ import annotations

from typing import Callable

import numpy as np


class Policy:
    def next_action(self, obs: np.ndarray) -> int:
        raise NotImplementedError

    # reference naming
    def nextAction(self, obs: np.ndarray) -> int:
        return self.next_action(obs)

    def play(self, mdp, max_steps: int = 10_000) -> float:
        """Run one episode greedily, return total reward (reference:
        Policy#play)."""
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done, _ = mdp.step(self.next_action(obs))
            total += r
            if done:
                break
        return total


class DQNPolicy(Policy):
    """Greedy argmax over Q-values. When produced by a learner's
    getPolicy(), save/load persist the Q-network (reference:
    DQNPolicy#save / DQNPolicy.load(path))."""

    def __init__(self, q_fn: Callable[[np.ndarray], np.ndarray],
                 learner=None):
        self.q_fn = q_fn
        self._learner = learner

    def next_action(self, obs: np.ndarray) -> int:
        return int(np.argmax(self.q_fn(obs[None])[0]))

    def save(self, path: str) -> None:
        if self._learner is None:
            raise ValueError("this DQNPolicy wraps a bare q_fn — save "
                             "via the learner (QLearningDiscreteDense"
                             ".save) or construct it via getPolicy()")
        self._learner.save(path)

    @staticmethod
    def load(path: str, mdp) -> "DQNPolicy":
        from deeplearning4j_tpu.rl.qlearning import QLearningDiscreteDense

        return QLearningDiscreteDense.load(path, mdp).getPolicy()


class EpsGreedy(Policy):
    """Annealed epsilon-greedy exploration wrapper (reference: EpsGreedy
    with epsilonNbStep/minEpsilon)."""

    def __init__(self, inner: Policy, n_actions: int, eps_start: float = 1.0,
                 eps_min: float = 0.1, anneal_steps: int = 10_000,
                 seed: int = 0):
        self.inner = inner
        self.n_actions = n_actions
        self.eps_start = eps_start
        self.eps_min = eps_min
        self.anneal_steps = max(anneal_steps, 1)
        self._step = 0
        self._rng = np.random.RandomState(seed)

    @property
    def epsilon(self) -> float:
        frac = min(self._step / self.anneal_steps, 1.0)
        return self.eps_start + (self.eps_min - self.eps_start) * frac

    def next_action(self, obs: np.ndarray) -> int:
        eps = self.epsilon
        self._step += 1
        if self._rng.rand() < eps:
            return int(self._rng.randint(self.n_actions))
        return self.inner.next_action(obs)


class ACPolicy(Policy):
    """Samples from the actor's categorical distribution (reference:
    ACPolicy); `greedy=True` takes the argmax instead."""

    def __init__(self, prob_fn: Callable[[np.ndarray], np.ndarray],
                 greedy: bool = False, seed: int = 0):
        self.prob_fn = prob_fn
        self.greedy = greedy
        self._rng = np.random.RandomState(seed)

    def next_action(self, obs: np.ndarray) -> int:
        p = np.asarray(self.prob_fn(obs[None])[0], np.float64)
        p = p / p.sum()
        if self.greedy:
            return int(np.argmax(p))
        return int(self._rng.choice(len(p), p=p))


__all__ = ["Policy", "DQNPolicy", "EpsGreedy", "ACPolicy"]
