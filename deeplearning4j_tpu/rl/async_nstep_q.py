"""Async n-step Q-learning — rl4j's second async learner (reference:
AsyncNStepQLearningDiscrete + AsyncNStepQLearningThreadDiscrete,
org/deeplearning4j/rl4j/learning/async/nstep/discrete/**).

Same actor/learner split as a3c.py (worker threads own env stepping,
lock-free immutable param snapshots, gradient compute outside the
lock, serialized apply): the difference is the objective — n-step
TD targets against a periodically-synced TARGET network
(R_k + gamma^k * max_a Q_target(s', a)) with eps-greedy actors, the
async precursor of the DQN family instead of actor-critic.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import types
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.learning.updaters import Adam, apply_updater
from deeplearning4j_tpu.rl.a3c import _Counter
from deeplearning4j_tpu.rl.mdp import MDP
from deeplearning4j_tpu.rl.policy import DQNPolicy
from deeplearning4j_tpu.rl.qlearning import _init_mlp, _mlp


@functools.lru_cache(maxsize=32)
def _compiled(lr: float):
    """Shared jitted fns (see a3c._compiled for why this must outlive
    instances: per-instance jit closures recompile per trainer)."""
    updater = Adam(learning_rate=lr)

    def grads_fn(params, obs, act, tgt):
        def loss_fn(p):
            q = _mlp(p, obs)
            sel = jnp.take_along_axis(
                q, act[:, None].astype(jnp.int32), -1)[:, 0]
            return jnp.mean((tgt - sel) ** 2)

        return jax.value_and_grad(loss_fn)(params)

    def apply_fn(params, opt_state, grads, it):
        updates, new_opt = apply_updater(updater, opt_state, grads,
                                         params, it)
        return jax.tree_util.tree_map(lambda p, u: p - u, params,
                                      updates), new_opt

    return types.SimpleNamespace(
        updater=updater,
        q=jax.jit(_mlp),
        grads=jax.jit(grads_fn),
        apply=jax.jit(apply_fn),
    )


@dataclasses.dataclass
class AsyncNStepQLConfiguration:
    seed: int = 0
    gamma: float = 0.99
    n_step: int = 5                    # rollout length (nstep)
    n_workers: int = 4                 # numThread
    learning_rate: float = 1e-3
    target_update: int = 50            # targetDqnUpdateFreq (in updates)
    eps_start: float = 1.0
    eps_min: float = 0.1
    anneal_updates: int = 300          # epsilonNbStep, in update units
    hidden: tuple = (64,)


class AsyncNStepQLearningDiscrete:
    """Public surface matches QLearningDiscreteDense/A3CDiscreteDense:
    train(updates) with a shared budget, getPolicy(), episode_rewards."""

    def __init__(self, mdp_factory: Callable[[], MDP],
                 conf: Optional[AsyncNStepQLConfiguration] = None):
        self.conf = c = conf or AsyncNStepQLConfiguration()
        self._mdp_factory = mdp_factory
        probe = mdp_factory()
        self._n_actions = probe.n_actions
        trunk = (probe.obs_size,) + tuple(c.hidden) + (probe.n_actions,)
        probe.close()
        self._params = _init_mlp(jax.random.key(c.seed), trunk)
        self._target = self._params
        fns = _compiled(c.learning_rate)
        self._q, self._grads, self._apply = fns.q, fns.grads, fns.apply
        self._opt_state = fns.updater.init_state(self._params)
        self._it = 0
        self._lock = threading.Lock()
        self.episode_rewards: List[float] = []

    def getPolicy(self) -> DQNPolicy:
        params = self._params
        return DQNPolicy(
            lambda o: np.asarray(self._q(params, jnp.asarray(o))))

    def _worker(self, wid: int, budget: _Counter):
        c = self.conf
        rng = np.random.RandomState(c.seed * 7919 + wid)
        env = self._mdp_factory()
        obs = env.reset()
        ep_r = 0.0
        while budget.take():
            params, target = self._params, self._target
            frac = min(self._it / max(c.anneal_updates, 1), 1.0)
            eps = c.eps_start + (c.eps_min - c.eps_start) * frac
            t_obs, t_act, t_rew = [], [], []
            done = False
            for _ in range(c.n_step):
                if rng.rand() < eps:
                    a = int(rng.randint(self._n_actions))
                else:
                    q = np.asarray(self._q(params,
                                           jnp.asarray(obs[None])))[0]
                    a = int(np.argmax(q))
                nobs, r, done, _info = env.step(a)
                t_obs.append(obs)
                t_act.append(a)
                t_rew.append(r)
                ep_r += r
                if done:
                    with self._lock:
                        self.episode_rewards.append(ep_r)
                    ep_r = 0.0
                    obs = env.reset()
                    break
                obs = nobs
            # n-step returns; bootstrap with the TARGET net unless the
            # rollout ended the episode (reference: QLearningUpdateAlgorithm)
            running = 0.0 if done else float(np.max(np.asarray(
                self._q(target, jnp.asarray(obs[None])))[0]))
            tgt = np.zeros(len(t_rew), np.float32)
            for t in reversed(range(len(t_rew))):
                running = t_rew[t] + c.gamma * running
                tgt[t] = running
            _loss, grads = self._grads(
                params, jnp.asarray(np.stack(t_obs)),
                jnp.asarray(np.asarray(t_act, np.int32)),
                jnp.asarray(tgt))
            with self._lock:
                self._params, self._opt_state = self._apply(
                    self._params, self._opt_state, grads,
                    jnp.asarray(self._it))
                self._it += 1
                if self._it % c.target_update == 0:
                    self._target = self._params
        env.close()

    def train(self, updates: int = 600) -> List[float]:
        budget = _Counter(updates)
        threads = [threading.Thread(target=self._worker,
                                    args=(w, budget), daemon=True)
                   for w in range(self.conf.n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return self.episode_rewards


__all__ = ["AsyncNStepQLearningDiscrete", "AsyncNStepQLConfiguration"]
