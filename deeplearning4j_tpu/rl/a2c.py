"""Synchronous advantage actor-critic (reference: rl4j A3CDiscreteDense —
org/deeplearning4j/rl4j/learning/async/a3c/discrete/**).

rl4j's A3C runs async Hogwild workers mutating shared nets — a CPU-era
pattern that is hostile to XLA (per-worker eager updates, no batching).
The TPU-idiomatic equivalent keeps the same math (n-step advantage
policy gradient + value regression + entropy bonus) but runs K env
copies in lockstep on the host and does ONE jitted update per rollout
with the batched trajectories.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.learning.updaters import Adam, apply_updater
from deeplearning4j_tpu.rl.mdp import MDP
from deeplearning4j_tpu.rl.policy import ACPolicy
from deeplearning4j_tpu.rl.qlearning import _init_mlp, _mlp


def actor_critic_loss(nets, obs, act, ret, value_coef, entropy_coef):
    """Shared A2C/A3C objective: n-step advantage policy gradient +
    value regression + entropy bonus (reference: AdvantageActorCritic
    gradient assembly in A3CThreadDiscrete/AdvantageActorCritic). ONE
    definition so the sync and async trainers cannot diverge."""
    logits = _mlp(nets["actor"], obs)
    logp = jax.nn.log_softmax(logits, -1)
    p = jnp.exp(logp)
    v = _mlp(nets["critic"], obs)[:, 0]
    adv = jax.lax.stop_gradient(ret - v)
    sel = jnp.take_along_axis(logp, act[:, None].astype(jnp.int32),
                              -1)[:, 0]
    pg = -jnp.mean(sel * adv)
    vloss = jnp.mean((ret - v) ** 2)
    ent = -jnp.mean(jnp.sum(p * logp, -1))
    return pg + value_coef * vloss - entropy_coef * ent


@dataclasses.dataclass
class A2CConfiguration:
    seed: int = 0
    gamma: float = 0.99
    n_step: int = 8                  # rollout length (reference: nstep)
    n_envs: int = 8                  # parallel env copies (replaces workers)
    learning_rate: float = 7e-4
    entropy_coef: float = 0.01
    value_coef: float = 0.5
    hidden: tuple = (64,)


class A2CDiscreteDense:
    def __init__(self, mdp_factory: Callable[[], MDP],
                 conf: Optional[A2CConfiguration] = None):
        self.conf = conf or A2CConfiguration()
        c = self.conf
        self.envs = [mdp_factory() for _ in range(c.n_envs)]
        m = self.envs[0]
        key = jax.random.key(c.seed)
        k1, k2 = jax.random.split(key)
        trunk = (m.obs_size,) + tuple(c.hidden)
        self.actor = _init_mlp(k1, trunk + (m.n_actions,))
        self.critic = _init_mlp(k2, trunk + (1,))
        self._updater = Adam(learning_rate=c.learning_rate)
        self._opt_state = self._updater.init_state(
            {"actor": self.actor, "critic": self.critic})
        self._probs = jax.jit(
            lambda p, x: jax.nn.softmax(_mlp(p, x), -1))
        self.episode_rewards: List[float] = []
        gamma, ec, vc = c.gamma, c.entropy_coef, c.value_coef

        def update(nets, opt_state, it, obs, act, ret):
            loss, grads = jax.value_and_grad(
                lambda n: actor_critic_loss(n, obs, act, ret, vc, ec))(nets)
            updates, new_opt = apply_updater(self._updater, opt_state,
                                             grads, nets, it)
            new_nets = jax.tree_util.tree_map(lambda p, u: p - u, nets,
                                              updates)
            return new_nets, new_opt, loss

        self._update = jax.jit(update, donate_argnums=(0, 1))

    def getPolicy(self, greedy: bool = True) -> ACPolicy:
        return ACPolicy(
            lambda x: np.asarray(self._probs(self.actor, jnp.asarray(x))),
            greedy=greedy, seed=self.conf.seed)

    def train(self, updates: int = 200) -> List[float]:
        c = self.conf
        rng = np.random.RandomState(c.seed)
        obs = [e.reset() for e in self.envs]
        ep_r = [0.0] * c.n_envs
        it = 0
        for _ in range(updates):
            traj_obs, traj_act, traj_rew, traj_done = [], [], [], []
            for _ in range(c.n_step):
                probs = np.asarray(self._probs(
                    self.actor, jnp.asarray(np.stack(obs))))
                acts = [int(rng.choice(len(p), p=p / p.sum()))
                        for p in probs]
                step_out = [e.step(a) for e, a in zip(self.envs, acts)]
                traj_obs.append(np.stack(obs))
                traj_act.append(np.asarray(acts, np.int32))
                traj_rew.append(np.asarray([s[1] for s in step_out],
                                           np.float32))
                traj_done.append(np.asarray([s[2] for s in step_out],
                                            np.float32))
                for i, (o, r, d, _info) in enumerate(step_out):
                    ep_r[i] += r
                    if d:
                        self.episode_rewards.append(ep_r[i])
                        ep_r[i] = 0.0
                        obs[i] = self.envs[i].reset()
                    else:
                        obs[i] = o
            # n-step returns bootstrapped from the critic
            last_v = np.asarray(_mlp(self.critic,
                                     jnp.asarray(np.stack(obs))))[:, 0]
            rets = np.zeros((c.n_step, c.n_envs), np.float32)
            running = last_v
            for t in reversed(range(c.n_step)):
                running = traj_rew[t] + c.gamma * running * (1 - traj_done[t])
                rets[t] = running
            nets = {"actor": self.actor, "critic": self.critic}
            nets, self._opt_state, _ = self._update(
                nets, self._opt_state, jnp.asarray(it),
                jnp.asarray(np.concatenate(traj_obs)),
                jnp.asarray(np.concatenate(traj_act)),
                jnp.asarray(rets.reshape(-1)))
            self.actor, self.critic = nets["actor"], nets["critic"]
            it += 1
        return self.episode_rewards


__all__ = ["A2CDiscreteDense", "A2CConfiguration"]
