"""MDP abstraction + in-repo test environments.

Reference: rl4j-api org/deeplearning4j/rl4j/mdp/MDP (reset/step/close,
getActionSpace/getObservationSpace) — gym-style. The test envs replace
rl4j's gym-java-client dependency (no egress, no gym): small exact
MDPs with known optimal returns, the same role BaseSparkTest plays for
Spark (SURVEY.md §4 distributed-without-cluster philosophy).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


class MDP:
    """reset() -> obs; step(a) -> (obs, reward, done, info)."""

    @property
    def obs_size(self) -> int:
        raise NotImplementedError

    @property
    def n_actions(self) -> int:
        raise NotImplementedError

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, dict]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class CorridorMDP(MDP):
    """1-D corridor: start left, +1 at the right end, -0.01 per step.
    Optimal: always move right."""

    def __init__(self, length: int = 8, max_steps: int = 40):
        self.length = length
        self.max_steps = max_steps
        self._pos = 0
        self._t = 0

    @property
    def obs_size(self) -> int:
        return self.length

    @property
    def n_actions(self) -> int:
        return 2  # left, right

    def _obs(self) -> np.ndarray:
        o = np.zeros(self.length, np.float32)
        o[self._pos] = 1.0
        return o

    def reset(self) -> np.ndarray:
        self._pos, self._t = 0, 0
        return self._obs()

    def step(self, action: int):
        self._t += 1
        self._pos = min(max(self._pos + (1 if action == 1 else -1), 0),
                        self.length - 1)
        done = self._pos == self.length - 1 or self._t >= self.max_steps
        reward = 1.0 if self._pos == self.length - 1 else -0.01
        return self._obs(), reward, done, {}


class GridWorldMDP(MDP):
    """n x n grid, start top-left, goal bottom-right (+1), step cost
    -0.01, falling off walls = no-op. Actions: up/down/left/right."""

    def __init__(self, n: int = 4, max_steps: int = 60):
        self.n = n
        self.max_steps = max_steps
        self._pos = (0, 0)
        self._t = 0

    @property
    def obs_size(self) -> int:
        return self.n * self.n

    @property
    def n_actions(self) -> int:
        return 4

    def _obs(self) -> np.ndarray:
        o = np.zeros(self.n * self.n, np.float32)
        o[self._pos[0] * self.n + self._pos[1]] = 1.0
        return o

    def reset(self) -> np.ndarray:
        self._pos, self._t = (0, 0), 0
        return self._obs()

    def step(self, action: int):
        self._t += 1
        r, c = self._pos
        dr, dc = [(-1, 0), (1, 0), (0, -1), (0, 1)][action]
        self._pos = (min(max(r + dr, 0), self.n - 1),
                     min(max(c + dc, 0), self.n - 1))
        at_goal = self._pos == (self.n - 1, self.n - 1)
        done = at_goal or self._t >= self.max_steps
        return self._obs(), (1.0 if at_goal else -0.01), done, {}


class SlowMDP(MDP):
    """Wraps an MDP with a fixed per-step latency — models the regime
    rl4j's async learning exists for (gym-java-client round trips,
    simulator physics): env stepping dominated by host-side waiting,
    which worker threads can overlap."""

    def __init__(self, inner: MDP, step_delay_s: float = 0.002):
        import time

        self._inner = inner
        self._delay = step_delay_s
        self._sleep = time.sleep

    @property
    def obs_size(self) -> int:
        return self._inner.obs_size

    @property
    def n_actions(self) -> int:
        return self._inner.n_actions

    def reset(self) -> np.ndarray:
        return self._inner.reset()

    def step(self, action: int):
        self._sleep(self._delay)
        return self._inner.step(action)

    def close(self) -> None:
        self._inner.close()


__all__ = ["MDP", "CorridorMDP", "GridWorldMDP", "SlowMDP"]
