"""Candidate generators (reference: arbiter org/deeplearning4j/arbiter/
optimize/generator/{GridSearchCandidateGenerator,
RandomSearchGenerator})."""

from __future__ import annotations

import itertools
from typing import Dict, Iterator, Optional

import numpy as np

from deeplearning4j_tpu.arbiter.space import ParameterSpace


class CandidateGenerator:
    def candidates(self) -> Iterator[Dict]:
        raise NotImplementedError


class RandomSearchGenerator(CandidateGenerator):
    def __init__(self, space: Dict[str, ParameterSpace], seed: int = 0,
                 max_candidates: Optional[int] = None):
        self.space = space
        self.seed = seed
        self.max_candidates = max_candidates

    def candidates(self) -> Iterator[Dict]:
        rng = np.random.RandomState(self.seed)
        n = 0
        while self.max_candidates is None or n < self.max_candidates:
            yield {k: s.sample(float(rng.rand()))
                   for k, s in self.space.items()}
            n += 1


class GridSearchCandidateGenerator(CandidateGenerator):
    """Cartesian product over each space's grid points; `mode` 'Sequential'
    walks in order, 'RandomOrder' shuffles (reference Mode enum)."""

    def __init__(self, space: Dict[str, ParameterSpace],
                 discretization_count: int = 5, mode: str = "Sequential",
                 seed: int = 0):
        self.space = space
        self.discretization_count = discretization_count
        self.mode = mode
        self.seed = seed

    def candidates(self) -> Iterator[Dict]:
        keys = list(self.space)
        axes = [self.space[k].grid_values(self.discretization_count)
                for k in keys]
        combos = list(itertools.product(*axes))
        if self.mode == "RandomOrder":
            np.random.RandomState(self.seed).shuffle(combos)
        for combo in combos:
            yield dict(zip(keys, combo))


class GeneticSearchCandidateGenerator(CandidateGenerator):
    """Genetic search in genotype space (reference: arbiter
    GeneticSearchCandidateGenerator + the genetic package's
    ChromosomeFactory / crossover / mutation operators and
    PopulationModel with culling).

    The genome is the vector of uniform u-values, one per space key —
    exactly the reference's double[] chromosome; decoding goes through
    each ParameterSpace.sample(u) so any space type participates. The
    runner feeds scores back through report(); until a full first
    generation is scored, candidates are random (the reference's
    RandomGenerator initialization phase).
    """

    def __init__(self, space: Dict[str, ParameterSpace],
                 population_size: int = 12, parent_fraction: float = 0.34,
                 crossover_rate: float = 0.85, mutation_rate: float = 0.15,
                 mutation_sigma: float = 0.15,
                 minimize: Optional[bool] = None,
                 seed: int = 0, max_candidates: Optional[int] = None):
        if population_size < 2:
            raise ValueError("population_size must be >= 2")
        self.space = space
        self.keys = list(space)
        self.population_size = population_size
        self.n_parents = max(2, int(round(parent_fraction
                                          * population_size)))
        self.crossover_rate = crossover_rate
        self.mutation_rate = mutation_rate
        self.mutation_sigma = mutation_sigma
        # None = inherit the direction from the runner's
        # OptimizationConfiguration (the authoritative place); a
        # conflicting explicit value raises there, because breeding
        # toward the wrong end silently performs worse than random.
        self.minimize = minimize
        self.max_candidates = max_candidates
        self._rng = np.random.RandomState(seed)
        self._pending: Dict[int, np.ndarray] = {}   # index -> genome
        self._scored: list = []                     # (score, genome)
        self._next_index = 0
        self.last_index: Optional[int] = None       # index of last yield

    def _decode(self, genome: np.ndarray) -> Dict:
        return {k: self.space[k].sample(float(u))
                for k, u in zip(self.keys, genome)}

    def _cull(self) -> None:
        """Keep only the fittest population_size genomes (the
        reference's PopulationModel culling) so long runs stay O(pop)
        per candidate instead of growing with every score ever seen."""
        minimize = True if self.minimize is None else self.minimize
        self._scored = sorted(self._scored, key=lambda sg: sg[0],
                              reverse=not minimize)[:self.population_size]

    def _breed(self) -> np.ndarray:
        self._cull()
        parents = self._scored[:self.n_parents]
        i, j = self._rng.choice(len(parents), 2, replace=False)
        a, b = parents[i][1], parents[j][1]
        if self._rng.rand() < self.crossover_rate:
            mask = self._rng.rand(len(a)) < 0.5   # uniform crossover
            child = np.where(mask, a, b)
        else:
            child = a.copy()
        mut = self._rng.rand(len(child)) < self.mutation_rate
        child = child + mut * self._rng.normal(
            0, self.mutation_sigma, len(child))
        return np.clip(child, 0.0, 1.0 - 1e-9)

    def candidates(self) -> Iterator[Dict]:
        while (self.max_candidates is None
               or self._next_index < self.max_candidates):
            if len(self._scored) >= self.population_size:
                genome = self._breed()
            else:
                genome = self._rng.rand(len(self.keys))
            idx = self._next_index
            self._next_index += 1
            self._pending[idx] = genome
            self.last_index = idx   # runner reads this for report()
            yield self._decode(genome)

    def report(self, index: int, score: Optional[float]) -> None:
        """Score feedback from the runner (reference:
        CandidateGenerator.reportResults). Failed candidates
        (score None) are dropped from the gene pool."""
        genome = self._pending.pop(index, None)
        if genome is not None and score is not None:
            self._scored.append((float(score), genome))


__all__ = ["CandidateGenerator", "RandomSearchGenerator",
           "GridSearchCandidateGenerator",
           "GeneticSearchCandidateGenerator"]
