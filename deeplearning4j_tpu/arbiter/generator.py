"""Candidate generators (reference: arbiter org/deeplearning4j/arbiter/
optimize/generator/{GridSearchCandidateGenerator,
RandomSearchGenerator})."""

from __future__ import annotations

import itertools
from typing import Dict, Iterator, Optional

import numpy as np

from deeplearning4j_tpu.arbiter.space import ParameterSpace


class CandidateGenerator:
    def candidates(self) -> Iterator[Dict]:
        raise NotImplementedError


class RandomSearchGenerator(CandidateGenerator):
    def __init__(self, space: Dict[str, ParameterSpace], seed: int = 0,
                 max_candidates: Optional[int] = None):
        self.space = space
        self.seed = seed
        self.max_candidates = max_candidates

    def candidates(self) -> Iterator[Dict]:
        rng = np.random.RandomState(self.seed)
        n = 0
        while self.max_candidates is None or n < self.max_candidates:
            yield {k: s.sample(float(rng.rand()))
                   for k, s in self.space.items()}
            n += 1


class GridSearchCandidateGenerator(CandidateGenerator):
    """Cartesian product over each space's grid points; `mode` 'Sequential'
    walks in order, 'RandomOrder' shuffles (reference Mode enum)."""

    def __init__(self, space: Dict[str, ParameterSpace],
                 discretization_count: int = 5, mode: str = "Sequential",
                 seed: int = 0):
        self.space = space
        self.discretization_count = discretization_count
        self.mode = mode
        self.seed = seed

    def candidates(self) -> Iterator[Dict]:
        keys = list(self.space)
        axes = [self.space[k].grid_values(self.discretization_count)
                for k in keys]
        combos = list(itertools.product(*axes))
        if self.mode == "RandomOrder":
            np.random.RandomState(self.seed).shuffle(combos)
        for combo in combos:
            yield dict(zip(keys, combo))


__all__ = ["CandidateGenerator", "RandomSearchGenerator",
           "GridSearchCandidateGenerator"]
