"""Hyperparameter optimization (reference: arbiter/** — parameter
spaces, grid/random candidate generators, LocalOptimizationRunner with
termination conditions and result tracking. SURVEY.md §2.41).

Design: a search space is a dict {name: ParameterSpace}; generators
yield candidate dicts; the runner calls a user score function per
candidate (which builds/trains/evaluates a model — same contract as
arbiter's ModelEvaluator + score function split, without the reflection
machinery the JVM needed).
"""

from deeplearning4j_tpu.arbiter.space import (
    ContinuousParameterSpace, DiscreteParameterSpace, FixedValue,
    IntegerParameterSpace, ParameterSpace,
)
from deeplearning4j_tpu.arbiter.generator import (
    GeneticSearchCandidateGenerator, GridSearchCandidateGenerator,
    RandomSearchGenerator,
)
from deeplearning4j_tpu.arbiter.runner import (
    CandidateResult, LocalOptimizationRunner, MaxCandidatesCondition,
    MaxTimeCondition, OptimizationConfiguration,
)

__all__ = [
    "ParameterSpace", "ContinuousParameterSpace", "DiscreteParameterSpace",
    "IntegerParameterSpace", "FixedValue",
    "GridSearchCandidateGenerator", "RandomSearchGenerator",
    "GeneticSearchCandidateGenerator",
    "OptimizationConfiguration", "LocalOptimizationRunner",
    "CandidateResult", "MaxCandidatesCondition", "MaxTimeCondition",
]
