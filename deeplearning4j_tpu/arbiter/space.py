"""Parameter spaces (reference: arbiter org/deeplearning4j/arbiter/
optimize/parameter/{continuous/ContinuousParameterSpace,
discrete/DiscreteParameterSpace,integer/IntegerParameterSpace,
FixedValue})."""

from __future__ import annotations

import math
from typing import Any, List, Sequence


class ParameterSpace:
    """Maps a uniform u in [0,1) to a concrete value; enumerable spaces
    also expose grid points for grid search."""

    def sample(self, u: float) -> Any:
        raise NotImplementedError

    def grid_values(self, resolution: int) -> List[Any]:
        return [self.sample((i + 0.5) / resolution)
                for i in range(resolution)]


class ContinuousParameterSpace(ParameterSpace):
    def __init__(self, min_value: float, max_value: float,
                 log_scale: bool = False):
        if log_scale and min_value <= 0:
            raise ValueError("log_scale needs min_value > 0")
        self.min = float(min_value)
        self.max = float(max_value)
        self.log_scale = log_scale

    def sample(self, u: float) -> float:
        if self.log_scale:
            lo, hi = math.log(self.min), math.log(self.max)
            return math.exp(lo + u * (hi - lo))
        return self.min + u * (self.max - self.min)


class IntegerParameterSpace(ParameterSpace):
    def __init__(self, min_value: int, max_value: int):
        self.min = int(min_value)
        self.max = int(max_value)

    def sample(self, u: float) -> int:
        return min(self.min + int(u * (self.max - self.min + 1)), self.max)

    def grid_values(self, resolution: int) -> List[int]:
        n = self.max - self.min + 1
        if resolution >= n:
            return list(range(self.min, self.max + 1))
        return sorted({self.sample((i + 0.5) / resolution)
                       for i in range(resolution)})


class DiscreteParameterSpace(ParameterSpace):
    def __init__(self, values: Sequence[Any]):
        if not values:
            raise ValueError("empty value set")
        self.values = list(values)

    def sample(self, u: float) -> Any:
        return self.values[min(int(u * len(self.values)),
                               len(self.values) - 1)]

    def grid_values(self, resolution: int) -> List[Any]:
        return list(self.values)


class FixedValue(ParameterSpace):
    def __init__(self, value: Any):
        self.value = value

    def sample(self, u: float) -> Any:
        return self.value

    def grid_values(self, resolution: int) -> List[Any]:
        return [self.value]


__all__ = ["ParameterSpace", "ContinuousParameterSpace",
           "IntegerParameterSpace", "DiscreteParameterSpace", "FixedValue"]
