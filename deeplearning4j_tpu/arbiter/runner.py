"""Optimization runner (reference: arbiter org/deeplearning4j/arbiter/
optimize/runner/LocalOptimizationRunner + api/termination/
{MaxCandidatesCondition,MaxTimeCondition} + OptimizationResult)."""

from __future__ import annotations

import dataclasses
import time
import traceback
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass
class CandidateResult:
    index: int
    candidate: Dict
    score: Optional[float]
    duration_s: float
    error: Optional[str] = None


class TerminationCondition:
    def terminate(self, runner: "LocalOptimizationRunner") -> bool:
        raise NotImplementedError


class MaxCandidatesCondition(TerminationCondition):
    def __init__(self, n: int):
        self.n = n

    def terminate(self, runner):
        return len(runner.results) >= self.n


class MaxTimeCondition(TerminationCondition):
    def __init__(self, seconds: float):
        self.seconds = seconds

    def terminate(self, runner):
        return (time.time() - runner._start_time) >= self.seconds


@dataclasses.dataclass
class OptimizationConfiguration:
    candidate_generator: Any
    score_function: Callable[[Dict], float]
    termination_conditions: List[TerminationCondition]
    minimize: bool = True         # reference: score function declares this


class LocalOptimizationRunner:
    """Sequential local runner (reference runs candidates on an executor
    pool; model training here already saturates the chip, so candidates
    run one-at-a-time by design — parallel HP search across hosts is the
    ShardedTrainer/multi-process layer's job)."""

    def __init__(self, config: OptimizationConfiguration):
        self.config = config
        self.results: List[CandidateResult] = []
        self._start_time = None

    def execute(self) -> List[CandidateResult]:
        self._start_time = time.time()
        conds = self.config.termination_conditions
        gen_obj = self.config.candidate_generator
        # The config owns the optimization direction; adaptive
        # generators inherit it (None) or must agree — a genetic search
        # breeding toward the wrong end is silently worse than random.
        if hasattr(gen_obj, "minimize"):
            if gen_obj.minimize is None:
                gen_obj.minimize = self.config.minimize
            elif bool(gen_obj.minimize) != bool(self.config.minimize):
                raise ValueError(
                    f"candidate generator minimize={gen_obj.minimize} "
                    f"conflicts with OptimizationConfiguration."
                    f"minimize={self.config.minimize}")
        # Termination is checked BEFORE pulling the next candidate so a
        # generator never materializes one that won't be scored (a
        # genetic generator would otherwise orphan the pending genome).
        gen = self.config.candidate_generator.candidates()
        i = 0
        while not any(c.terminate(self) for c in conds):
            cand = next(gen, None)
            if cand is None:
                break
            t0 = time.time()
            try:
                score = float(self.config.score_function(cand))
                err = None
            except Exception:
                score, err = None, traceback.format_exc()
            self.results.append(CandidateResult(
                index=i, candidate=cand, score=score,
                duration_s=time.time() - t0, error=err))
            # Score feedback for adaptive generators (reference:
            # CandidateGenerator.reportResults — genetic search needs
            # it). Use the GENERATOR's index for the feedback key: a
            # pre-warmed generator handed to a fresh runner has already
            # advanced its counter, so the runner's loop index would
            # desync and every report would be silently dropped.
            report = getattr(gen_obj, "report", None)
            if report is not None:
                report(getattr(gen_obj, "last_index", i), score)
            i += 1
        return self.results

    def bestResult(self) -> Optional[CandidateResult]:
        scored = [r for r in self.results if r.score is not None]
        if not scored:
            return None
        key = (min if self.config.minimize else max)
        return key(scored, key=lambda r: r.score)

    def numCandidatesCompleted(self) -> int:
        return len(self.results)

    def numCandidatesFailed(self) -> int:
        return sum(1 for r in self.results if r.error is not None)


__all__ = ["CandidateResult", "OptimizationConfiguration",
           "LocalOptimizationRunner", "MaxCandidatesCondition",
           "MaxTimeCondition", "TerminationCondition"]
