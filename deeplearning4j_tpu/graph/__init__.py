"""Graph vertex embeddings — the ``deeplearning4j-graph`` module.

Reference (eclipse/deeplearning4j monorepo, module
``deeplearning4j/deeplearning4j-graph``):

- ``org/deeplearning4j/graph/api/{IGraph,Vertex,Edge,NoEdgeHandling}.java``
  — the graph API consumed by the embedding models.
- ``org/deeplearning4j/graph/graph/Graph.java`` — adjacency-list
  in-memory graph.
- ``org/deeplearning4j/graph/data/GraphLoader.java`` — edge-list file
  loaders.
- ``org/deeplearning4j/graph/iterator/{RandomWalkIterator,
  WeightedRandomWalkIterator}.java`` — uniform / edge-weight-biased
  random walk sequence generators.
- ``org/deeplearning4j/graph/models/deepwalk/{DeepWalk,GraphHuffman,
  InMemoryGraphLookupTable}.java`` — the DeepWalk model (Perozzi et al.
  2014): skip-gram over random walks with a degree-keyed Huffman
  hierarchical-softmax output.
- ``org/deeplearning4j/graph/models/GraphVectors.java`` — the query
  interface (vertex vector, similarity, nearest vertices).

TPU-first redesign
------------------
The reference walks the graph one step at a time per walk and does one
JNI-dispatched gradient update per (center, context) pair. Here:

- The graph is stored as CSR (``indptr``/``indices`` + an edge-aligned
  GLOBAL cumulative-weight array), so WHOLE BATCHES of random walks
  advance one step per numpy operation: uniform walks gather
  ``indices[indptr[cur] + floor(u * degree[cur])]`` for every active
  walk at once; weighted walks draw a target mass inside each row's
  span of the global cumsum and ``np.searchsorted`` it back to an edge
  — no per-walk Python loop, no ragged row scan.
- Training reuses the device-batched hierarchical-softmax skip-gram
  step from :mod:`deeplearning4j_tpu.nlp.word2vec` (``_sg_hs_step``):
  thousands of (center, target) pairs per compiled XLA step instead of
  per-pair scalar updates. The Huffman tree is keyed by vertex degree
  exactly like the reference's ``GraphHuffman``.
"""
from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.nlp.vocab import AbstractCache
from deeplearning4j_tpu.nlp.word2vec import _sg_hs_step


class NoEdgeHandling:
    """What a walk does at a vertex with no outgoing edge (reference:
    org/deeplearning4j/graph/api/NoEdgeHandling.java)."""
    SELF_LOOP_ON_DISCONNECTED = "SELF_LOOP_ON_DISCONNECTED"
    EXCEPTION_ON_DISCONNECTED = "EXCEPTION_ON_DISCONNECTED"


class Vertex:
    """A graph vertex: integer index + arbitrary value payload
    (reference: org/deeplearning4j/graph/api/Vertex.java)."""

    def __init__(self, idx: int, value=None):
        self.idx = int(idx)
        self.value = value

    def vertexID(self) -> int:
        return self.idx

    def getValue(self):
        return self.value

    def __repr__(self):
        return f"Vertex({self.idx}, {self.value!r})"


class Edge:
    """reference: org/deeplearning4j/graph/api/Edge.java."""

    def __init__(self, frm: int, to: int, value=None,
                 directed: bool = False):
        self.frm = int(frm)
        self.to = int(to)
        self.value = value
        self.directed = bool(directed)

    def getFrom(self) -> int:
        return self.frm

    def getTo(self) -> int:
        return self.to


class Graph:
    """Adjacency-list graph (reference:
    org/deeplearning4j/graph/graph/Graph.java). Vertices are dense
    integer ids ``0..n-1``; edges carry an optional float weight used
    by the weighted walk iterator."""

    def __init__(self, num_vertices: int,
                 allow_multiple_edges: bool = True,
                 vertex_factory: Optional[Callable[[int], object]] = None):
        if num_vertices <= 0:
            raise ValueError("num_vertices must be positive")
        self._vertices = [
            Vertex(i, vertex_factory(i) if vertex_factory else None)
            for i in range(num_vertices)]
        self._adj: List[List[Tuple[int, float]]] = \
            [[] for _ in range(num_vertices)]
        self._allow_multi = allow_multiple_edges
        self._edge_count = 0
        self._csr = None   # invalidated on mutation

    # -- construction --------------------------------------------------
    def addEdge(self, frm: int, to: int, weight: float = 1.0,
                directed: bool = False) -> None:
        if not (0 <= frm < self.numVertices()
                and 0 <= to < self.numVertices()):
            raise ValueError(
                f"edge ({frm},{to}) out of range for "
                f"{self.numVertices()} vertices")
        if weight <= 0:
            raise ValueError("edge weight must be > 0")
        if not self._allow_multi and any(
                t == to for t, _ in self._adj[frm]):
            return
        self._adj[frm].append((to, float(weight)))
        if not directed and frm != to:
            self._adj[to].append((frm, float(weight)))
        self._edge_count += 1
        self._csr = None

    # -- queries (reference IGraph surface) ----------------------------
    def numVertices(self) -> int:
        return len(self._vertices)

    def numEdges(self) -> int:
        return self._edge_count

    def getVertex(self, idx: int) -> Vertex:
        return self._vertices[idx]

    def getVertexDegree(self, idx: int) -> int:
        return len(self._adj[idx])

    def getConnectedVertexIndices(self, idx: int) -> List[int]:
        return [t for t, _ in self._adj[idx]]

    # -- CSR view (the walk engine's format) ---------------------------
    def csr(self):
        """(indptr[n+1], indices[m], cumw[m]) — ``cumw`` is the GLOBAL
        running sum of edge weights in CSR order, so row r's weights
        occupy ``cumw[indptr[r]:indptr[r+1]]`` ending at the row's
        total-mass prefix; weighted sampling is one global
        ``searchsorted`` (see module docstring)."""
        if self._csr is None:
            n = self.numVertices()
            degrees = np.array([len(a) for a in self._adj], np.int64)
            indptr = np.zeros(n + 1, np.int64)
            np.cumsum(degrees, out=indptr[1:])
            indices = np.empty(indptr[-1], np.int64)
            weights = np.empty(indptr[-1], np.float64)
            for r, adj in enumerate(self._adj):
                if adj:
                    indices[indptr[r]:indptr[r + 1]] = [t for t, _ in adj]
                    weights[indptr[r]:indptr[r + 1]] = [w for _, w in adj]
            self._csr = (indptr, indices, np.cumsum(weights))
        return self._csr


class GraphLoader:
    """Edge-list file loaders (reference:
    org/deeplearning4j/graph/data/GraphLoader.java)."""

    @staticmethod
    def loadUndirectedGraphEdgeListFile(path: str, num_vertices: int,
                                        delimiter: str = ",") -> Graph:
        g = Graph(num_vertices)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delimiter)
                if len(parts) < 2:
                    raise ValueError(f"bad edge line: {line!r}")
                g.addEdge(int(parts[0]), int(parts[1]))
        return g

    @staticmethod
    def loadWeightedEdgeListFile(path: str, num_vertices: int,
                                 delimiter: str = ",",
                                 directed: bool = False) -> Graph:
        g = Graph(num_vertices)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(delimiter)
                if len(parts) < 3:
                    raise ValueError(
                        f"weighted edge line needs from,to,weight: "
                        f"{line!r}")
                g.addEdge(int(parts[0]), int(parts[1]),
                          weight=float(parts[2]), directed=directed)
        return g


# ---------------------------------------------------------------------
# Random walk generation — vectorised over ALL walks simultaneously
# ---------------------------------------------------------------------

def generate_random_walks(
        graph: Graph, walk_length: int,
        starts: Optional[Sequence[int]] = None,
        weighted: bool = False, seed: int = 0,
        no_edge_handling: str =
        NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED) -> np.ndarray:
    """All walks advance together, one numpy op per step (reference
    iterators RandomWalkIterator / WeightedRandomWalkIterator produce
    one IVertexSequence at a time; here the whole batch is one
    [num_walks, walk_length+1] matrix — the shape the batched HS
    trainer wants).

    ``walk_length`` counts EDGES, matching the reference's convention
    (a length-L walk visits L+1 vertices)."""
    indptr, indices, cumw = graph.csr()
    if starts is None:
        starts = np.arange(graph.numVertices(), dtype=np.int64)
    cur = np.asarray(starts, np.int64).copy()
    if cur.size and (cur.min() < 0 or cur.max() >= graph.numVertices()):
        raise ValueError(
            f"start vertices out of range [0, {graph.numVertices()}): "
            f"{cur[(cur < 0) | (cur >= graph.numVertices())].tolist()}")

    degrees = (indptr[1:] - indptr[:-1])
    if no_edge_handling == NoEdgeHandling.EXCEPTION_ON_DISCONNECTED:
        dead = np.unique(cur[degrees[cur] == 0])
        if dead.size:
            raise ValueError(
                f"vertices {dead.tolist()} have no outgoing edges "
                "(NoEdgeHandling.EXCEPTION_ON_DISCONNECTED)")

    rng = np.random.default_rng(seed)
    walks = np.empty((len(cur), walk_length + 1), np.int64)
    walks[:, 0] = cur
    row_base = np.concatenate(([0.0], cumw))  # mass before row start

    for step in range(walk_length):
        deg = degrees[cur]
        alive = deg > 0
        if no_edge_handling == NoEdgeHandling.EXCEPTION_ON_DISCONNECTED \
                and not alive.all():
            raise ValueError(
                f"walk reached a disconnected vertex at step {step} "
                "(NoEdgeHandling.EXCEPTION_ON_DISCONNECTED)")
        u = rng.random(len(cur))
        nxt = cur.copy()                      # self-loop on dead ends
        if alive.any():
            if weighted:
                lo = row_base[indptr[cur[alive]]]
                hi = row_base[indptr[cur[alive]] + deg[alive]]
                pos = np.searchsorted(cumw, lo + u[alive] * (hi - lo),
                                      side="right")
                pos = np.minimum(pos, indptr[cur[alive]] + deg[alive] - 1)
                nxt[alive] = indices[pos]
            else:
                offs = (u[alive] * deg[alive]).astype(np.int64)
                nxt[alive] = indices[indptr[cur[alive]] + offs]
        cur = nxt
        walks[:, step + 1] = cur
    return walks


class RandomWalkIterator:
    """Uniform random walks, one start per vertex (reference:
    org/deeplearning4j/graph/iterator/RandomWalkIterator.java). Kept as
    a thin iterator facade over the batched generator for API parity —
    the model consumes the batch directly."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0,
                 no_edge_handling: str =
                 NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
                 weighted: bool = False):
        self.graph = graph
        self.walk_length = walk_length
        self.no_edge_handling = no_edge_handling
        self.weighted = weighted
        # persistent RNG so each reset() yields FRESH walks, like the
        # reference iterator's long-lived java.util.Random
        self._seed_rng = np.random.default_rng(seed)
        self.reset()

    def reset(self) -> None:
        self._walks = generate_random_walks(
            self.graph, self.walk_length, weighted=self.weighted,
            seed=int(self._seed_rng.integers(2 ** 31)),
            no_edge_handling=self.no_edge_handling)
        self._pos = 0

    def hasNext(self) -> bool:
        return self._pos < len(self._walks)

    def next(self) -> List[int]:
        if not self.hasNext():
            raise StopIteration
        w = self._walks[self._pos].tolist()
        self._pos += 1
        return w


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-biased walks (reference:
    org/deeplearning4j/graph/iterator/WeightedRandomWalkIterator.java)."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0,
                 no_edge_handling: str =
                 NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED):
        super().__init__(graph, walk_length, seed=seed,
                         no_edge_handling=no_edge_handling,
                         weighted=True)


# ---------------------------------------------------------------------
# DeepWalk
# ---------------------------------------------------------------------

def _cosine(a: np.ndarray, b: np.ndarray) -> float:
    denom = np.linalg.norm(a) * np.linalg.norm(b)
    return float(a @ b / denom) if denom else 0.0


def _nearest(mat: np.ndarray, vertex: int, top: int) -> List[int]:
    q = mat[vertex]
    sims = mat @ q / (np.linalg.norm(mat, axis=1)
                      * np.linalg.norm(q) + 1e-12)
    sims[vertex] = -np.inf
    return np.argsort(-sims)[:top].tolist()

class GraphHuffman:
    """Degree-keyed Huffman coding of the vertex set (reference:
    org/deeplearning4j/graph/models/deepwalk/GraphHuffman.java — there
    codes are built over vertex degrees so hub vertices get short
    paths). Reuses the word2vec vocab cache's two-array O(V) builder;
    vertices are cache "words" keyed by their id string."""

    def __init__(self, graph: Graph):
        cache = AbstractCache()
        for v in range(graph.numVertices()):
            # +1 so degree-0 vertices still carry positive mass and the
            # frequency-DESC sort the builder assumes stays total
            cache.addToken(str(v), by=graph.getVertexDegree(v) + 1.0)
        cache.finalize_vocab(min_word_frequency=0)
        self.cache = cache
        self.n_inner = cache.build_huffman()
        # vertex id -> frequency-sorted cache row (the embedding row)
        self.vertex_to_row = np.array(
            [cache.indexOf(str(v)) for v in range(graph.numVertices())],
            np.int32)
        self.row_to_vertex = np.empty(graph.numVertices(), np.int32)
        self.row_to_vertex[self.vertex_to_row] = np.arange(
            graph.numVertices(), dtype=np.int32)

    def getCodeLength(self, vertex: int) -> int:
        return len(self.cache.vocabWords()[
            self.vertex_to_row[vertex]].codes)

    def path_tables(self):
        """Padded [V, Lmax] (points, codes, mask) device tables in ROW
        order — the same layout SequenceVectors._init_tables builds."""
        words = self.cache.vocabWords()
        lmax = max(len(vw.codes) for vw in words)
        v = len(words)
        pts = np.zeros((v, lmax), np.int32)
        cds = np.zeros((v, lmax), np.float32)
        msk = np.zeros((v, lmax), np.float32)
        for vw in words:
            L = len(vw.codes)
            pts[vw.index, :L] = vw.points
            cds[vw.index, :L] = vw.codes
            msk[vw.index, :L] = 1.0
        return jnp.asarray(pts), jnp.asarray(cds), jnp.asarray(msk)


class DeepWalk:
    """DeepWalk vertex embeddings (reference:
    org/deeplearning4j/graph/models/deepwalk/DeepWalk.java + its
    InMemoryGraphLookupTable): skip-gram with hierarchical softmax over
    random walk windows. Training is device-batched: every window pair
    of every walk in the batch goes through ONE compiled HS step.

    Implements the reference ``GraphVectors`` query interface
    (org/deeplearning4j/graph/models/GraphVectors.java)."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 learning_rate: float = 0.025, seed: int = 12345,
                 batch_size: int = 4096):
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.seed = seed
        self.batch_size = batch_size
        self.syn0 = None
        self.syn1 = None
        self._huffman: Optional[GraphHuffman] = None
        self._graph: Optional[Graph] = None
        self._rng = np.random.default_rng(seed)

    class Builder:
        """reference: DeepWalk.Builder fluent config."""

        def __init__(self):
            self._kw = {}

        def vectorSize(self, n: int):
            self._kw["vector_size"] = n
            return self

        def windowSize(self, n: int):
            self._kw["window_size"] = n
            return self

        def learningRate(self, lr: float):
            self._kw["learning_rate"] = lr
            return self

        def seed(self, s: int):
            self._kw["seed"] = s
            return self

        def batchSize(self, b: int):
            self._kw["batch_size"] = b
            return self

        def build(self) -> "DeepWalk":
            return DeepWalk(**self._kw)

    # -- lifecycle -----------------------------------------------------
    def initialize(self, graph: Graph) -> None:
        """Build the degree-Huffman tree + tables (reference:
        DeepWalk#initialize)."""
        self._graph = graph
        self._huffman = GraphHuffman(graph)
        v, d = graph.numVertices(), self.vector_size
        rng = np.random.default_rng(self.seed)
        self.syn0 = jnp.asarray((rng.random((v, d)) - 0.5) / d,
                                jnp.float32)
        self.syn1 = jnp.zeros((max(self._huffman.n_inner, 1), d),
                              jnp.float32)
        self._tables = self._huffman.path_tables()

    def fit(self, graph: Optional[Graph] = None, walk_length: int = 40,
            walks_per_vertex: int = 1, epochs: int = 1,
            weighted: bool = False) -> "DeepWalk":
        """Generate walks and train (reference: DeepWalk#fit(IGraph,int)
        — there one pass over a GraphWalkIteratorProvider; here
        ``walks_per_vertex`` x ``epochs`` batched passes)."""
        if graph is not None and self._graph is not graph:
            self.initialize(graph)
        if self._graph is None:
            raise ValueError("call initialize(graph) or pass graph")
        total = epochs * walks_per_vertex
        done = 0
        for _ in range(epochs):
            for _ in range(walks_per_vertex):
                walks = generate_random_walks(
                    self._graph, walk_length, weighted=weighted,
                    seed=int(self._rng.integers(2 ** 31)))
                lr = self.learning_rate * max(
                    1.0 - done / max(total, 1), 0.05)
                self._train_on_walks(walks, lr)
                done += 1
        return self

    def _train_on_walks(self, walks: np.ndarray, lr: float) -> None:
        """All (center, context) window pairs of all walks -> shuffled
        device batches through the shared HS skip-gram step."""
        v2r = self._huffman.vertex_to_row
        rows = v2r[walks]                       # [W, L+1] cache rows
        L = rows.shape[1]
        centers, contexts = [], []
        for off in range(1, self.window_size + 1):
            if off >= L:
                break
            centers.append(rows[:, :-off].ravel())
            contexts.append(rows[:, off:].ravel())
            # symmetric window: each pair also trains reversed
            centers.append(rows[:, off:].ravel())
            contexts.append(rows[:, :-off].ravel())
        c = np.concatenate(centers).astype(np.int32)
        o = np.concatenate(contexts).astype(np.int32)
        perm = self._rng.permutation(len(c))
        c, o = c[perm], o[perm]
        pts, cds, msk = self._tables
        for s in range(0, len(c), self.batch_size):
            self.syn0, self.syn1, self._last_loss = _sg_hs_step(
                self.syn0, self.syn1,
                jnp.asarray(c[s:s + self.batch_size]),
                jnp.asarray(o[s:s + self.batch_size]),
                pts, cds, msk, jnp.float32(lr))

    # -- GraphVectors interface ----------------------------------------
    def numVertices(self) -> int:
        self._check_fitted()
        return self._graph.numVertices()

    def getVertexVector(self, vertex: int) -> np.ndarray:
        self._check_fitted()
        row = int(self._huffman.vertex_to_row[vertex])
        return np.asarray(self.syn0[row])

    def getVectorMatrix(self) -> np.ndarray:
        """[numVertices, D] in VERTEX-id order."""
        self._check_fitted()
        return np.asarray(self.syn0)[self._huffman.vertex_to_row]

    def similarity(self, v1: int, v2: int) -> float:
        return _cosine(self.getVertexVector(v1),
                       self.getVertexVector(v2))

    def verticesNearest(self, vertex: int, top: int = 10) -> List[int]:
        self._check_fitted()
        return _nearest(self.getVectorMatrix(), vertex, top)

    def _check_fitted(self):
        if self.syn0 is None or self._graph is None:
            raise ValueError("DeepWalk not initialized — call fit()")


# ---------------------------------------------------------------------
# Serde (reference: org/deeplearning4j/graph/models/loader/
# GraphVectorSerializer.java — line-per-vertex text format)
# ---------------------------------------------------------------------

def writeGraphVectors(model: DeepWalk, path: str) -> None:
    mat = model.getVectorMatrix()
    with open(path, "w") as f:
        f.write(f"{mat.shape[0]} {mat.shape[1]}\n")
        for i, row in enumerate(mat):
            f.write(str(i) + " "
                    + " ".join(f"{x:.8g}" for x in row) + "\n")


class StaticGraphVectors:
    """Query-only GraphVectors over a loaded matrix (what
    GraphVectorSerializer.loadTxtVectors returns)."""

    def __init__(self, matrix: np.ndarray):
        self.matrix = matrix

    def numVertices(self) -> int:
        return self.matrix.shape[0]

    def getVertexVector(self, vertex: int) -> np.ndarray:
        return self.matrix[vertex]

    def similarity(self, v1: int, v2: int) -> float:
        return _cosine(self.matrix[v1], self.matrix[v2])

    def verticesNearest(self, vertex: int, top: int = 10) -> List[int]:
        return _nearest(self.matrix, vertex, top)


def loadGraphVectors(path: str) -> StaticGraphVectors:
    with open(path) as f:
        n, d = map(int, f.readline().split())
        mat = np.empty((n, d), np.float32)
        for line in f:
            parts = line.split()
            mat[int(parts[0])] = [float(x) for x in parts[1:]]
    return StaticGraphVectors(mat)
