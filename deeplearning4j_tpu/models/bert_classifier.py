"""BERT sequence classification — the fine-tuning recipe.

Reference workflow: import/pretrain BERT (SameDiff TF-import path,
SURVEY.md §3.4), then fine-tune with a pooled classification head — the
GLUE-style task every reference BERT user runs next.

TPU design: reuses TransformerEncoder (same tp/sp shardings); the head
is first-token ("[CLS]") pooling -> tanh dense -> n_classes logits, and
the WHOLE fine-tune step (encoder fwd+bwd, head, updater) is one XLA
executable.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig, TransformerEncoder,
)


class BertSequenceClassifier:
    def __init__(self, config: TransformerConfig, n_classes: int,
                 attn_impl: str = "default"):
        self.encoder = TransformerEncoder(config, attn_impl=attn_impl)
        self.cfg = config
        self.n_classes = n_classes

    # -- params ---------------------------------------------------------
    def init_params(self, key=None,
                    encoder_params: Optional[Dict[str, Any]] = None):
        """Fresh head; encoder params either fresh or transplanted from
        a pretrained/imported encoder (the transfer-learning path)."""
        key = key if key is not None else jax.random.key(0)
        k_enc, k_pool, k_cls = jax.random.split(key, 3)
        d = self.cfg.d_model
        enc = encoder_params if encoder_params is not None \
            else self.encoder.init_params(k_enc)
        pdt = self.encoder._pdtype
        params = dict(enc)
        params["pooler"] = {
            "W": 0.02 * jax.random.normal(k_pool, (d, d), pdt),
            "b": jnp.zeros((d,), pdt),
        }
        params["classifier"] = {
            "W": 0.02 * jax.random.normal(k_cls, (d, self.n_classes), pdt),
            "b": jnp.zeros((self.n_classes,), pdt),
        }
        return params

    # -- forward --------------------------------------------------------
    def logits(self, params, ids, mask=None, train=False, rng=None):
        cd = self.encoder._cdtype
        hidden = self.encoder.encode(params, ids, mask=mask, train=train,
                                     rng=rng)
        cls = hidden[:, 0]                      # [N, D] first-token pool
        pooled = jnp.tanh(cls @ params["pooler"]["W"].astype(cd)
                          + params["pooler"]["b"].astype(cd))
        out = pooled @ params["classifier"]["W"].astype(cd) \
            + params["classifier"]["b"].astype(cd)
        return out.astype(jnp.float32)

    def loss(self, params, ids, labels, mask=None, train=True, rng=None):
        lg = self.logits(params, ids, mask=mask, train=train, rng=rng)
        logp = jax.nn.log_softmax(lg)
        onehot = jax.nn.one_hot(labels, self.n_classes, dtype=logp.dtype)
        return -(onehot * logp).sum(-1).mean()

    # -- compiled fine-tune step ---------------------------------------
    def make_train_step(self, updater):
        apply_updates = TransformerEncoder._apply_updates

        def step(params, opt_state, it_step, ids, labels, mask, rng):
            loss, grads = jax.value_and_grad(self.loss)(
                params, ids, labels, mask=mask, train=True, rng=rng)
            new_params, new_opt = apply_updates(updater, params, opt_state,
                                                grads, it_step)
            return new_params, new_opt, loss

        return jax.jit(step, donate_argnums=(0, 1))

    def predict(self, params, ids, mask=None):
        return jnp.argmax(self.logits(params, ids, mask=mask), axis=-1)
