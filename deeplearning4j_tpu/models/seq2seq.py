"""Seq2seq encoder-decoder LSTM (reference: the dl4j-examples
AdditionRNN / seq2seq recipe built from ComputationGraph with
graph/vertex/impl/rnn/{LastTimeStepVertex,DuplicateToTimeSeriesVertex} —
BASELINE.md config 3's 'Char-RNN / seq2seq LSTM' family).

Encoder LSTM reads the source sequence; its final hidden state (the
"thought vector") is broadcast along the decoder's time axis and
concatenated with the (teacher-forced) decoder input; a decoder LSTM +
RnnOutputLayer emit the target sequence. The whole train step — both
RNNs' lax.scans, the vertex plumbing, softmax CE — compiles into one
XLA executable.
"""

from __future__ import annotations

from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.nn.conf import (
    InputType, LSTM, RnnOutputLayer,
)
from deeplearning4j_tpu.nn.graph import (
    ComputationGraph, ComputationGraphConfiguration,
    DuplicateToTimeSeriesVertex, LastTimeStepVertex, MergeVertex,
)


class Seq2SeqLSTM:
    """Builder for the encoder-decoder graph.

    in_features/out_features: one-hot (or feature) widths of source and
    target alphabets; decoder input is the shifted target (teacher
    forcing), exactly like the reference example.
    """

    def __init__(self, in_features: int, out_features: int,
                 hidden: int = 128, t_in: int = 12, t_out: int = 12,
                 seed: int = 42, updater=None):
        self.in_features = in_features
        self.out_features = out_features
        self.hidden = hidden
        self.t_in = t_in
        self.t_out = t_out
        self.seed = seed
        self.updater = updater or Adam(5e-3)

    def conf(self) -> ComputationGraphConfiguration:
        b = (ComputationGraphConfiguration.graphBuilder()
             .seed(self.seed).updater(self.updater)
             .addInputs("encoderInput", "decoderInput")
             .setInputTypes(
                 InputType.recurrent(self.in_features, self.t_in),
                 InputType.recurrent(self.out_features, self.t_out)))
        b.addLayer("encoder",
                   LSTM(n_out=self.hidden, activation="tanh"),
                   "encoderInput")
        b.addVertex("thought", LastTimeStepVertex(), "encoder")
        b.addVertex("dup", DuplicateToTimeSeriesVertex(),
                    "thought", "decoderInput")
        b.addVertex("decoderIn", MergeVertex(), "decoderInput", "dup")
        b.addLayer("decoder",
                   LSTM(n_out=self.hidden, activation="tanh"),
                   "decoderIn")
        b.addLayer("output",
                   RnnOutputLayer(n_out=self.out_features,
                                  activation="softmax", loss="mcxent"),
                   "decoder")
        return b.setOutputs("output").build()

    def init(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()
