"""Mixture-of-Experts FFN with expert parallelism.

The reference has no MoE / expert parallelism at all (SURVEY.md §2
parallelism list: data parallelism only) — this is a TPU-first
extension in the GShard/Switch style:

- Token-choice top-k routing with a STATIC per-expert capacity
  ``C = ceil(tokens/E * capacity_factor * k)``: dispatch and combine are
  dense one-hot einsum tensors, so the whole layer is fixed-shape XLA —
  no sorts-with-dynamic-output, no ragged buffers.
- Expert weights are stacked on a leading [E, ...] axis; under a mesh
  the stack is sharded over the 'model' axis (expert parallelism rides
  the tensor-parallel axis), and GSPMD lowers the dispatch/combine
  einsums to the expert all-to-alls over ICI.
- Switch-style load-balancing auxiliary loss: E * Σ_e (token fraction
  routed to e) * (mean router prob of e); differentiable through the
  router probs.

Tokens that overflow an expert's capacity are dropped (contribute zero
to the layer output — the residual connection carries them), standard
Switch behavior.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def capacity(n_tokens: int, n_experts: int, capacity_factor: float,
             top_k: int) -> int:
    return max(1, int(math.ceil(
        n_tokens * capacity_factor * top_k / n_experts)))


def router_dispatch(probs: jnp.ndarray, top_k: int, cap: int):
    """probs: [S, E] router probabilities. Returns (combine [S, E, C]
    float32, aux scalar). combine holds the (normalized) gate for each
    token's kept expert/slot assignments; zero rows = dropped tokens."""
    s, e = probs.shape
    counts = jnp.zeros((e,), jnp.float32)       # slots used per expert
    remaining = probs
    combine = jnp.zeros((s, e, cap), jnp.float32)
    first_choice = None
    gate_sum = jnp.zeros((s,), jnp.float32)
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)            # [S]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)
        gate = jnp.sum(probs * onehot, axis=-1)         # [S]
        if first_choice is None:
            first_choice = onehot
        # position of each token within its chosen expert: tokens
        # earlier in the batch fill slots first (deterministic)
        pos = jnp.cumsum(onehot, axis=0) - onehot + counts
        mypos = jnp.sum(pos * onehot, axis=-1)          # [S]
        keep = (mypos < cap).astype(jnp.float32)
        counts = counts + jnp.sum(onehot * keep[:, None], axis=0)
        slot = jax.nn.one_hot(jnp.clip(mypos, 0, cap - 1).astype(jnp.int32),
                              cap, dtype=jnp.float32)
        combine = combine + (gate * keep)[:, None, None] \
            * onehot[:, :, None] * slot[:, None, :]
        gate_sum = gate_sum + gate * keep
        remaining = remaining * (1.0 - onehot)
    if top_k > 1:
        # GShard top-k gate normalization. NOT applied for top-1:
        # gate/gate == 1 would zero d(output)/d(router), leaving the
        # router trainable only through the aux loss — Switch keeps the
        # raw gate so the router learns from the task loss.
        combine = combine / jnp.maximum(gate_sum, 1e-9)[:, None, None]
    # Switch aux loss on the FIRST choice: fraction routed * mean prob
    frac = jnp.mean(first_choice, axis=0)               # [E]
    mean_prob = jnp.mean(probs, axis=0)                 # [E]
    aux = e * jnp.sum(frac * mean_prob)
    return combine, aux


def moe_ffn(x, wr, we1, be1, we2, be2, *, top_k: int,
            capacity_factor: float, sharded: bool = False,
            activation=jax.nn.gelu, group_size: int | None = None):
    """x: [S, D] tokens -> ([S, D], aux loss).

    wr [D, E] router; we1 [E, D, F], be1 [E, F], we2 [E, F, D],
    be2 [E, D] stacked expert FFNs.

    group_size: dispatch within fixed-size token groups (GShard-style —
    typically one sequence per group). The combine/dispatch tensors are
    then [G, g, E, C_g] with C_g = ceil(g*cf*k/E), i.e. LINEAR in total
    tokens; a single global group would be O(S^2) and OOM at
    production sizes. None = one group (fine for small S).
    """
    s, d = x.shape
    e = wr.shape[-1]
    g = group_size or s
    if s % g != 0:
        raise ValueError(f"token count {s} not divisible by "
                         f"group_size {g}")
    n_groups = s // g
    cap = capacity(g, e, capacity_factor, top_k)
    xg = x.reshape(n_groups, g, d)
    logits = (xg @ wr.astype(x.dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    combine, aux = jax.vmap(
        lambda p: router_dispatch(p, top_k, cap))(probs)
    aux = jnp.mean(aux)
    combine = combine.astype(x.dtype)                   # [G, g, E, C]
    dispatch = (combine > 0).astype(x.dtype)

    def ep(t):
        # expert-stacked intermediates: groups ride the 'data' axis,
        # experts the 'model' axis (EP) — GSPMD turns the
        # dispatch/combine einsums into the expert all-to-alls
        if not sharded:
            return t
        return lax.with_sharding_constraint(
            t, P("data", "model", *([None] * (t.ndim - 2))))

    xe = ep(jnp.einsum("gsec,gsd->gecd", dispatch, xg))
    he = activation(ep(jnp.einsum("gecd,edf->gecf", xe,
                                  we1.astype(x.dtype))
                       + be1.astype(x.dtype)[None, :, None, :]))
    ye = ep(jnp.einsum("gecf,efd->gecd", he, we2.astype(x.dtype))
            + be2.astype(x.dtype)[None, :, None, :])
    y = jnp.einsum("gsec,gecd->gsd", combine, ye)
    return y.reshape(s, d), aux
