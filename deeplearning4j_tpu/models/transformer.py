"""TransformerEncoder — the flagship distributed model.

Reference parity target: BERT-base via SameDiff TF-import
(BASELINE.md; SURVEY.md §3.4). The reference executes the imported
graph node-by-node in a Java interpreter loop; here the model is a pure
jax function whose whole training step compiles to one XLA program, and
whose parallelism is declared as sharding specs over a
('data', 'model') mesh:

- DP: batch axis sharded over 'data'.
- TP (Megatron-style): QKV and MLP-in projections column-sharded over
  'model' (P(None, 'model')), attention-out and MLP-out row-sharded
  (P('model', None)) — XLA GSPMD inserts the all-reduces on ICI.
- SP (sequence parallelism): between blocks, activations are sharded
  over the token axis on 'model' (P('data', 'model', None)) so
  layernorm/residual/dropout work is divided rather than replicated —
  the reshard to/from head-sharded attention is GSPMD's all-to-all.
  This is what lets sequence length scale past one chip's HBM, the
  capability the reference entirely lacks (SURVEY.md §5 long-context).

The encoder trains masked-LM style (tied output head) or
classification; both heads are provided.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.common.serde import serializable
from deeplearning4j_tpu.parallel.mesh import axis_size as _axis_size


@serializable
@dataclasses.dataclass
class TransformerConfig:
    vocab_size: int = 30522          # BERT-base vocab
    max_len: int = 512
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    dropout: float = 0.1
    type_vocab: int = 2
    eps: float = 1e-12
    dtype: str = "float32"           # params; compute may be bf16
    compute_dtype: str = "bfloat16"  # MXU-native
    seed: int = 0
    # Mixture-of-Experts (0 = dense FFN). When set, EVERY layer's FFN is
    # an expert-parallel MoE, sharded over the 'model' mesh axis
    # (models/moe.py). Composes with every engine: make_train_step
    # (GSPMD EP), make_ring_train_step (SP x EP: shard-local routing,
    # aux pmean'd over the mesh), and PipelinedTransformer (PP x EP:
    # per-stage aux sums counted on real microbatch ticks only).
    n_experts: int = 0
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def bert_base() -> TransformerConfig:
    return TransformerConfig()


def tiny_config(vocab=128, max_len=64, d_model=64, n_layers=2, n_heads=4,
                d_ff=128) -> TransformerConfig:
    return TransformerConfig(vocab_size=vocab, max_len=max_len,
                             d_model=d_model, n_layers=n_layers,
                             n_heads=n_heads, d_ff=d_ff,
                             compute_dtype="float32")


class TransformerEncoder:
    def __init__(self, config: TransformerConfig, attn_impl: str = "default"):
        """attn_impl: 'default' (fused XLA softmax attention) or 'flash'
        (ops.flash_attention dispatcher: Pallas kernels on TPU,
        blockwise online-softmax elsewhere — O(T) memory)."""
        self.cfg = config
        self.attn_impl = attn_impl
        self._pdtype = jnp.dtype(config.dtype)
        self._cdtype = jnp.dtype(config.compute_dtype)

    # ------------------------------------------------------------------
    # parameters
    # ------------------------------------------------------------------
    def init_params(self, key=None) -> Dict[str, Any]:
        cfg = self.cfg
        key = key if key is not None else jax.random.key(cfg.seed)
        d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab_size
        std = 0.02

        def norm(k, shape):
            return std * jax.random.normal(k, shape, self._pdtype)

        keys = jax.random.split(key, 4 + cfg.n_layers)
        params = {
            "tok_emb": norm(keys[0], (v, d)),
            "pos_emb": norm(keys[1], (cfg.max_len, d)),
            "type_emb": norm(keys[2], (cfg.type_vocab, d)),
            "emb_ln": {"gamma": jnp.ones((d,), self._pdtype),
                       "beta": jnp.zeros((d,), self._pdtype)},
            "layers": [],
        }
        for li in range(cfg.n_layers):
            ks = jax.random.split(keys[4 + li], 6)
            lp = {
                "wqkv": norm(ks[0], (d, 3 * d)),
                "bqkv": jnp.zeros((3 * d,), self._pdtype),
                "wo": norm(ks[1], (d, d)),
                "bo": jnp.zeros((d,), self._pdtype),
                "ln1": {"gamma": jnp.ones((d,), self._pdtype),
                        "beta": jnp.zeros((d,), self._pdtype)},
                "ln2": {"gamma": jnp.ones((d,), self._pdtype),
                        "beta": jnp.zeros((d,), self._pdtype)},
            }
            if cfg.n_experts:
                e = cfg.n_experts
                lp.update({
                    "wr": norm(ks[4], (d, e)),
                    "we1": norm(ks[2], (e, d, f)),
                    "be1": jnp.zeros((e, f), self._pdtype),
                    "we2": norm(ks[3], (e, f, d)),
                    "be2": jnp.zeros((e, d), self._pdtype),
                })
            else:
                lp.update({
                    "w1": norm(ks[2], (d, f)),
                    "b1": jnp.zeros((f,), self._pdtype),
                    "w2": norm(ks[3], (f, d)),
                    "b2": jnp.zeros((d,), self._pdtype),
                })
            params["layers"].append(lp)
        params["mlm_bias"] = jnp.zeros((v,), self._pdtype)
        return params

    # ------------------------------------------------------------------
    # sharding specs (Megatron TP + SP between blocks)
    # ------------------------------------------------------------------
    def param_specs(self) -> Dict[str, Any]:
        rep = P()
        ln = {"gamma": rep, "beta": rep}
        layer = {
            "wqkv": P(None, "model"),   # column-parallel
            "bqkv": P("model"),
            "wo": P("model", None),     # row-parallel
            "bo": rep,
            "ln1": ln,
            "ln2": ln,
        }
        if self.cfg.n_experts:
            layer.update({
                # expert parallelism: expert stack over 'model'
                "wr": rep,
                "we1": P("model", None, None),
                "be1": P("model", None),
                "we2": P("model", None, None),
                "be2": P("model", None),
            })
        else:
            layer.update({
                "w1": P(None, "model"),
                "b1": P("model"),
                "w2": P("model", None),
                "b2": rep,
            })
        return {
            "tok_emb": P(None, "model"),
            "pos_emb": rep,
            "type_emb": rep,
            "emb_ln": ln,
            "layers": [dict(layer) for _ in range(self.cfg.n_layers)],
            "mlm_bias": rep,
        }

    # ------------------------------------------------------------------
    # forward
    # ------------------------------------------------------------------
    def _ln(self, x, p):
        m = jnp.mean(x, -1, keepdims=True)
        v = jnp.var(x, -1, keepdims=True)
        return (x - m) * lax.rsqrt(v + self.cfg.eps) * p["gamma"] + p["beta"]

    def _sp(self, x, sharded: bool):
        """Sequence-parallel constraint between blocks (token axis on
        'model'); no-op when running unsharded."""
        if not sharded:
            return x
        return lax.with_sharding_constraint(x, P("data", "model", None))

    def _attn_sp(self, x, sharded: bool):
        if not sharded:
            return x
        return lax.with_sharding_constraint(x, P("data", None, "model"))

    def encode(self, params, ids, type_ids=None, mask=None, train=False,
               rng=None, sharded=False, return_aux=False):
        """ids: [N, T] int32 -> hidden [N, T, D]."""
        cfg = self.cfg
        n, t = ids.shape
        cd = self._cdtype
        x = params["tok_emb"].astype(cd)[ids]
        x = x + params["pos_emb"].astype(cd)[None, :t]
        if type_ids is not None:
            x = x + params["type_emb"].astype(cd)[type_ids]
        x = self._ln(x, {k: v.astype(cd) for k, v in params["emb_ln"].items()})
        x = self._sp(x, sharded)

        att_mask = None
        if mask is not None:
            att_mask = mask[:, None, None, :]  # [N,1,1,T] key padding

        attn_fn = None
        if self.attn_impl == "flash":
            from deeplearning4j_tpu.ops.flash_attention import attention

            def attn_fn(q, k, v, m):
                key_mask = None if m is None else m[:, 0, 0, :]
                return attention(q, k, v, key_mask)

        keys = (jax.random.split(rng, cfg.n_layers)
                if (train and rng is not None) else [None] * cfg.n_layers)
        aux_total = jnp.float32(0.0)
        for li, lp in enumerate(params["layers"]):
            x, aux = self._block(x, lp, att_mask, train, keys[li], sharded,
                                 attn_fn=attn_fn)
            aux_total = aux_total + aux
        if return_aux:
            return x, aux_total
        return x

    def _block(self, x, lp, att_mask, train, rng, sharded, attn_fn=None):
        cfg = self.cfg
        cd = self._cdtype
        n, t, d = x.shape
        h, hd = cfg.n_heads, cfg.head_dim

        # attention (post-LN like BERT: LN after residual)
        qkv = x @ lp["wqkv"].astype(cd) + lp["bqkv"].astype(cd)
        qkv = self._attn_sp(qkv, sharded)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(y):
            return y.reshape(n, t, h, hd).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        if attn_fn is not None:
            # pluggable attention: ring / ulysses / pallas flash.
            # att_mask must go through the impl (which may need to
            # rotate it around the ring) — never drop it silently.
            ctx = attn_fn(q, k, v, att_mask)
        else:
            scale = 1.0 / jnp.sqrt(jnp.asarray(hd, cd))
            logits = jnp.einsum("nhqd,nhkd->nhqk", q, k) * scale
            if att_mask is not None:
                neg = jnp.asarray(jnp.finfo(logits.dtype).min, logits.dtype)
                logits = jnp.where(att_mask.astype(bool), logits, neg)
            w = jax.nn.softmax(logits, axis=-1)
            ctx = jnp.einsum("nhqk,nhkd->nhqd", w, v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(n, t, d)
        att = ctx @ lp["wo"].astype(cd) + lp["bo"].astype(cd)
        if train and rng is not None and cfg.dropout > 0:
            rng, sub = jax.random.split(rng)
            keep = 1.0 - cfg.dropout
            att = att * jax.random.bernoulli(sub, keep, att.shape) / keep
        x = self._sp(x + att, sharded)
        x = self._ln(x, {k2: v2.astype(cd) for k2, v2 in lp["ln1"].items()})

        # MLP — dense FFN or expert-parallel MoE (models/moe.py)
        aux = jnp.float32(0.0)
        if "we1" in lp:
            from deeplearning4j_tpu.models.moe import moe_ffn

            out, aux = moe_ffn(
                x.reshape(n * t, d), lp["wr"], lp["we1"], lp["be1"],
                lp["we2"], lp["be2"], top_k=cfg.expert_top_k,
                capacity_factor=cfg.capacity_factor, sharded=sharded,
                group_size=t)  # per-sequence dispatch groups
            out = out.reshape(n, t, d)
        else:
            hmid = jax.nn.gelu(x @ lp["w1"].astype(cd) + lp["b1"].astype(cd))
            out = hmid @ lp["w2"].astype(cd) + lp["b2"].astype(cd)
        if train and rng is not None and cfg.dropout > 0:
            rng, sub = jax.random.split(rng)
            keep = 1.0 - cfg.dropout
            out = out * jax.random.bernoulli(sub, keep, out.shape) / keep
        x = self._sp(x + out, sharded)
        x = self._ln(x, {k2: v2.astype(cd) for k2, v2 in lp["ln2"].items()})
        return x, aux

    def mlm_logits(self, params, hidden):
        """Tied-embedding MLM head: hidden @ tok_emb^T + bias."""
        return (hidden @ params["tok_emb"].astype(hidden.dtype).T
                + params["mlm_bias"].astype(hidden.dtype))

    # ------------------------------------------------------------------
    # losses / training step
    # ------------------------------------------------------------------
    def mlm_loss(self, params, ids, labels, mask_positions, train=True,
                 rng=None, sharded=False, masked_capacity=None):
        """labels: [N,T] int32 with targets; mask_positions: [N,T] 1.0
        where the token was masked (loss only there).

        Memory/FLOPs design: the [N,T,V] log-probability tensor is never
        materialized — per-token CE is logit[label] - logsumexp(logits),
        which XLA fuses into the vocab matmul's epilogue. With
        `masked_capacity=K`, only the top-K masked positions per row are
        projected to the vocab at all (hidden gather before the V-wide
        matmul) — the standard MLM-head optimization: ~15% of positions
        carry loss, so the 768x30522 matmul shrinks ~6.7x. Positions
        beyond K are dropped from the loss (choose K >= max masked/row
        for exactness).
        """
        hidden, aux = self.encode(params, ids, train=train, rng=rng,
                                  sharded=sharded, return_aux=True)
        if masked_capacity is not None:
            k = int(masked_capacity)
            # indices of the K largest mask flags per row (masked first;
            # ties among zeros harmless — they get weight 0)
            w, idx = jax.lax.top_k(mask_positions, k)        # [N,K]
            hidden = jnp.take_along_axis(
                hidden, idx[..., None], axis=1)              # [N,K,D]
            labels = jnp.take_along_axis(labels, idx, axis=1)
            mask_positions = w
        logits = self.mlm_logits(params, hidden).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tok = jnp.take_along_axis(logits, labels[..., None],
                                  axis=-1)[..., 0]
        denom = jnp.maximum(jnp.sum(mask_positions), 1.0)
        ce = -jnp.sum((tok - lse) * mask_positions) / denom
        if self.cfg.n_experts:
            ce = ce + self.cfg.aux_loss_weight * aux
        return ce

    @staticmethod
    def _apply_updates(updater, params, opt_state, grads, it_step):
        """Single definition of update application — shared by the
        GSPMD and ring train steps so updater-policy changes can't
        drift between them."""
        from deeplearning4j_tpu.learning.updaters import apply_updater

        updates, new_opt = apply_updater(updater, opt_state, grads,
                                         params, it_step)
        new_params = jax.tree_util.tree_map(lambda p, u: p - u,
                                            params, updates)
        return new_params, new_opt

    def make_train_step(self, updater, mesh: Optional[Mesh] = None,
                        masked_capacity: Optional[int] = None):
        """Build the compiled train step; with a mesh, params/opt are
        sharded per param_specs and the batch over 'data'.
        masked_capacity: see mlm_loss (vocab-head gather optimization)."""
        sharded = mesh is not None

        def step(params, opt_state, it_step, ids, labels, mask_pos, rng):
            loss, grads = jax.value_and_grad(self.mlm_loss)(
                params, ids, labels, mask_pos, True, rng, sharded,
                masked_capacity)
            new_params, new_opt = self._apply_updates(
                updater, params, opt_state, grads, it_step)
            return new_params, new_opt, loss

        if not sharded:
            return jax.jit(step, donate_argnums=(0, 1))

        specs = self.param_specs()
        pspec = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))

        dp = NamedSharding(mesh, P("data", None))
        rep = NamedSharding(mesh, P())
        return jax.jit(
            step,
            in_shardings=(pspec, None, rep, dp, dp, dp, rep),
            # pin the updated params to the SAME shardings as the input:
            # without this GSPMD may emit them re-sharded (observed with
            # MoE: pos_emb came back P('model')), and feeding them to the
            # next step then fails the in_shardings check
            out_shardings=(pspec, None, rep),
            donate_argnums=(0, 1),
        )

    # ------------------------------------------------------------------
    # context parallelism (ring / Ulysses) — DP x SP under shard_map
    # ------------------------------------------------------------------
    def _encode_local(self, params, ids, sp_axis, train, rng, attn,
                      pad_mask=None):
        """Per-shard encode for shard_map: ids is the LOCAL token shard
        [Nl, Tl]; position embeddings are offset by this shard's ring
        index; attention runs via ring/ulysses collectives over sp.
        pad_mask: LOCAL [Nl, Tl], 1.0 = real token (travels around the
        ring with its K/V block inside the attention impl)."""
        from deeplearning4j_tpu.parallel.ring_attention import (
            ring_attention, ulysses_attention,
        )

        cfg = self.cfg
        cd = self._cdtype
        n, t = ids.shape
        n_sp = _axis_size(sp_axis)  # static inside shard_map
        if t * n_sp > cfg.max_len:
            raise ValueError(
                f"global sequence {t}*{n_sp}={t * n_sp} exceeds "
                f"max_len={cfg.max_len}; dynamic_slice would clamp and "
                f"silently reuse positions")
        sp = lax.axis_index(sp_axis)
        x = params["tok_emb"].astype(cd)[ids]
        pos = lax.dynamic_slice_in_dim(params["pos_emb"].astype(cd),
                                       sp * t, t, axis=0)
        x = x + pos[None]
        x = self._ln(x, {k: v.astype(cd)
                         for k, v in params["emb_ln"].items()})

        base = (ring_attention if attn == "ring" else ulysses_attention)

        def attn_fn(q, k, v, att_mask):
            assert att_mask is None  # padding travels as kv_mask instead
            return base(q, k, v, axis_name=sp_axis, kv_mask=pad_mask)
        keys = (jax.random.split(rng, cfg.n_layers)
                if (train and rng is not None) else [None] * cfg.n_layers)
        aux_total = jnp.float32(0.0)
        for li, lp in enumerate(params["layers"]):
            # MoE under SP: each shard routes its LOCAL token block
            # (per-sequence-shard dispatch groups); aux is averaged
            # over shards by the caller
            x, aux = self._block(x, lp, None, train, keys[li], False,
                                 attn_fn=attn_fn)
            aux_total = aux_total + aux
        return x, aux_total

    def make_ring_train_step(self, updater, mesh: Mesh, attn: str = "ring"):
        """Compiled DP x SP (context-parallel) MLM train step.

        mesh must have axes ('data', 'sp'). Params are replicated; the
        batch is sharded over 'data' and the TOKEN axis over 'sp' —
        each device holds [N/dp, T/sp] and attention streams K/V blocks
        around the sp ring (or all-to-alls heads for attn='ulysses').
        The reference has no such capability (SURVEY.md §5); this is the
        long-context path. Gradients psum over both axes.
        """
        from deeplearning4j_tpu.parallel.mesh import shard_map

        if attn not in ("ring", "ulysses"):
            raise ValueError(f"attn must be ring|ulysses: {attn}")

        def per_shard_grads(params, ids, labels, mask_pos, pad_mask, rng):
            # distinct dropout streams per shard
            rng = jax.random.fold_in(rng, lax.axis_index("data"))
            rng = jax.random.fold_in(rng, lax.axis_index("sp"))

            def local_loss(p):
                hidden, aux = self._encode_local(
                    p, ids, "sp", True, rng, attn, pad_mask=pad_mask)
                logits = self.mlm_logits(p, hidden).astype(jnp.float32)
                logp = jax.nn.log_softmax(logits, axis=-1)
                tok_lp = jnp.take_along_axis(
                    logp, labels[..., None], axis=-1)[..., 0]
                num = lax.psum(jnp.sum(tok_lp * mask_pos), ("data", "sp"))
                den = lax.psum(jnp.sum(mask_pos), ("data", "sp"))
                loss = -num / jnp.maximum(den, 1.0)
                if self.cfg.n_experts:
                    # shard-local routing: model-level balance loss is
                    # the mean over all (data, sp) shards
                    loss = loss + self.cfg.aux_loss_weight * lax.pmean(
                        aux, ("data", "sp"))
                return loss

            loss, grads = jax.value_and_grad(local_loss)(params)
            grads = lax.psum(grads, ("data", "sp"))
            return loss, grads

        dp_sp = P("data", "sp")
        rep = P()

        def step(params, opt_state, it_step, ids, labels, mask_pos, rng,
                 pad_mask=None):
            if pad_mask is None:  # static branch: None never traces
                smapped = shard_map(
                    lambda p, i, l, m, r: per_shard_grads(p, i, l, m,
                                                          None, r),
                    mesh=mesh,
                    in_specs=(rep, dp_sp, dp_sp, dp_sp, rep),
                    out_specs=(rep, rep), check_rep=False)
                loss, grads = smapped(params, ids, labels, mask_pos, rng)
            else:
                smapped = shard_map(
                    per_shard_grads, mesh=mesh,
                    in_specs=(rep, dp_sp, dp_sp, dp_sp, dp_sp, rep),
                    out_specs=(rep, rep), check_rep=False)
                loss, grads = smapped(params, ids, labels, mask_pos,
                                      pad_mask, rng)
            new_params, new_opt = self._apply_updates(
                updater, params, opt_state, grads, it_step)
            return new_params, new_opt, loss

        return jax.jit(step, donate_argnums=(0, 1))

    def shard_params(self, params, mesh: Mesh):
        specs = self.param_specs()
        return jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            params, specs,
            is_leaf=lambda x: isinstance(x, (jax.Array,)) or isinstance(x, P))

    def num_params(self, params) -> int:
        return sum(int(l.size) for l in jax.tree_util.tree_leaves(params))
