"""Causal language model (GPT-style) with KV-cache generation.

Parity note: the reference has no decoder-only LM; this is an
EXTENSION in the same spirit as the ring/Ulysses and MoE recipes —
the model families a reference user graduates to. What makes it
TPU-native:

- training = one jit step with in-graph causal masking (plain fused
  einsum attention; at these sequence lengths XLA's fusion covers it —
  ``ops/flash_attention`` remains the opt-in for long sequences);
- generation = batched PARALLEL prefill (one forward pass writes the
  whole prompt's K/V) + ``lax.scan`` decode over a STATIC-shape KV
  cache ([L, N, H, max_len, hd], position-masked) — no dynamic shapes,
  no per-token dispatch; one compiled program generates the whole
  continuation;
- the decode step is pinned against the recompute-everything forward
  in tests (cache correctness is asserted, not assumed).

Architecture: pre-LN transformer decoder, learned positions, tied
embedding LM head (weights follow models/transformer.py conventions).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.models.transformer import TransformerConfig


class CausalLM:
    def __init__(self, config: TransformerConfig,
                 compute_dtype=jnp.bfloat16):
        self.cfg = config
        self._cdtype = compute_dtype
        # jit cache for generate(): keyed on the shape/config tuple so
        # repeated calls reuse the compiled prefill+decode program
        # (a fresh @jax.jit closure per call would retrace every time)
        self._gen_cache: Dict[Any, Any] = {}

    # -- params ---------------------------------------------------------
    def init_params(self, key=None) -> Dict[str, Any]:
        cfg = self.cfg
        key = key if key is not None else jax.random.key(0)
        d, f = cfg.d_model, cfg.d_ff
        ks = jax.random.split(key, 2 + cfg.n_layers)

        def norm(k, shape, scale):
            return (jax.random.normal(k, shape, jnp.float32)
                    * scale).astype(jnp.float32)

        p = {"tok_emb": norm(ks[0], (cfg.vocab_size, d), 0.02),
             "pos_emb": norm(ks[1], (cfg.max_len, d), 0.01),
             "ln_f": {"g": jnp.ones((d,), jnp.float32),
                      "b": jnp.zeros((d,), jnp.float32)},
             "layers": []}
        for li in range(cfg.n_layers):
            lk = jax.random.split(ks[2 + li], 4)
            p["layers"].append({
                "ln1": {"g": jnp.ones((d,), jnp.float32),
                        "b": jnp.zeros((d,), jnp.float32)},
                "wqkv": norm(lk[0], (d, 3 * d), 0.02),
                "bqkv": jnp.zeros((3 * d,), jnp.float32),
                "wo": norm(lk[1], (d, d), 0.02 / (2 * cfg.n_layers) ** 0.5),
                "bo": jnp.zeros((d,), jnp.float32),
                "ln2": {"g": jnp.ones((d,), jnp.float32),
                        "b": jnp.zeros((d,), jnp.float32)},
                "w1": norm(lk[2], (d, f), 0.02),
                "b1": jnp.zeros((f,), jnp.float32),
                "w2": norm(lk[3], (f, d), 0.02 / (2 * cfg.n_layers) ** 0.5),
                "b2": jnp.zeros((d,), jnp.float32),
            })
        return p

    # -- shared pieces --------------------------------------------------
    def _ln(self, x, p):
        m = jnp.mean(x, axis=-1, keepdims=True)
        v = jnp.var(x, axis=-1, keepdims=True)
        return ((x - m) * lax.rsqrt(v + 1e-5) * p["g"].astype(x.dtype)
                + p["b"].astype(x.dtype))

    def _heads(self, y, n, t):
        cfg = self.cfg
        return y.reshape(n, t, cfg.n_heads, cfg.head_dim) \
                .transpose(0, 2, 1, 3)

    # -- training forward ----------------------------------------------
    def forward(self, params, ids, train=False, rng=None,
                return_kv=False):
        """ids [N,T] -> logits [N,T,V] (causal). With return_kv, also
        returns the per-layer K/V stacks [L,N,H,T,hd] (the parallel
        prefill path of generate())."""
        cfg = self.cfg
        cd = self._cdtype
        n, t = ids.shape
        x = params["tok_emb"].astype(cd)[ids] \
            + params["pos_emb"].astype(cd)[None, :t]
        causal = jnp.tril(jnp.ones((t, t), bool))[None, None]
        scale = 1.0 / jnp.sqrt(jnp.asarray(cfg.head_dim, cd))
        keys = (jax.random.split(rng, cfg.n_layers)
                if (train and rng is not None) else [None] * cfg.n_layers)
        all_k, all_v = [], []
        for lp, k in zip(params["layers"], keys):
            h = self._ln(x, lp["ln1"])
            qkv = h @ lp["wqkv"].astype(cd) + lp["bqkv"].astype(cd)
            q, kk, v = (self._heads(y, n, t)
                        for y in jnp.split(qkv, 3, axis=-1))
            if return_kv:
                all_k.append(kk)
                all_v.append(v)
            logits = jnp.einsum("nhqd,nhkd->nhqk", q, kk) * scale
            neg = jnp.asarray(jnp.finfo(logits.dtype).min, logits.dtype)
            logits = jnp.where(causal, logits, neg)
            w = jax.nn.softmax(logits, axis=-1)
            ctx = jnp.einsum("nhqk,nhkd->nhqd", w, v)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(n, t, cfg.d_model)
            att = ctx @ lp["wo"].astype(cd) + lp["bo"].astype(cd)
            if train and k is not None and cfg.dropout > 0:
                k, sub = jax.random.split(k)
                keep = 1.0 - cfg.dropout
                att = att * jax.random.bernoulli(sub, keep,
                                                 att.shape) / keep
            x = x + att
            h = self._ln(x, lp["ln2"])
            mid = jax.nn.gelu(h @ lp["w1"].astype(cd)
                              + lp["b1"].astype(cd))
            out = mid @ lp["w2"].astype(cd) + lp["b2"].astype(cd)
            x = x + out
        x = self._ln(x, params["ln_f"])
        logits = x @ params["tok_emb"].astype(cd).T
        if return_kv:
            return logits, jnp.stack(all_k), jnp.stack(all_v)
        return logits

    def lm_loss(self, params, ids, train=True, rng=None):
        """Next-token cross entropy over ids[:, :-1] -> ids[:, 1:]."""
        logits = self.forward(params, ids[:, :-1], train, rng)
        targets = ids[:, 1:]
        lse = jax.scipy.special.logsumexp(
            logits.astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(
            logits.astype(jnp.float32), targets[..., None],
            axis=-1)[..., 0]
        return jnp.mean(lse - picked)

    def make_train_step(self, updater):
        from deeplearning4j_tpu.learning.updaters import apply_updater

        @jax.jit
        def step(params, opt_state, it_step, ids, rng):
            loss, grads = jax.value_and_grad(
                lambda p: self.lm_loss(p, ids, True, rng))(params)
            updates, new_opt = apply_updater(updater, opt_state, grads,
                                             params, it_step)
            new_p = jax.tree_util.tree_map(lambda p, u: p - u, params,
                                           updates)
            return new_p, new_opt, loss

        return step

    # -- KV-cache generation --------------------------------------------
    def _decode_one(self, params, ck, cv, pos, tok):
        """One decode step. tok [N] int32 at position ``pos``; ck/cv
        [L,N,H,max_len,hd]. Returns (logits [N,V], new ck, cv)."""
        cfg = self.cfg
        cd = self._cdtype
        n = tok.shape[0]
        x = params["tok_emb"].astype(cd)[tok] \
            + params["pos_emb"].astype(cd)[pos]
        scale = 1.0 / jnp.sqrt(jnp.asarray(cfg.head_dim, cd))
        valid = (jnp.arange(cfg.max_len) <= pos)[None, None, None, :]
        for li, lp in enumerate(params["layers"]):
            h = self._ln(x, lp["ln1"])
            qkv = h @ lp["wqkv"].astype(cd) + lp["bqkv"].astype(cd)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            hs = lambda y: y.reshape(n, cfg.n_heads, 1, cfg.head_dim)
            q, k, v = hs(q), hs(k), hs(v)
            ck = lax.dynamic_update_slice(ck, k[None], (li, 0, 0, pos, 0))
            cv = lax.dynamic_update_slice(cv, v[None], (li, 0, 0, pos, 0))
            logits = jnp.einsum("nhqd,nhkd->nhqk", q, ck[li]) * scale
            neg = jnp.asarray(jnp.finfo(logits.dtype).min, logits.dtype)
            logits = jnp.where(valid, logits, neg)
            w = jax.nn.softmax(logits, axis=-1)
            ctx = jnp.einsum("nhqk,nhkd->nhqd", w, cv[li])
            ctx = ctx.reshape(n, cfg.d_model)
            x = x + ctx @ lp["wo"].astype(cd) + lp["bo"].astype(cd)
            h = self._ln(x, lp["ln2"])
            x = x + jax.nn.gelu(
                h @ lp["w1"].astype(cd) + lp["b1"].astype(cd)) \
                @ lp["w2"].astype(cd) + lp["b2"].astype(cd)
        x = self._ln(x, params["ln_f"])
        return (x @ params["tok_emb"].astype(cd).T).astype(jnp.float32), \
            ck, cv

    def generate(self, params, prompt_ids, max_new_tokens: int,
                 temperature: float = 0.0,
                 rng: Optional[jax.Array] = None):
        """Greedy (temperature 0) or sampled continuation. The WHOLE
        loop — prompt prefill + max_new_tokens decode steps — is one
        jit-compiled lax.scan program with a static-shape KV cache."""
        cfg = self.cfg
        prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
        n, t0 = prompt_ids.shape
        if t0 + max_new_tokens > cfg.max_len:
            raise ValueError(
                f"prompt ({t0}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_len ({cfg.max_len})")
        rng = rng if rng is not None else jax.random.key(0)
        cache_key = (n, t0, max_new_tokens, float(temperature))
        if cache_key in self._gen_cache:
            # LRU: re-insert on hit so eviction drops the COLDEST
            # program, not the oldest-inserted (which may be the
            # hottest shape in a serving mix)
            run = self._gen_cache.pop(cache_key)
            self._gen_cache[cache_key] = run
            return run(params, prompt_ids, rng)

        def sample(key, logits):
            if temperature > 0.0:
                nxt = jax.random.categorical(key, logits / temperature,
                                             axis=-1)
            else:
                nxt = jnp.argmax(logits, axis=-1)
            return nxt.astype(jnp.int32)

        @jax.jit
        def run(params, prompt, rng):
            shape = (cfg.n_layers, n, cfg.n_heads, cfg.max_len,
                     cfg.head_dim)
            # PARALLEL prefill: one batched forward over the whole
            # prompt writes every position's K/V at once (MXU-shaped
            # matmuls), instead of t0 serial single-token steps
            logits_p, ks, vs = self.forward(params, prompt,
                                            return_kv=True)
            ck = jnp.zeros(shape, self._cdtype).at[:, :, :, :t0].set(ks)
            cv = jnp.zeros(shape, self._cdtype).at[:, :, :, :t0].set(vs)
            rng0, rng1 = jax.random.split(rng)
            first = sample(rng0, logits_p[:, -1].astype(jnp.float32))

            def decode(carry, i):
                ck, cv, tok, key = carry
                # tok is generated token i, sitting at position t0+i
                logits, ck, cv = self._decode_one(params, ck, cv,
                                                  t0 + i, tok)
                key, sub = jax.random.split(key)
                nxt = sample(sub, logits)
                return (ck, cv, nxt, key), nxt

            if max_new_tokens == 1:
                return first[:, None]
            _, toks = lax.scan(decode, (ck, cv, first, rng1),
                               jnp.arange(max_new_tokens - 1))
            return jnp.concatenate([first[:, None],
                                    toks.transpose(1, 0)], axis=1)

        if len(self._gen_cache) >= 8:   # bound compiled-program growth
            # dict preserves insertion order and hits re-insert, so the
            # first key is always the least-recently-used program
            self._gen_cache.pop(next(iter(self._gen_cache)))
        self._gen_cache[cache_key] = run
        return run(params, prompt_ids, rng)

    def num_params(self, params) -> int:
        return sum(int(v.size) for v in jax.tree_util.tree_leaves(params))
