"""First-class model implementations (flagship: TransformerEncoder —
the BERT-base-equivalent of the reference's SameDiff TF-import path,
built TPU-first with explicit DP/TP/SP shardings)."""

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig, TransformerEncoder,
)

__all__ = ["TransformerConfig", "TransformerEncoder"]
