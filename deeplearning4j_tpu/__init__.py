"""deeplearning4j_tpu — a TPU-native deep learning framework.

A ground-up rebuild of the capabilities of Deeplearning4j (reference:
ltxz2008/deeplearning4j, a fork of eclipse/deeplearning4j) designed for
TPU hardware: eager NDArray tensor API over jax.Array, whole-step XLA
compilation instead of per-op JNI dispatch, a SameDiff-equivalent graph
autodiff engine, layer/config-driven networks (MultiLayerNetwork /
ComputationGraph equivalents), ETL, evaluation, checkpointing, and
data/tensor-parallel training over ``jax.sharding.Mesh`` where the
reference's Aeron parameter server collapses into XLA collectives.

Reference architecture map: see SURVEY.md at the repo root.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.ndarray.factory import Nd4j
from deeplearning4j_tpu.ndarray.ndarray import NDArray

__all__ = ["Nd4j", "NDArray", "__version__"]
