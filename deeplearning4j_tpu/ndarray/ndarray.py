"""NDArray — the eager tensor type (reference: nd4j INDArray/BaseNDArray).

Reference behavior (SURVEY.md §2.1, §3.3): a mutable, view-supporting
eager array whose every op crosses JNI into libnd4j. The TPU-native
design instead wraps an immutable ``jax.Array`` and implements the
reference's *mutating* API (``addi``, ``assign``, ``putScalar``, flat
views) as **rebinding**: an in-place op computes a new functional value
and swaps the wrapper's buffer. This is the "versioned array" approach —
it preserves the reference's API contract (callers observe the mutation
through the same NDArray object) without fighting XLA's functional
model. True aliasing views are deliberately NOT replicated; code that
needs the reference's flat-param-view trick uses pytrees + donation at
the jit boundary instead (see nn/multilayer).

Every method is eager: fine for scripting/tests, but hot loops belong
inside jit-compiled steps (nn/, autodiff/) where XLA fuses the graph —
the whole-step-compile design this framework exists for.
"""

from __future__ import annotations

from typing import Any, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ndarray.dtypes import DataType


def _unwrap(x):
    return x._buf if isinstance(x, NDArray) else x


class NDArray:
    """Eager n-dimensional array over ``jax.Array``.

    Reference: org/nd4j/linalg/api/ndarray/INDArray.java (interface),
    BaseNDArray.java (impl). Mutating methods rebind ``_buf``.
    """

    __slots__ = ("_buf",)

    def __init__(self, buf):
        if isinstance(buf, NDArray):
            buf = buf._buf
        if not isinstance(buf, (jax.Array, np.ndarray)):
            buf = jnp.asarray(buf)
        if isinstance(buf, np.ndarray):
            buf = jnp.asarray(buf)
        self._buf = buf

    # ------------------------------------------------------------------
    # structure
    # ------------------------------------------------------------------
    @property
    def jax(self) -> jax.Array:
        """The underlying immutable jax.Array (escape hatch to raw jax)."""
        return self._buf

    def shape(self) -> tuple[int, ...]:
        return tuple(self._buf.shape)

    def rank(self) -> int:
        return self._buf.ndim

    def length(self) -> int:
        return int(self._buf.size)

    def size(self, dim: int) -> int:
        return self._buf.shape[dim]

    def dataType(self) -> DataType:
        return DataType.from_any(self._buf.dtype)

    def dtype(self):
        return self._buf.dtype

    def isVector(self) -> bool:
        return self._buf.ndim == 1 or (
            self._buf.ndim == 2 and 1 in self._buf.shape
        )

    def isMatrix(self) -> bool:
        return self._buf.ndim == 2

    def isScalar(self) -> bool:
        return self._buf.ndim == 0 or self._buf.size == 1

    def isEmpty(self) -> bool:
        return self._buf.size == 0

    def rows(self) -> int:
        return self._buf.shape[0]

    def columns(self) -> int:
        return self._buf.shape[1]

    # ------------------------------------------------------------------
    # conversion / copies
    # ------------------------------------------------------------------
    def dup(self) -> "NDArray":
        return NDArray(jnp.array(self._buf))

    def castTo(self, dtype) -> "NDArray":
        return NDArray(self._buf.astype(DataType.from_any(dtype).jax))

    def toNumpy(self) -> np.ndarray:
        return np.asarray(self._buf)

    def ravel(self) -> "NDArray":
        return NDArray(self._buf.ravel())

    def flatten(self) -> "NDArray":
        return NDArray(self._buf.ravel())

    def reshape(self, *shape) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return NDArray(self._buf.reshape(shape))

    def transpose(self, *axes) -> "NDArray":
        if not axes:
            return NDArray(self._buf.T)
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return NDArray(jnp.transpose(self._buf, axes))

    def permute(self, *axes) -> "NDArray":
        return self.transpose(*axes)

    def broadcast(self, *shape) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return NDArray(jnp.broadcast_to(self._buf, shape))

    def repeat(self, repeats, axis=None) -> "NDArray":
        return NDArray(jnp.repeat(self._buf, repeats, axis=axis))

    def swapAxes(self, a: int, b: int) -> "NDArray":
        return NDArray(jnp.swapaxes(self._buf, a, b))

    # ------------------------------------------------------------------
    # mutation-by-rebind (reference: in-place INDArray ops)
    # ------------------------------------------------------------------
    def assign(self, other) -> "NDArray":
        v = _unwrap(other)
        self._buf = jnp.broadcast_to(jnp.asarray(v, dtype=self._buf.dtype), self._buf.shape)
        return self

    def putScalar(self, idx, value) -> "NDArray":
        if isinstance(idx, int):
            idx = np.unravel_index(idx, self._buf.shape)
        self._buf = self._buf.at[tuple(idx)].set(value)
        return self

    def put(self, *args) -> "NDArray":
        """put(idx, value) with idx a raw index OR a list/tuple of
        INDArrayIndex (reference: INDArray#put(INDArrayIndex[], ...))."""
        from deeplearning4j_tpu.ndarray.indexing import (
            INDArrayIndex, resolve_indices,
        )

        if len(args) < 2:
            raise TypeError(
                "put(idx..., value) needs at least an index and a value")
        *idxs, value = args
        if len(idxs) == 1 and not isinstance(idxs[0], INDArrayIndex):
            idx = idxs[0]
            if isinstance(idx, (list, tuple)) and idx and \
                    isinstance(idx[0], INDArrayIndex):
                idx = resolve_indices(idx)
        else:
            idx = resolve_indices(idxs)
        self._buf = self._buf.at[idx].set(_unwrap(value))
        return self

    # -- indexing (reference: INDArray#get with NDArrayIndex) ----------
    def get(self, *idxs) -> "NDArray":
        from deeplearning4j_tpu.ndarray.indexing import resolve_indices

        return NDArray(self._buf[resolve_indices(idxs)])

    def getRow(self, i: int) -> "NDArray":
        return NDArray(self._buf[i])

    def getRows(self, *rows: int) -> "NDArray":
        return NDArray(self._buf[np.asarray(rows)])

    def getColumn(self, i: int) -> "NDArray":
        return NDArray(self._buf[:, i])

    def getColumns(self, *cols: int) -> "NDArray":
        return NDArray(self._buf[:, np.asarray(cols)])

    def putRow(self, i: int, row) -> "NDArray":
        self._buf = self._buf.at[i].set(_unwrap(row))
        return self

    def putColumn(self, i: int, col) -> "NDArray":
        self._buf = self._buf.at[:, i].set(_unwrap(col))
        return self

    def slice(self, i: int, dim: int = 0) -> "NDArray":
        """i-th subarray along `dim` (reference: INDArray#slice)."""
        return NDArray(jnp.take(self._buf, i, axis=dim))

    def tensorAlongDimension(self, index: int, *dims: int) -> "NDArray":
        """TAD (reference: INDArray#tensorAlongDimension): the index-th
        sub-tensor spanning `dims`, iterating the remaining dims in
        C order."""
        nd = self._buf.ndim
        dset = {d % nd for d in dims}
        other = [d for d in range(nd) if d not in dset]
        moved = jnp.moveaxis(self._buf, other, range(len(other)))
        flat = moved.reshape((-1,) + moved.shape[len(other):])
        return NDArray(flat[index])

    def tensorsAlongDimension(self, *dims: int) -> int:
        nd = self._buf.ndim
        dset = {d % nd for d in dims}
        n = 1
        for d in range(nd):
            if d not in dset:
                n *= self._buf.shape[d]
        return n

    def getDouble(self, *idx) -> float:
        if len(idx) == 1 and isinstance(idx[0], int) and self._buf.ndim != 1:
            idx = np.unravel_index(idx[0], self._buf.shape)
        else:
            idx = tuple(idx)
        return float(self._buf[idx])

    def getInt(self, *idx) -> int:
        return int(self.getDouble(*idx))

    # ------------------------------------------------------------------
    # arithmetic — functional variants return new arrays; `i` variants
    # rebind self (reference: add/addi, sub/subi, ... INDArray.java)
    # ------------------------------------------------------------------
    def add(self, other) -> "NDArray":
        return NDArray(self._buf + _unwrap(other))

    def addi(self, other) -> "NDArray":
        self._buf = self._buf + _unwrap(other)
        return self

    def sub(self, other) -> "NDArray":
        return NDArray(self._buf - _unwrap(other))

    def subi(self, other) -> "NDArray":
        self._buf = self._buf - _unwrap(other)
        return self

    def rsub(self, other) -> "NDArray":
        return NDArray(_unwrap(other) - self._buf)

    def rsubi(self, other) -> "NDArray":
        self._buf = _unwrap(other) - self._buf
        return self

    def mul(self, other) -> "NDArray":
        return NDArray(self._buf * _unwrap(other))

    def muli(self, other) -> "NDArray":
        self._buf = self._buf * _unwrap(other)
        return self

    def div(self, other) -> "NDArray":
        return NDArray(self._buf / _unwrap(other))

    def divi(self, other) -> "NDArray":
        self._buf = self._buf / _unwrap(other)
        return self

    def rdiv(self, other) -> "NDArray":
        return NDArray(_unwrap(other) / self._buf)

    def rdivi(self, other) -> "NDArray":
        self._buf = _unwrap(other) / self._buf
        return self

    def neg(self) -> "NDArray":
        return NDArray(-self._buf)

    def negi(self) -> "NDArray":
        self._buf = -self._buf
        return self

    def fmod(self, other) -> "NDArray":
        return NDArray(jnp.fmod(self._buf, _unwrap(other)))

    # broadcast-along-dimension ops (reference: addRowVector etc.)
    def addRowVector(self, row) -> "NDArray":
        return NDArray(self._buf + _unwrap(row).reshape(1, -1))

    def addColumnVector(self, col) -> "NDArray":
        return NDArray(self._buf + _unwrap(col).reshape(-1, 1))

    def mulRowVector(self, row) -> "NDArray":
        return NDArray(self._buf * _unwrap(row).reshape(1, -1))

    def mulColumnVector(self, col) -> "NDArray":
        return NDArray(self._buf * _unwrap(col).reshape(-1, 1))

    def subRowVector(self, row) -> "NDArray":
        return NDArray(self._buf - _unwrap(row).reshape(1, -1))

    def divRowVector(self, row) -> "NDArray":
        return NDArray(self._buf / _unwrap(row).reshape(1, -1))

    # ------------------------------------------------------------------
    # linear algebra
    # ------------------------------------------------------------------
    def mmul(self, other) -> "NDArray":
        """Matrix multiply (MXU path under jit; reference MmulHelper)."""
        return NDArray(self._buf @ _unwrap(other))

    def mmuli(self, other) -> "NDArray":
        self._buf = self._buf @ _unwrap(other)
        return self

    def tensorMmul(self, other, axes) -> "NDArray":
        return NDArray(jnp.tensordot(self._buf, _unwrap(other), axes=axes))

    def dot(self, other) -> float:
        return float(jnp.vdot(self._buf, _unwrap(other)))

    # ------------------------------------------------------------------
    # reductions (reference: execReduce* legacy loops in libnd4j)
    # ------------------------------------------------------------------
    def _reduce(self, fn, dims, keepdims=False):
        axis = None if not dims else (dims if len(dims) > 1 else dims[0])
        out = fn(self._buf, axis=axis, keepdims=keepdims)
        return NDArray(out) if isinstance(out, jax.Array) and out.ndim > 0 else NDArray(jnp.asarray(out))

    def sum(self, *dims, keepdims=False):
        r = self._reduce(jnp.sum, dims, keepdims)
        return r if dims or keepdims else r.item()

    def mean(self, *dims, keepdims=False):
        r = self._reduce(jnp.mean, dims, keepdims)
        return r if dims or keepdims else r.item()

    def max(self, *dims, keepdims=False):
        r = self._reduce(jnp.max, dims, keepdims)
        return r if dims or keepdims else r.item()

    def min(self, *dims, keepdims=False):
        r = self._reduce(jnp.min, dims, keepdims)
        return r if dims or keepdims else r.item()

    def prod(self, *dims, keepdims=False):
        r = self._reduce(jnp.prod, dims, keepdims)
        return r if dims or keepdims else r.item()

    def std(self, *dims, ddof: int = 1):
        out = jnp.std(self._buf, axis=(dims if dims else None), ddof=ddof)
        return NDArray(out) if dims else float(out)

    def var(self, *dims, ddof: int = 1):
        out = jnp.var(self._buf, axis=(dims if dims else None), ddof=ddof)
        return NDArray(out) if dims else float(out)

    # *Number() scalar reductions (reference: INDArray#sumNumber etc.)
    def sumNumber(self) -> float:
        return float(jnp.sum(self._buf))

    def meanNumber(self) -> float:
        return float(jnp.mean(self._buf))

    def maxNumber(self) -> float:
        return float(jnp.max(self._buf))

    def minNumber(self) -> float:
        return float(jnp.min(self._buf))

    def prodNumber(self) -> float:
        return float(jnp.prod(self._buf))

    def stdNumber(self, bias_corrected: bool = True) -> float:
        return float(jnp.std(self._buf, ddof=1 if bias_corrected else 0))

    def varNumber(self, bias_corrected: bool = True) -> float:
        return float(jnp.var(self._buf, ddof=1 if bias_corrected else 0))

    def argMax(self, *dims):
        if not dims:
            return int(jnp.argmax(self._buf))
        return NDArray(jnp.argmax(self._buf, axis=dims[0]))

    def argMin(self, *dims):
        if not dims:
            return int(jnp.argmin(self._buf))
        return NDArray(jnp.argmin(self._buf, axis=dims[0]))

    def cumsum(self, axis=None) -> "NDArray":
        return NDArray(jnp.cumsum(self._buf, axis=axis))

    def norm1(self, *dims):
        r = self._reduce(lambda a, axis, keepdims: jnp.sum(jnp.abs(a), axis=axis, keepdims=keepdims), dims)
        return r if dims else r.item()

    def norm2(self, *dims):
        r = self._reduce(lambda a, axis, keepdims: jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=keepdims)), dims)
        return r if dims else r.item()

    def normMax(self, *dims):
        r = self._reduce(lambda a, axis, keepdims: jnp.max(jnp.abs(a), axis=axis, keepdims=keepdims), dims)
        return r if dims else r.item()

    def item(self):
        v = self._buf
        if v.dtype == jnp.bool_:
            return bool(v)
        if jnp.issubdtype(v.dtype, jnp.integer):
            return int(v)
        return float(v)

    # ------------------------------------------------------------------
    # comparisons (return NDArray of bool / used as masks)
    # ------------------------------------------------------------------
    def gt(self, other) -> "NDArray":
        return NDArray(self._buf > _unwrap(other))

    def gte(self, other) -> "NDArray":
        return NDArray(self._buf >= _unwrap(other))

    def lt(self, other) -> "NDArray":
        return NDArray(self._buf < _unwrap(other))

    def lte(self, other) -> "NDArray":
        return NDArray(self._buf <= _unwrap(other))

    def eq(self, other) -> "NDArray":
        return NDArray(self._buf == _unwrap(other))

    def neq(self, other) -> "NDArray":
        return NDArray(self._buf != _unwrap(other))

    def equalsWithEps(self, other, eps: float = 1e-5) -> bool:
        o = _unwrap(other)
        if tuple(o.shape) != tuple(self._buf.shape):
            return False
        return bool(jnp.all(jnp.abs(self._buf - o) <= eps))

    def equals(self, other) -> bool:
        return self.equalsWithEps(other, 1e-5)

    # ------------------------------------------------------------------
    # python protocol
    # ------------------------------------------------------------------
    def __getitem__(self, idx) -> "NDArray":
        if isinstance(idx, NDArray):
            idx = idx._buf
        return NDArray(self._buf[idx])

    def __setitem__(self, idx, value):
        if isinstance(idx, NDArray):
            idx = idx._buf
        self._buf = self._buf.at[idx].set(_unwrap(value))

    def __add__(self, other):
        return self.add(other)

    __radd__ = __add__

    def __sub__(self, other):
        return self.sub(other)

    def __rsub__(self, other):
        return self.rsub(other)

    def __mul__(self, other):
        return self.mul(other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self.div(other)

    def __rtruediv__(self, other):
        return self.rdiv(other)

    def __matmul__(self, other):
        return self.mmul(other)

    def __neg__(self):
        return self.neg()

    def __pow__(self, p):
        return NDArray(self._buf ** _unwrap(p))

    def __len__(self):
        return self._buf.shape[0]

    def __iter__(self):
        for i in range(self._buf.shape[0]):
            yield NDArray(self._buf[i])

    def __float__(self):
        return float(self._buf)

    def __int__(self):
        return int(self._buf)

    def __bool__(self):
        return bool(self._buf)

    def __array__(self, dtype=None):
        a = np.asarray(self._buf)
        return a.astype(dtype) if dtype is not None else a

    def __repr__(self):
        return f"NDArray{self.shape()}:{self._buf.dtype.name}\n{np.asarray(self._buf)!r}"

    def __jax_array__(self):
        return self._buf


# Register NDArray as a pytree so it can flow through jit/grad transparently.
jax.tree_util.register_pytree_node(
    NDArray,
    lambda a: ((a._buf,), None),
    lambda _, children: NDArray(children[0]),
)
