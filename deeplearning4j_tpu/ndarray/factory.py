"""Nd4j — the static array factory (reference: org/nd4j/linalg/factory/Nd4j.java).

The reference's factory routes through a pluggable backend (nd4j-native /
nd4j-cuda) chosen at classload. Here there is exactly one backend — XLA —
and device placement is jax's default-device semantics; `Nd4j` is a
namespace of constructors plus RNG state (reference: Nd4j.getRandom(),
a Philox generator in libnd4j — ours is jax's threefry key, split per
call so eager random calls are reproducible from the seed).
"""

from __future__ import annotations

from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ndarray.dtypes import DataType, DEFAULT_FLOAT
from deeplearning4j_tpu.ndarray.ndarray import NDArray, _unwrap


def _dt(dtype) -> jnp.dtype:
    if dtype is None:
        return DEFAULT_FLOAT.jax
    return DataType.from_any(dtype).jax


class _RandomState:
    """Counter-split PRNG (reference: NativeRandom/Philox RNG, §2.39).

    Key creation is LAZY: `jax.random.key` initializes the XLA backend,
    and this object is instantiated at package-import time — an eager
    key would break ``jax.distributed.initialize`` (which must run
    before any backend-touching call in multi-process programs)."""

    def __init__(self, seed: int = 0):
        self._seed = seed
        self._key = None

    def setSeed(self, seed: int):
        # stay lazy: creating the key here would initialize the backend
        # (see class docstring)
        self._seed = seed
        self._key = None

    def next_key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        self._key, sub = jax.random.split(self._key)
        return sub


class Nd4j:
    """Static factory namespace. Reference: Nd4j.java."""

    _random = _RandomState(seed=0)

    # -- RNG ------------------------------------------------------------
    @staticmethod
    def getRandom() -> _RandomState:
        return Nd4j._random

    @staticmethod
    def setSeed(seed: int):
        Nd4j._random.setSeed(seed)

    # -- constructors ---------------------------------------------------
    @staticmethod
    def create(data=None, shape=None, dtype=None) -> NDArray:
        """Nd4j.create(data[, shape]) — from nested lists/numpy, or zeros."""
        if data is None and shape is not None:
            return Nd4j.zeros(*shape, dtype=dtype)
        arr = jnp.asarray(_unwrap(data), dtype=_dt(dtype) if dtype or not hasattr(data, "dtype") else None)
        if arr.dtype == jnp.float64:
            arr = arr.astype(_dt(dtype))
        if shape is not None:
            arr = arr.reshape(shape)
        return NDArray(arr)

    @staticmethod
    def zeros(*shape, dtype=None) -> NDArray:
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return NDArray(jnp.zeros(shape, dtype=_dt(dtype)))

    @staticmethod
    def ones(*shape, dtype=None) -> NDArray:
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return NDArray(jnp.ones(shape, dtype=_dt(dtype)))

    @staticmethod
    def zerosLike(a) -> NDArray:
        return NDArray(jnp.zeros_like(_unwrap(a)))

    @staticmethod
    def onesLike(a) -> NDArray:
        return NDArray(jnp.ones_like(_unwrap(a)))

    @staticmethod
    def valueArrayOf(shape, value, dtype=None) -> NDArray:
        if isinstance(shape, int):
            shape = (shape,)
        return NDArray(jnp.full(tuple(shape), value, dtype=_dt(dtype)))

    @staticmethod
    def scalar(value, dtype=None) -> NDArray:
        return NDArray(jnp.asarray(value, dtype=_dt(dtype) if dtype else None))

    @staticmethod
    def eye(n: int, dtype=None) -> NDArray:
        return NDArray(jnp.eye(n, dtype=_dt(dtype)))

    @staticmethod
    def arange(*args, dtype=None) -> NDArray:
        return NDArray(jnp.arange(*args, dtype=_dt(dtype)))

    @staticmethod
    def linspace(start, stop, num, dtype=None) -> NDArray:
        return NDArray(jnp.linspace(start, stop, num, dtype=_dt(dtype)))

    # -- random constructors -------------------------------------------
    @staticmethod
    def rand(*shape, dtype=None) -> NDArray:
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return NDArray(jax.random.uniform(Nd4j._random.next_key(), shape, dtype=_dt(dtype)))

    @staticmethod
    def randn(*shape, dtype=None) -> NDArray:
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return NDArray(jax.random.normal(Nd4j._random.next_key(), shape, dtype=_dt(dtype)))

    @staticmethod
    def randint(minval, maxval, shape, dtype=DataType.INT) -> NDArray:
        return NDArray(
            jax.random.randint(Nd4j._random.next_key(), tuple(shape), minval, maxval, dtype=_dt(dtype))
        )

    @staticmethod
    def bernoulli(p, shape, dtype=None) -> NDArray:
        return NDArray(
            jax.random.bernoulli(Nd4j._random.next_key(), p, tuple(shape)).astype(_dt(dtype))
        )

    @staticmethod
    def shuffle(a) -> NDArray:
        """Permute rows in place (reference: Nd4j.shuffle)."""
        perm = jax.random.permutation(Nd4j._random.next_key(), _unwrap(a).shape[0])
        if isinstance(a, NDArray):
            a._buf = a._buf[perm]
            return a
        return NDArray(_unwrap(a)[perm])

    # -- combining ------------------------------------------------------
    @staticmethod
    def concat(axis: int, *arrays) -> NDArray:
        return NDArray(jnp.concatenate([_unwrap(a) for a in arrays], axis=axis))

    @staticmethod
    def hstack(*arrays) -> NDArray:
        return NDArray(jnp.hstack([_unwrap(a) for a in arrays]))

    @staticmethod
    def vstack(*arrays) -> NDArray:
        return NDArray(jnp.vstack([_unwrap(a) for a in arrays]))

    @staticmethod
    def stack(axis: int, *arrays) -> NDArray:
        return NDArray(jnp.stack([_unwrap(a) for a in arrays], axis=axis))

    @staticmethod
    def pile(*arrays) -> NDArray:
        return Nd4j.stack(0, *arrays)

    @staticmethod
    def tile(a, *reps) -> NDArray:
        if len(reps) == 1 and isinstance(reps[0], (tuple, list)):
            reps = tuple(reps[0])
        return NDArray(jnp.tile(_unwrap(a), reps))

    @staticmethod
    def where(cond, x, y) -> NDArray:
        return NDArray(jnp.where(_unwrap(cond), _unwrap(x), _unwrap(y)))

    # -- linalg ---------------------------------------------------------
    @staticmethod
    def gemm(a, b, transposeA: bool = False, transposeB: bool = False, alpha: float = 1.0) -> NDArray:
        A = _unwrap(a).T if transposeA else _unwrap(a)
        B = _unwrap(b).T if transposeB else _unwrap(b)
        return NDArray(alpha * (A @ B))

    @staticmethod
    def matmul(a, b) -> NDArray:
        return NDArray(_unwrap(a) @ _unwrap(b))

    # -- misc -----------------------------------------------------------
    @staticmethod
    def sort(a, axis=-1, descending: bool = False) -> NDArray:
        out = jnp.sort(_unwrap(a), axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return NDArray(out)

    @staticmethod
    def argsort(a, axis=-1) -> NDArray:
        return NDArray(jnp.argsort(_unwrap(a), axis=axis))

    @staticmethod
    def exec(op_name: str, *args, **kwargs):
        """Execute a registered op by name (reference: Nd4j.exec(CustomOp),
        dispatching through OpRegistrator — SURVEY.md §3.3)."""
        from deeplearning4j_tpu.ops.registry import get_op

        return get_op(op_name)(*args, **kwargs)

    # -- array serde (reference: the Nd4j.{writeTxt,readTxt,saveBinary,
    # readBinary,writeAsNumpy,createFromNpyFile} statics) --------------
    @staticmethod
    def writeTxt(a, path: str) -> None:
        """Shape/dtype-preserving text format: one JSON header line,
        then the flattened values (reference: Nd4j.writeTxt — the Java
        text layout is JVM-specific; this is the same capability with
        a self-describing header)."""
        import json as _json

        arr = np.asarray(_unwrap(a))
        with open(path, "w") as f:
            f.write(_json.dumps({"shape": list(arr.shape),
                                 "dtype": arr.dtype.name}) + "\n")
            flat = arr.ravel()
            if arr.dtype.kind == "b":
                f.write("\n".join(str(int(v)) for v in flat))
            else:
                f.write("\n".join(repr(v.item()) for v in flat))
            f.write("\n")

    @staticmethod
    def readTxt(path: str) -> NDArray:
        import json as _json

        with open(path) as f:
            head = _json.loads(f.readline())
            vals = [line.strip() for line in f if line.strip()]
        dt = np.dtype(head["dtype"])
        if dt.kind == "b":
            # np.bool_("False") is True (non-empty string); parse 0/1
            arr = np.array([v in ("1", "True") for v in vals],
                           dtype=bool)
        else:
            arr = np.array(vals, dtype=dt)   # numpy parses strings
        return NDArray(jnp.asarray(arr.reshape(head["shape"])))

    @staticmethod
    def saveBinary(a, path: str) -> None:
        """reference: Nd4j.saveBinary — here the npy container (the
        natural binary substrate; see writeAsNumpy for interop). The
        file object keeps np.save from appending '.npy' to the exact
        path the caller chose."""
        with open(path, "wb") as f:
            np.save(f, np.asarray(_unwrap(a)), allow_pickle=False)

    @staticmethod
    def readBinary(path: str) -> NDArray:
        return NDArray(jnp.asarray(np.load(path, allow_pickle=False)))

    # numpy interop keeps the reference names
    writeAsNumpy = saveBinary
    createFromNpyFile = readBinary
