"""Eager NDArray tensor core (reference: nd4j INDArray/BaseNDArray/Nd4j).

The reference implements an eager tensor API in Java backed by a C++
kernel library, with every op crossing JNI (SURVEY.md §2.1-2.7, §3.3).
Here the eager API is a thin typed wrapper over ``jax.Array``: single
eager ops dispatch through XLA's eager executor, and anything on a hot
path is expected to be traced into a jit-compiled whole step instead
(the reference has no such fusion — that is the core design delta).
"""

from deeplearning4j_tpu.ndarray.dtypes import DataType
from deeplearning4j_tpu.ndarray.ndarray import NDArray
from deeplearning4j_tpu.ndarray.factory import Nd4j
from deeplearning4j_tpu.ndarray.indexing import (
    INDArrayIndex, NDArrayIndex,
)

__all__ = ["DataType", "NDArray", "Nd4j", "INDArrayIndex", "NDArrayIndex"]
